#!/bin/bash
# Probes the axon TPU tunnel every 10 min; logs to .tpu_probe.log
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 90 python -c "import jax; ds=jax.devices(); print(ds[0].platform, len(ds))" 2>&1 | tail -1)
  echo "$ts $out" >> /root/repo/.tpu_probe.log
  if echo "$out" | grep -qiE '^(tpu|axon)'; then
    echo "$ts TUNNEL_UP" >> /root/repo/.tpu_probe.log
  fi
  sleep 600
done
