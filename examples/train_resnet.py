"""Image classification with the high-level API (ref: paddle.Model fit).

ResNet-18 on FakeData (swap in Cifar10(data_file=...) for the real thing):

    python examples/train_resnet.py --steps 10
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import DataLoader
    from paddle_tpu.models.resnet import resnet18
    from paddle_tpu.vision.datasets import FakeData

    pt.seed(0)
    net = resnet18(num_classes=10)
    model = Model(net)
    model.prepare(optimizer=opt.Momentum(learning_rate=0.01, momentum=0.9),
                  loss=nn.functional.cross_entropy)

    ds = FakeData(size=args.steps * args.batch, image_shape=(3, 32, 32),
                  num_classes=10)
    loader = DataLoader(ds, batch_size=args.batch, shuffle=True, drop_last=True)
    history = model.fit(loader, epochs=1, log_freq=2)
    return history


if __name__ == "__main__":
    main()
