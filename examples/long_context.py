"""Long-context training with sequence parallelism.

Shows the two context-parallel modes on the flagship model:
  * ring:    KV blocks rotate over ICI (ppermute); best when S/chip is big
  * ulysses: all_to_all seq<->head re-sharding; best when heads >= sp

Runs on the CPU virtual mesh by default (8 devices); the same code scales
to a TPU slice — only the mesh shape changes.

    python examples/long_context.py --mode ring --seq 512
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# device choice is explicit (--device tpu to run on a slice); the default
# is the 8-device CPU virtual mesh so the example runs anywhere
_ON_TPU = "--device=tpu" in sys.argv or (
    "--device" in sys.argv
    and sys.argv[sys.argv.index("--device") + 1:][:1] == ["tpu"])
if not _ON_TPU:
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["ring", "ulysses"], default="ring")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--device", choices=["cpu", "tpu"], default="cpu")
    args = ap.parse_args()

    pt.seed(0)
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4 if args.mode == "ulysses" else 2,
        max_position_embeddings=args.seq,
        sequence_parallel=args.mode)
    mesh = HybridMesh(dp=args.dp, sp=args.sp,
                      devices=jax.devices()[:args.dp * args.sp])
    print(f"mesh dp={args.dp} sp={args.sp}, mode={args.mode}, S={args.seq}")

    with mesh:
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3)
        state = init_state(model, optimizer, mesh)
        rs = np.random.RandomState(0)
        ids = jax.device_put(
            jnp.asarray(rs.randint(0, cfg.vocab_size, (args.dp * 2, args.seq))),
            mesh.batch_sharding())
        labels = jnp.concatenate(
            [ids[:, 1:], -100 * jnp.ones((ids.shape[0], 1), ids.dtype)], axis=1)
        labels = jax.device_put(labels, mesh.batch_sharding())
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, mesh)
        for i in range(args.steps):
            state, loss = step(state, ids, labels)
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
