"""Hybrid-parallel LLaMA training on a device mesh (dp x fsdp x tp).

Runs on real chips when available, or on a virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_multichip.py --devices 8 --steps 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--fsdp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=2,
                    help="expert-parallel width for the MoE loss-equality "
                         "leg (0/1 skips it)")
    args = ap.parse_args()

    # flags must be in place BEFORE the backend initialises (first
    # jax.devices() call) — same dance as __graft_entry__.dryrun_multichip
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        f" --xla_force_host_platform_device_count={args.devices}"
    import jax
    if jax.default_backend() != "tpu" or len(jax.devices()) < args.devices:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.clear_backends()
        except Exception:
            pass

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import HybridMesh
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    mesh = HybridMesh(dp=args.dp, fsdp=args.fsdp, tp=args.tp,
                      devices=jax.devices()[:args.devices])
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=64,
                           num_attention_heads=4, num_key_value_heads=2)
    batch = args.dp * args.fsdp * 2
    rs = np.random.RandomState(0)

    with mesh:
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3,
                              grad_clip=opt.ClipGradByGlobalNorm(1.0))
        state = init_state(model, optimizer, mesh)
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, mesh)
        for i in range(args.steps):
            ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, 16)))
            labels = jnp.concatenate(
                [ids[:, 1:], -100 * jnp.ones((batch, 1), ids.dtype)], axis=1)
            ids = jax.device_put(ids, mesh.batch_sharding())
            labels = jax.device_put(labels, mesh.batch_sharding())
            state, loss = step(state, ids, labels)
            print(f"step {i} loss {float(loss):.4f} "
                  f"(mesh dp={args.dp} fsdp={args.fsdp} tp={args.tp})")

    if args.ep > 1:
        # expert-parallel leg: the MoE loss under an ep mesh (experts
        # sharded, tokens all-to-all'd through the grouped GEMM) must
        # equal the single-device loss on the same batch
        from paddle_tpu.models.moe_llm import MoEConfig, MoEForCausalLM
        pt.seed(0)
        moe_cfg = MoEConfig(base=cfg, num_experts=4, top_k=2,
                            capacity_factor=None, moe_every=1)
        moe = MoEForCausalLM(moe_cfg)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 16)))
        labels = jnp.concatenate(
            [ids[:, 1:], -100 * jnp.ones((2, 1), ids.dtype)], axis=1)
        ref = float(moe.loss(ids, labels))
        ep_mesh = HybridMesh(ep=args.ep, devices=jax.devices()[:args.ep])
        with ep_mesh:
            ep_loss = float(moe.loss(ids, labels))
        print(f"moe loss single={ref:.6f} ep{args.ep}={ep_loss:.6f}")
        np.testing.assert_allclose(ep_loss, ref, rtol=2e-5)
    return float(loss)


if __name__ == "__main__":
    main()
