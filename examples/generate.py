"""Text generation with the KV-cache decode loop.

Greedy / top-k / top-p sampling and beam search on any of the decoder
models (LLaMA / Mistral / Qwen2) — one compiled while_loop, pre-allocated
cache, no per-step recompiles.

    python examples/generate.py --model mistral --strategy top_p
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# device choice is explicit; default CPU so the example runs anywhere
_ON_TPU = "--device=tpu" in sys.argv or (
    "--device" in sys.argv
    and sys.argv[sys.argv.index("--device") + 1:][:1] == ["tpu"])
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["llama", "mistral", "qwen2"],
                    default="llama")
    ap.add_argument("--strategy", choices=["greedy", "top_k", "top_p", "beam"],
                    default="greedy")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--device", choices=["cpu", "tpu"], default="cpu")
    args = ap.parse_args()

    pt.seed(0)
    if args.model == "llama":
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        model = LlamaForCausalLM(LlamaConfig.tiny()).eval()
    elif args.model == "mistral":
        from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
        model = MistralForCausalLM(MistralConfig.tiny()).eval()
    else:
        from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM
        model = Qwen2ForCausalLM(Qwen2Config.tiny()).eval()

    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (1, 8)))

    if args.strategy == "beam":
        from paddle_tpu.models.decoding import beam_search
        out, scores = beam_search(model, prompt, num_beams=4,
                                  max_new_tokens=args.max_new_tokens)
        print("beam score:", float(scores[0]))
    else:
        from paddle_tpu.models.decoding import generate
        kw = {"greedy": dict(temperature=0.0),
              "top_k": dict(temperature=0.8, top_k=50),
              "top_p": dict(temperature=0.8, top_p=0.9)}[args.strategy]
        out = generate(model, prompt, max_new_tokens=args.max_new_tokens,
                       rng=jax.random.PRNGKey(0), **kw)
    print(f"{args.model}/{args.strategy}:", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
