"""Pretrain a LLaMA-architecture causal LM end-to-end.

Shows the canonical pipeline: token-bin data (native C++ fast loader when
present), fused train step, AMP-style bf16 params + fp32 master weights,
checkpoint/resume, MFU logging. Defaults to a tiny config so it runs
anywhere; pass --size 0.8b on a real chip.

    python examples/train_llama.py --steps 20
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--size", default="tiny", choices=["tiny", "0.8b"])
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args()

    import paddle_tpu as pt
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    if args.size == "tiny":
        cfg = LlamaConfig.tiny()
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=12,
                          num_attention_heads=16, num_key_value_heads=16,
                          dtype=jnp.bfloat16, remat=True, scan_layers=True)

    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(
        learning_rate=opt.lr.CosineAnnealingDecay(3e-4, T_max=args.steps),
        weight_decay=0.1, grad_clip=opt.ClipGradByGlobalNorm(1.0),
        multi_precision=True)
    state = init_state(model, optimizer)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)

    rs = np.random.RandomState(0)
    for i in range(args.steps):
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (args.batch, args.seq)))
        labels = jnp.concatenate(
            [ids[:, 1:], -100 * jnp.ones((args.batch, 1), ids.dtype)], axis=1)
        state, loss = step(state, ids, labels)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    if args.ckpt_dir:
        from paddle_tpu.train.checkpoint import CheckpointManager
        CheckpointManager(args.ckpt_dir).save(args.steps, state)
        print("saved checkpoint to", args.ckpt_dir)
    return float(loss)


if __name__ == "__main__":
    main()
