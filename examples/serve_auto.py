"""Serve any local HF checkpoint: auto registry -> continuous-batching
engine with mixed greedy + beam traffic, or family-agnostic generation.

    python examples/serve_auto.py /path/to/hf_checkpoint_dir

(ref: PaddleNLP `llm` predictor entrypoint + AutoModelForCausalLM.)
"""
import sys

import numpy as np

from paddle_tpu.models.auto import auto_from_pretrained
from paddle_tpu.models.decoding import generic_generate
from paddle_tpu.serving import LLMEngine, Request


def main(ckpt_dir):
    model = auto_from_pretrained(ckpt_dir)
    prompts = [np.arange(3, 11), np.arange(5, 12), np.arange(2, 8)]

    if type(model).__name__ == "LlamaForCausalLM" or hasattr(model, "model"):
        # llama-family: the paged continuous-batching engine (fast path)
        eng = LLMEngine(model, num_slots=2, block_size=16,
                        max_prompt_len=32, max_seq_len=64)
        for p in prompts[:2]:
            eng.generate(p, max_new_tokens=12,
                         stream=lambda r, t: print(f"req {r.req_id} -> {t}"))
        eng.generate(prompts[2], max_new_tokens=12, num_beams=2)  # beams
        out = eng.run()
        for rid, toks in sorted(out.items()):
            print(f"req {rid}: {toks}")
    else:
        # any other causal family: generic full-forward decoding
        out = generic_generate(model, np.stack([prompts[0]]),
                               max_new_tokens=12)
        print(np.asarray(out))


if __name__ == "__main__":
    main(sys.argv[1])
