// Native host-side data runtime (the TPU-native counterpart of the
// reference's C++ DataLoader worker pool / pinned-memory pipeline:
// paddle/fluid/operators/reader/ + paddle/phi/core/memory host allocator).
//
// Responsibilities:
//   * mmap a token-bin file (uint16/uint32 tokens) with zero copies
//   * a background thread pool cuts shuffled (input, label) windows into a
//     lock-free-ish ring of pre-touched buffers so Python never blocks on
//     page faults or memcpy — the feed thread only hands out pointers
//   * deterministic xorshift shuffling keyed by (seed, epoch)
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).
//
// Build: make -C native  (produces libfastloader.so)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <queue>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Batch {
  std::vector<int32_t> tokens;  // [batch, seq+1] window; caller splits x/y
};

struct Loader {
  // mmap state
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t file_bytes = 0;
  int token_width = 2;  // bytes per token: 2 (uint16) or 4 (uint32)
  size_t n_tokens = 0;

  // batch geometry
  int batch = 0;
  int seq = 0;
  uint64_t seed = 0;

  // prefetch ring
  size_t capacity = 8;
  std::queue<Batch*> ready;
  std::queue<Batch*> free_bufs;
  std::vector<Batch*> all_bufs;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> cursor{0};

  uint64_t rng_state;

  uint64_t next_rand() {
    // xorshift64* — deterministic, fast, good enough for window sampling
    uint64_t x = rng_state;
    x ^= x >> 12;
    x ^= x << 25;
    x ^= x >> 27;
    rng_state = x;
    return x * 0x2545F4914F6CDD1DULL;
  }

  int32_t token_at(size_t i) const {
    if (token_width == 2) {
      uint16_t v;
      std::memcpy(&v, data + i * 2, 2);
      return (int32_t)v;
    }
    uint32_t v;
    std::memcpy(&v, data + i * 4, 4);
    return (int32_t)v;
  }

  void fill(Batch* b) {
    const size_t window = (size_t)seq + 1;
    const size_t max_start = n_tokens - window;
    b->tokens.resize((size_t)batch * window);
    for (int r = 0; r < batch; ++r) {
      size_t start;
      {
        std::lock_guard<std::mutex> lk(mu);  // rng shared: serialize draws
        start = (size_t)(next_rand() % (max_start + 1));
      }
      for (size_t t = 0; t < window; ++t)
        b->tokens[(size_t)r * window + t] = token_at(start + t);
    }
  }

  void worker_loop() {
    while (!stop.load()) {
      Batch* buf = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop.load() || !free_bufs.empty(); });
        if (stop.load()) return;
        buf = free_bufs.front();
        free_bufs.pop();
      }
      fill(buf);
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push(buf);
      }
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* fl_open(const char* path, int token_width, int batch, int seq,
              uint64_t seed, int n_workers, int prefetch) {
  auto* L = new Loader();
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { ::close(L->fd); delete L; return nullptr; }
  L->file_bytes = (size_t)st.st_size;
  L->token_width = token_width;
  L->n_tokens = L->file_bytes / (size_t)token_width;
  void* m = mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (m == MAP_FAILED) { ::close(L->fd); delete L; return nullptr; }
  madvise(m, L->file_bytes, MADV_RANDOM);
  L->data = (const uint8_t*)m;
  L->batch = batch;
  L->seq = seq;
  L->seed = seed;
  L->rng_state = seed ? seed : 0x9E3779B97F4A7C15ULL;
  L->capacity = (size_t)(prefetch > 0 ? prefetch : 8);
  if ((size_t)seq + 1 > L->n_tokens) { munmap(m, L->file_bytes); ::close(L->fd); delete L; return nullptr; }
  for (size_t i = 0; i < L->capacity; ++i) {
    auto* b = new Batch();
    L->all_bufs.push_back(b);
    L->free_bufs.push(b);
  }
  int nw = n_workers > 0 ? n_workers : 2;
  for (int i = 0; i < nw; ++i)
    L->workers.emplace_back([L] { L->worker_loop(); });
  return L;
}

uint64_t fl_num_tokens(void* h) { return ((Loader*)h)->n_tokens; }

// Blocks until a batch is ready; copies into out [batch*(seq+1)] int32.
int fl_next(void* h, int32_t* out) {
  auto* L = (Loader*)h;
  Batch* b = nullptr;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return L->stop.load() || !L->ready.empty(); });
    if (L->stop.load()) return -1;
    b = L->ready.front();
    L->ready.pop();
  }
  std::memcpy(out, b->tokens.data(), b->tokens.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_bufs.push(b);
  }
  L->cv_free.notify_one();
  return 0;
}

void fl_close(void* h) {
  auto* L = (Loader*)h;
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  for (auto* b : L->all_bufs) delete b;
  if (L->data) munmap((void*)L->data, L->file_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
