// Native byte-level BPE encoder (capability ref: PaddleNLP FastTokenizer —
// the reference ships a C++ tokenizer runtime; this is the TPU-framework's
// equivalent for the host-side input pipeline).
//
// Design: Python trains the merge table (offline); this library runs the hot
// per-text encode loop. Greedy lowest-rank merging over a byte sequence,
// pair lookup in a flat hash map. ctypes ABI, no C++ types across the
// boundary. Calls release the GIL (ctypes does that), so a Python thread
// pool parallelizes batch encoding across cores.

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<int32_t, int32_t>& p) const {
        return (static_cast<size_t>(p.first) << 32) ^
               static_cast<uint32_t>(p.second);
    }
};

struct Bpe {
    // (left,right) -> {rank, merged_id}
    std::unordered_map<std::pair<int32_t, int32_t>, std::pair<int32_t, int32_t>,
                       PairHash> merges;
    int32_t byte_ids[256];
};

}  // namespace

extern "C" {

// merges: n rows of [left_id, right_id, merged_id], ordered by rank (row
// index IS the rank). byte_ids: 256 entries mapping byte -> initial token id.
void* bpe_new(const int32_t* merges, int64_t n, const int32_t* byte_ids) {
    Bpe* b = new Bpe();
    b->merges.reserve(static_cast<size_t>(n) * 2);
    for (int64_t i = 0; i < n; ++i) {
        b->merges[{merges[i * 3], merges[i * 3 + 1]}] = {
            static_cast<int32_t>(i), merges[i * 3 + 2]};
    }
    std::memcpy(b->byte_ids, byte_ids, 256 * sizeof(int32_t));
    return b;
}

void bpe_free(void* handle) { delete static_cast<Bpe*>(handle); }

// Encode utf-8 `text` (len bytes) into `out` (capacity max_out).
// Returns number of ids written, or -(needed) if max_out is too small.
int64_t bpe_encode(void* handle, const uint8_t* text, int64_t len,
                   int32_t* out, int64_t max_out) {
    const Bpe* b = static_cast<const Bpe*>(handle);
    std::vector<int32_t> ids;
    ids.reserve(len);
    for (int64_t i = 0; i < len; ++i) ids.push_back(b->byte_ids[text[i]]);

    // greedy BPE: repeatedly merge the lowest-rank adjacent pair
    while (ids.size() >= 2) {
        int32_t best_rank = INT32_MAX, best_pos = -1, best_id = 0;
        for (size_t i = 0; i + 1 < ids.size(); ++i) {
            auto it = b->merges.find({ids[i], ids[i + 1]});
            if (it != b->merges.end() && it->second.first < best_rank) {
                best_rank = it->second.first;
                best_pos = static_cast<int32_t>(i);
                best_id = it->second.second;
            }
        }
        if (best_pos < 0) break;
        ids[best_pos] = best_id;
        ids.erase(ids.begin() + best_pos + 1);
    }

    if (static_cast<int64_t>(ids.size()) > max_out)
        return -static_cast<int64_t>(ids.size());
    std::memcpy(out, ids.data(), ids.size() * sizeof(int32_t));
    return static_cast<int64_t>(ids.size());
}

}  // extern "C"
