"""Reference communication-API parity layer, Dirac/global initializers,
masked_multihead_attention, optimizer.set_lr."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as D
import paddle_tpu.nn as nn
import paddle_tpu.nn.initializer as I


def test_distributed_namespace_complete():
    for name in ["init_parallel_env", "get_rank", "get_world_size",
                 "all_reduce", "all_gather", "all_gather_object", "broadcast",
                 "reduce", "scatter", "alltoall", "alltoall_single", "send",
                 "recv", "isend", "irecv", "reduce_scatter", "barrier",
                 "new_group", "get_group", "wait", "spawn", "launch",
                 "ParallelEnv", "DataParallel", "fleet", "split", "ReduceOp",
                 "get_backend", "destroy_process_group", "is_initialized"]:
        assert hasattr(D, name), name


def test_group_and_env():
    g = D.new_group([0, 1, 2])
    assert g.nranks == 3 and D.get_group(g.id) is g
    assert D.is_initialized() and D.get_backend() == "xla"
    env = D.ParallelEnv()
    assert env.world_size >= 1 and env.rank == 0
    D.destroy_process_group()
    assert D.get_group(0) is None


def test_alltoall_single():
    from functools import partial
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = D.HybridMesh(dp=4, devices=jax.devices()[:4])
    x = jnp.arange(16.0).reshape(4, 4)  # member i holds row i (4 cols)

    @partial(shard_map, mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    def do(v):
        return D.alltoall_single(v.reshape(4, 1), axis_name="dp").reshape(1, 4)

    out = np.asarray(do(x))
    np.testing.assert_allclose(out, np.asarray(x).T)


def test_data_parallel_wrapper_forwards():
    pt.seed(0)
    m = nn.Linear(4, 2)
    dp = D.DataParallel(m)
    x = jnp.ones((3, 4))
    np.testing.assert_allclose(np.asarray(dp(x)), np.asarray(m(x)))
    assert dp.state_dict().keys() == m.state_dict().keys()


def test_wait_noop():
    x = jnp.ones(3)
    assert D.wait(x) is x


def test_dirac_initializer():
    w = I.Dirac()((4, 4, 3, 3))
    # channel i passes through at kernel center
    x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 8, 8), jnp.float32)
    import paddle_tpu.nn.functional as F
    y = F.conv2d(x, w, padding=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-6)


def test_set_global_initializer():
    I.set_global_initializer(I.Constant(2.0), I.Constant(1.0))
    try:
        lin = nn.Linear(3, 3)
        assert float(lin.weight.min()) == 2.0
        assert float(lin.bias.max()) == 1.0
    finally:
        I.set_global_initializer(None, None)
    lin2 = nn.Linear(3, 3)
    assert float(lin2.weight.min()) != 2.0


def test_masked_multihead_attention_matches_cache_decode():
    from paddle_tpu.incubate.nn import functional as IF
    rs = np.random.RandomState(0)
    b, h, d, max_len = 2, 2, 8, 6
    cache_k = jnp.zeros((b, max_len, h, d), jnp.float32)
    cache_v = jnp.zeros((b, max_len, h, d), jnp.float32)
    # fill two positions step by step, check final step vs full attention
    outs = []
    steps = [jnp.asarray(rs.randn(b, 3 * h * d).astype(np.float32))
             for _ in range(3)]
    for pos, x in enumerate(steps):
        out, cache_k, cache_v = IF.masked_multihead_attention(
            x, cache_k, cache_v, pos, num_heads=h)
        outs.append(out)
    # reference: full attention over the accumulated k/v
    from paddle_tpu.ops.attention import xla_attention
    qkv = jnp.stack(steps, axis=1).reshape(b, 3, 3 * h, d)
    q, k, v = jnp.split(qkv, 3, axis=2)  # [b, 3, h, d] each
    ref = xla_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(outs[-1]),
                               np.asarray(ref[:, -1].reshape(b, h * d)),
                               rtol=1e-5, atol=1e-5)


def test_optimizer_set_lr():
    import paddle_tpu.optimizer as opt
    o = opt.SGD(learning_rate=0.1)
    o.set_lr(0.5)
    assert o.get_lr() == 0.5
    sched = opt.StepDecay(learning_rate=0.1, step_size=10)
    o2 = opt.SGD(learning_rate=sched)
    with pytest.raises(RuntimeError):
        o2.set_lr(0.5)


def test_set_lr_takes_effect_inside_compiled_step():
    """The lr is optimizer STATE: set_lr(value, state) must change a jitted
    step's behaviour without recompilation (ADVICE r1: a Python-float lr is
    folded into the trace as a constant and set_lr silently no-ops)."""
    import paddle_tpu.optimizer as opt
    o = opt.SGD(learning_rate=0.1)
    params = {"w": jnp.ones((2,))}
    state = o.init(params)
    grads = {"w": jnp.ones((2,))}

    compiled = jax.jit(lambda p, g, s: o.step(p, g, s))
    p1, state = compiled(params, grads, state)
    np.testing.assert_allclose(np.asarray(p1["w"]), 1.0 - 0.1, rtol=1e-6)

    state = o.set_lr(0.5, state)
    assert o.get_lr(state) == 0.5
    p2, state = compiled(p1, grads, state)  # same compiled fn, new lr
    np.testing.assert_allclose(np.asarray(p2["w"]), (1.0 - 0.1) - 0.5,
                               rtol=1e-6)


def test_dist_split_linear():
    pt.seed(0)
    x = jnp.ones((2, 8))
    y = D.split(x, (8, 4), operation="linear", axis=1)
    assert y.shape == (2, 4)


def test_split_layer_retained_and_deterministic():
    import paddle_tpu.distributed as D2
    pt.seed(0)
    x = jnp.ones((2, 8))
    y1 = D2.split(x, (8, 4), operation="linear", axis=1, name="tp_fc")
    y2 = D2.split(x, (8, 4), operation="linear", axis=1, name="tp_fc")
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
    assert D2.get_split_layer("tp_fc") is not None


def test_destroy_single_group():
    D.destroy_process_group()
    g1 = D.new_group([0, 1])
    g2 = D.new_group([2, 3])
    D.destroy_process_group(g1)
    assert D.get_group(g1.id) is None and D.get_group(g2.id) is g2
    D.destroy_process_group()


def test_dirac_surplus_channels_zero():
    import torch
    w = np.asarray(I.Dirac()((4, 2, 3, 3)))
    ref = torch.nn.init.dirac_(torch.empty(4, 2, 3, 3)).numpy()
    np.testing.assert_allclose(w, ref)
    wg = np.asarray(I.Dirac(groups=2)((4, 2, 3, 3)))
    refg = torch.nn.init.dirac_(torch.empty(4, 2, 3, 3), groups=2).numpy()
    np.testing.assert_allclose(wg, refg)


def test_hsigmoid_accepts_2d_labels():
    import paddle_tpu.nn.functional as F
    pt.seed(0)
    layer = nn.HSigmoidLoss(8, 4)
    x = jnp.ones((3, 8))
    l1 = layer(x, jnp.asarray([0, 1, 2]))
    l2 = layer(x, jnp.asarray([[0], [1], [2]]))
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))


def test_pad_channel_last_consistent():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.tensor import pad as tpad
    x = jnp.ones((1, 4, 5, 2))  # NHWC
    a = F.pad(x, [1, 1], data_format="NHWC")
    b = tpad(x, [1, 1], data_format="NHWC")
    assert a.shape == b.shape == (1, 4, 7, 2)  # W padded, C untouched
