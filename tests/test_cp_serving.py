"""Context-parallel long-context serving (ISSUE 18): the sequence-sharded
paged KV pool, ring/Ulysses-merged chunked prefill, and psum-merged
cross-shard decode.

The contract under test is BIT-IDENTITY: a cp>1 engine must emit exactly
the tokens its cp=1 twin emits — through plain decode, chunked prefill,
speculative decoding, preemption/replay, radix prefix reuse, and int8 KV
pools — because every shard_map'd program merges per-shard online-softmax
partials into the same replicated result the single-device program
computes. Plus: the ``PT_CP=0`` kill switch, the ``too_long`` graceful
admission rejection, the ``serving.cp_gather`` chaos site's
exception-atomicity, cp-scaled admission capacity, the cp metric gauges,
and the roofline merge-traffic term.

CPU-safe: conftest forces an 8-device virtual mesh.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.mesh import HybridMesh
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import clear_jit_caches
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.roofline import ModelGeometry, phase_bytes
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, dtype=jnp.float32)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft(model):
    pt.seed(1)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, dtype=jnp.float32)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=2, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14, vocab=64):
    return [rs.randint(1, vocab, (int(l),))
            for l in rs.randint(lo, hi, size=n)]


def _run(model, prompts, max_new=6, **ekw):
    eng = _mk(model, **ekw)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    out = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    eng.assert_quiescent()
    return out, eng


# ------------------------------------------------------------ mesh axis

def test_hybrid_mesh_cp_axis():
    m = HybridMesh(cp=2, devices=__import__("jax").devices()[:2])
    assert m.cp == 2 and m.size("cp") == 2
    assert "cp" in m.axis_names


# ------------------------------------------------- greedy identity suite

@pytest.mark.parametrize("cp", [2, 4])
def test_greedy_identity_plain_decode(model, cp):
    rs = np.random.RandomState(0)
    prompts = _prompts(3, rs)
    ref, _ = _run(model, prompts)
    got, eng = _run(model, prompts, cp=cp)
    assert eng.cp == cp and eng.exe.mesh is not None
    assert got == ref


@pytest.mark.parametrize("cp", [2, 4])
def test_greedy_identity_chunked_prefill(model, cp):
    """Prompts longer than max_prompt_len ride the shard_map'd chunked
    prefill whose per-shard partials merge via the ring rotation."""
    rs = np.random.RandomState(1)
    prompts = [rs.randint(1, 64, (30,)), rs.randint(1, 64, (21,))]
    ref, _ = _run(model, prompts)
    got, _ = _run(model, prompts, cp=cp)
    assert got == ref


def test_greedy_identity_ulysses_merge(model, monkeypatch):
    """PT_CP_IMPL=ulysses swaps the chunk merge for the tiled
    all_to_all; heads (4) divide by cp (2) so it is eligible — and the
    tokens must still match cp=1 exactly."""
    rs = np.random.RandomState(2)
    prompts = [rs.randint(1, 64, (26,))]
    ref, _ = _run(model, prompts)
    monkeypatch.setenv("PT_CP_IMPL", "ulysses")
    got, _ = _run(model, prompts, cp=2)
    assert got == ref


def test_greedy_identity_spec_decode(model, draft):
    """Draft-and-verify under cp: the target verify chunk runs sharded
    with merged partials, the rewind runs through the cp jit."""
    rs = np.random.RandomState(3)
    prompts = _prompts(3, rs)
    ref, re = _run(model, prompts, max_new=8, draft_model=draft)
    got, ge = _run(model, prompts, max_new=8, draft_model=draft, cp=2)
    assert ge.stats["spec_ticks"] > 0          # speculation actually ran
    assert got == ref
    assert ge.stats["spec_accepted"] == re.stats["spec_accepted"]


def test_greedy_identity_preempt_replay(model):
    """A starved pool forces preempt + replay (chunked re-prefill of
    prompt+generated) — identical tokens to the cp=1 twin under the
    same pressure."""
    rs = np.random.RandomState(4)
    prompts = [rs.randint(1, 64, (int(n),)) for n in (10, 12, 8)]
    kw = dict(num_slots=3, num_blocks=18, preemption=True,
              prefix_caching=False)
    ref, re = _run(model, prompts, **kw)
    got, ge = _run(model, prompts, cp=2, **kw)
    assert got == ref


def test_greedy_identity_radix_prefix_reuse(model):
    """Shared prompt prefixes adopt trie blocks by reference; the
    boundary-block COW copy crosses shards via the gather-psum-scatter
    program and tokens still match."""
    rs = np.random.RandomState(5)
    base = rs.randint(1, 64, (9,)).tolist()
    prompts = [base + [7], base + [11, 13], base[:6] + [3, 2]]

    def seq(cp):
        eng = _mk(model, num_slots=2, cp=cp)
        out = {}
        for p in prompts:                      # sequential → trie reuse
            rid = eng.add_request(Request(p, max_new_tokens=6))
            while not eng.requests[rid].done:
                eng.step()
            out[rid] = list(map(int, eng.requests[rid].tokens))
        eng.assert_quiescent()
        return out, eng

    ref, re = seq(1)
    got, ge = seq(2)
    assert got == ref
    stats = ge.mgr.cache_stats
    assert stats.get("token_hits", 0) + stats.get("hit_blocks", 0) > 0


def test_greedy_identity_int8_kv(model):
    """int8 KV pools shard alongside the codes: per-position scale pools
    carry P('cp') too, and quantize-on-write lands each chunk's K/V in
    the owning shard."""
    rs = np.random.RandomState(6)
    prompts = _prompts(3, rs)
    ref, _ = _run(model, prompts, kv_dtype="int8")
    got, eng = _run(model, prompts, kv_dtype="int8", cp=2)
    assert got == ref
    assert eng.cache.k_scales                 # quantized pool actually on


# ------------------------------------------------------- kill switches

def test_pt_cp_zero_collapses_to_single_device(model, monkeypatch):
    monkeypatch.setenv("PT_CP", "0")
    eng = _mk(model, cp=4)
    assert eng.cp == 1 and eng.exe.cp == 1 and eng.exe.mesh is None
    rs = np.random.RandomState(7)
    rid = eng.add_request(Request(rs.randint(1, 64, (6,)),
                                  max_new_tokens=4))
    out = eng.run()
    assert len(out[rid]) == 4
    eng.assert_quiescent()


def test_cp1_engine_unchanged(model):
    """cp=1 must not build a mesh, shard anything, or register shard
    gauges — bit-identical to the pre-cp engine."""
    eng = _mk(model, cp=1)
    assert eng.exe.mesh is None
    assert not hasattr(eng.exe, "_cp_tick")


# ------------------------------------------------- admission: too_long

def test_too_long_finishes_gracefully_instead_of_wedging(model):
    """A prompt whose worst case exceeds the whole pool must come back
    finished with finish_reason='too_long' — not raise, not sit at the
    FCFS head starving everyone behind it."""
    eng = _mk(model, num_blocks=4)
    rs = np.random.RandomState(8)
    rid = eng.add_request(Request(rs.randint(1, 64, (30,)),
                                  max_new_tokens=8))
    req = eng.requests[rid]
    assert req.done and req.finish_reason == "too_long"
    assert not eng.queue                       # never occupies the queue
    # the engine still serves a normal request afterwards
    rid2 = eng.add_request(Request([1, 2, 3], max_new_tokens=3))
    out = eng.run()
    assert len(out[rid2]) == 3
    eng.assert_quiescent()
    assert eng.stats["rejected"] >= 1


def test_admissible_length_scales_with_cp(model):
    """The point of cp: each shard holds num_blocks/cp physical blocks,
    so a cp-wide pool admits ~cp× the prompt length a single device
    holds. num_blocks scales with cp; the boundary prompt that finishes
    'too_long' at cp=1 admits at cp=2."""
    long_p = list(np.random.RandomState(9).randint(1, 64, (40,)))
    small = _mk(model, num_blocks=8, max_seq_len=64)       # 32 positions
    rid = small.add_request(Request(long_p, max_new_tokens=4))
    assert small.requests[rid].finish_reason == "too_long"
    big = _mk(model, num_blocks=16, max_seq_len=64, cp=2)  # 64 positions
    rid = big.add_request(Request(long_p, max_new_tokens=4))
    assert not big.requests[rid].done          # admitted, queued
    out = big.run()
    assert len(out[rid]) == 4
    big.assert_quiescent()
    # per-shard footprint: 8 blocks each, the small engine's whole pool
    assert int(np.asarray(big.cache.k_pools[0]).shape[0]) == 16


def test_num_blocks_rounds_up_to_cp_multiple(model):
    eng = _mk(model, num_blocks=9, cp=2)
    assert eng.mgr.num_blocks == 10


# ------------------------------------------------- punted combinations

def test_cp_refuses_beams_lora_and_handoff(model):
    eng = _mk(model, cp=2)
    with pytest.raises(NotImplementedError, match="beam"):
        eng.add_request(Request([1, 2, 3], max_new_tokens=2, num_beams=2))
    with pytest.raises(NotImplementedError, match="handoff"):
        eng.extract_sequence(0)
    from paddle_tpu.serving.adapters import AdapterStore
    with pytest.raises(NotImplementedError, match="LoRA"):
        _mk(model, cp=2, adapter_store=AdapterStore(model))


# ---------------------------------------------- serving.cp_gather chaos

def test_chaos_cp_gather_exception_atomic(model):
    """An injected cp_gather fault fires BEFORE table growth and the
    donating tick jit: the tick aborts with cache/tables/ledger
    untouched, no blocks leak, the run still finishes with the clean
    run's exact tokens, and the fleet ends quiescent + reconciled."""
    rs = np.random.RandomState(10)
    prompts = _prompts(3, rs)
    ref, _ = _run(model, prompts, cp=2)
    eng = _mk(model, cp=2)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    fired = 0
    with FAULTS.scope("serving.cp_gather", on={1, 3}, exc=InjectedFault):
        while eng.has_work():
            try:
                eng.step()
            except InjectedFault:
                fired += 1
    assert fired == 2
    out = {r: list(map(int, req.tokens))
           for r, req in eng.pop_finished().items()}
    assert out == ref
    eng.assert_quiescent()
    assert eng.kv.reconcile()["ok"]


def test_cp_gather_site_only_arms_above_cp1(model):
    rs = np.random.RandomState(11)
    eng = _mk(model)                           # cp=1: site never fires
    eng.add_request(Request(rs.randint(1, 64, (5,)), max_new_tokens=4))
    with FAULTS.scope("serving.cp_gather", exc=InjectedFault):
        eng.run()
    eng.assert_quiescent()
    assert FAULTS.hits["serving.cp_gather"] == 0
    FAULTS.clear()


# ----------------------------------------------------- metrics + roofline

def test_cp_gauges_and_gather_histogram(model):
    rs = np.random.RandomState(12)
    _run(model, _prompts(2, rs), cp=2)
    assert METRICS.get("serving_cp_axis_size").value() == 2
    assert METRICS.get("serving_cp_gather_seconds").value()["count"] > 0
    per_shard = METRICS.get("serving_cp_shard_blocks")
    assert per_shard.value(shard="0") >= 0


def test_shard_occupancy_buckets_contiguous_split():
    from paddle_tpu.serving.cp import shard_occupancy
    assert shard_occupancy([0, 1, 7, 8, 15], 16, 2) == [3, 2]
    assert shard_occupancy([], 16, 4) == [0, 0, 0, 0]


def test_roofline_bills_cp_merge_traffic(model):
    g1 = ModelGeometry.from_config(model.cfg, dtype_bytes=4)
    from dataclasses import replace
    g2 = replace(g1, cp=2)
    b1 = phase_bytes(g1, tokens=64, weight_passes=1, kv_read_positions=640)
    b2 = phase_bytes(g2, tokens=64, weight_passes=1, kv_read_positions=640)
    extra = 64 * g1.num_layers * g1.heads * (g1.head_dim + 2) * 4.0 * 0.5 * 2
    assert b2 == pytest.approx(b1 + extra)
