"""Beam search INSIDE the continuous-batching engine: a num_beams=K
request occupies K cache slots, shares prompt blocks copy-on-write, and
its result equals ``paged_beam_search`` (which itself equals the static
beam) — including while OTHER requests decode greedily in the same ticks.

Ref: PaddleNLP llm/predict/predictor.py block-attention serving with
beam/sampling decode strategies.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import paged_beam_search
from paddle_tpu.serving import LLMEngine, Request


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def win_model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, sliding_window=8)
    return LlamaForCausalLM(cfg)


def test_engine_beam_matches_paged_beam_search(model):
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 64, (7,))
    new, K = 6, 3
    ref_seq, ref_score = paged_beam_search(model, prompt,
                                           max_new_tokens=new, num_beams=K,
                                           eos_token_id=1, block_size=4)
    eng = LLMEngine(model, num_slots=4, block_size=4, max_prompt_len=16,
                    max_seq_len=24, eos_token_id=1)
    rid = eng.add_request(Request(prompt, max_new_tokens=new, num_beams=K))
    out = eng.run()
    assert out[rid] == [int(t) for t in np.asarray(ref_seq)[len(prompt):]]
    assert eng.requests[rid].finish_reason == "beam"
    np.testing.assert_allclose(eng.requests[rid].beam_score,
                               float(ref_score), rtol=1e-5)
    # every block went back to the pool
    assert eng.mgr.free_blocks == eng.mgr.num_blocks


@pytest.mark.slow
def test_engine_beam_rides_with_greedy_traffic(model):
    """A beam request and greedy requests interleave in the same ticks;
    each result equals its isolated reference, under oversubscription."""
    rs = np.random.RandomState(4)
    g_prompts = [rs.randint(0, 64, (int(l),))
                 for l in rs.randint(3, 12, size=5)]
    b_prompt = rs.randint(0, 64, (6,))
    new, K = 5, 2
    ref_seq, _ = paged_beam_search(model, b_prompt, max_new_tokens=new,
                                   num_beams=K, eos_token_id=1,
                                   block_size=4)
    g_refs = [np.asarray(generate(model, p[None], max_new_tokens=new,
                                  eos_token_id=1))[0]
              for p in g_prompts]

    eng = LLMEngine(model, num_slots=3, block_size=4, max_prompt_len=16,
                    max_seq_len=24, eos_token_id=1)
    rids = [eng.add_request(Request(p, max_new_tokens=new))
            for p in g_prompts[:2]]
    beam_rid = eng.add_request(Request(b_prompt, max_new_tokens=new,
                                       num_beams=K))
    rids += [eng.add_request(Request(p, max_new_tokens=new))
             for p in g_prompts[2:]]
    out = eng.run()
    assert out[beam_rid] == [int(t)
                             for t in np.asarray(ref_seq)[len(b_prompt):]]
    for rid, p, ref in zip(rids, g_prompts, g_refs):
        got = out[rid]
        want = [int(t) for t in ref[len(p): len(p) + len(got)]]
        assert got == want
        r = eng.requests[rid]
        if r.finish_reason == "eos":
            assert got[-1] == 1
        else:
            assert len(got) == new
    assert eng.mgr.free_blocks == eng.mgr.num_blocks


def test_engine_beam_blocks_shared_not_duplicated(model):
    """While the group runs, the prompt's full blocks are SHARED: live
    pool usage stays far below K * (prompt + generated) blocks."""
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, 64, (12,))     # 3 full blocks at bs=4
    K = 4
    eng = LLMEngine(model, num_slots=4, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    rid = eng.add_request(Request(prompt, max_new_tokens=8, num_beams=K))
    eng.step()                            # prefill + first select
    g = eng.groups[rid]
    live = eng._group_live_blocks(g)
    dense = K * eng.mgr.blocks_needed(len(prompt) + 1)
    assert live < dense, (live, dense)
    assert live <= eng.mgr.blocks_needed(len(prompt)) + 2 * K
    eng.run()
    assert eng.mgr.free_blocks == eng.mgr.num_blocks


def test_engine_beam_validation(model, win_model):
    eng = LLMEngine(model, num_slots=2, block_size=4)
    with pytest.raises(ValueError, match="num_beams"):
        eng.add_request(Request([1, 2], num_beams=0))
    with pytest.raises(ValueError, match="exceeds num_slots"):
        eng.add_request(Request([1, 2], num_beams=3))
    with pytest.raises(ValueError, match="streaming"):
        eng.add_request(Request([1, 2], num_beams=2,
                                stream=lambda r, t: None))
    weng = LLMEngine(win_model, num_slots=4, block_size=4)
    with pytest.raises(NotImplementedError, match="sliding-window"):
        weng.add_request(Request([1, 2], num_beams=2))


def test_per_request_sampling_params(model):
    """Each request carries its own temperature/top_p: greedy-override
    rows exactly match solo greedy while sampled rows ride the same
    ticks; the whole engine run is seed-deterministic."""
    rs = np.random.RandomState(7)
    p_greedy = rs.randint(0, 64, (6,))
    p_sampled = rs.randint(0, 64, (7,))
    ref = np.asarray(generate(model, p_greedy[None], max_new_tokens=6))[0]

    def run(seed):
        eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                        max_seq_len=24, temperature=0.9, top_p=0.95,
                        seed=seed)
        rg = eng.add_request(Request(p_greedy, max_new_tokens=6,
                                     temperature=0.0))
        rsamp = eng.add_request(Request(p_sampled, max_new_tokens=6))
        out = eng.run()
        return out[rg], out[rsamp]

    g1, s1 = run(0)
    g2, s2 = run(0)
    g3, s3 = run(5)
    assert g1 == [int(t) for t in ref[len(p_greedy):]]
    assert g1 == g2 == g3                 # greedy immune to seed
    assert s1 == s2                       # sampling seed-deterministic
    assert len(s3) == 6                   # different seed still completes
