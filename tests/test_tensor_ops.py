"""Op semantics vs numpy golden values (SURVEY.md §4; ref test/legacy_test/)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


def test_creation():
    assert pt.zeros([2, 3]).shape == (2, 3)
    assert pt.ones([4]).dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(pt.arange(0, 10, 2)), np.arange(0, 10, 2))
    assert pt.full([2], 7.0)[0] == 7.0
    assert pt.eye(3)[1, 1] == 1.0
    np.testing.assert_allclose(np.asarray(pt.linspace(0, 1, 5)), np.linspace(0, 1, 5))


def test_math_golden():
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    j = jnp.asarray(x)
    np.testing.assert_allclose(np.asarray(pt.exp(j)), np.exp(x), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.log(jnp.abs(j))), np.log(np.abs(x)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.rsqrt(jnp.abs(j) + 1)), 1 / np.sqrt(np.abs(x) + 1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.clip(j, -0.5, 0.5)), np.clip(x, -0.5, 0.5))
    np.testing.assert_allclose(np.asarray(pt.lerp(j, j + 1, 0.5)), x + 0.5, rtol=1e-6)


def test_reductions():
    x = np.random.RandomState(1).rand(2, 5).astype(np.float32)
    j = jnp.asarray(x)
    np.testing.assert_allclose(np.asarray(pt.sum(j, axis=1)), x.sum(1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.mean(j, axis=0, keepdim=True)), x.mean(0, keepdims=True), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pt.std(j)), x.std(ddof=1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.logsumexp(j, axis=1)),
                               np.log(np.exp(x).sum(1)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.cumsum(j, axis=1)), x.cumsum(1), rtol=1e-6)


def test_matmul_and_linalg():
    a = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(3).randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pt.matmul(jnp.asarray(a), jnp.asarray(b))),
                               a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pt.matmul(jnp.asarray(a), jnp.asarray(b.T), transpose_y=True)),
        a @ b, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pt.einsum("ij,jk->ik", jnp.asarray(a), jnp.asarray(b))),
                               a @ b, rtol=1e-5)
    sq = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(pt.inverse(jnp.asarray(sq))) @ sq,
                               np.eye(3), atol=1e-4)


def test_manipulation():
    x = jnp.arange(24).reshape(2, 3, 4)
    assert pt.reshape(x, [6, 4]).shape == (6, 4)
    assert pt.flatten(x, 1).shape == (2, 12)
    assert pt.squeeze(pt.unsqueeze(x, 0), 0).shape == x.shape
    assert pt.concat([x, x], axis=1).shape == (2, 6, 4)
    parts = pt.split(x, [1, -1], axis=1)
    assert parts[0].shape == (2, 1, 4) and parts[1].shape == (2, 2, 4)
    assert pt.transpose(x, [2, 0, 1]).shape == (4, 2, 3)
    assert pt.tile(x, [2, 1, 1]).shape == (4, 3, 4)
    assert len(pt.unbind(x, axis=0)) == 2
    assert pt.gather(x, jnp.array([0, 0, 1]), axis=0).shape == (3, 3, 4)


def test_search_sort():
    x = jnp.asarray([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    v, i = pt.topk(x, 2)
    np.testing.assert_allclose(np.asarray(v), [[3, 2], [5, 4]])
    assert int(pt.argmax(x, axis=1)[0]) == 0
    np.testing.assert_allclose(np.asarray(pt.sort(x, axis=1)), np.sort(np.asarray(x), 1))
    assert pt.nonzero(jnp.array([0, 1, 1])).shape == (2, 1)


def test_logic():
    x = jnp.array([1, 2, 3])
    assert bool(pt.equal_all(x, x))
    assert bool(pt.allclose(x.astype(jnp.float32), x.astype(jnp.float32) + 1e-9))
    assert bool(pt.any(pt.greater_than(x, 2)))


def test_random_reproducible():
    pt.seed(42)
    a = pt.rand([3, 3])
    pt.seed(42)
    b = pt.rand([3, 3])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert pt.randn([2, 2]).shape == (2, 2)
    assert pt.randint(0, 10, [5]).dtype == jnp.int64 or pt.randint(0, 10, [5]).dtype == jnp.int32
    p = pt.randperm(10)
    assert sorted(np.asarray(p).tolist()) == list(range(10))


def test_pad_and_where():
    x = jnp.ones((2, 3))
    assert pt.pad(x, [1, 1], value=0.0).shape == (2, 5)
    # full-form (len == 2*ndim): per-dim pairs in DIM order (reference
    # convention: "padding starts from the first dimension")
    assert pt.pad(x, [1, 1, 2, 2], value=0.0).shape == (4, 7)
    out = pt.where(x > 0, x, -x)
    np.testing.assert_allclose(np.asarray(out), np.ones((2, 3)))
    np.testing.assert_allclose(np.asarray(pt.masked_fill(x, x > 0, 5.0)), np.full((2, 3), 5.0))


def test_scatter_gather_nd():
    x = jnp.zeros((4, 3))
    out = pt.scatter(x, jnp.array([1, 3]), jnp.ones((2, 3)))
    assert float(out[1, 0]) == 1.0 and float(out[0, 0]) == 0.0
    idx = jnp.array([[0, 1], [2, 2]])
    g = pt.gather_nd(jnp.arange(9.0).reshape(3, 3), idx)
    np.testing.assert_allclose(np.asarray(g), [1.0, 8.0])


# -- round-1 gap-fill ops (complex, integrals, scatter variants) -------------

class TestGapFillOps:
    def test_complex_polar(self):
        import paddle_tpu as pt
        r = np.asarray(pt.polar(jnp.array([2.0]), jnp.array([np.pi / 2])))
        np.testing.assert_allclose(r.real, 0.0, atol=1e-6)
        np.testing.assert_allclose(r.imag, 2.0, atol=1e-6)
        z = pt.complex(jnp.array([1.0]), jnp.array([-1.0]))
        assert np.asarray(pt.conj(z)).imag[0] == 1.0
        np.testing.assert_allclose(np.asarray(pt.angle(z)), -np.pi / 4, atol=1e-6)

    def test_trapezoid_matches_torch(self):
        torch = pytest.importorskip("torch")
        y = np.random.default_rng(0).standard_normal((3, 7)).astype(np.float32)
        x = np.sort(np.random.default_rng(1).standard_normal(7)).astype(np.float32)
        import paddle_tpu as pt
        np.testing.assert_allclose(
            np.asarray(pt.trapezoid(jnp.asarray(y), x=jnp.asarray(x))),
            torch.trapezoid(torch.tensor(y), x=torch.tensor(x)).numpy(), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.cumulative_trapezoid(jnp.asarray(y), dx=0.5)),
            torch.cumulative_trapezoid(torch.tensor(y), dx=0.5).numpy(), atol=1e-5)

    def test_logcumsumexp_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.default_rng(2).standard_normal((4, 5)).astype(np.float32)
        import paddle_tpu as pt
        np.testing.assert_allclose(
            np.asarray(pt.logcumsumexp(jnp.asarray(x), axis=1)),
            torch.logcumsumexp(torch.tensor(x), dim=1).numpy(), atol=1e-5)

    def test_renorm_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.default_rng(3).standard_normal((3, 4, 5)).astype(np.float32)
        import paddle_tpu as pt
        got = np.asarray(pt.renorm(jnp.asarray(x), 2.0, 0, 1.0))
        ref = torch.renorm(torch.tensor(x), 2, 0, 1.0).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_take_modes(self):
        import paddle_tpu as pt
        x = jnp.arange(6).reshape(2, 3)
        np.testing.assert_array_equal(
            np.asarray(pt.take(x, jnp.array([0, 7]), "wrap")), [0, 1])
        np.testing.assert_array_equal(
            np.asarray(pt.take(x, jnp.array([0, 7]), "clip")), [0, 5])
        np.testing.assert_array_equal(
            np.asarray(pt.take(x, jnp.array([-1]), "wrap")), [5])

    def test_splits_and_atleast(self):
        import paddle_tpu as pt
        parts = pt.tensor_split(jnp.arange(7), 3)
        assert [p.shape[0] for p in parts] == [3, 2, 2]
        a, b = pt.hsplit(jnp.ones((2, 4)), 2)
        assert a.shape == (2, 2)
        assert pt.atleast_2d(jnp.array(1.0)).shape == (1, 1)

    def test_index_fill_and_masked_scatter(self):
        torch = pytest.importorskip("torch")
        import paddle_tpu as pt
        x = np.random.default_rng(4).standard_normal((3, 4)).astype(np.float32)
        got = np.asarray(pt.index_fill(jnp.asarray(x), jnp.array([0, 2]), 1, 9.0))
        ref = torch.tensor(x).index_fill(1, torch.tensor([0, 2]), 9.0).numpy()
        np.testing.assert_allclose(got, ref)
        mask = x > 0
        vals = np.arange(mask.sum(), dtype=np.float32) + 100
        got = np.asarray(pt.masked_scatter(jnp.asarray(x), jnp.asarray(mask),
                                           jnp.asarray(vals)))
        ref = torch.tensor(x).masked_scatter(torch.tensor(mask),
                                             torch.tensor(vals)).numpy()
        np.testing.assert_allclose(got, ref)

    def test_random_families(self):
        import paddle_tpu as pt
        pt.seed(0)
        p = np.asarray(pt.poisson(jnp.full((2000,), 4.0)))
        assert p.dtype == np.float32 and abs(p.mean() - 4.0) < 0.3
        g = np.asarray(pt.standard_gamma(jnp.full((2000,), 3.0)))
        assert abs(g.mean() - 3.0) < 0.3
        ln = np.asarray(pt.log_normal(0.0, 0.25, (2000,)))
        assert ln.min() > 0

    def test_special_functions(self):
        import paddle_tpu as pt
        np.testing.assert_allclose(float(pt.i0(jnp.array(1.0))), 1.2660658, atol=1e-4)
        np.testing.assert_allclose(float(pt.polygamma(jnp.array(2.0), 1)),
                                   0.6449341, atol=1e-4)
        m, e = pt.frexp(jnp.array([10.0]))
        np.testing.assert_allclose(np.asarray(m) * 2.0 ** np.asarray(e), 10.0)
