"""Metrics-server endpoint surface (ISSUE 12 satellite): the
``/roofline`` report, the guarded ``/profile`` capture (400 on bad
input, 409 while busy, one real capture into ``PT_PROFILE_DIR``), and
the 404 catch-all that names every route."""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

import paddle_tpu.observability.httpd as httpd
from paddle_tpu.observability.httpd import MetricsServer
from paddle_tpu.observability.roofline import (
    ModelGeometry, record_serving_throughput, reset_serving_roofline)


def _get(url, timeout=30):
    """(status, body text) — error statuses arrive as HTTPError with the
    same body."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture()
def srv():
    s = MetricsServer(port=0, host="127.0.0.1")
    yield s
    s.stop()


@pytest.fixture(autouse=True)
def _clean_roofline():
    reset_serving_roofline()
    yield
    reset_serving_roofline()


def test_unknown_path_404_names_the_routes(srv):
    status, body = _get(f"http://127.0.0.1:{srv.port}/nope")
    assert status == 404
    for route in ("/metrics", "/healthz", "/roofline", "/slo",
                  "/tenants", "/profile"):
        assert route in body


def test_slo_endpoint_serves_tracker_scorecards(srv):
    from paddle_tpu.observability.slo import Objective, SLOTracker
    base = f"http://127.0.0.1:{srv.port}"
    status, body = _get(base + "/slo")
    assert status == 200
    doc = json.loads(body)
    assert doc["enabled"] is True
    assert doc["trackers"] == []              # none constructed yet
    t = SLOTracker({"*": [Objective("availability", target=0.99)]},
                   clock=iter([0.0, 1.0]).__next__)
    t.poll()
    status, body = _get(base + "/slo")
    doc = json.loads(body)
    assert status == 200
    (snap,) = [s for s in doc["trackers"] if s["tracker"] == t.seq]
    assert snap["polls"] == 1
    assert snap["objectives"]["*"][0]["name"] == "availability"
    (row,) = snap["status"]
    assert (row["tenant"], row["objective"]) == ("*", "availability")
    assert row["breaching"] is False


def test_tenants_endpoint_serves_the_cost_ledger(srv):
    from paddle_tpu.observability import GOODPUT
    from paddle_tpu.observability.slo import SLOTracker
    t = SLOTracker()
    GOODPUT.good(7, tenant="acme")
    GOODPUT.waste("spec_rejected", 3, tenant="acme")
    GOODPUT.saved(2, tenant=None)             # bills __system__
    status, body = _get(f"http://127.0.0.1:{srv.port}/tenants")
    assert status == 200
    doc = json.loads(body)
    (snap,) = [s for s in doc["trackers"] if s["tracker"] == t.seq]
    assert snap["tenants"]["acme"]["good_tokens"] == 7
    assert snap["tenants"]["acme"]["waste_tokens"] == {"spec_rejected": 3}
    assert snap["tenants"]["__system__"]["saved_tokens"] == 2
    assert snap["good_tokens_total"] == 7


def test_roofline_endpoint_serves_the_ledger(srv):
    base = f"http://127.0.0.1:{srv.port}"
    status, body = _get(base + "/roofline")
    assert status == 200
    doc = json.loads(body)
    assert doc["machine"] == {"peak_flops": 0.0, "peak_hbm_bps": 0.0,
                              "balance_flops_per_byte": 0.0}
    assert doc["phases"] == {}                      # nothing recorded yet
    # overlap-aware anatomy (ISSUE 20) rides along, all-zero at rest
    assert doc["tick_anatomy"]["host_hidden_seconds"] == 0.0
    assert doc["tick_anatomy"]["overlap_fraction"] == 0.0
    g = ModelGeometry(num_layers=2, hidden=8, intermediate=16, vocab=32,
                      heads=2, kv_heads=1, head_dim=4)
    record_serving_throughput("decode", seconds=1.0, tokens=4,
                              weight_passes=1, kv_read_positions=16,
                              geom=g, peak_flops=197e12,
                              peak_hbm_bps=819e9)
    status, body = _get(base + "/roofline")
    doc = json.loads(body)
    assert status == 200
    assert doc["machine"]["peak_hbm_bps"] == pytest.approx(819e9)
    assert set(doc["phases"]) == {"decode"}
    assert doc["phases"]["decode"]["bound"] == "bandwidth-bound"
    assert doc["phases"]["decode"]["mbu"] > 0


@pytest.mark.parametrize("query", [
    "",                       # missing seconds entirely
    "?seconds=",              # present but empty
    "?seconds=abc",           # non-numeric
    "?seconds=0",             # must be > 0
    "?seconds=-3",
    "?seconds=601",           # above the cap
])
def test_profile_bad_seconds_is_400(srv, query):
    status, body = _get(f"http://127.0.0.1:{srv.port}/profile{query}")
    assert status == 400, body


def test_profile_second_capture_while_busy_is_409(srv, monkeypatch):
    release = threading.Event()
    started = threading.Event()

    def fake_capture(seconds):
        started.set()
        assert release.wait(timeout=10)
        return {"dir": "fake", "seconds": seconds}

    monkeypatch.setattr(httpd, "_run_profile_capture", fake_capture)
    base = f"http://127.0.0.1:{srv.port}"
    first: dict = {}

    def go():
        first["resp"] = _get(base + "/profile?seconds=1")

    t = threading.Thread(target=go, name="pt-test-profile")
    t.start()
    try:
        assert started.wait(timeout=10)          # capture is in flight
        status, body = _get(base + "/profile?seconds=1")
        assert status == 409
        assert "already running" in body
    finally:
        release.set()
        t.join(timeout=10)
    status, body = _get(base + "/profile?seconds=1")   # lock released
    assert status == 200
    assert json.loads(body)["dir"] == "fake"
    assert first["resp"][0] == 200


def test_profile_capture_failure_is_500_and_releases_lock(srv, monkeypatch):
    def boom(seconds):
        raise RuntimeError("no backend")

    monkeypatch.setattr(httpd, "_run_profile_capture", boom)
    base = f"http://127.0.0.1:{srv.port}"
    status, body = _get(base + "/profile?seconds=1")
    assert status == 500
    assert "RuntimeError" in body
    monkeypatch.setattr(httpd, "_run_profile_capture",
                        lambda s: {"dir": "ok", "seconds": s})
    status, _ = _get(base + "/profile?seconds=1")
    assert status == 200                          # the 500 path unlocked


@pytest.mark.slow
def test_profile_real_capture_writes_pt_profile_dir(srv, tmp_path,
                                                    monkeypatch):
    """One real (short) jax.profiler capture through the endpoint: 200,
    the JSON names the dir, and trace artifacts land under it."""
    out = tmp_path / "cap"
    monkeypatch.setenv("PT_PROFILE_DIR", str(out))
    t0 = time.monotonic()
    # generous timeout: the first profiler start in a process initialises
    # the backend trace machinery, which can dwarf the capture itself
    status, body = _get(f"http://127.0.0.1:{srv.port}/profile?seconds=0.2",
                        timeout=240)
    assert status == 200, body
    assert time.monotonic() - t0 >= 0.2           # it really slept
    doc = json.loads(body)
    assert doc == {"dir": str(out), "seconds": 0.2}
    files = [f for _, _, fs in os.walk(out) for f in fs]
    assert files, "capture wrote no trace artifacts"
