"""Fault-injection registry unit tests (paddle_tpu/utils/faults.py).

The chaos layer itself must be boring and exact: rules fire on the hit
indices they were given, seeded schedules replay bit-for-bit, scopes
clean up after themselves. Every serving/training chaos test builds on
these semantics.
"""
import pathlib
import re

import pytest

import paddle_tpu.utils.faults as faults
from paddle_tpu.utils.faults import (FAULTS, SITES, FaultRegistry,
                                     InjectedCrash, InjectedFault,
                                     fault_point, fault_value)

pytestmark = pytest.mark.chaos


def test_noop_without_rules():
    assert fault_point("nowhere") is None
    assert fault_value("nowhere", 42) == 42
    assert not FAULTS.active()


def test_on_hits_fire_exactly():
    FAULTS.install("s", on={1, 3}, exc=InjectedFault)
    fault_point("s")                       # hit 0: clean
    with pytest.raises(InjectedFault):
        fault_point("s")                   # hit 1
    fault_point("s")                       # hit 2: clean
    with pytest.raises(InjectedFault):
        fault_point("s")                   # hit 3
    fault_point("s")                       # hit 4: clean
    assert FAULTS.log == [("s", 1), ("s", 3)]


def test_every_kth_hit():
    FAULTS.install("e", every=3, exc=MemoryError)
    pattern = []
    for _ in range(9):
        try:
            fault_point("e")
            pattern.append(0)
        except MemoryError:
            pattern.append(1)
    assert pattern == [0, 0, 1] * 3


def test_times_bound_exhausts():
    FAULTS.install("t", every=1, times=2, exc=InjectedFault)
    for _ in range(2):
        with pytest.raises(InjectedFault):
            fault_point("t")
    fault_point("t")                       # exhausted: clean forever after
    fault_point("t")


def test_hits_relative_to_install():
    """A rule's ``on`` indices count from ITS installation, not from the
    process-wide site counter — late-installed rules stay predictable."""
    for _ in range(5):
        fault_point("r")                   # pre-warm the site counter
    FAULTS.install("r", on={0}, exc=InjectedFault)
    with pytest.raises(InjectedFault):
        fault_point("r")


def test_scope_installs_and_removes():
    with FAULTS.scope("sc", on={0}, exc=InjectedFault):
        with pytest.raises(InjectedFault):
            fault_point("sc")
    fault_point("sc")                      # out of scope: clean
    assert not FAULTS.active()


def test_action_return_value_and_fault_value():
    FAULTS.install("loss", on={1}, action=lambda ctx: float("nan"))
    import math
    assert fault_value("loss", 1.0) == 1.0             # hit 0: default
    assert math.isnan(fault_value("loss", 1.0))        # hit 1: override
    assert fault_value("loss", 2.5) == 2.5


def test_action_receives_context():
    seen = {}
    FAULTS.install("ctx", on={0}, action=lambda c: seen.update(c))
    fault_point("ctx", rid=7, engine="E")
    assert seen["rid"] == 7 and seen["engine"] == "E"


def test_seeded_schedule_reproducible():
    a = FaultRegistry()
    b = FaultRegistry()
    ra = a.schedule("x", seed=123, p=0.3, horizon=50, exc=InjectedFault)
    rb = b.schedule("x", seed=123, p=0.3, horizon=50, exc=InjectedFault)
    assert ra.on == rb.on and 0 < len(ra.on) < 50
    rc = a.schedule("y", seed=124, p=0.3, horizon=50, exc=InjectedFault)
    assert rc.on != ra.on                  # different seed, different chaos


def test_clear_site_and_all():
    FAULTS.install("a", every=1, exc=InjectedFault)
    FAULTS.install("b", every=1, exc=InjectedFault)
    FAULTS.clear("a")
    fault_point("a")                       # cleared: clean
    with pytest.raises(InjectedFault):
        fault_point("b")
    FAULTS.clear()
    fault_point("b")
    assert not FAULTS.active()


def test_injected_crash_is_runtimeerror():
    """ElasticRunner's restart net catches RuntimeError — the simulated
    kill must ride it."""
    assert issubclass(InjectedCrash, RuntimeError)


def test_stall_action_sleeps():
    import time
    FAULTS.install("z", on={0}, stall_s=0.05)
    t0 = time.monotonic()
    fault_point("z")
    assert time.monotonic() - t0 >= 0.04


# ------------------------------------------------------- delay faults

def test_delay_alone_sleeps_and_returns_none():
    """A pure delay rule slows the site down but injects no failure —
    the straggler fault (ISSUE 16). The sleep goes through the
    registry's swappable ``FAULTS.sleep`` so tests stay instant."""
    slept = []
    FAULTS.sleep = slept.append
    FAULTS.install("d", on={0, 1}, delay_s=0.25)
    assert fault_point("d") is None        # delayed, NOT raised
    assert fault_point("d") is None
    assert fault_point("d") is None        # hit 2: not matched, no sleep
    assert slept == [0.25, 0.25]


def test_delay_composes_with_exc_and_action():
    """``delay_s`` stacks under the other behaviours: sleep first, then
    raise/act — a slow failure, not a fast one."""
    slept = []
    FAULTS.sleep = slept.append
    FAULTS.install("dx", on={0}, delay_s=0.1, exc=InjectedFault)
    with pytest.raises(InjectedFault):
        fault_point("dx")
    FAULTS.install("da", on={0}, delay_s=0.2, action=lambda c: "v")
    assert fault_value("da", "default") == "v"
    assert slept == [0.1, 0.2]


def test_clear_restores_real_sleep():
    import time
    FAULTS.sleep = lambda s: None
    FAULTS.clear()
    assert FAULTS.sleep is time.sleep


# ------------------------------------------------- site registry (SITES)

def test_sites_registry_matches_code():
    """Every ``fault_point``/``fault_value`` site literal in the package
    is documented in ``faults.SITES`` and vice versa — a new chaos site
    cannot land without its one-line contract, and a dead entry cannot
    linger after the site is removed."""
    pkg = pathlib.Path(faults.__file__).resolve().parents[1]
    pat = re.compile(r"fault_(?:point|value)\(\s*['\"]([a-z_.]+)['\"]")
    found = set()
    for py in pkg.rglob("*.py"):
        found |= set(pat.findall(py.read_text()))
    assert found == set(SITES), (
        f"undocumented sites: {sorted(found - set(SITES))}; "
        f"stale SITES entries: {sorted(set(SITES) - found)}")


def test_sites_have_contracts():
    for site, contract in SITES.items():
        assert isinstance(contract, str) and contract.strip(), site
