"""Graceful degradation under pressure (ISSUE 16): the ladder's
hysteresis state machine, the per-rung effects (spec off, shrunken
prefill chunks, best-effort shedding, OverloadError backpressure), the
PT_DEGRADE kill switch's bit-identity promise, per-tenant token-bucket
rate limiting, durable session snapshots surviving a DOUBLE replica
death with greedy output intact, transport validation/retry/hedging on
the KV handoff, and the seeded chaos-storm acceptance run. Every chaos
path must leave the fleet quiescent and the block ledger clean."""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import METRICS
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.serving import (DegradationController, LLMEngine,
                                OverloadError, QueueFullError, Replica,
                                Request, Router, SessionSnapshot,
                                TransportPolicy)
from paddle_tpu.serving.transfer import (KVPayload, KVTransferError,
                                         validate_payload)
from paddle_tpu.utils.faults import FAULTS, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module", autouse=True)
def _preserve_global_rng():
    from paddle_tpu.core import random as _prng
    saved = None if _prng._global is None else _prng._global.key
    yield
    if saved is None:
        _prng._global = None
    else:
        _prng.seed(0)
        _prng._global.key = saved


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=4, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14):
    return [rs.randint(0, 64, (int(l),)) for l in rs.randint(lo, hi, size=n)]


def _reference(model, prompts, max_new=10, **ekw):
    eng = _mk(model, **ekw)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    return {rid: list(map(int, t)) for rid, t in eng.run().items()}


def _ctrl(**kw):
    """A controller that holds whatever level tests force: no signals,
    infinite down-patience, so polls from the engine gauge sweep never
    walk a forced rung back down mid-test."""
    kw.setdefault("signals", [])
    kw.setdefault("down_patience", 10 ** 9)
    return DegradationController(**kw)


def _series(name):
    inst = METRICS.get(name)
    return {} if inst is None else {k: c[0] for k, c in inst._series.items()}


def _flight_kinds():
    return [e["kind"] for e in FLIGHT.events()]


# ---------------------------------------------------- ladder state machine

def test_ladder_climbs_fast_descends_slowly():
    """up_patience=1 escalates on the first bad poll; recovery needs
    down_patience consecutive calm polls PER RUNG, descending one rung
    at a time — an oscillating signal cannot flap service levels."""
    sig = {"target": 0}
    c = DegradationController(signals=[("test", lambda c: sig["target"])],
                              up_patience=1, down_patience=3)
    assert c.poll() == 0
    sig["target"] = 3
    assert c.poll() == 3                  # one bad poll: straight to L3
    assert c.peak_level == 3
    sig["target"] = 0
    assert c.poll() == 3                  # calm poll 1: hold
    assert c.poll() == 3                  # calm poll 2: hold
    assert c.poll() == 2                  # calm poll 3: ONE rung down
    assert c.poll() == 2
    assert c.poll() == 2
    assert c.poll() == 1
    sig["target"] = 2
    assert c.poll() == 2                  # relapse climbs again immediately
    sig["target"] = 0
    for _ in range(6):
        c.poll()
    assert c.level == 0                   # full recovery
    whys = [t["why"] for t in c.transitions]
    assert whys == ["test", "recovery", "recovery", "test",
                    "recovery", "recovery"]
    tr = _series("serving_degrade_transitions_total")
    assert tr[("up", "3")] == 1 and tr[("up", "2")] == 1
    assert sum(v for (d, _), v in tr.items() if d == "down") == 4
    assert _flight_kinds().count("serving.degrade") == 6
    assert _series("serving_degrade_level")[()] == 0.0


def test_up_patience_debounces_escalation():
    sig = {"target": 4}
    c = DegradationController(signals=[("t", lambda c: sig["target"])],
                              up_patience=3)
    assert c.poll() == 0 and c.poll() == 0
    assert c.poll() == 4                  # third consecutive bad poll


def test_broken_signal_reads_as_healthy():
    def boom(c):
        raise RuntimeError("signal crashed")
    c = DegradationController(signals=[("boom", boom)])
    assert c.poll() == 0
    assert c.last_targets == {"boom": 0}


def test_kill_switch_pins_level_zero(monkeypatch):
    sig = {"target": 4}
    c = DegradationController(signals=[("t", lambda c: sig["target"])])
    c.poll()
    assert c.level == 4 and not c.accepting_sessions()
    monkeypatch.setenv("PT_DEGRADE", "0")
    # every effect goes permissive immediately, before any poll
    assert c.active_level == 0
    assert c.spec_enabled() and c.accepting_sessions()
    assert not c.shed_best_effort()
    assert c.prefill_budget(16) == 16
    assert c.poll() == 0                  # and the poll records the drop
    assert c.transitions[-1]["why"] == "kill_switch"
    monkeypatch.delenv("PT_DEGRADE")
    assert c.poll() == 4                  # switch back on: signals rule


def test_effect_thresholds_per_rung():
    c = _ctrl()
    expect = {0: (True, 16, False, True), 1: (False, 16, False, True),
              2: (False, 4, False, True), 3: (False, 4, True, True),
              4: (False, 4, True, False)}
    for lvl, (spec, budget, shed, accept) in expect.items():
        c.force_level(lvl)
        assert (c.spec_enabled(), c.prefill_budget(16),
                c.shed_best_effort(), c.accepting_sessions()) \
            == (spec, budget, shed, accept), f"rung {lvl}"


# ----------------------------------------------------- rung effects, live

def test_level_zero_bit_identical(model):
    """An engine carrying a controller at L0 produces byte-for-byte the
    tokens of an engine built without one."""
    rs = np.random.RandomState(40)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    eng = _mk(model, degrade=DegradationController())
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=10))
    got = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    assert got == ref


def test_l1_disables_spec_decoding(model, draft):
    rs = np.random.RandomState(41)
    prompts = _prompts(4, rs)
    ref = _reference(model, prompts)          # plain engine, no draft
    c = _ctrl()
    c.force_level(1)
    eng = _mk(model, draft_model=draft, spec_k=2, degrade=c)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=10))
    got = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    assert eng.stats["spec_ticks"] == 0       # never drafted
    assert got == ref                         # and greedy-identical


def test_l2_shrinks_prefill_chunks(model):
    """At L2 every prefill chunk is at most cap // chunk_shrink tokens;
    the jitted geometry is untouched and output stays greedy-identical."""
    from paddle_tpu.observability.requests import REQUESTS
    rs = np.random.RandomState(42)
    prompts = _prompts(3, rs, lo=20, hi=30)   # > max_prompt_len: chunked
    ref = _reference(model, prompts, max_prompt_len=8, max_seq_len=64)
    c = _ctrl(chunk_shrink=4)
    c.force_level(2)
    eng = _mk(model, max_prompt_len=8, max_seq_len=64, degrade=c)
    REQUESTS.enable()
    reqs = [Request(p, max_new_tokens=10) for p in prompts]
    for r in reqs:
        eng.add_request(r)
    out = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    chunks = []
    for r in reqs:
        line = REQUESTS.timeline(r.trace_id)
        chunks += [e["tokens"] for e in line["events"]
                   if e["kind"] == "prefill_chunk"]
    assert chunks and max(chunks) <= 8 // 4
    assert out == ref


def test_l3_sheds_only_best_effort(model):
    rs = np.random.RandomState(43)
    c = _ctrl()
    eng = _mk(model, degrade=c)
    eng.sched.set_tenant_priority("B", "best_effort")
    c.force_level(3)
    reqs = [Request(rs.randint(0, 64, (5,)), max_new_tokens=4,
                    tenant_id="A" if i % 2 == 0 else "B") for i in range(4)]
    for r in reqs:
        eng.add_request(r)
    for _ in range(60):
        if not eng.has_work():
            break
        eng.step()
    by_id = {r.req_id: r for r in reqs}
    done = sorted(by_id[rid].tenant_id for rid in eng.pop_finished())
    assert done == ["A", "A"]                 # best-effort deferred, queued
    assert _series("serving_degrade_shed_total").get(("B",), 0) > 0
    assert ("A",) not in _series("serving_degrade_shed_total")
    c.force_level(0)                          # recovery: B admits and runs
    out = eng.run()
    assert sorted(by_id[rid].tenant_id for rid in out) == ["B", "B"]
    eng.kv.assert_quiescent()


def test_l4_rejects_new_sessions_engine_and_router(model):
    c = _ctrl()
    c.force_level(4)
    eng = _mk(model, degrade=c)
    with pytest.raises(OverloadError) as ei:
        eng.add_request(Request(np.arange(5), max_new_tokens=4))
    assert isinstance(ei.value, QueueFullError)   # shed handlers compose
    assert _series("serving_rejections_total").get(("degraded",)) == 1
    router = Router([Replica(_mk(model), name="r0")], degrade=_ctrl())
    router.degrade.force_level(4)
    with pytest.raises(OverloadError):
        router.add_request(Request(np.arange(5), max_new_tokens=4))
    assert router.degrade.owner is router     # router claims the poll


# -------------------------------------------------- token-bucket rate limit

def test_tenant_rate_limit_throttles_and_refills(model):
    clk = [0.0]
    eng = _mk(model)
    eng.sched.clock = lambda: clk[0]
    eng.sched.set_tenant_rate("T", max_tokens_per_s=10.0, burst=10.0)
    rs = np.random.RandomState(44)
    rt = [Request(rs.randint(0, 64, (5,)), max_new_tokens=12, tenant_id="T")
          for _ in range(2)]
    free = Request(rs.randint(0, 64, (5,)), max_new_tokens=12,
                   tenant_id="U")
    for r in rt:
        eng.add_request(r)
    eng.add_request(free)
    eng.step()
    # the first T admission cost 5 + 12 = 17 tokens against a 10-token
    # burst — the bucket overdrafts to -7, the second T is throttled
    # until the overdraft refills; U carries no limit and is untouched
    assert _series("serving_tenant_throttled_total").get(("T",), 0) >= 1
    assert ("U",) not in _series("serving_tenant_throttled_total")
    assert eng.sched._bucket_level("T", clk[0]) <= 0.0
    clk[0] += 5.0                             # refill 50 tokens (capped)
    out = eng.run()
    assert len(out) == 3                      # throttled request admitted
    eng.kv.assert_quiescent()


def test_tenant_rate_remove_restores_unlimited(model):
    eng = _mk(model)
    eng.sched.set_tenant_rate("T", max_tokens_per_s=1.0)
    eng.sched.set_tenant_rate("T", None)
    assert not eng.sched.tenant_rate


# -------------------------------------------------------- session snapshots

def test_snapshot_capture_and_resume_ids(model):
    eng = _mk(model)
    req = Request(np.arange(6), max_new_tokens=6, session_id="s1",
                  tenant_id="t1")
    eng.add_request(req)
    for _ in range(4):
        eng.step()
    snap = eng.snapshot_session(req.req_id)
    assert isinstance(snap, SessionSnapshot)
    assert snap.tokens == tuple(req.tokens) and snap.gen == len(req.tokens)
    assert snap.session_id == "s1" and snap.tenant_id == "t1"
    ids = snap.resume_ids()
    assert list(ids[:6]) == list(range(6))
    assert list(ids[6:]) == list(req.tokens)
    assert _series("serving_session_snapshots_total").get((), 0) == 1
    assert eng.snapshot_session(10 ** 9) is None      # unknown rid: no-op


def test_double_death_restores_from_snapshot(model):
    """The acceptance core: a request whose SECOND replica dies (the
    exactly-once requeue already spent) is restored from its snapshot
    onto a survivor and finishes with greedy output bit-identical to an
    undisturbed run — no replica_death failures anywhere."""
    rs = np.random.RandomState(45)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts, max_new=8)
    reps = [Replica(_mk(model), name=f"r{i}") for i in range(3)]
    router = Router(reps, snapshot_every=1)
    seen = {"r0": 0, "r1": 0}

    def kill_two(ctx):
        name = ctx["replica"]
        if name in seen:
            seen[name] += 1
            if (name, seen[name]) in (("r0", 2), ("r1", 6)):
                raise InjectedFault(f"induced {name} death")

    with FAULTS.scope("router.replica_death", action=kill_two):
        for p in prompts:
            router.add_request(Request(p, max_new_tokens=8))
        out = {rid: list(map(int, t)) for rid, t in router.run().items()}
    assert router.stats["deaths"] == 2
    assert all(req.finish_reason != "replica_death"
               for req in router.requests.values())
    assert out == ref
    assert _series("router_session_restores_total").get((), 0) >= 1
    assert "router.session_restore" in _flight_kinds()
    waste = _series("serving_waste_total")
    assert waste.get(("replay_prefill",), 0) > 0      # billed honestly
    router.assert_quiescent()


def test_restore_cap_fails_closed(model):
    """max_session_restores bounds the replay loop: past the cap the
    request fails with replica_death instead of cycling forever."""
    rs = np.random.RandomState(46)
    prompts = _prompts(6, rs)
    reps = [Replica(_mk(model), name=f"r{i}") for i in range(3)]
    router = Router(reps, snapshot_every=1, max_session_restores=0)
    seen = {"r0": 0, "r1": 0}

    def kill_two(ctx):
        name = ctx["replica"]
        if name in seen:
            seen[name] += 1
            if (name, seen[name]) in (("r0", 2), ("r1", 6)):
                raise InjectedFault(f"induced {name} death")

    with FAULTS.scope("router.replica_death", action=kill_two):
        for p in prompts:
            router.add_request(Request(p, max_new_tokens=8))
        router.run()
    # with restores disabled the double-death request fails closed
    assert any(req.finish_reason == "replica_death"
               for req in router.requests.values())
    router.assert_quiescent()


# ------------------------------------------------------ transport hardening

def test_validate_payload_rejects_corruption(model):
    eng = _mk(model)
    eng.add_request(Request(np.arange(6), max_new_tokens=4))
    eng.step()
    rid = next(iter(eng.requests))
    payload = eng.extract_sequence(rid)
    assert payload.expect is not None         # sealed at extraction
    tgt = _mk(model)
    validate_payload(payload, tgt)            # pristine: passes
    zeroed = dataclasses.replace(payload, k=jnp.zeros_like(payload.k))
    with pytest.raises(KVTransferError, match="checksum"):
        validate_payload(zeroed, tgt)
    truncated = dataclasses.replace(payload, n_blocks=0)
    with pytest.raises(KVTransferError, match="drifted|truncated"):
        validate_payload(truncated, tgt)
    bad_geom = dataclasses.replace(
        payload, k=payload.k[:, :, :, :1], v=payload.v[:, :, :, :1],
        expect=None)
    with pytest.raises(KVTransferError, match="geometry"):
        validate_payload(bad_geom, tgt)


def test_partial_transfer_retried_exactly_once(model):
    """A corrupted first shipment is rejected by validation and re-sent
    from the pristine source payload; one retry, greedy identity, no
    leaked blocks on either replica."""
    rs = np.random.RandomState(47)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    reps = [Replica(_mk(model), name="p0", role="prefill"),
            Replica(_mk(model), name="d0", role="decode")]
    router = Router(reps)

    def corrupt(ctx):
        p = ctx["payload"]
        # a COPY: the source payload must stay pristine for the retry
        return dataclasses.replace(p, k=jnp.zeros_like(p.k))

    with FAULTS.scope("router.kv_partial", on={0}, action=corrupt):
        for p in prompts:
            router.add_request(Request(p, max_new_tokens=10))
        out = {rid: list(map(int, t)) for rid, t in router.run().items()}
    assert out == ref
    assert _series("router_transfer_retries_total") == {("d0", "partial"): 1}
    assert "router.kv_retry" in _flight_kinds()
    router.assert_quiescent()


def test_transfer_retries_exhausted_fails_handoff_cleanly(model):
    """When EVERY attempt ships garbage the handoff gives up without
    installing anything; the payload stays pending (no corrupt state on
    the decode replica, no leaked blocks)."""
    rs = np.random.RandomState(48)
    reps = [Replica(_mk(model), name="p0", role="prefill"),
            Replica(_mk(model), name="d0", role="decode")]
    router = Router(reps, transport=TransportPolicy(
        max_attempts=2, backoff_base_s=0.0, hedge=False))

    def corrupt(ctx):
        p = ctx["payload"]
        return dataclasses.replace(p, k=jnp.zeros_like(p.k))

    with FAULTS.scope("router.kv_partial", every=1, action=corrupt):
        router.add_request(Request(rs.randint(0, 64, (6,)),
                                   max_new_tokens=4))
        for _ in range(30):
            router.step()
    assert sum(_series("router_transfer_retries_total").values()) >= 2
    assert router._pending                    # still awaiting a clean wire
    # the wire heals: the SAME pending payload now installs and finishes
    out = router.run()
    assert len(out) == 1
    router.assert_quiescent()


def test_hedged_handoff_loser_leaves_no_leak(model):
    """A straggling primary ships past the deadline; the hedge to the
    other decode replica wins, the late primary copy is dropped without
    install (exactly-once), and nothing leaks on any replica."""
    rs = np.random.RandomState(49)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    reps = [Replica(_mk(model), name="p0", role="prefill"),
            Replica(_mk(model), name="d0", role="decode"),
            Replica(_mk(model), name="d1", role="decode")]
    router = Router(reps, transport=TransportPolicy(deadline_s=0.01,
                                                    max_attempts=1))
    with FAULTS.scope("router.kv_stall", on={0}, delay_s=0.05):
        for p in prompts:
            router.add_request(Request(p, max_new_tokens=10))
        out = {rid: list(map(int, t)) for rid, t in router.run().items()}
    assert out == ref
    assert router.stats["hedges"] == 1
    assert _series("router_hedges_total").get((), 0) == 1
    kinds = _flight_kinds()
    assert "router.kv_hedge" in kinds and "router.kv_hedge_win" in kinds
    assert _series("router_hedge_rate").get((), 0) > 0
    router.assert_quiescent()


def test_deadline_derived_from_history_needs_samples():
    from paddle_tpu.observability.metrics import MetricsRegistry
    scratch = MetricsRegistry()
    h = scratch.histogram("router_kv_transfer_seconds", "scratch",
                          buckets=(0.01, 0.1, 1.0))
    tp = TransportPolicy(min_samples=4, deadline_margin=2.0,
                         min_deadline_s=0.05)
    assert tp.deadline(h) is None             # cold start: never hedge
    for _ in range(4):
        h.observe(0.1)
    d = tp.deadline(h)
    assert d is not None and d >= 0.05
    assert TransportPolicy(deadline_s=0.3).deadline(h) == 0.3


# ----------------------------------------------------------- chaos storm

def test_chaos_storm_acceptance(model):
    """The ISSUE 16 acceptance gate, in miniature: replica death x2
    (one request loses BOTH its replicas), a KV-transfer straggler, a
    partial transfer, and allocation pressure — all at once, seeded.
    Every request finishes with reference-identical greedy output, the
    ladder visibly climbed and returned to L0, and the fleet is
    quiescent with a clean block ledger on every replica."""
    rs = np.random.RandomState(50)
    prompts = _prompts(8, rs)
    ref = _reference(model, prompts, max_new=8, preemption=True)

    def storm_signal(c):
        # aggressive goodput window so the miniature storm registers
        ratio, volume = c.window_goodput()
        if volume < 8 or ratio != ratio:
            return 0
        return 2 if ratio < 0.9 else 0

    deg = DegradationController(signals=[("storm", storm_signal)],
                                up_patience=1, down_patience=2)
    reps = [Replica(_mk(model, preemption=True), name="p0",
                    role="prefill")] + \
           [Replica(_mk(model, preemption=True), name=f"d{i}",
                    role="decode") for i in range(3)]
    router = Router(reps, degrade=deg, snapshot_every=1)
    seen = {"d0": 0, "d1": 0}

    def kill_two(ctx):
        name = ctx["replica"]
        if name in seen:
            seen[name] += 1
            if (name, seen[name]) in (("d0", 4), ("d1", 6)):
                raise InjectedFault(f"induced {name} death")

    def corrupt(ctx):
        p = ctx["payload"]
        return dataclasses.replace(p, k=jnp.zeros_like(p.k))

    with FAULTS.scope("router.replica_death", action=kill_two), \
            FAULTS.scope("router.kv_stall", on={1}, delay_s=0.02), \
            FAULTS.scope("router.kv_partial", on={0}, action=corrupt), \
            FAULTS.scope("serving.alloc", on={1, 3}, exc=MemoryError):
        for p in prompts:
            router.add_request(Request(p, max_new_tokens=8))
        out = {rid: list(map(int, t))
               for rid, t in router.run().items()}
    # --- every request finished, correctly, despite the storm
    assert len(out) == len(prompts)
    assert all(req.finish_reason != "replica_death"
               for req in router.requests.values())
    assert out == ref
    assert router.stats["deaths"] == 2
    assert _series("router_session_restores_total").get((), 0) >= 1
    assert sum(_series("router_transfer_retries_total").values()) >= 1
    # --- the ladder reacted and recovered, visibly
    assert deg.peak_level >= 2
    for _ in range(3 * deg.down_patience + 3):
        deg.poll()                            # post-storm settle
    assert deg.level == 0
    assert _series("serving_degrade_transitions_total")
    kinds = _flight_kinds()
    assert "serving.degrade" in kinds
    # --- and the fleet is clean: no leaked blocks, ledger reconciled
    router.assert_quiescent()
    for rep in router.replicas:
        r = rep.engine.kv.reconcile()
        assert r["ok"], (rep.name, r["diffs"])
