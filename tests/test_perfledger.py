"""Bench-history perf ledger (ISSUE 12): the tier-1 gate that the
ledger parses every ``BENCH_r0*.json`` the repo has accumulated, plus
synthetic-history coverage of the regression verdicts, comparability
rules, the history append path, and the CLI exit codes.

The module under test is deliberately pure stdlib (bench.py's
orchestrator loads it by file path and must never import jax); the
import here goes through the package like any other test."""
import json
import os
import pathlib

import pytest

from paddle_tpu.observability import perfledger as pl

_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


# ------------------------------------------------------ the repo's history
def test_ledger_parses_every_bench_round_in_the_tree():
    """Acceptance criterion: every BENCH_r0*.json in the tree parses
    into the trajectory — a malformed artifact fails tier-1."""
    files = sorted(p.name for p in pathlib.Path(_ROOT).glob("BENCH_r*.json"))
    assert len(files) >= 5
    rounds = pl.load_rounds(_ROOT)
    labels = [r["label"] for r in rounds]
    for f in files:
        assert os.path.splitext(f)[0] in labels
    by_label = {r["label"]: r for r in rounds}
    # rounds that recorded a parseable result line must flatten to legs
    parseable = [r for r in rounds if r["parsed_ok"]]
    assert len(parseable) >= 2
    for r in parseable:
        assert r["legs"], f"{r['label']} parsed but yielded no legs"
        assert all(isinstance(v, float) for v in r["legs"].values())
        assert r["degraded"] in (True, False)
    # the two newest artifacts are on-chip rounds with a headline leg
    for lbl in ("BENCH_r04", "BENCH_r05"):
        assert by_label[lbl]["parsed_ok"], f"{lbl} must parse"
        assert "headline" in by_label[lbl]["legs"]


def test_ledger_report_and_markdown_render_from_repo_history():
    rounds = pl.load_rounds(_ROOT)
    report = pl.build_report(rounds)
    n = len(rounds)
    assert report["trajectory"], "no legs tracked at all"
    for leg, series in report["trajectory"].items():
        assert len(series) == n, f"{leg} series misses rounds"
    assert report["newest"] is not None
    assert report["status"] in ("ok", "fail")
    md = pl.render_markdown(report)
    assert md.startswith("# bench trajectory")
    assert f"**status: {report['status']}**" in md
    for r in rounds:
        assert r["label"] in md


def test_ledger_cli_runs_on_the_repo(capsys):
    assert pl.main(["--dir", _ROOT]) == 0           # report always renders
    out = capsys.readouterr().out
    assert "# bench trajectory" in out
    assert pl.main(["--dir", _ROOT, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) >= {"rounds", "trajectory", "legs", "status"}


# ------------------------------------------------------- synthetic history
def _write_round(root, n, value, degraded=False, extra=None, metrics=None):
    parsed = {"value": value, "degraded": degraded}
    if extra:
        parsed["extra"] = extra
    if metrics:
        parsed["metrics"] = metrics
    doc = {"n": n, "rc": 0, "tail": "", "parsed": parsed}
    with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(doc, f)


def test_regression_verdict_and_check_exit_code(tmp_path):
    root = str(tmp_path)
    _write_round(root, 1, 100.0,
                 extra={"mfu": 0.31, "configs": {"a": {"value": 10.0}}},
                 metrics={"spec": {"speedup": 2.0},
                          "broken": {"error": "boom"}})
    _write_round(root, 2, 80.0,
                 extra={"mfu": 0.33, "configs": {"a": {"value": 10.2}}},
                 metrics={"spec": {"speedup": 2.05}})
    report = pl.build_report(pl.load_rounds(root))
    assert report["comparable"]
    assert report["legs"]["headline"]["verdict"] == "regressed"
    assert report["legs"]["headline"]["delta_pct"] == pytest.approx(-0.2)
    assert report["legs"]["mfu"]["verdict"] == "improved"   # +6.5% > 5%
    assert report["legs"]["config:a"]["verdict"] == "ok"    # +2% within
    assert report["legs"]["metrics:spec"]["verdict"] == "ok"
    assert "metrics:broken" not in report["legs"]   # error subs skipped
    assert report["status"] == "fail"
    assert report["regressed"] == ["headline"]
    assert pl.main(["--dir", root, "--check"]) == 1
    assert pl.main(["--dir", root, "--check", "--threshold", "0.5"]) == 0
    assert pl.main(["--dir", root]) == 0            # no --check: report only


def test_degraded_round_is_never_compared_against_on_chip(tmp_path):
    root = str(tmp_path)
    _write_round(root, 1, 100.0, degraded=False)
    _write_round(root, 2, 5.0, degraded=True)       # CPU smoke: 20x slower
    report = pl.build_report(pl.load_rounds(root))
    assert not report["comparable"]
    assert report["legs"]["headline"]["verdict"] == "incomparable"
    assert report["status"] == "ok"                 # cannot fail the gate
    assert "not comparable" in pl.render_markdown(report)
    assert pl.main(["--dir", root, "--check"]) == 0


def test_new_and_missing_legs(tmp_path):
    root = str(tmp_path)
    _write_round(root, 1, 100.0, extra={"configs": {"old": {"value": 1.0}}})
    _write_round(root, 2, 101.0, extra={"configs": {"new": {"value": 2.0}}})
    legs = pl.build_report(pl.load_rounds(root))["legs"]
    assert legs["config:new"]["verdict"] == "new"
    assert legs["config:old"]["verdict"] == "missing"
    assert legs["headline"]["verdict"] == "ok"


def test_unparseable_round_is_flagged_not_fatal(tmp_path):
    root = str(tmp_path)
    with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
        f.write("{not json")
    _write_round(root, 2, 50.0)
    rounds = pl.load_rounds(root)
    assert [r["parsed_ok"] for r in rounds] == [False, True]
    report = pl.build_report(rounds)
    assert report["newest"] == "BENCH_r02"
    assert report["previous"] is None
    md = pl.render_markdown(report)
    assert "✗" in md                                 # the broken round shows


def test_append_history_roundtrip_and_dedup(tmp_path):
    root = str(tmp_path)
    _write_round(root, 1, 100.0)
    assert pl.append_history({"value": 90.0, "degraded": False}, root)
    rounds = pl.load_rounds(root)
    assert [r["label"] for r in rounds] == ["BENCH_r01", "run01"]
    assert rounds[-1]["legs"]["headline"] == 90.0
    # a history line identical to a file round is the same run snapshotted
    # by the driver — it must not appear twice
    assert pl.append_history({"value": 100.0}, root)
    rounds = pl.load_rounds(root)
    assert [r["label"] for r in rounds] == ["BENCH_r01", "run01"]


def test_empty_dir_exit_codes(tmp_path, capsys):
    assert pl.main(["--dir", str(tmp_path)]) == 0
    assert pl.main(["--dir", str(tmp_path), "--check"]) == 2
    assert "no BENCH_r*.json" in capsys.readouterr().out


def test_flatten_legs_ignores_junk():
    assert pl.flatten_legs(None) == {}
    assert pl.flatten_legs({"value": "fast"}) == {}      # non-numeric
    assert pl.flatten_legs({"value": True}) == {}        # bool is not a leg
    legs = pl.flatten_legs({"value": 3, "extra": {"mfu": 0.0}})
    assert legs == {"headline": 3.0}                     # mfu 0.0 = unmeasured
