"""autograd functional API + audio features + file-backed datasets."""
import gzip
import struct

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import paddle_tpu.audio as audio
import paddle_tpu.autograd as ag


# -- autograd ----------------------------------------------------------------

def test_jacobian_hessian():
    def f(x):
        return (x ** 3).sum()

    x = jnp.asarray([1.0, 2.0])
    j = ag.jacobian(f, x)
    assert np.allclose(np.asarray(j), 3 * np.asarray(x) ** 2)
    h = ag.hessian(f, x)
    assert np.allclose(np.asarray(h), np.diag(6 * np.asarray(x)))


def test_jvp_vjp_vhp():
    def f(x):
        return jnp.sin(x).sum()

    x = jnp.asarray([0.3, 0.7])
    v = jnp.asarray([1.0, 2.0])
    out, tangent = ag.jvp(f, x, v)
    assert np.allclose(float(tangent), float((jnp.cos(x) * v).sum()), rtol=1e-6)
    out, g = ag.vjp(f, x)
    assert np.allclose(np.asarray(g), np.cos(np.asarray(x)), rtol=1e-6)
    out, hv = ag.vhp(f, x, v)
    assert np.allclose(np.asarray(hv), -np.sin(np.asarray(x)) * np.asarray(v),
                       rtol=1e-6)


def test_pylayer_custom_vjp():
    class Double(ag.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return 2.0 * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            return 10.0 * g  # deliberately wrong to prove custom vjp is used

    x = jnp.asarray(3.0)
    y = Double.apply(x)
    assert float(y) == 6.0
    g = jax.grad(lambda x: Double.apply(x))(x)
    assert float(g) == 10.0


# -- audio -------------------------------------------------------------------

def test_mel_scale_roundtrip():
    freqs = jnp.asarray([50.0, 440.0, 1000.0, 4000.0])
    for htk in (False, True):
        back = audio.mel_to_hz(audio.hz_to_mel(freqs, htk), htk)
        assert np.allclose(np.asarray(back), np.asarray(freqs), rtol=1e-4)


def test_fbank_matches_torchaudio_style():
    fb = audio.compute_fbank_matrix(sr=16000, n_fft=400, n_mels=40)
    assert fb.shape == (40, 201)
    assert bool((fb >= 0).all())
    # every filter has support, triangles overlap
    assert bool((fb.sum(axis=1) > 0).all())


def test_spectrogram_matches_torch():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 2048).astype(np.float32)
    spec = audio.Spectrogram(n_fft=256, hop_length=128)(jnp.asarray(x))
    want = torch.stft(torch.tensor(x), n_fft=256, hop_length=128,
                      window=torch.hann_window(256, periodic=True),
                      center=True, pad_mode="reflect",
                      return_complex=True).abs().pow(2).numpy()
    assert spec.shape == want.shape
    assert np.allclose(np.asarray(spec), want, atol=1e-2)


def test_mfcc_shapes_and_finite():
    x = jnp.asarray(np.random.RandomState(1).randn(2, 4096).astype(np.float32))
    mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=64)(x)
    assert mel.shape[1] == 64
    mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_mels=64, n_fft=512)(x)
    assert mfcc.shape[1] == 13
    assert bool(jnp.isfinite(mfcc).all())
    db = audio.power_to_db(mel, top_db=80.0)
    assert float(db.max()) - float(db.min()) <= 80.0 + 1e-3


# -- datasets ----------------------------------------------------------------

def test_mnist_idx_reader(tmp_path):
    from paddle_tpu.vision.datasets import MNIST
    rs = np.random.RandomState(0)
    imgs = rs.randint(0, 255, (5, 28, 28), dtype=np.uint8)
    labels = rs.randint(0, 10, (5,), dtype=np.uint8)
    ip = tmp_path / "images.idx3-ubyte.gz"
    lp = tmp_path / "labels.idx1-ubyte.gz"
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 5, 28, 28) + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 5) + labels.tobytes())
    ds = MNIST(str(ip), str(lp))
    assert len(ds) == 5
    img, lab = ds[2]
    assert img.shape == (1, 28, 28)
    assert lab == int(labels[2])
    assert np.allclose(img[0], imgs[2].astype(np.float32))


def test_fake_data_deterministic():
    from paddle_tpu.vision.datasets import FakeData
    ds = FakeData(size=8, image_shape=(3, 16, 16), num_classes=4)
    img1, lab1 = ds[3]
    img2, lab2 = ds[3]
    assert np.array_equal(img1, img2) and lab1 == lab2
    assert img1.shape == (3, 16, 16) and 0 <= lab1 < 4


def test_get_window_matches_scipy():
    import scipy.signal
    import paddle_tpu.audio as A
    for n in [7, 8, 16]:
        for name in ["hann", "hamming", "blackman"]:
            np.testing.assert_allclose(
                np.asarray(A.get_window(name, n)),
                scipy.signal.get_window(name, n), atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(A.get_window(name, n, fftbins=False)),
                scipy.signal.get_window(name, n, fftbins=False), atol=1e-6)


def test_profiler_namespace():
    import paddle_tpu.utils.profiler as P
    sched = P.make_scheduler(closed=1, ready=1, record=2, skip_first=1)
    assert [sched(i) for i in range(6)] == \
        ["closed", "closed", "ready", "record", "record", "closed"]
    with P.RecordEvent("x"):
        pass
    assert P.ProfilerTarget.TPU == "tpu"


def test_callbacks_visualdl(tmp_path):
    import json
    import paddle_tpu.callbacks as C
    assert C.LRScheduler is C.LRSchedulerCallback
    v = C.VisualDL(log_dir=str(tmp_path), log_freq=1)
    v.on_train_batch_end(0, logs={"loss": 1.5})
    v.on_eval_end(logs={"acc": 0.9})
    v.on_train_end()
    lines = [json.loads(l) for l in
             (tmp_path / "scalars.jsonl").read_text().splitlines()]
    assert lines[0]["tag"] == "train/loss" and lines[1]["tag"] == "eval/acc"


def test_device_helpers():
    from paddle_tpu.core import device as D
    assert D.is_compiled_with_cuda() is False
    assert "cpu" in D.get_all_device_type()
    D.synchronize()
