"""Collective-deadlock lint: catches divergent-cond collectives and
collective while-predicates; passes clean SPMD code. Plus a source-level
clock lint: durations must never come from the wall clock."""
import pathlib
import re

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from paddle_tpu.utils.lint import (
    assert_no_collective_deadlock,
    lint_collectives,
)

AX = [("x", 4)]


def test_clean_collective_sequence():
    def f(v):
        s = lax.psum(v, "x")
        g = lax.all_gather(v, "x")
        return s + g.sum()

    rep = lint_collectives(f, jnp.ones(2), axis_env=AX)
    assert rep.ok
    assert [n for n, _ in rep.sequence] == ["psum", "all_gather"]


def test_cond_divergence_flagged():
    def f(v):
        return lax.cond(v.sum() > 0,
                        lambda u: lax.psum(u, "x"),
                        lambda u: u * 2,
                        v)

    rep = lint_collectives(f, jnp.ones(2), axis_env=AX)
    assert not rep.ok
    assert rep.issues[0].kind == "cond-divergence"
    with pytest.raises(RuntimeError):
        assert_no_collective_deadlock(f, jnp.ones(2), axis_env=AX)


def test_cond_symmetric_ok():
    def f(v):
        return lax.cond(v.sum() > 0,
                        lambda u: lax.psum(u * 2, "x"),
                        lambda u: lax.psum(u + 1, "x"),
                        v)

    rep = lint_collectives(f, jnp.ones(2), axis_env=AX)
    assert rep.ok
    assert [n for n, _ in rep.sequence] == ["psum"]


def test_while_cond_collective_flagged():
    def f(v):
        def cond(c):
            return lax.psum(c.sum(), "x") < 10

        def body(c):
            return c + 1

        return lax.while_loop(cond, body, v)

    rep = lint_collectives(f, jnp.ones(2), axis_env=AX)
    assert not rep.ok
    assert any(i.kind == "while-cond-collective" for i in rep.issues)


def test_nested_scan_collectives_found():
    def f(v):
        def body(c, _):
            return lax.ppermute(c, "x", [(i, (i + 1) % 4) for i in range(4)]), None

        out, _ = lax.scan(body, v, None, length=3)
        return lax.psum(out, "x")

    rep = lint_collectives(f, jnp.ones(2), axis_env=AX)
    assert rep.ok
    names = [n for n, _ in rep.sequence]
    assert names == ["ppermute", "psum"]


def test_pipeline_shard_map_body_lints_clean():
    """The PRODUCTION pipeline schedule (PipelineLayer's shard_map body)
    passes the deadlock lint — this closes the shard_map-pipeline lint
    item from SURVEY §5 against the real code, not a toy."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import HybridMesh
    from paddle_tpu.distributed.pipeline import PipelineLayer
    from paddle_tpu.utils.lint import lint_collectives

    pt.seed(0)
    blocks = [nn.Sequential(nn.Linear(8, 8), nn.GELU()) for _ in range(4)]
    pipe = PipelineLayer(blocks, num_stages=4, num_microbatches=2)
    mesh = HybridMesh(pp=4, devices=__import__("jax").devices()[:4])

    # lint the whole pipelined forward: the shard_map body's collectives
    # (ppermute handoffs inside the tick scan) appear in the sequence
    rep = lint_collectives(lambda x: pipe(x, mesh=mesh),
                           jnp.ones((4, 8)))
    assert rep.ok, rep.issues
    names = [n for n, _ in rep.sequence]
    assert "ppermute" in names


# --------------------------------------------------------------- clock lint
# Durations measured with time.time() jump when NTP steps the wall clock —
# every duration in paddle_tpu must ride time.monotonic()/perf_counter or
# the observability span API. Files with a LEGITIMATE wall-clock need
# (timestamps for humans, not durations) go on the allowlist with a reason.
_WALLCLOCK_ALLOWLIST = {
    # e.g. "paddle_tpu/some/module.py": "emits human-readable timestamps",
    "paddle_tpu/observability/flight.py":
        "t_wall in dump artifacts — humans correlate crash dumps by wall "
        "clock; every duration in the module rides time.monotonic()",
    "paddle_tpu/observability/shipper.py":
        "t_wall in shipped JSONL records — cross-process correlation "
        "timestamp; intervals/deltas ride time.monotonic()",
}


def test_no_wall_clock_durations_in_paddle_tpu():
    root = pathlib.Path(__file__).resolve().parent.parent
    pkg = root / "paddle_tpu"
    pat = re.compile(r"\btime\.time\s*\(")
    offenders = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        if rel in _WALLCLOCK_ALLOWLIST:
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if pat.search(line.split("#", 1)[0]):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "wall-clock time.time() used for timing (use time.monotonic() or "
        "the observability span API, or allowlist with a reason):\n"
        + "\n".join(offenders))


# ---------------------------------------------------------- thread-name lint
# Every background thread paddle_tpu spawns must carry a "pt-" name so the
# conftest leak fixture (and an operator's py-spy dump) can attribute any
# survivor to its subsystem. Same allowlist mechanism as the clock lint.
_THREAD_NAME_ALLOWLIST = {
    # e.g. "paddle_tpu/some/module.py": "thread name set post-construction",
}


def test_threads_carry_pt_name_prefix():
    root = pathlib.Path(__file__).resolve().parent.parent
    pkg = root / "paddle_tpu"
    offenders = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        if rel in _THREAD_NAME_ALLOWLIST:
            continue
        text = path.read_text()
        for m in re.finditer(r"\bthreading\.Thread\s*\(", text):
            # the constructor call may span lines — scan a window past
            # the open paren for the name= kwarg
            window = text[m.start():m.start() + 500]
            if not re.search(r"""name\s*=\s*f?["']pt-""", window):
                lineno = text.count("\n", 0, m.start()) + 1
                offenders.append(f"{rel}:{lineno}")
    assert not offenders, (
        'threading.Thread without a name="pt-..." (the leak fixture cannot '
        "attribute unnamed survivors; allowlist with a reason if the name "
        "is set elsewhere):\n" + "\n".join(offenders))


# ------------------------------------------------------ instrument hygiene
# Every metric instrument registered under paddle_tpu/ must carry a
# non-empty help string (the generated metrics reference renders it) and
# a name under one of the approved subsystem prefixes, so the exported
# namespace stays groupable in a Prometheus/Grafana deployment.
_INSTRUMENT_PREFIXES = (
    "serving_", "router_", "train_", "io_", "ckpt_", "moe_", "compile_",
    "collective_", "elastic_", "faults_", "steptimer_", "device_",
)
_INSTRUMENT_ALLOWLIST = {
    # e.g. "paddle_tpu/some/module.py": "registers dynamic names",
}


def test_metric_instruments_have_help_and_approved_prefix():
    root = pathlib.Path(__file__).resolve().parent.parent
    pkg = root / "paddle_tpu"
    offenders = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        if rel in _INSTRUMENT_ALLOWLIST:
            continue
        text = path.read_text()
        for m in re.finditer(r"\bMETRICS\.(counter|gauge|histogram)\s*\(",
                             text):
            # registrations span lines — scan a window past the open
            # paren for the first two string literals (name, help)
            window = text[m.end():m.end() + 500]
            lits = re.findall(r'"((?:[^"\\]|\\.)*)"', window)
            lineno = text.count("\n", 0, m.start()) + 1
            if not lits:
                offenders.append(f"{rel}:{lineno}: no literal name")
                continue
            name = lits[0]
            if not name.startswith(_INSTRUMENT_PREFIXES):
                offenders.append(
                    f"{rel}:{lineno}: {name!r} lacks an approved prefix "
                    f"{_INSTRUMENT_PREFIXES}")
            if len(lits) < 2 or not lits[1].strip():
                offenders.append(f"{rel}:{lineno}: {name!r} has no help "
                                 "string")
    assert not offenders, (
        "metric instruments without help text or an approved name prefix "
        "(fix the registration or allowlist the file with a reason):\n"
        + "\n".join(offenders))


# ------------------------------------------------- memledger choke points
# Every block-mutating method on the KV/block-manager stack must notify
# the per-pool memory ledger (ISSUE 13) — a mutation path that skips it
# silently breaks the sum(states) == num_blocks reconciliation the chaos
# suites assert per tick. Methods that mutate only by delegating to a
# notifying method go on the allowlist with a reason.
_MEMLEDGER_FILES = ("paddle_tpu/serving/kv.py", "paddle_tpu/models/paged.py")
_MEMLEDGER_METHODS = {"allocate", "free", "free_prefix", "adopt_prefix",
                      "_evict_one", "take_copy_plan"}
_MEMLEDGER_ALLOWLIST = {
    "paddle_tpu/serving/kv.py::KVManager.allocate":
        "delegates to the block manager, whose allocate notifies",
    "paddle_tpu/serving/kv.py::KVManager.free":
        "delegates to the block manager, whose free notifies",
    "paddle_tpu/models/paged.py::RefBlockManager.allocate":
        "delegates to BlockManager.allocate, which notifies",
}


def test_block_mutators_notify_the_memledger():
    import ast
    root = pathlib.Path(__file__).resolve().parent.parent
    offenders = []
    for rel in _MEMLEDGER_FILES:
        text = (root / rel).read_text()
        tree = ast.parse(text)
        for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if fn.name not in _MEMLEDGER_METHODS:
                    continue
                key = f"{rel}::{cls.name}.{fn.name}"
                if key in _MEMLEDGER_ALLOWLIST:
                    continue
                body = ast.get_source_segment(text, fn) or ""
                if "ledger." not in body:
                    offenders.append(f"{rel}:{fn.lineno}: "
                                     f"{cls.name}.{fn.name}")
    assert not offenders, (
        "block-mutating methods that never notify the memory ledger "
        "(record the transition with self.ledger.<hook>, or allowlist "
        "with a reason if a delegate notifies):\n" + "\n".join(offenders))


# ----------------------------------------------- metrics-reference coverage
# The generated metrics reference (``python -m paddle_tpu.observability``)
# renders whatever _INSTRUMENT_MODULES imports — a module that registers
# instruments but is missing from that tuple silently drops its metrics
# from the reference. Modules whose registrations are intentionally
# off-reference go in the allowlist with a reason.
_REFERENCE_ALLOWLIST = {
    # e.g. "paddle_tpu/some/module.py": "registers per-test scratch names",
}


def test_instrument_registering_modules_are_in_the_reference():
    from paddle_tpu.observability.__main__ import _INSTRUMENT_MODULES
    root = pathlib.Path(__file__).resolve().parent.parent
    pkg = root / "paddle_tpu"
    offenders = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        if rel in _REFERENCE_ALLOWLIST:
            continue
        if not re.search(r"\bMETRICS\.(counter|gauge|histogram)\s*\(",
                         path.read_text()):
            continue
        mod = ".".join(path.relative_to(root).with_suffix("").parts)
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        if mod not in _INSTRUMENT_MODULES:
            offenders.append(f"{rel}: registers instruments but {mod!r} "
                             "is not in observability.__main__."
                             "_INSTRUMENT_MODULES")
    assert not offenders, (
        "modules whose instruments the generated metrics reference would "
        "silently omit (add them to _INSTRUMENT_MODULES or allowlist with "
        "a reason):\n" + "\n".join(offenders))


def test_pipeline_divergent_handoff_flagged():
    """A stage that only hands off inside one cond branch deadlocks —
    the lint catches it before it reaches hardware."""
    import jax.numpy as jnp
    from jax import lax
    from paddle_tpu.utils.lint import lint_collectives

    def bad_stage(x):
        return lax.cond(
            x.sum() > 0,
            lambda v: lax.ppermute(v, "pp", [(0, 1), (1, 2), (2, 3), (3, 0)]),
            lambda v: v,
            x)

    rep = lint_collectives(bad_stage, jnp.ones((2, 2)), axis_env=[("pp", 4)])
    assert not rep.ok
    assert any(i.kind == "cond-divergence" for i in rep.issues)
