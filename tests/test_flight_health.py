"""ISSUE 4 surfaces: flight recorder, compile introspection, metrics
shipper, health/SLO evaluator, overlap-aware MFU, /healthz + /flight."""
import json
import math
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import (
    FLIGHT,
    HEALTH,
    METRICS,
    TRACER,
    FlightRecorder,
    HealthEvaluator,
    HealthRule,
    MetricsServer,
    MetricsShipper,
    install_default_rules,
    instrumented_jit,
)
from paddle_tpu.observability.flops import record_throughput
from paddle_tpu.observability.health import (
    counter_ratio,
    counter_value,
    histogram_quantile,
)
from paddle_tpu.train.trainer import Trainer, TrainerArgs
from paddle_tpu.utils.watchdog import WatchdogTrip


def _http_get(url):
    """(status, parsed-json body) — 503 arrives as HTTPError, same body."""
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ------------------------------------------------------------ flight recorder
def test_flight_ring_bounds_and_orders():
    fr = FlightRecorder(capacity=4, directory=None)
    for i in range(10):
        fr.record("tick", step=i)
    evs = fr.events()
    assert len(evs) == 4                       # ring bounded
    assert [e["step"] for e in evs] == [6, 7, 8, 9]   # newest kept, in order
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    assert fr.total_recorded == 10
    assert fr.last_step == 9
    assert all(e["kind"] == "tick" for e in evs)


def test_flight_set_capacity_keeps_newest():
    fr = FlightRecorder(capacity=8, directory=None)
    for i in range(6):
        fr.record("tick", step=i)
    fr.set_capacity(2)
    assert fr.capacity == 2
    assert [e["step"] for e in fr.events()] == [4, 5]
    with pytest.raises(ValueError):
        fr.set_capacity(0)


def test_flight_dump_atomic_and_parseable(tmp_path):
    fr = FlightRecorder(capacity=4, directory=str(tmp_path))
    for i in range(7):
        fr.record("train.step", step=i, loss=float(i))
    path = fr.dump(reason="unit")
    assert path == str(tmp_path / "flight_00000006.json")
    assert not list(tmp_path.glob("*.tmp"))    # atomic: no partial left
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit"
    assert doc["last_step"] == 6
    assert doc["total_recorded"] == 7
    assert doc["dropped"] == 3                 # 7 recorded, ring of 4
    assert [e["step"] for e in doc["events"]] == [3, 4, 5, 6]
    assert fr.dumps == 1


def test_flight_dump_without_destination_is_noop():
    fr = FlightRecorder(capacity=4, directory=None)
    fr.record("tick")
    assert fr.dump(reason="nowhere") is None
    assert fr.dumps == 0


# --------------------------------------------------- chaos acceptance scenario
@pytest.mark.chaos
def test_nan_storm_leaves_flight_dump_and_crit_health(tmp_path):
    """The acceptance path end-to-end: a NaN storm kills the trainer via
    WatchdogTrip; the crash leaves a parseable flight_*.json holding the
    give-up and the steps leading up to it, and /healthz flips from OK
    to CRIT (HTTP 503) on the nan_skip_rate rule."""
    from paddle_tpu.utils.faults import FAULTS
    pt.seed(0)
    FLIGHT.dir = str(tmp_path)
    srv = MetricsServer(port=0, host="127.0.0.1")
    try:
        status, body = _http_get(f"http://127.0.0.1:{srv.port}/healthz")
        assert (status, body["status"]) == (200, "OK")   # before the storm

        m = nn.Linear(4, 1)
        tr = Trainer(m, opt.SGD(0.1),
                     lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                     TrainerArgs(max_steps=50, log_every=0, max_bad_steps=3))
        FAULTS.install("train.loss", every=1, action=lambda c: float("nan"))
        rs = np.random.RandomState(1)
        data = ((rs.randn(2, 4).astype(np.float32),
                 rs.randn(2, 1).astype(np.float32)) for _ in range(50))
        with pytest.raises(WatchdogTrip, match="non-finite"):
            tr.fit(data)

        dumps = sorted(tmp_path.glob("flight_*.json"))
        assert dumps, "crash left no flight dump"
        with open(dumps[-1]) as f:
            doc = json.load(f)
        kinds = [e["kind"] for e in doc["events"]]
        assert doc["reason"].startswith("train.crash:WatchdogTrip")
        assert "train.giveup" in kinds          # the triggering event
        assert "train.crash" in kinds
        assert kinds.count("fault") == 3        # every chaos hit on record
        assert kinds.count("train.nan_skip") == 3

        status, body = _http_get(f"http://127.0.0.1:{srv.port}/healthz")
        assert status == 503                    # dumb TCP checkers see it
        assert body["status"] == "CRIT"
        by_name = {r["name"]: r for r in body["rules"]}
        assert by_name["nan_skip_rate"]["status"] == "CRIT"

        status, body = _http_get(f"http://127.0.0.1:{srv.port}/flight")
        assert status == 200
        assert any(e["kind"] == "train.giveup" for e in body["events"])
    finally:
        srv.stop()


# ------------------------------------------------------- compile introspection
def _counter(snap, name, fn):
    return snap["counters"].get(f'{name}{{fn="{fn}"}}', 0)


def test_instrumented_jit_hit_miss_and_span_accounting():
    TRACER.enable()
    f = instrumented_jit(lambda x: x * 2 + 1, name="toy")

    out = f(np.arange(4, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(out), [1, 3, 5, 7])
    snap = METRICS.snapshot()
    assert _counter(snap, "compile_cache_misses_total", "toy") == 1
    assert _counter(snap, "compile_cache_hits_total", "toy") == 0
    assert snap["histograms"]['compile_seconds{fn="toy"}']["count"] == 1
    compiles_before = sum(
        1 for e in TRACER.export()["traceEvents"] if e["name"] == "jit.compile")
    assert compiles_before == 1

    f(np.arange(4, dtype=np.float32) + 1)      # same signature → cache hit
    snap = METRICS.snapshot()
    assert _counter(snap, "compile_cache_hits_total", "toy") == 1
    assert _counter(snap, "compile_cache_misses_total", "toy") == 1
    assert snap["histograms"]['compile_seconds{fn="toy"}']["count"] == 1
    compiles_after = sum(
        1 for e in TRACER.export()["traceEvents"] if e["name"] == "jit.compile")
    assert compiles_after == compiles_before   # a hit opens no compile span

    f(np.arange(8, dtype=np.float32))          # new shape → second compile
    snap = METRICS.snapshot()
    assert _counter(snap, "compile_cache_misses_total", "toy") == 2
    assert f.cache_size == 2
    assert f.flops_per_call > 0                # CPU cost_analysis reports
    assert [e["kind"] for e in FLIGHT.events()].count("compile") == 2


def test_instrumented_jit_kill_switch(monkeypatch):
    monkeypatch.setenv("PT_COMPILE_INTROSPECTION", "0")
    f = instrumented_jit(lambda x: x + 1, name="off")
    assert not hasattr(f, "cache_size")        # bare jax.jit, no wrapper
    np.testing.assert_allclose(np.asarray(f(np.ones(2))), [2, 2])
    assert _counter(METRICS.snapshot(), "compile_cache_misses_total", "off") == 0


def test_instrumented_jit_falls_back_when_aot_breaks(monkeypatch):
    f = instrumented_jit(lambda x: x * 3, name="brittle")

    def boom(args, kwargs):
        raise RuntimeError("no AOT on this backend")
    monkeypatch.setattr(f, "_compile", boom)
    out = f(np.ones(2, dtype=np.float32))      # still computes via plain jit
    np.testing.assert_allclose(np.asarray(out), [3, 3])
    assert f._broken and f.cache_size == 0
    misses = _counter(METRICS.snapshot(), "compile_cache_misses_total",
                      "brittle")
    f(np.ones(2, dtype=np.float32))            # broken → counters frozen
    assert _counter(METRICS.snapshot(), "compile_cache_misses_total",
                    "brittle") == misses


def test_trainer_step_compiles_once_then_hits():
    pt.seed(0)
    m = nn.Linear(4, 1)
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                 TrainerArgs(max_steps=4, log_every=0))
    rs = np.random.RandomState(0)
    data = ((rs.randn(2, 4).astype(np.float32),
             rs.randn(2, 1).astype(np.float32)) for _ in range(4))
    tr.fit(data)
    snap = METRICS.snapshot()
    assert _counter(snap, "compile_cache_misses_total", "train.step") == 1
    assert _counter(snap, "compile_cache_hits_total", "train.step") == 3


# ------------------------------------------------------------ metrics shipper
def test_shipper_ships_deltas(tmp_path):
    path = str(tmp_path / "ship.jsonl")
    c = METRICS.counter("ship_unit_total")
    sh = MetricsShipper(path, interval_s=60)
    c.inc(5)
    sh.ship_now()
    c.inc(2)
    sh.ship_now()
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["seq"] for r in recs] == [0, 1]
    assert recs[0]["deltas"] == {}             # first ship has no baseline
    assert recs[0]["snapshot"]["counters"]["ship_unit_total"] == 5.0
    assert recs[1]["deltas"]["ship_unit_total"] == 2.0
    assert recs[1]["snapshot"]["counters"]["ship_unit_total"] == 7.0


def test_shipper_rotation_caps_disk(tmp_path):
    path = str(tmp_path / "ship.jsonl")
    sh = MetricsShipper(path, interval_s=60, max_bytes=300, max_files=3)
    for _ in range(40):
        sh.ship_now()
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["ship.jsonl", "ship.jsonl.1", "ship.jsonl.2"]
    for p in tmp_path.iterdir():               # every generation parseable
        with open(p) as f:
            assert all(isinstance(json.loads(line), dict) for line in f)
    with pytest.raises(ValueError):
        MetricsShipper(path, max_files=0)


def test_shipper_thread_lifecycle(tmp_path):
    sh = MetricsShipper(str(tmp_path / "s.jsonl"), interval_s=30)
    sh.start()
    names = [t.name for t in threading.enumerate()]
    assert "pt-metrics-shipper" in names       # leak fixture needs the prefix
    sh.stop()
    assert "pt-metrics-shipper" not in [t.name for t in threading.enumerate()]
    assert sh.shipped >= 1                     # stop() takes a final ship
    assert sh.errors == 0


# ------------------------------------------------------------- http endpoints
def test_healthz_and_flight_endpoints():
    srv = MetricsServer(port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _http_get(base + "/healthz")
        assert (status, body["status"]) == (200, "OK")
        assert {r["name"] for r in body["rules"]} >= {
            "nan_skip_rate", "elastic_restarts"}

        FLIGHT.record("unit.event", step=3)
        status, body = _http_get(base + "/flight")
        assert status == 200
        assert body["last_step"] == 3
        assert body["events"][-1]["kind"] == "unit.event"

        HEALTH.rule("unit_always_crit", lambda: 10.0, warn=1.0, crit=5.0)
        try:
            status, body = _http_get(base + "/healthz")
            assert (status, body["status"]) == (503, "CRIT")
        finally:
            HEALTH.remove_rule("unit_always_crit")
    finally:
        srv.stop()


# ---------------------------------------------------------- histogram quantile
def test_histogram_quantile_units():
    h = METRICS.histogram("hq_unit_seconds", "t", buckets=(1.0, 2.0, 4.0))
    assert math.isnan(h.quantile(0.5))         # empty → NaN, not 0
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    assert h.quantile(0.5) == pytest.approx(1.5)    # interpolated in (1,2]
    assert h.quantile(1.0) == pytest.approx(4.0)
    h.observe(100.0)                           # lands in +Inf
    assert h.quantile(1.0) == pytest.approx(4.0)    # clamped to top bound
    with pytest.raises(ValueError):
        h.quantile(1.5)


# ---------------------------------------------------------- overlap-aware MFU
def test_record_throughput_overlap_math():
    # 100 tok/s measured over a 2 s window where 1 s of host work hid
    # under device compute → device-side rate is 2× the naive one
    m = record_throughput(100.0, flops_per_token=2.0, peak_flops=1000.0,
                          hidden_host_s=1.0, window_s=2.0)
    g = METRICS.snapshot()["gauges"]
    assert m == pytest.approx(0.2)
    assert g["train_mfu"] == pytest.approx(0.2)
    assert g["train_mfu_overlap"] == pytest.approx(0.4)

    record_throughput(100.0, flops_per_token=2.0, peak_flops=1000.0)
    g = METRICS.snapshot()["gauges"]
    assert g["train_mfu_overlap"] == pytest.approx(g["train_mfu"])  # no window


# ------------------------------------------------------------ health semantics
def test_health_rule_thresholds():
    mk = lambda v: HealthRule("r", lambda: v, warn=2.0, crit=5.0)
    assert mk(1.0).evaluate()["status"] == "OK"
    assert mk(2.0).evaluate()["status"] == "WARN"    # thresholds inclusive
    assert mk(5.0).evaluate()["status"] == "CRIT"
    nan = mk(float("nan")).evaluate()
    assert (nan["status"], nan["value"]) == ("OK", None)   # no data ≠ incident
    with pytest.raises(ValueError):
        HealthRule("bad", lambda: 0, warn=5.0, crit=2.0)


def test_health_broken_getter_is_crit():
    def boom():
        raise RuntimeError("probe wiring broke")
    r = HealthRule("probe", boom, warn=1.0, crit=2.0).evaluate()
    assert r["status"] == "CRIT"
    assert "probe wiring broke" in r["error"]


def test_health_evaluator_fold_and_replace():
    ev = HealthEvaluator()
    assert ev.evaluate()["status"] == "OK"     # unconfigured must not page
    ev.rule("a", lambda: 0.0, warn=1.0, crit=2.0)
    ev.rule("b", lambda: 1.5, warn=1.0, crit=2.0)
    assert ev.evaluate()["status"] == "WARN"   # worst rule wins
    ev.rule("b", lambda: 0.0, warn=1.0, crit=2.0)     # same name replaces
    assert len(ev.rules) == 2
    assert ev.evaluate()["status"] == "OK"
    ev.remove_rule("a")
    assert [r.name for r in ev.rules] == ["b"]


def test_default_rules_track_registry():
    ev = install_default_rules(HealthEvaluator())
    assert ev.evaluate()["status"] == "OK"     # fresh registry → all quiet
    METRICS.counter("train_steps_total", "t").inc(10)
    METRICS.counter("train_nan_skips_total", "t").inc(1)
    rep = {r["name"]: r for r in ev.evaluate()["rules"]}
    assert rep["nan_skip_rate"]["status"] == "WARN"   # 0.1 ≥ warn 0.05
    assert rep["nan_skip_rate"]["value"] == pytest.approx(0.1)


def test_health_getter_factories():
    METRICS.counter("hg_num_total", "t").inc(3)
    METRICS.counter("hg_den_total", "t").inc(12)
    assert counter_value("hg_num_total")() == 3.0
    assert counter_ratio("hg_num_total", "hg_den_total")() == 0.25
    assert counter_ratio("hg_num_total", "hg_absent_total")() == 0.0
    assert math.isnan(histogram_quantile("hg_absent_seconds", 0.5)())


# ------------------------------------------------------------- orbax satellite
def test_orbax_checkpoint_instrumented(tmp_path):
    ocp = pytest.importorskip("orbax.checkpoint")  # noqa: F841
    from paddle_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "ck"), use_orbax=True)
    state = {"w": np.arange(6, dtype=np.float32), "step": np.int64(7)}
    mgr.save(7, state)
    restored = mgr.restore(state)
    np.testing.assert_allclose(np.asarray(restored["w"]), state["w"])
    snap = METRICS.snapshot()
    assert snap["counters"]["ckpt_saves_total"] == 1
    assert snap["counters"]["ckpt_restores_total"] == 1
    assert snap["histograms"]["ckpt_save_seconds"]["count"] == 1
    assert snap["histograms"]["ckpt_restore_seconds"]["count"] == 1
    kinds = [(e["kind"], e.get("backend")) for e in FLIGHT.events()]
    assert ("ckpt.save", "orbax") in kinds
