"""T5 encoder-decoder + Ulysses sequence-parallel attention."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration


@pytest.fixture(scope="module")
def t5():
    pt.seed(0)
    return T5ForConditionalGeneration(T5Config.tiny())


def test_t5_forward_shapes(t5):
    rs = np.random.RandomState(0)
    src = jnp.asarray(rs.randint(0, 256, (2, 12)))
    tgt = jnp.asarray(rs.randint(0, 256, (2, 8)))
    logits = t5(src, tgt)
    assert logits.shape == (2, 8, 256)
    assert bool(jnp.isfinite(logits).all())


def test_t5_trains(t5):
    import paddle_tpu.optimizer as opt
    from paddle_tpu.core.module import combine, partition_trainable

    rs = np.random.RandomState(1)
    src = jnp.asarray(rs.randint(0, 256, (4, 10)))
    labels = jnp.asarray(rs.randint(0, 256, (4, 6)))

    model = t5
    params, skel = partition_trainable(model)
    optimizer = opt.AdamW(learning_rate=1e-2)
    state = optimizer.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: combine(p, skel).loss(src, labels))(params)
        params, state = optimizer.step(params, grads, state)
        return params, state, loss

    losses = []
    for _ in range(8):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_t5_attention_mask(t5):
    """Padding positions must not affect the encoding of real positions."""
    rs = np.random.RandomState(2)
    src = jnp.asarray(rs.randint(1, 256, (1, 6)))
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0]])
    # change the masked tokens: output at unmasked positions must not move
    src2 = src.at[:, 4:].set(7)
    enc1 = t5.t5.encode(src, mask)
    enc2 = t5.t5.encode(src2, mask)
    assert np.allclose(np.asarray(enc1[:, :4]), np.asarray(enc2[:, :4]),
                       atol=1e-5)


def test_t5_generate(t5):
    rs = np.random.RandomState(3)
    src = jnp.asarray(rs.randint(0, 256, (2, 8)))
    out = t5.generate(src, max_new_tokens=5)
    assert out.shape == (2, 5)
    assert bool((out >= 0).all()) and bool((out < 256).all())


def test_t5_relative_bias_buckets():
    from paddle_tpu.models.t5 import _relative_position_bucket
    rel = jnp.arange(-10, 11)
    bi = _relative_position_bucket(rel, True, 32, 128)
    uni = _relative_position_bucket(rel, False, 32, 128)
    assert int(bi.min()) >= 0 and int(bi.max()) < 32
    assert int(uni.min()) >= 0 and int(uni.max()) < 32
    # causal: future positions (rel > 0 => n < 0) collapse to bucket 0
    assert int(uni[-1]) == 0


@pytest.mark.slow
def test_ulysses_matches_full_attention():
    from paddle_tpu.distributed import HybridMesh
    from paddle_tpu.distributed.ulysses import make_ulysses_attention
    from paddle_tpu.ops import attention as A

    mesh = HybridMesh(dp=1, fsdp=1, pp=1, tp=1, sp=8,
                      devices=jax.devices()[:8])
    rs = np.random.RandomState(0)
    B, S, H, D = 2, 32, 8, 16
    q = jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))

    want = A.xla_attention(q, k, v, is_causal=True)
    with mesh:
        fn = make_ulysses_attention(mesh, causal=True)
        got = fn(q, k, v)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=2e-5), \
        np.abs(np.asarray(got) - np.asarray(want)).max()


@pytest.mark.parametrize("sp_mode,sp", [("ulysses", 4), ("ring", 8)])
@pytest.mark.slow
def test_t5_relative_bias_over_sequence_parallel(sp_mode, sp):
    """Full T5 (encoder + causal decoder self-attn) under sp: the LEARNED
    relative position bias rides the additive-bias path; loss AND grads
    (incl. d(rel_bias)) equal the single-device model (VERDICT r2 item 5)."""
    from paddle_tpu.distributed import HybridMesh

    pt.seed(0)
    model = T5ForConditionalGeneration(T5Config.tiny())
    rs = np.random.RandomState(3)
    src = jnp.asarray(rs.randint(1, 256, (2, 32)))
    labels = jnp.asarray(rs.randint(1, 256, (2, 32)))
    amask = jnp.asarray([[1] * 32, [1] * 25 + [0] * 7], jnp.int32)

    def loss_fn(m):
        return m.loss(src, labels, attention_mask=amask)

    ref_loss, ref_grads = pt.value_and_grad(loss_fn)(model)

    pt.seed(0)
    model_sp = T5ForConditionalGeneration(
        T5Config.tiny(sequence_parallel=sp_mode))
    mesh = HybridMesh(sp=sp, devices=jax.devices()[:sp])
    with mesh:
        got_loss, got_grads = jax.jit(pt.value_and_grad(loss_fn))(model_sp)
    np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=2e-5)
    for r, g in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-5)
