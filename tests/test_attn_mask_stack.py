"""attn_mask + padded-varlen through the WHOLE attention stack: Pallas
varlen is covered in test_pallas.py; here ring, Ulysses, the LLaMA sp
dispatch, and BERT's varlen path (VERDICT r1 missing #3).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.distributed.ring_attention import make_ring_attention
from paddle_tpu.distributed.ulysses import make_ulysses_attention
from paddle_tpu.ops.attention import xla_attention


def _qkv(rs, b, s, h, d, hkv=None):
    hkv = hkv or h
    q = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, s, hkv, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, s, hkv, d).astype(np.float32))
    return q, k, v


@pytest.mark.slow
def test_ring_attention_kv_lens_matches_masked_full():
    b, s, h, d = 2, 32, 2, 8
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs, b, s, h, d)
    lens = jnp.asarray([32, 13], jnp.int32)
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None]
    ref = xla_attention(q, k, v, attn_mask=pad & causal)
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=True, varlen=True)
        out = attend(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out * valid_q),
                               np.asarray(ref * valid_q),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_dense_mask_fwd_and_grad():
    b, s, h, d = 1, 16, 2, 4
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs, b, s, h, d)
    # arbitrary (non-prefix) key mask, e.g. blockwise document mask
    rng_mask = rs.rand(b, s, s) > 0.3
    # keep the diagonal so no row is fully dead (causal & diag always kept)
    mask = jnp.asarray(rng_mask | np.eye(s, dtype=bool)[None])
    causal = jnp.tril(jnp.ones((s, s), bool))[None]
    ref_mask4 = (mask & causal)[:, None]

    ref = xla_attention(q, k, v, attn_mask=ref_mask4)
    ref_g = jax.grad(lambda q, k, v: jnp.sum(
        xla_attention(q, k, v, attn_mask=ref_mask4) ** 2),
        argnums=(0, 1, 2))(q, k, v)

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=True, masked=True)
        out = attend(q, k, v, mask)
        got_g = jax.grad(lambda q, k, v: jnp.sum(
            attend(q, k, v, mask) ** 2), argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=5e-5)


def test_ulysses_kv_lens_and_mask():
    b, s, h, d = 2, 32, 8, 4
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, b, s, h, d)
    lens = jnp.asarray([32, 9], jnp.int32)
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None]
    ref = xla_attention(q, k, v, attn_mask=pad & causal)
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ulysses_attention(mesh, causal=True, varlen=True)
        out = attend(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out * valid_q),
                               np.asarray(ref * valid_q),
                               rtol=2e-4, atol=2e-5)

    # dense mask path
    mask = jnp.asarray((rs.rand(b, s, s) > 0.3) | np.eye(s, dtype=bool)[None])
    ref2 = xla_attention(q, k, v, attn_mask=(mask[:, None] & causal))
    with mesh:
        attend2 = make_ulysses_attention(mesh, causal=True, masked=True)
        out2 = attend2(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_mistral_window_composes_with_ulysses():
    """Mistral x Ulysses now WORKS (r1 raised): global sliding window via
    the full-sequence inner attention after the all_to_all."""
    b, s, h, d, w = 1, 32, 8, 4, 10
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, b, s, h, d)
    ref = xla_attention(q, k, v, is_causal=True, window=w)
    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ulysses_attention(mesh, causal=True, window=w)
        out = attend(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_llama_ring_with_attn_mask():
    """Model-level: LLaMA with sequence_parallel='ring' accepts attn_mask
    (r1: it raised NotImplementedError)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    b, s = 2, 32
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))
    lens = jnp.asarray([32, 17], jnp.int32)
    pad2d = (jnp.arange(s)[None, :] < lens[:, None]).astype(jnp.float32)

    ref_logits = model(ids, attn_mask=(pad2d[:, None, None, :] > 0))

    cfg_sp = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                              num_attention_heads=4, num_key_value_heads=2,
                              vocab_size=64, sequence_parallel="ring")
    pt.seed(0)
    model_sp = LlamaForCausalLM(cfg_sp)
    mesh = HybridMesh(sp=8)
    with mesh:
        got_logits = model_sp(ids, attn_mask=(pad2d > 0))
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[..., None]
    np.testing.assert_allclose(np.asarray(got_logits * valid_q),
                               np.asarray(ref_logits * valid_q),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_llama_sp_bool_broadcast_mask_and_float_bias():
    """A [B,1,1,S] BOOL key-padding mask broadcasts through the sp
    dispatch; float additive and per-head masks ride the sp BIAS path
    (VERDICT r2 item 5 — they used to raise) and match the non-sp model."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    b, s = 2, 32
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)))
    lens = jnp.asarray([32, 15], jnp.int32)
    keep = jnp.arange(s)[None, :] < lens[:, None]           # [B, S] bool

    ref = model(ids, attn_mask=keep[:, None, None, :])

    pt.seed(0)
    cfg_sp = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                              num_attention_heads=4, num_key_value_heads=2,
                              vocab_size=64, sequence_parallel="ring")
    model_sp = LlamaForCausalLM(cfg_sp)
    mesh = HybridMesh(sp=8)
    with mesh:
        got = model_sp(ids, attn_mask=keep[:, None, None, :])
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[..., None]
    np.testing.assert_allclose(np.asarray(got * valid_q),
                               np.asarray(ref * valid_q),
                               rtol=2e-3, atol=2e-4)

    # float additive mask: sp bias path == non-sp additive path
    additive = jnp.where(keep, 0.0, -1e9)[:, None, None, :]
    ref_add = model(ids, attn_mask=additive)
    with mesh:
        got_add = model_sp(ids, attn_mask=additive)
    np.testing.assert_allclose(np.asarray(got_add * valid_q),
                               np.asarray(ref_add * valid_q),
                               rtol=2e-3, atol=2e-4)
    # per-head bool mask: folded to 0/-inf additive, same result per head
    per_head = jnp.broadcast_to(keep[:, None, None, :],
                                (b, cfg.num_attention_heads, s, s))
    ref_ph = model(ids, attn_mask=per_head)
    with mesh:
        got_ph = model_sp(ids, attn_mask=per_head)
    np.testing.assert_allclose(np.asarray(got_ph * valid_q),
                               np.asarray(ref_ph * valid_q),
                               rtol=2e-3, atol=2e-4)


def _alibi_bias(h, s):
    """[1, H, S, S] ALiBi: -slope_h * (i - j), the classic per-head bias."""
    slopes = 2.0 ** (-np.arange(1, h + 1) / 2.0)
    i = np.arange(s)[:, None]
    j = np.arange(s)[None, :]
    return jnp.asarray(-slopes[None, :, None, None]
                       * (i - j)[None, None], jnp.float32)


@pytest.mark.slow
def test_ring_additive_per_head_bias_fwd_and_grads():
    """Ring attention with an ALiBi/T5-style additive per-head bias ==
    full attention; grads (incl. d(bias) — T5's bias is LEARNED) match."""
    b, s, h, d = 2, 32, 4, 8
    rs = np.random.RandomState(7)
    q, k, v = _qkv(rs, b, s, h, d)
    bias = _alibi_bias(h, s)

    ref = xla_attention(q, k, v, attn_mask=bias, is_causal=True)
    ref_g = jax.grad(lambda q, k, v, bi: jnp.sum(
        xla_attention(q, k, v, attn_mask=bi, is_causal=True) ** 2),
        argnums=(0, 1, 2, 3))(q, k, v, bias)

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=True,
                                     bias_shape=bias.shape)
        out = attend(q, k, v, bias)
        got_g = jax.grad(lambda q, k, v, bi: jnp.sum(
            attend(q, k, v, bi) ** 2), argnums=(0, 1, 2, 3))(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.slow
def test_ring_bias_composes_with_bool_mask_and_gqa():
    """Additive bias + dense bool mask + GQA heads through the ring."""
    b, s, h, d = 2, 16, 4, 4
    rs = np.random.RandomState(8)
    q, k, v = _qkv(rs, b, s, h, d, hkv=2)
    bias = _alibi_bias(h, s)
    mask = jnp.asarray(rs.rand(b, s, s) > 0.3) | jnp.eye(s, dtype=bool)[None]
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None]
    ref_mask = jnp.where(mask[:, None] & causal, bias, -1e30)
    ref = xla_attention(q, k, v, attn_mask=ref_mask)

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=True, masked=True,
                                     bias_shape=bias.shape)
        out = attend(q, k, v, mask, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_ulysses_additive_per_head_bias_fwd_and_grads():
    """Ulysses with a per-head additive bias: the bias head dim shards
    over sp to match the post-all_to_all head slice; fwd + grads parity."""
    b, s, h, d = 2, 32, 8, 4
    rs = np.random.RandomState(9)
    q, k, v = _qkv(rs, b, s, h, d)
    bias = _alibi_bias(h, s)

    ref = xla_attention(q, k, v, attn_mask=bias, is_causal=True)
    ref_g = jax.grad(lambda q, k, v, bi: jnp.sum(
        xla_attention(q, k, v, attn_mask=bi, is_causal=True) ** 2),
        argnums=(0, 1, 2, 3))(q, k, v, bias)

    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ulysses_attention(mesh, causal=True,
                                        bias_shape=bias.shape)
        out = attend(q, k, v, bias)
        got_g = jax.grad(lambda q, k, v, bi: jnp.sum(
            attend(q, k, v, bi) ** 2), argnums=(0, 1, 2, 3))(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.slow
def test_ulysses_per_head_bias_composes_with_tp():
    """tp x sp: bias heads shard (tp-major, sp-minor) to exactly the head
    range each device computes after the all_to_all."""
    b, s, h, d = 2, 16, 8, 4
    rs = np.random.RandomState(10)
    q, k, v = _qkv(rs, b, s, h, d)
    bias = _alibi_bias(h, s)
    ref = xla_attention(q, k, v, attn_mask=bias, is_causal=True)

    mesh = HybridMesh(tp=2, sp=4)
    with mesh:
        attend = make_ulysses_attention(mesh, causal=True, head_spec="tp",
                                        bias_shape=bias.shape)
        out = attend(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_bert_varlen_matches_dense_mask():
    """BERT varlen_attention (kv_lens fused path) == additive-mask path on
    valid positions."""
    from paddle_tpu.models.bert import BertConfig, BertModel

    kw = dict(vocab_size=100, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=64,
              hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    pt.seed(0)
    m_dense = BertModel(BertConfig(**kw))
    pt.seed(0)
    m_varlen = BertModel(BertConfig(varlen_attention=True, **kw))

    rs = np.random.RandomState(5)
    b, s = 2, 24
    ids = jnp.asarray(rs.randint(0, 100, (b, s)))
    lens = np.asarray([24, 11])
    mask = jnp.asarray((np.arange(s)[None, :] < lens[:, None])
                       .astype(np.int64))

    seq_d, _ = m_dense(ids, attention_mask=mask)
    seq_v, _ = m_varlen(ids, attention_mask=mask)
    valid = np.asarray(mask)[..., None].astype(bool)
    np.testing.assert_allclose(np.asarray(seq_v) * valid,
                               np.asarray(seq_d) * valid,
                               rtol=1e-4, atol=1e-5)
