"""Stochastic speculative sampling preserves the target distribution:
the acceptance-rejection rule (accept w.p. min(1, p/q), residual
resample) makes the emitted stream distributed exactly as sampling the
target alone — verified statistically against the exactly-computed
target marginal, with the draft's own marginal as the power check."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.speculative import (speculative_generate,
                                           speculative_sample)


def _models(vocab=16, sharpen=False):
    pt.seed(0)
    target = LlamaForCausalLM(LlamaConfig.tiny(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, vocab_size=vocab,
        tie_word_embeddings=False))
    pt.seed(1)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        num_hidden_layers=1, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, vocab_size=vocab,
        tie_word_embeddings=False))
    if sharpen:
        # random tiny models are both near-uniform; a PEAKED target vs a
        # flat draft gives the distribution test statistical power
        target.lm_head = target.lm_head * 24.0
        draft.lm_head = draft.lm_head * 0.5
    return target, draft


def test_temperature_zero_falls_back_to_lossless_greedy():
    target, draft = _models()
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 16, (1, 5)))
    ref, _ = speculative_generate(target, draft, ids, max_new_tokens=6,
                                  gamma=2)
    got, _ = speculative_sample(target, draft, ids, max_new_tokens=6,
                                gamma=2, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.slow
def test_sampling_matches_target_distribution():
    vocab = 16
    target, draft = _models(vocab, sharpen=True)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, vocab, (1, 5))

    def dist(model, prefix):
        lg = np.asarray(model(jnp.asarray(prefix)), np.float32)[0, -1]
        e = np.exp(lg - lg.max())
        return e / e.sum()

    # exact second-token marginal under the target (and under the draft,
    # as the power check: the sampler must track p, not q)
    p1 = dist(target, ids)
    q1 = dist(draft, ids)
    p_marg = np.zeros(vocab)
    q_marg = np.zeros(vocab)
    for t1 in range(vocab):
        ext = np.concatenate([ids, [[t1]]], axis=1)
        p_marg += p1[t1] * dist(target, ext)
        q_marg += q1[t1] * dist(draft, ext)

    n = 1200
    counts = np.zeros(vocab)
    for seed in range(n):
        out, _ = speculative_sample(target, draft, jnp.asarray(ids),
                                    max_new_tokens=2, gamma=2, seed=seed)
        counts[int(np.asarray(out)[0, ids.shape[1] + 1])] += 1
    emp = counts / n

    tv_target = 0.5 * np.abs(emp - p_marg).sum()
    tv_draft = 0.5 * np.abs(emp - q_marg).sum()
    ref_gap = 0.5 * np.abs(p_marg - q_marg).sum()
    assert ref_gap > 0.15, "power check needs distinguishable models"
    assert tv_target < 0.12, (tv_target, ref_gap)
    assert tv_target < tv_draft, (tv_target, tv_draft)
