"""paddle_tpu.distribution vs torch.distributions golden values."""
import numpy as np
import pytest
import torch
import torch.distributions as td

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distribution as D


@pytest.fixture(autouse=True)
def _seed():
    pt.seed(0)


def _t(x):
    return torch.tensor(np.asarray(x, np.float32))


PAIRS = [
    (lambda: D.Normal(0.5, 1.3), lambda: td.Normal(_t(0.5), _t(1.3))),
    (lambda: D.Uniform(-1.0, 2.0), lambda: td.Uniform(_t(-1.0), _t(2.0))),
    (lambda: D.Laplace(0.3, 0.8), lambda: td.Laplace(_t(0.3), _t(0.8))),
    (lambda: D.Gumbel(0.1, 1.2), lambda: td.Gumbel(_t(0.1), _t(1.2))),
    (lambda: D.Exponential(1.7), lambda: td.Exponential(_t(1.7))),
    (lambda: D.Gamma(2.0, 3.0), lambda: td.Gamma(_t(2.0), _t(3.0))),
    (lambda: D.Beta(2.0, 3.0), lambda: td.Beta(_t(2.0), _t(3.0))),
    (lambda: D.LogNormal(0.2, 0.5), lambda: td.LogNormal(_t(0.2), _t(0.5))),
    (lambda: D.Cauchy(0.0, 1.0), lambda: td.Cauchy(_t(0.0), _t(1.0))),
    (lambda: D.StudentT(5.0, 0.1, 1.1), lambda: td.StudentT(_t(5.0), _t(0.1), _t(1.1))),
]


@pytest.mark.parametrize("mk_p,mk_t", PAIRS,
                         ids=[p[0]().__class__.__name__ for p in PAIRS])
def test_log_prob_matches_torch(mk_p, mk_t):
    p, t = mk_p(), mk_t()
    # evaluate inside each distribution's support
    lo = {"Uniform": -0.9, "Exponential": 0.1, "Gamma": 0.1, "Beta": 0.05,
          "LogNormal": 0.1}.get(type(p).__name__, -2.0)
    hi = {"Uniform": 1.9, "Beta": 0.95}.get(type(p).__name__, 3.0)
    xs = np.linspace(lo, hi, 7).astype(np.float32)
    got = np.asarray(p.log_prob(jnp.asarray(xs)))
    want = t.log_prob(torch.tensor(xs)).numpy()
    assert np.allclose(got, want, rtol=1e-4, atol=1e-5), type(p).__name__
    if hasattr(t, "entropy") and type(p).__name__ not in ("StudentT",):
        try:
            want_e = t.entropy().numpy()
        except NotImplementedError:
            return
        got_e = np.asarray(p.entropy())
        assert np.allclose(got_e, want_e, rtol=1e-4, atol=1e-5), type(p).__name__


def test_discrete_log_prob():
    b = D.Bernoulli(probs=0.3)
    tb = td.Bernoulli(_t(0.3))
    for v in (0.0, 1.0):
        assert np.allclose(float(b.log_prob(jnp.asarray(v))),
                           tb.log_prob(_t(v)).item(), rtol=1e-5)
    c = D.Categorical(logits=jnp.asarray([0.1, 0.5, -0.3]))
    tc = td.Categorical(logits=_t([0.1, 0.5, -0.3]))
    for v in range(3):
        assert np.allclose(float(c.log_prob(jnp.asarray(v))),
                           tc.log_prob(torch.tensor(v)).item(), rtol=1e-5)
    assert np.allclose(float(c.entropy()), tc.entropy().item(), rtol=1e-5)
    g = D.Geometric(0.4)
    tg = td.Geometric(_t(0.4))
    assert np.allclose(float(g.log_prob(jnp.asarray(3.0))),
                       tg.log_prob(_t(3.0)).item(), rtol=1e-5)
    po = D.Poisson(2.5)
    tp = td.Poisson(_t(2.5))
    assert np.allclose(float(po.log_prob(jnp.asarray(4.0))),
                       tp.log_prob(_t(4.0)).item(), rtol=1e-5)
    m = D.Multinomial(5, jnp.asarray([0.2, 0.3, 0.5]))
    tm = td.Multinomial(5, probs=_t([0.2, 0.3, 0.5]))
    v = np.array([1.0, 2.0, 2.0], np.float32)
    assert np.allclose(float(m.log_prob(jnp.asarray(v))),
                       tm.log_prob(torch.tensor(v)).item(), rtol=1e-5)
    d = D.Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
    tdd = td.Dirichlet(_t([1.0, 2.0, 3.0]))
    v = np.array([0.2, 0.3, 0.5], np.float32)
    assert np.allclose(float(d.log_prob(jnp.asarray(v))),
                       tdd.log_prob(torch.tensor(v)).item(), rtol=1e-4)
    assert np.allclose(float(d.entropy()), tdd.entropy().item(), rtol=1e-4)


def test_sampling_moments():
    n = D.Normal(jnp.asarray([0.0, 2.0]), jnp.asarray([1.0, 0.5]))
    s = n.sample((20000,), rng=jax.random.PRNGKey(0))
    assert s.shape == (20000, 2)
    assert np.allclose(np.asarray(s.mean(0)), [0.0, 2.0], atol=0.05)
    assert np.allclose(np.asarray(s.std(0)), [1.0, 0.5], atol=0.05)
    g = D.Gamma(3.0, 2.0).sample((20000,), rng=jax.random.PRNGKey(1))
    assert abs(float(g.mean()) - 1.5) < 0.05
    c = D.Categorical(probs=jnp.asarray([0.2, 0.8]))
    cs = c.sample((10000,), rng=jax.random.PRNGKey(2))
    assert abs(float((cs == 1).mean()) - 0.8) < 0.02
    m = D.Multinomial(10, jnp.asarray([0.5, 0.5])).sample(
        (100,), rng=jax.random.PRNGKey(3))
    assert np.all(np.asarray(m.sum(-1)) == 10)
    # rsample is differentiable (reparameterised)
    grad = jax.grad(lambda mu: D.Normal(mu, 1.0).rsample(
        (100,), rng=jax.random.PRNGKey(4)).mean())(0.0)
    assert abs(float(grad) - 1.0) < 1e-5


@pytest.mark.parametrize("mk_p,mk_q,mk_tp,mk_tq", [
    (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(1.0, 2.0),
     lambda: td.Normal(_t(0.0), _t(1.0)), lambda: td.Normal(_t(1.0), _t(2.0))),
    (lambda: D.Beta(2.0, 3.0), lambda: D.Beta(4.0, 1.5),
     lambda: td.Beta(_t(2.0), _t(3.0)), lambda: td.Beta(_t(4.0), _t(1.5))),
    (lambda: D.Gamma(2.0, 1.0), lambda: D.Gamma(3.0, 2.0),
     lambda: td.Gamma(_t(2.0), _t(1.0)), lambda: td.Gamma(_t(3.0), _t(2.0))),
    (lambda: D.Exponential(1.0), lambda: D.Exponential(2.5),
     lambda: td.Exponential(_t(1.0)), lambda: td.Exponential(_t(2.5))),
    (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(0.5, 2.0),
     lambda: td.Laplace(_t(0.0), _t(1.0)), lambda: td.Laplace(_t(0.5), _t(2.0))),
    (lambda: D.Bernoulli(probs=0.3), lambda: D.Bernoulli(probs=0.6),
     lambda: td.Bernoulli(_t(0.3)), lambda: td.Bernoulli(_t(0.6))),
], ids=["normal", "beta", "gamma", "exponential", "laplace", "bernoulli"])
def test_kl_matches_torch(mk_p, mk_q, mk_tp, mk_tq):
    got = float(D.kl_divergence(mk_p(), mk_q()))
    want = td.kl_divergence(mk_tp(), mk_tq()).item()
    assert np.allclose(got, want, rtol=1e-4, atol=1e-6)


def test_transformed_distribution():
    base = D.Normal(0.0, 1.0)
    # exp(Normal) == LogNormal
    tdist = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(0.0, 1.0)
    xs = jnp.asarray([0.5, 1.0, 2.0])
    assert np.allclose(np.asarray(tdist.log_prob(xs)),
                       np.asarray(ln.log_prob(xs)), rtol=1e-5)
    # affine(Normal) == shifted/scaled Normal
    tdist2 = D.TransformedDistribution(base, [D.AffineTransform(2.0, 3.0)])
    n2 = D.Normal(2.0, 3.0)
    assert np.allclose(np.asarray(tdist2.log_prob(xs)),
                       np.asarray(n2.log_prob(xs)), rtol=1e-5)
    # tanh transform round-trip + jacobian sanity vs torch
    tt = D.TanhTransform()
    x = jnp.asarray([-1.5, 0.0, 0.7])
    assert np.allclose(np.asarray(tt.inverse(tt.forward(x))), np.asarray(x), atol=1e-5)
    want = td.TanhTransform().log_abs_det_jacobian(
        torch.tensor(np.asarray(x)), torch.tensor(np.tanh(np.asarray(x)))).numpy()
    assert np.allclose(np.asarray(tt.forward_log_det_jacobian(x)), want, atol=1e-5)


def test_kl_unregistered_raises():
    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0.0, 1.0), D.Gamma(1.0, 1.0))
