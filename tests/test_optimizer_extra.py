"""NAdam/RAdam vs torch reference steps; ASGD/Rprop semantics; LBFGS
convergence; new collectives; extra losses (SURVEY.md §2.4, §2.2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt


class _OneParam(pt.Module):
    def __init__(self, w):
        super().__init__()
        self.w = jnp.asarray(w)


def _run_steps(optimizer, w0, grads_seq):
    m = _OneParam(w0)
    state = optimizer.init(m)
    for g in grads_seq:
        gm = _OneParam(jnp.asarray(g))
        m, state = optimizer.step(m, gm, state)
    return np.asarray(m.w)


def _torch_steps(torch_opt_cls, w0, grads_seq, **kw):
    import torch
    p = torch.nn.Parameter(torch.tensor(np.asarray(w0)))
    o = torch_opt_cls([p], **kw)
    for g in grads_seq:
        p.grad = torch.tensor(np.asarray(g))
        o.step()
    return p.detach().numpy()


W0 = np.array([1.0, -2.0, 3.0], np.float32)
GRADS = [np.array([0.1, -0.2, 0.3], np.float32),
         np.array([-0.05, 0.1, 0.2], np.float32),
         np.array([0.2, 0.0, -0.1], np.float32)]


def test_nadam_matches_torch():
    import torch
    got = _run_steps(opt.NAdam(learning_rate=0.01), W0, GRADS)
    want = _torch_steps(torch.optim.NAdam, W0, GRADS, lr=0.01)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_radam_matches_torch():
    import torch
    got = _run_steps(opt.RAdam(learning_rate=0.01), W0, GRADS * 4)
    want = _torch_steps(torch.optim.RAdam, W0, GRADS * 4, lr=0.01)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rprop_matches_torch():
    import torch
    got = _run_steps(opt.Rprop(learning_rate=0.01), W0, GRADS)
    want = _torch_steps(torch.optim.Rprop, W0, GRADS, lr=0.01)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_asgd_averages_gradients():
    # batch_num=2: step uses mean of the last 2 grads
    o = opt.ASGD(learning_rate=0.1, batch_num=2)
    m = _OneParam(np.zeros(2, np.float32))
    state = o.init(m)
    g1 = _OneParam(np.array([1.0, 0.0], np.float32))
    g2 = _OneParam(np.array([0.0, 1.0], np.float32))
    m, state = o.step(m, g1, state)     # d = g1, p -= lr*d/2
    np.testing.assert_allclose(np.asarray(m.w), [-0.05, 0.0], atol=1e-6)
    m, state = o.step(m, g2, state)     # d = g1+g2
    np.testing.assert_allclose(np.asarray(m.w), [-0.1, -0.05], atol=1e-6)
    m, state = o.step(m, g2, state)     # d = g2+g2 (g1 evicted)
    np.testing.assert_allclose(np.asarray(m.w), [-0.1, -0.15], atol=1e-6)


def test_lbfgs_converges_on_quadratic():
    class M(pt.Module):
        def __init__(self):
            super().__init__()
            self.w = jnp.asarray(np.array([5.0, -3.0], np.float32))

    target = jnp.asarray(np.array([1.0, 2.0], np.float32))

    def loss_fn(m):
        d = m.w - target
        return jnp.sum(jnp.array([[2.0, 0.3], [0.3, 1.0]]) @ d * d)

    o = opt.LBFGS(learning_rate=1.0, max_iter=30, history_size=5)
    loss, m = o.minimize(loss_fn, M())
    assert float(loss) < 1e-8
    np.testing.assert_allclose(np.asarray(m.w), np.asarray(target), atol=1e-4)


def test_optimizers_jit_and_multiprecision():
    """New optimizers run under jit with bf16 params + fp32 masters."""
    # lr large enough that one step is visible at bf16 resolution
    for cls in (opt.NAdam, opt.RAdam, opt.Rprop, opt.ASGD):
        o = cls(learning_rate=0.5, multi_precision=True)
        m = _OneParam(jnp.asarray(W0, jnp.bfloat16))
        state = o.init(m)
        g = _OneParam(jnp.asarray(GRADS[0], jnp.bfloat16))
        step = jax.jit(lambda mm, gg, ss: o.step(mm, gg, ss))
        m2, state = step(m, g, state)
        assert m2.w.dtype == jnp.bfloat16
        assert not np.allclose(np.asarray(m2.w, np.float32),
                               np.asarray(m.w, np.float32))


# -- collectives -------------------------------------------------------------

def test_reduce_scatter_gather_p2p():
    from functools import partial
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import HybridMesh
    from paddle_tpu.distributed import collective as C

    mesh = HybridMesh(dp=4, devices=jax.devices()[:4])
    x = jnp.arange(8.0).reshape(4, 2)

    @partial(shard_map, mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    def do_reduce(v):
        return C.reduce(v, dst=1, op="sum", axis_name="dp")

    out = do_reduce(x)
    total = x.sum(axis=0)
    np.testing.assert_allclose(np.asarray(out[1]), total)          # dst got sum
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(x[0]))  # others keep

    @partial(shard_map, mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    def do_p2p(v):
        return C.send(v, dst=2, src=0, axis_name="dp")

    out = do_p2p(x)
    np.testing.assert_allclose(np.asarray(out[2]), np.asarray(x[0]))
    np.testing.assert_allclose(np.asarray(out[3]), np.asarray(x[3]))

    ys = jnp.arange(16.0).reshape(4, 4)

    @partial(shard_map, mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    def do_scatter2(v):
        return C.scatter(v.reshape(4), src=1, axis_name="dp").reshape(1, 1)

    out = do_scatter2(ys)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.asarray(ys[1]))


def test_all_gather_object_single_process():
    from paddle_tpu.distributed.collective import all_gather_object
    assert all_gather_object({"a": 1}) == [{"a": 1}]


# -- extra losses ------------------------------------------------------------

def test_dice_loss_perfect_prediction():
    label = jnp.asarray(np.array([[0], [1]], np.int64))
    probs = jax.nn.one_hot(label.squeeze(-1), 3)
    assert float(F.dice_loss(probs, label)) < 1e-4


def test_log_loss_matches_formula():
    p = jnp.asarray([0.9, 0.2])
    y = jnp.asarray([1.0, 0.0])
    got = np.asarray(F.log_loss(p, y))
    want = -np.log(np.array([0.9 + 1e-4, 0.8 + 1e-4]))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_npair_loss_finite_and_separates():
    rs = np.random.RandomState(0)
    anchor = jnp.asarray(rs.randn(4, 8).astype(np.float32))
    labels = jnp.asarray(np.array([0, 1, 2, 3]))
    # positives identical to anchors -> similarity strongest on diagonal
    tight = float(F.npair_loss(anchor * 10, anchor * 10, labels, l2_reg=0.0))
    loose = float(F.npair_loss(anchor * 10,
                               jnp.asarray(rs.randn(4, 8).astype(np.float32)) * 10,
                               labels, l2_reg=0.0))
    assert np.isfinite(tight) and tight < loose


def test_temporal_shift_moves_channels():
    nt, c, h, w = 4, 8, 2, 2   # 2 clips x 2 frames
    x = jnp.asarray(np.arange(nt * c * h * w, dtype=np.float32)
                    .reshape(nt, c, h, w))
    out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
    assert out.shape == x.shape
    # first quarter of channels at frame 0 now hold frame 1's values
    np.testing.assert_allclose(np.asarray(out[0, :2]), np.asarray(x[1, :2]))
    # last frame's shifted-back block is zero-padded
    np.testing.assert_allclose(np.asarray(out[1, :2]), 0.0)
    # middle quarter shifts forward
    np.testing.assert_allclose(np.asarray(out[1, 2:4]), np.asarray(x[0, 2:4]))
    # remainder untouched
    np.testing.assert_allclose(np.asarray(out[0, 4:]), np.asarray(x[0, 4:]))


def test_margin_cross_entropy_reduces_to_ce_without_margin():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(np.clip(rs.randn(4, 6), -1, 1).astype(np.float32))
    label = jnp.asarray(rs.randint(0, 6, 4))
    got = float(F.margin_cross_entropy(logits, label, margin1=1.0, margin2=0.0,
                                       margin3=0.0, scale=1.0))
    one_hot = jax.nn.one_hot(label, 6)
    want = float(jnp.mean(-jnp.sum(
        one_hot * jax.nn.log_softmax(logits, -1), -1)))
    assert abs(got - want) < 1e-5


def test_margin_cross_entropy_penalises_target():
    logits = jnp.asarray(np.array([[0.9, 0.1, -0.5]], np.float32))
    label = jnp.asarray([0])
    plain = float(F.margin_cross_entropy(logits, label, margin2=0.0, scale=8.0))
    margined = float(F.margin_cross_entropy(logits, label, margin2=0.5, scale=8.0))
    assert margined > plain  # margin makes the target harder


def test_dlpack_roundtrip():
    from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack
    x = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = from_dlpack(x)  # jax-to-jax via __dlpack__ protocol
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    import torch
    t = torch.arange(4, dtype=torch.float32)
    z = from_dlpack(t)
    np.testing.assert_allclose(np.asarray(z), t.numpy())


def test_iinfo_finfo():
    assert pt.iinfo(pt.int32).max == 2**31 - 1
    assert pt.finfo(pt.bfloat16).bits == 16


def test_set_grad_enabled_context():
    with pt.set_grad_enabled(False):
        pass
    assert pt.is_grad_enabled()
