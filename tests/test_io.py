"""IO tests: loader determinism, sharding, native reader (SURVEY.md §4)."""
import numpy as np
import pytest

from paddle_tpu.io import (
    BatchSampler,
    DataLoader,
    Dataset,
    DistributedBatchSampler,
    RandomSampler,
    Subset,
    TensorDataset,
    TokenBinDataset,
    random_split,
)


class _Square(Dataset):
    def __len__(self):
        return 10

    def __getitem__(self, i):
        return np.asarray([i, i * i])


def test_tensor_dataset_and_loader():
    xs = np.arange(20).reshape(10, 2)
    ys = np.arange(10)
    dl = DataLoader(TensorDataset(xs, ys), batch_size=4)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    assert batches[-1][0].shape == (2, 2)
    dl2 = DataLoader(TensorDataset(xs, ys), batch_size=4, drop_last=True)
    assert len(list(dl2)) == 2


def test_shuffle_deterministic_by_seed():
    dl_a = DataLoader(_Square(), batch_size=2, shuffle=True, seed=7)
    dl_b = DataLoader(_Square(), batch_size=2, shuffle=True, seed=7)
    a = [b[0].tolist() for b in dl_a]
    b = [b[0].tolist() for b in dl_b]
    # note: RandomSampler advances epoch per-iteration; same seed, epoch 0
    assert a == b


def test_random_split_and_subset():
    parts = random_split(_Square(), [7, 3])
    assert len(parts[0]) == 7 and len(parts[1]) == 3
    all_firsts = sorted(int(parts[0][i][0]) for i in range(7)) + \
        sorted(int(parts[1][i][0]) for i in range(3))
    assert sorted(all_firsts) == list(range(10))


def test_distributed_batch_sampler_partitions():
    ds = _Square()
    seen = []
    for rank in range(2):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=rank)
        for batch in s:
            seen += batch
    assert sorted(seen) == list(range(10))


def test_worker_prefetch_loader():
    dl = DataLoader(_Square(), batch_size=3, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    flat = np.concatenate([b[:, 0] for b in batches])
    assert sorted(flat.tolist()) == list(range(10))


def test_native_token_bin(tmp_path):
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, 5000, 100_000).astype(np.uint16)
    path = tmp_path / "toks.bin"
    tokens.tofile(path)
    ds = TokenBinDataset(str(path), batch_size=4, seq_len=64, seed=3,
                         num_batches=5)
    assert ds.num_tokens == 100_000
    batches = list(ds)
    assert len(batches) == 5
    for x, y in batches:
        assert x.shape == (4, 64) and y.shape == (4, 64)
        # label is input shifted by one within the same window
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
        assert x.min() >= 0 and x.max() < 5000
    # windows must come from the file
    x0 = batches[0][0][0]
    joined = tokens.astype(np.int32)
    pos = np.where(joined == x0[0])[0]
    assert any((joined[p:p + 64] == x0).all() for p in pos if p + 64 <= len(joined))
