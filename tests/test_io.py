"""IO tests: loader determinism, sharding, native reader (SURVEY.md §4)."""
import numpy as np
import pytest

from paddle_tpu.io import (
    BatchSampler,
    DataLoader,
    Dataset,
    DistributedBatchSampler,
    RandomSampler,
    Subset,
    TensorDataset,
    TokenBinDataset,
    random_split,
)


class _Square(Dataset):
    def __len__(self):
        return 10

    def __getitem__(self, i):
        return np.asarray([i, i * i])


def test_tensor_dataset_and_loader():
    xs = np.arange(20).reshape(10, 2)
    ys = np.arange(10)
    dl = DataLoader(TensorDataset(xs, ys), batch_size=4)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    assert batches[-1][0].shape == (2, 2)
    dl2 = DataLoader(TensorDataset(xs, ys), batch_size=4, drop_last=True)
    assert len(list(dl2)) == 2


def test_shuffle_deterministic_by_seed():
    dl_a = DataLoader(_Square(), batch_size=2, shuffle=True, seed=7)
    dl_b = DataLoader(_Square(), batch_size=2, shuffle=True, seed=7)
    a = [b[0].tolist() for b in dl_a]
    b = [b[0].tolist() for b in dl_b]
    # note: RandomSampler advances epoch per-iteration; same seed, epoch 0
    assert a == b


def test_random_split_and_subset():
    parts = random_split(_Square(), [7, 3])
    assert len(parts[0]) == 7 and len(parts[1]) == 3
    all_firsts = sorted(int(parts[0][i][0]) for i in range(7)) + \
        sorted(int(parts[1][i][0]) for i in range(3))
    assert sorted(all_firsts) == list(range(10))


def test_distributed_batch_sampler_partitions():
    ds = _Square()
    seen = []
    for rank in range(2):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=rank)
        for batch in s:
            seen += batch
    assert sorted(seen) == list(range(10))


def test_worker_prefetch_loader():
    dl = DataLoader(_Square(), batch_size=3, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    flat = np.concatenate([b[:, 0] for b in batches])
    assert sorted(flat.tolist()) == list(range(10))


def test_native_token_bin(tmp_path):
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, 5000, 100_000).astype(np.uint16)
    path = tmp_path / "toks.bin"
    tokens.tofile(path)
    ds = TokenBinDataset(str(path), batch_size=4, seq_len=64, seed=3,
                         num_batches=5)
    assert ds.num_tokens == 100_000
    batches = list(ds)
    assert len(batches) == 5
    for x, y in batches:
        assert x.shape == (4, 64) and y.shape == (4, 64)
        # label is input shifted by one within the same window
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
        assert x.min() >= 0 and x.max() < 5000
    # windows must come from the file
    x0 = batches[0][0][0]
    joined = tokens.astype(np.int32)
    pos = np.where(joined == x0[0])[0]
    assert any((joined[p:p + 64] == x0).all() for p in pos if p + 64 <= len(joined))


# -- multiprocess workers ----------------------------------------------------

class _SquareDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i * i], np.int64)


class _FailingDataset(_SquareDataset):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return super().__getitem__(i)


def test_mp_workers_match_serial():
    from paddle_tpu.io import DataLoader
    ds = _SquareDataset(23)
    serial = [b for b in DataLoader(ds, batch_size=4, num_workers=0)]
    parallel = [b for b in DataLoader(ds, batch_size=4, num_workers=3)]
    assert len(serial) == len(parallel)
    for a, b in zip(serial, parallel):
        np.testing.assert_array_equal(a, b)


def test_mp_workers_shuffle_deterministic():
    from paddle_tpu.io import DataLoader
    ds = _SquareDataset(17)
    a = [b for b in DataLoader(ds, batch_size=4, shuffle=True, seed=7,
                               num_workers=2)]
    b = [b for b in DataLoader(ds, batch_size=4, shuffle=True, seed=7,
                               num_workers=0)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_mp_worker_error_propagates():
    from paddle_tpu.io import DataLoader
    import pytest as _pytest
    ds = _FailingDataset(8)
    with _pytest.raises(RuntimeError, match="boom at 5"):
        list(DataLoader(ds, batch_size=2, num_workers=2))


def test_get_worker_info():
    from paddle_tpu.io import DataLoader, get_worker_info
    assert get_worker_info() is None

    class _InfoDataset(_SquareDataset):
        def __getitem__(self, i):
            info = get_worker_info()
            assert info is not None and 0 <= info.id < info.num_workers
            return np.asarray([info.num_workers], np.int64)

    out = list(DataLoader(_InfoDataset(6), batch_size=2, num_workers=2))
    assert all(int(b[0, 0]) == 2 for b in out)


def test_threaded_iterable_error_propagates():
    from paddle_tpu.io import DataLoader, IterableDataset
    import pytest as _pytest

    class _Boom(IterableDataset):
        def __iter__(self):
            yield np.zeros(1)
            raise ValueError("iterable boom")

    with _pytest.raises(ValueError, match="iterable boom"):
        list(DataLoader(_Boom(), batch_size=1, num_workers=1))


def test_worker_seed_from_loader_seed():
    from paddle_tpu.io import DataLoader, get_worker_info

    class _SeedDataset(_SquareDataset):
        def __getitem__(self, i):
            return np.asarray([get_worker_info().seed], np.int64)

    out = list(DataLoader(_SeedDataset(4), batch_size=1, num_workers=2,
                          seed=1234))
    seeds = {int(b[0, 0]) for b in out}
    assert seeds <= {1234, 1235} and len(seeds) >= 1
