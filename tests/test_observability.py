"""Observability subsystem (ISSUE 2): registry semantics, histogram
bucketing, export golden-formats, span nesting/Chrome-trace validity,
disabled-mode no-ops — plus the acceptance runs: a serving chaos run and
a trainer run, each dumping metrics (JSON + Prometheus) and a valid
Chrome trace with the fault-injection / preemption / NaN-skip events
visible."""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import observability as obs
from paddle_tpu.observability import METRICS, TRACER, dump, span, instant
from paddle_tpu.observability.flops import (PEAK_BF16, chip_peak_flops, mfu,
                                            record_throughput)
from paddle_tpu.observability.metrics import MetricsRegistry


# ------------------------------------------------------------- registry

def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_get_or_create_same_instrument():
    reg = MetricsRegistry()
    a = reg.counter("x_total")
    b = reg.counter("x_total")
    assert a is b
    # conflicting re-registration (different kind or labels) raises
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("site",))


def test_labels_and_prebound():
    reg = MetricsRegistry()
    c = reg.counter("hits_total", labelnames=("site",))
    c.inc(site="a")
    c.inc(2, site="b")
    bound = c.labels(site="a")
    bound.inc(3)
    assert c.value(site="a") == 4
    assert c.value(site="b") == 2
    with pytest.raises(ValueError):
        c.inc(wrong="a")            # undeclared label
    with pytest.raises(ValueError):
        c.inc()                     # missing declared label


def test_gauge_set_inc_dec():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value() == 13


def test_histogram_bucket_boundaries_le_inclusive():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    snap = h.value()
    # le is INCLUSIVE (Prometheus): 0.1 falls in the 0.1 bucket
    assert snap["buckets"] == {"0.1": 2, "1": 4, "10": 5, "+Inf": 6}
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(106.65)


def test_histogram_rejects_bad_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=(1.0, 1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("bad2", buckets=())


# -------------------------------------------------------------- exports

def _tiny_registry():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "requests served", labelnames=("code",)) \
       .inc(3, code="200")
    reg.gauge("depth", "queue depth").set(2)
    reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)) \
       .observe(0.05)
    return reg


def test_json_export_one_line_golden():
    reg = _tiny_registry()
    line = reg.to_json()
    assert "\n" not in line
    assert json.loads(line) == {
        "counters": {'reqs_total{code="200"}': 3},
        "gauges": {"depth": 2},
        "histograms": {"lat_seconds": {
            "buckets": {"0.1": 1, "1": 1, "+Inf": 1},
            "sum": 0.05, "count": 1}},
    }


def test_prometheus_export_golden():
    text = _tiny_registry().to_prometheus()
    assert text == (
        "# HELP depth queue depth\n"
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 1\n'
        'lat_seconds_bucket{le="+Inf"} 1\n'
        "lat_seconds_sum 0.05\n"
        "lat_seconds_count 1\n"
        "# HELP reqs_total requests served\n"
        "# TYPE reqs_total counter\n"
        'reqs_total{code="200"} 3\n'
    )


def test_disabled_registry_is_noop():
    reg = _tiny_registry()
    before = reg.to_json()
    reg.disable()
    reg.counter("reqs_total", labelnames=("code",)).inc(99, code="200")
    reg.gauge("depth").set(999)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(9.9)
    assert reg.to_json() == before      # export still works, frozen
    reg.enable()
    reg.gauge("depth").set(7)
    assert reg.get("depth").value() == 7


# -------------------------------------------------------------- tracing

def test_span_nesting_and_chrome_trace_validity():
    TRACER.enable()
    with span("outer", step=1):
        with span("inner"):
            pass
        instant("marker", kind="test")
    doc = json.loads(TRACER.export_chrome_trace())
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"outer", "inner", "marker"}
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert by_name["marker"]["ph"] == "i"
    # nesting: inner's [ts, ts+dur) is contained in outer's
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["tid"] == threading.get_ident()
    assert outer["args"] == {"step": 1}


def test_span_decorator_honors_later_enablement():
    @span("decorated")
    def f():
        return 42

    assert f() == 42                    # tracer off: no event, value intact
    assert TRACER.export()["traceEvents"] == []
    TRACER.enable()
    assert f() == 42
    assert [e["name"] for e in TRACER.export()["traceEvents"]] == ["decorated"]


def test_disabled_tracer_records_nothing():
    with span("ghost"):
        instant("ghost-marker")
    assert TRACER.export()["traceEvents"] == []


def test_tracer_event_cap_counts_drops():
    from paddle_tpu.observability.tracing import Tracer
    t = Tracer(max_events=2)
    t.enable()
    for i in range(4):
        t.instant(f"e{i}")
    assert len(t.export()["traceEvents"]) == 2
    assert t.export()["otherData"]["dropped_events"] == 2


def test_dump_writes_three_artifacts(tmp_path):
    METRICS.counter("dump_probe_total").inc()
    TRACER.enable()
    with span("probe"):
        pass
    paths = dump(str(tmp_path / "snap"))
    blob = json.loads((tmp_path / "snap.metrics.json").read_text())
    assert blob["counters"]["dump_probe_total"] == 1
    assert "dump_probe_total 1" in (tmp_path / "snap.prom").read_text()
    trace = json.loads((tmp_path / "snap.trace.json").read_text())
    assert [e["name"] for e in trace["traceEvents"]] == ["probe"]
    assert set(paths) == {"json", "prom", "trace"}


# ------------------------------------------------------- FLOPs/MFU table

def test_flops_table_and_throughput_choke_point():
    assert chip_peak_flops(kind="TPU v5 lite") == PEAK_BF16["TPU v5 lite"]
    assert chip_peak_flops(kind="TPU v5p") == PEAK_BF16["TPU v5p"]
    assert chip_peak_flops(kind="cpu") == 0.0
    assert mfu(1000.0, 1e9, 0.0) == 0.0         # unknown peak → undefined
    got = record_throughput(1000.0, 1e9, 2e12)
    assert got == pytest.approx(0.5)
    snap = METRICS.snapshot()["gauges"]
    assert snap["train_tokens_per_sec"] == 1000.0
    assert snap["train_mfu"] == pytest.approx(0.5)


# --------------------------------------------------- acceptance: serving

@pytest.mark.chaos
def test_serving_chaos_run_dumps_full_telemetry(tmp_path):
    """A chaos-driven serve (induced preemptions + allocator faults)
    leaves a complete telemetry story: counters in JSON and Prometheus,
    latency histograms populated, and a valid Chrome trace whose
    timeline shows the engine ticks AND each injected fault."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import LLMEngine, Request
    from paddle_tpu.utils.faults import FAULTS

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    model = LlamaForCausalLM(cfg)
    FAULTS.install("serving.preempt", every=4, times=4,
                   action=lambda ctx: ctx["engine"]._preempt())
    TRACER.enable()
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    rs = np.random.RandomState(0)
    for n in rs.randint(4, 10, 4):
        eng.add_request(Request(rs.randint(0, 64, (int(n),)),
                                max_new_tokens=6))
    ticks = 0
    while eng.has_work():
        eng.step()
        ticks += 1
        assert ticks < 200
    eng.assert_quiescent()
    paths = dump(str(tmp_path / "serve"))

    blob = json.loads(open(paths["json"]).read())
    ctr, hist = blob["counters"], blob["histograms"]
    assert ctr["serving_admissions_total"] >= 4
    assert ctr["serving_preemptions_total"] > 0
    assert ctr['faults_injected_total{site="serving.preempt"}'] > 0
    assert ctr["serving_tokens_total"] >= 4 * 6
    assert hist["serving_ttft_seconds"]["count"] >= 4
    assert hist["serving_tick_seconds"]["count"] == ticks

    prom = open(paths["prom"]).read()
    assert "# TYPE serving_preemptions_total counter" in prom
    assert 'serving_ttft_seconds_bucket{le="+Inf"}' in prom

    trace = json.loads(open(paths["trace"]).read())
    names = [e["name"] for e in trace["traceEvents"]]
    assert names.count("serving.step") == ticks
    assert "fault:serving.preempt" in names
    faults = [e for e in trace["traceEvents"]
              if e["name"] == "fault:serving.preempt"]
    assert all(e["ph"] == "i" for e in faults)


# --------------------------------------------------- acceptance: trainer

@pytest.mark.chaos
def test_trainer_chaos_run_dumps_full_telemetry(tmp_path):
    """A short training run with an injected NaN storm dumps telemetry
    showing the steps, the skips, and where each fault landed on the
    span timeline."""
    from paddle_tpu.train.trainer import Trainer, TrainerArgs
    from paddle_tpu.utils.faults import FAULTS

    pt.seed(0)
    m = nn.Linear(4, 1)
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                 TrainerArgs(max_steps=6, log_every=0, max_bad_steps=10))
    FAULTS.install("train.loss", on={1, 3}, action=lambda c: float("nan"))
    TRACER.enable()
    rs = np.random.RandomState(0)
    data = ((rs.randn(2, 4).astype(np.float32),
             rs.randn(2, 1).astype(np.float32)) for _ in range(6))
    state = tr.fit(data)
    assert int(state.step) == 6
    paths = dump(str(tmp_path / "train"))

    blob = json.loads(open(paths["json"]).read())
    ctr = blob["counters"]
    assert ctr["train_steps_total"] == 6
    assert ctr["train_nan_skips_total"] == 2
    assert ctr['faults_injected_total{site="train.loss"}'] == 2
    assert blob["histograms"]["train_step_seconds"]["count"] == 6
    assert blob["gauges"]["train_loss"] == pytest.approx(
        tr.history[-1]["loss"] if tr.history else blob["gauges"]["train_loss"])

    prom = open(paths["prom"]).read()
    assert "train_nan_skips_total 2" in prom
    assert "# TYPE train_step_seconds histogram" in prom

    trace = json.loads(open(paths["trace"]).read())
    names = [e["name"] for e in trace["traceEvents"]]
    assert names.count("train.step") == 6
    assert names.count("fault:train.loss") == 2
