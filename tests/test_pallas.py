"""Pallas kernels vs XLA reference, interpret mode on CPU (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import apply_rope, rope_cos_sin, xla_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.norms import rms_norm
from paddle_tpu.ops.pallas.rope import fused_rope


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 256])
def test_flash_fwd_matches_xla(causal, seq):
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, seq, 2, 64).astype(np.float32)) for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_matches_xla(causal):
    rs = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rs.randn(1, 128, 2, 32).astype(np.float32)) for _ in range(3))
    ref = jax.grad(lambda *a: jnp.sum(xla_attention(*a, is_causal=causal) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=causal, interpret=True) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_varlen_kv_lens_matches_masked_xla(causal):
    """Padded-varlen path: kv_lens masking == dense key-padding mask, for
    valid query rows, fwd + grads (ref flash_attn varlen capability)."""
    rs = np.random.RandomState(3)
    b, s, h, d = 3, 256, 2, 32
    lens = jnp.asarray([256, 130, 7], jnp.int32)
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
               for _ in range(3))
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]

    ref = xla_attention(q, k, v, attn_mask=pad, is_causal=causal)
    got = flash_attention(q, k, v, causal=causal, kv_lens=lens,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got * valid_q),
                               np.asarray(ref * valid_q),
                               rtol=1e-5, atol=1e-5)

    # grads: loss only over valid query rows (callers mask the padding)
    def loss(attend):
        def f(q, k, v):
            out = attend(q, k, v)
            return jnp.sum((out * valid_q) ** 2)
        return f

    ref_g = jax.grad(loss(lambda q, k, v: xla_attention(
        q, k, v, attn_mask=pad, is_causal=causal)), argnums=(0, 1, 2))(q, k, v)
    got_g = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, kv_lens=lens, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_flash_varlen_gqa_and_padded_rows_zero():
    """kv_lens composes with GQA; a row with ZERO valid keys (fully-masked
    softmax) emits exact zeros and finite (zero) grads, not NaN."""
    rs = np.random.RandomState(4)
    b, s, h, hkv, d = 2, 128, 4, 2, 32
    lens = jnp.asarray([128, 64], jnp.int32)
    q = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, s, hkv, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, s, hkv, d).astype(np.float32))
    out = flash_attention(q, k, v, causal=False, kv_lens=lens, interpret=True)
    assert np.all(np.isfinite(np.asarray(out)))

    # GQA + kv_lens matches repeated-KV dense-masked reference on valid rows
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    ref = xla_attention(q, k, v, attn_mask=pad, is_causal=False)
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]
    np.testing.assert_allclose(np.asarray(out * valid_q),
                               np.asarray(ref * valid_q), rtol=1e-5, atol=1e-5)

    # a row with NO valid keys: fully-masked softmax -> zero rows, zero grads
    lens0 = jnp.asarray([128, 0], jnp.int32)
    out0 = flash_attention(q, k, v, causal=False, kv_lens=lens0,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out0[1]), 0.0, atol=1e-6)
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, causal=False, kv_lens=lens0, interpret=True) ** 2))(q)
    assert np.all(np.isfinite(np.asarray(g))), "masked rows must not NaN grads"
    np.testing.assert_allclose(np.asarray(g[1]), 0.0, atol=1e-6)


def test_sdpa_dispatch_kv_lens_xla_path():
    """scaled_dot_product_attention honours kv_lens on the XLA path too."""
    from paddle_tpu.ops.attention import scaled_dot_product_attention
    rs = np.random.RandomState(5)
    b, s, h, d = 2, 64, 2, 16
    lens = jnp.asarray([64, 20], jnp.int32)
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
               for _ in range(3))
    pad = (jnp.arange(s)[None, :] < lens[:, None])[:, None, None, :]
    ref = xla_attention(q, k, v, attn_mask=pad)
    got = scaled_dot_product_attention(q, k, v, kv_lens=lens)
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, :, None, None]
    np.testing.assert_allclose(np.asarray(got * valid_q),
                               np.asarray(ref * valid_q), rtol=1e-5, atol=1e-5)


def test_flash_bf16():
    rs = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rs.randn(1, 128, 2, 64)).astype(jnp.bfloat16) for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32), rtol=2e-2, atol=2e-2)


def test_rms_norm_kernel():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 8, 256).astype(np.float32))
    w = jnp.asarray(rs.rand(256).astype(np.float32) + 0.5)
    ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
    got = rms_norm(x, w, 1e-6, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
    # grads
    rg = jax.grad(lambda x, w: jnp.sum(
        (x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w) ** 2),
        argnums=(0, 1))(x, w)
    gg = jax.grad(lambda x, w: jnp.sum(rms_norm(x, w, 1e-6, True) ** 2),
                  argnums=(0, 1))(x, w)
    for r, g in zip(rg, gg):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


def test_fused_rope_matches_reference():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 16, 4, 64).astype(np.float32))
    cos, sin = rope_cos_sin(16, 64)
    ref = apply_rope(x, cos, sin)
    got = fused_rope(x, cos, sin, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_alibi_matches_xla(causal):
    """In-tile ALiBi (iota-computed, no O(S^2) bias tensor) == the XLA
    path's materialised additive bias, fwd + grads."""
    rs = np.random.RandomState(5)
    h = 4
    q, k, v = (jnp.asarray(rs.randn(2, 128, h, 32).astype(np.float32))
               for _ in range(3))
    slopes = jnp.asarray(2.0 ** (-np.arange(1, h + 1)), jnp.float32)

    ref = xla_attention(q, k, v, is_causal=causal, alibi_slopes=slopes)
    got = flash_attention(q, k, v, causal=causal, alibi_slopes=slopes,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    ref_g = jax.grad(lambda *a: jnp.sum(xla_attention(
        *a, is_causal=causal, alibi_slopes=slopes) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    got_g = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=causal, alibi_slopes=slopes, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_flash_alibi_explicit_bias_reference():
    """The slope convention is exactly bias = -m * (q_pos - k_pos)."""
    rs = np.random.RandomState(6)
    h, s = 2, 128
    q, k, v = (jnp.asarray(rs.randn(1, s, h, 32).astype(np.float32))
               for _ in range(3))
    slopes = jnp.asarray([0.5, 0.25], jnp.float32)
    i = np.arange(s)[:, None]
    j = np.arange(s)[None, :]
    bias = jnp.asarray(-np.asarray(slopes)[None, :, None, None]
                       * (i - j)[None, None], jnp.float32)
    ref = xla_attention(q, k, v, attn_mask=bias, is_causal=True)
    got = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_alibi_gqa_decode_and_window():
    """ALiBi composes with GQA, end-aligned decode queries, a sliding
    window, and per-batch [B, H] slopes."""
    rs = np.random.RandomState(7)
    b, sk, h, hkv, d = 2, 256, 4, 2, 32
    k = jnp.asarray(rs.randn(b, sk, hkv, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, sk, hkv, d).astype(np.float32))
    slopes = jnp.asarray(rs.rand(b, h).astype(np.float32))

    # decode: 128 queries aligned to the end of the key axis
    q = jnp.asarray(rs.randn(b, 128, h, d).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=True, alibi_slopes=slopes)
    got = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # banded sliding window
    qf = jnp.asarray(rs.randn(b, sk, h, d).astype(np.float32))
    ref_w = xla_attention(qf, k, v, is_causal=True, window=64,
                          alibi_slopes=slopes)
    got_w = flash_attention(qf, k, v, causal=True, window=64,
                            alibi_slopes=slopes, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-5)


def test_flash_alibi_varlen_decode_alignment():
    """ALiBi + kv_lens + sq < sk: query positions end-align to each row's
    VALID cache length, not the padded buffer — kernel == per-row solo."""
    rs = np.random.RandomState(9)
    b, sk, sq, h, d = 2, 256, 128, 2, 32
    q = jnp.asarray(rs.randn(b, sq, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, sk, h, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, sk, h, d).astype(np.float32))
    lens = jnp.asarray([256, 170], jnp.int32)
    slopes = jnp.asarray([0.5, 0.125], jnp.float32)

    got = flash_attention(q, k, v, causal=True, kv_lens=lens,
                          alibi_slopes=slopes, interpret=True)
    ref = xla_attention(q, k, v, is_causal=True, kv_lens=lens,
                        alibi_slopes=slopes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # row 1 must equal a solo call against its TRIMMED cache (the ground
    # truth both paths claim to implement)
    solo = xla_attention(q[1:], k[1:, :170], v[1:, :170], is_causal=True,
                         alibi_slopes=slopes)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(solo[0]),
                               rtol=1e-5, atol=1e-5)


# ----------------------------------------------------- non-aligned lengths
@pytest.mark.parametrize("causal", [False, True])
def test_flash_nonaligned_length_pads_not_shrinks(causal):
    """ADVICE r4: s=1000 used to step the tile down to bq=8 (a ~64x
    smaller MXU tile); now the wrapper pads to an aligned length, masks
    the padded keys (causally or via kv_lens) and slices the tail. This
    exercises that path end-to-end: fwd + grads == XLA at s=1000."""
    rs = np.random.RandomState(7)
    s = 1000
    q, k, v = (jnp.asarray(rs.randn(1, s, 2, 32).astype(np.float32))
               for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    assert got.shape == (1, s, 2, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    g_ref = jax.grad(lambda *a: jnp.sum(
        xla_attention(*a, is_causal=causal) ** 2), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=causal, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_flash_nonaligned_decode_kv_pad():
    """Decode against a non-aligned cache (sq != sk, sk=1000): K/V pad +
    introduced kv_lens keep end-aligned query positions exact."""
    rs = np.random.RandomState(8)
    q = jnp.asarray(rs.randn(2, 128, 2, 32).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 1000, 2, 32).astype(np.float32))
    v = jnp.asarray(rs.randn(2, 1000, 2, 32).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_nonaligned_window():
    """Banded grid + equal q/k padding (s == sk keeps q_off == 0)."""
    rs = np.random.RandomState(9)
    s = 520
    q, k, v = (jnp.asarray(rs.randn(1, s, 2, 32).astype(np.float32))
               for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=True, window=128)
    got = flash_attention(q, k, v, causal=True, window=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
