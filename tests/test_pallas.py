"""Pallas kernels vs XLA reference, interpret mode on CPU (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import apply_rope, rope_cos_sin, xla_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.ops.pallas.norms import rms_norm
from paddle_tpu.ops.pallas.rope import fused_rope


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 256])
def test_flash_fwd_matches_xla(causal, seq):
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, seq, 2, 64).astype(np.float32)) for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_matches_xla(causal):
    rs = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rs.randn(1, 128, 2, 32).astype(np.float32)) for _ in range(3))
    ref = jax.grad(lambda *a: jnp.sum(xla_attention(*a, is_causal=causal) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=causal, interpret=True) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


def test_flash_bf16():
    rs = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rs.randn(1, 128, 2, 64)).astype(jnp.bfloat16) for _ in range(3))
    ref = xla_attention(q, k, v, is_causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32), rtol=2e-2, atol=2e-2)


def test_rms_norm_kernel():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 8, 256).astype(np.float32))
    w = jnp.asarray(rs.rand(256).astype(np.float32) + 0.5)
    ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
    got = rms_norm(x, w, 1e-6, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
    # grads
    rg = jax.grad(lambda x, w: jnp.sum(
        (x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w) ** 2),
        argnums=(0, 1))(x, w)
    gg = jax.grad(lambda x, w: jnp.sum(rms_norm(x, w, 1e-6, True) ** 2),
                  argnums=(0, 1))(x, w)
    for r, g in zip(rg, gg):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


def test_fused_rope_matches_reference():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 16, 4, 64).astype(np.float32))
    cos, sin = rope_cos_sin(16, 64)
    ref = apply_rope(x, cos, sin)
    got = fused_rope(x, cos, sin, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
