"""Quantized serving subsystem (ISSUE 17): weight-only int8/int4 via
``quantize_for_serving`` (dense + MoE expert stacks + SmoothQuant fold),
the int8 paged KV cache with per-(position, kv-head) scale pools —
kernel-level dequant parity, engine greedy identity, radix/COW
semantics, the cross-replica extract→ship→install wire with sealed
scale checksums, the ``PT_QUANT_KV`` trace-time kill-switch contract
(env flip requires ``clear_jit_caches``), the ``serving.kv_quant``
chaos site's exception-atomicity, and the actual-dtype bytes fixes in
``cache_block_bytes`` / roofline ``ModelGeometry``."""
import copy

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
from paddle_tpu.models.paged import PagedKVCache, clear_jit_caches
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.roofline import (ModelGeometry,
                                               kv_bytes_per_position,
                                               weight_bytes)
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.quantization import QuantizedWeight
from paddle_tpu.serving import LLMEngine, Replica, Request, Router
from paddle_tpu.serving.kv import cache_block_bytes
from paddle_tpu.serving.quant import (QuantizedExpertStack,
                                      expert_stack_quantize, quant_quality,
                                      quantize_for_serving,
                                      smooth_for_serving,
                                      weights_quant_enabled)
from paddle_tpu.serving.transfer import (DeviceKVTransfer, KVTransferError,
                                         validate_payload)
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module", autouse=True)
def _preserve_global_rng():
    from paddle_tpu.core import random as _prng
    saved = None if _prng._global is None else _prng._global.key
    yield
    if saved is None:
        _prng._global = None
    else:
        pt.seed(0)
        _prng._global.key = saved


@pytest.fixture(autouse=True)
def _fresh_jits():
    # PT_QUANT_KV is read at trace time: tests that flip it must not
    # inherit (or leak) traced programs keyed on another test's mode
    clear_jit_caches()
    yield
    clear_jit_caches()


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, dtype=jnp.float32)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft(model):
    pt.seed(1)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, dtype=jnp.float32)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=4, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14, vocab=64):
    return [rs.randint(1, vocab, (int(l),))
            for l in rs.randint(lo, hi, size=n)]


def _run(model, prompts, max_new=8, **ekw):
    eng = _mk(model, **ekw)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    out = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    eng.assert_quiescent()
    return out, eng


def _match_rate(a, b):
    pairs = [(x, y) for rid in a for x, y in zip(a[rid], b[rid])]
    return float(np.mean([x == y for x, y in pairs]))


# ------------------------------------------------- kernel dequant parity

def _quantize_pool(rng, n, bs, h_kv, d):
    f = rng.normal(size=(n, bs, h_kv, d)).astype(np.float32)
    scale = np.maximum(np.abs(f).max(axis=-1), 1e-8) / 127.0
    q = np.clip(np.round(f / scale[..., None]), -127, 127).astype(np.int8)
    deq = q.astype(np.float32) * scale[..., None]
    return jnp.asarray(q), jnp.asarray(scale), jnp.asarray(deq)


def test_decode_parity_quantized_pool():
    """Pallas-interpret and XLA decode over an int8 pool must both equal
    the f32 reference run over the dequantized pool."""
    rng = np.random.default_rng(0)
    b, h, h_kv, d, bs, mb, n = 3, 4, 2, 16, 8, 4, 24
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kq, ks, kd = _quantize_pool(rng, n, bs, h_kv, d)
    vq, vs, vd = _quantize_pool(rng, n, bs, h_kv, d)
    tables = np.full((b, mb), n, np.int32)
    lens = np.asarray([9, 17, 4], np.int32)
    for i in range(b):
        need = -(-int(lens[i]) // bs)
        tables[i, :need] = rng.choice(n, size=need, replace=False)
    tables = jnp.asarray(tables)
    ref = pa.paged_decode_attention_xla(q, kd, vd, tables, lens)
    out_x = pa.paged_decode_attention_xla(q, kq, vq, tables, lens,
                                          k_scale=ks, v_scale=vs)
    out_p = pa.paged_decode_attention_pallas(q, kq, vq, tables, lens,
                                             k_scale=ks, v_scale=vs,
                                             interpret=True)
    assert np.abs(np.asarray(out_x) - np.asarray(ref)).max() < 2e-5
    assert np.abs(np.asarray(out_p) - np.asarray(ref)).max() < 2e-5


def test_chunk_parity_quantized_pool():
    rng = np.random.default_rng(1)
    a, c, h, h_kv, d, bs, mb, n = 2, 5, 4, 2, 16, 8, 5, 24
    q = jnp.asarray(rng.normal(size=(a, c, h, d)), jnp.float32)
    kq, ks, kd = _quantize_pool(rng, n, bs, h_kv, d)
    vq, vs, vd = _quantize_pool(rng, n, bs, h_kv, d)
    offs = np.asarray([3, 11], np.int32)
    cls = np.asarray([5, 4], np.int32)
    tables = np.full((a, mb), n, np.int32)
    for i in range(a):
        need = -(-int(offs[i] + cls[i]) // bs)
        tables[i, :need] = rng.choice(n, size=need, replace=False)
    tables = jnp.asarray(tables)
    ref = pa.paged_chunk_attention_xla(q, kd, vd, tables, offs, cls)
    out_x = pa.paged_chunk_attention_xla(q, kq, vq, tables, offs, cls,
                                         k_scale=ks, v_scale=vs)
    out_p = pa.paged_chunk_attention_pallas(q, kq, vq, tables, offs, cls,
                                            k_scale=ks, v_scale=vs,
                                            interpret=True)
    for i, cl in enumerate(cls):
        assert np.abs(np.asarray(out_x)[i, :cl]
                      - np.asarray(ref)[i, :cl]).max() < 2e-5
        assert np.abs(np.asarray(out_p)[i, :cl]
                      - np.asarray(ref)[i, :cl]).max() < 2e-5


# ---------------------------------------------------------- cache init

def test_cache_init_int8_geometry():
    c = PagedKVCache.init(2, 8, 4, 2, 16, 3, 4, jnp.float32,
                          kv_dtype="int8")
    assert all(p.dtype == jnp.int8 for p in (*c.k_pools, *c.v_pools))
    assert len(c.k_scales) == 2 and len(c.v_scales) == 2
    # one f32 scale per (block, position, kv-head)
    assert c.k_scales[0].shape == (8, 4, 2)
    assert c.k_scales[0].dtype == jnp.float32


def test_cache_init_rejects_unsupported_kv_dtype():
    with pytest.raises(ValueError):
        PagedKVCache.init(2, 8, 4, 2, 16, 3, 4, jnp.float32,
                          kv_dtype="int4")


def test_cache_block_bytes_halves_at_real_head_dim():
    """At head_dim 64 the int8 pool (1 B codes + 4 B per-head scale) is
    ~0.53x the bf16 pool — the capacity win the subsystem exists for."""
    bf16 = PagedKVCache.init(2, 8, 16, 2, 64, 3, 4, jnp.bfloat16)
    int8 = PagedKVCache.init(2, 8, 16, 2, 64, 3, 4, jnp.bfloat16,
                             kv_dtype="int8")
    ratio = cache_block_bytes(int8) / cache_block_bytes(bf16)
    assert ratio <= 0.55, ratio


# ----------------------------------------------- engine greedy identity

def test_int8_kv_engine_matches_bf16_greedy(model):
    rs = np.random.RandomState(0)
    prompts = _prompts(6, rs)
    ref, _ = _run(model, prompts)
    out, eng = _run(model, prompts, kv_dtype="int8")
    assert eng.cache.k_scales and eng.cache.k_pools[0].dtype == jnp.int8
    # tiny random models have near-tied logits; on real checkpoints the
    # bench asserts >= 0.95 — here the fixed seed gives a high floor
    assert _match_rate(ref, out) >= 0.85


def test_kv_kill_switch_bitexact(model, monkeypatch):
    """PT_QUANT_KV=0 at construction: kv_dtype='int8' falls back to
    model-dtype pools and output is BIT-identical to the bf16 engine."""
    rs = np.random.RandomState(1)
    prompts = _prompts(4, rs)
    ref, _ = _run(model, prompts)
    monkeypatch.setenv("PT_QUANT_KV", "0")
    out, eng = _run(model, prompts, kv_dtype="int8")
    assert not eng.cache.k_scales          # bf16 pool: no scale pools
    assert eng.cache.k_pools[0].dtype == model.cfg.dtype
    assert out == ref


def test_weights_kill_switch_identity(model, monkeypatch):
    monkeypatch.setenv("PT_QUANT_WEIGHTS", "0")
    assert not weights_quant_enabled()
    m = quantize_for_serving(copy.deepcopy(model), "weight_only_int8")
    assert getattr(m, "_wo_bits", None) is None
    assert not isinstance(m.model.layers[0].self_attn.qkv_proj,
                          QuantizedWeight)


def test_full_quant_stack_spec_chunked_prefill(model, draft):
    """int8 KV + int8 weights under the FULL engine — spec decode and a
    chunked-prefill prompt — runs to completion, stays quiescent, and
    tracks the bf16 greedy stream."""
    rs = np.random.RandomState(2)
    prompts = _prompts(3, rs) + [rs.randint(1, 64, (21,))]
    ref, _ = _run(model, prompts, max_prompt_len=8, draft_model=draft)
    qm = quantize_for_serving(copy.deepcopy(model), "weight_only_int8")
    out, eng = _run(qm, prompts, max_prompt_len=8, draft_model=draft,
                    kv_dtype="int8")
    assert all(len(t) == 8 for t in out.values())
    assert _match_rate(ref, out) >= 0.7


def test_preempt_replay_under_int8(model):
    """Preemption + resume-replay re-prefills through the quantized
    scatter path; the engine must finish cleanly and stay quiescent."""
    rs = np.random.RandomState(3)
    prompts = _prompts(6, rs, lo=6, hi=12)
    out, eng = _run(model, prompts, kv_dtype="int8", num_slots=2,
                    num_blocks=14, preemption=True, max_seq_len=24)
    assert all(len(t) == 8 for t in out.values())


# ------------------------------------------------- radix/COW semantics

def test_prefix_cache_partial_boundary_cow_int8(model):
    """Shared prefix diverging MID-block: the radix trie COW-copies the
    partial block — codes AND scale rows — so cached and uncached int8
    engines emit identical tokens."""
    rs = np.random.RandomState(4)
    base = rs.randint(1, 64, (10,))           # 2.5 blocks at block_size 4
    prompts = [base,
               np.concatenate([base[:6], rs.randint(1, 64, (5,))]),
               np.concatenate([base[:9], rs.randint(1, 64, (3,))])]
    plain, _ = _run(model, prompts, kv_dtype="int8", prefix_caching=False)
    cached, eng = _run(model, prompts, kv_dtype="int8",
                       prefix_caching=True)
    assert cached == plain
    assert eng.kv.reconcile()["ok"]


def test_prefix_adopt_evict_refcounts_int8(model):
    """Sequential same-prefix requests adopt parked blocks (refcounts on
    the int8 pool + scale rows), evictions reclaim them, and the ledger
    reconciles block-for-block."""
    rs = np.random.RandomState(5)
    base = rs.randint(1, 64, (8,))
    eng = _mk(model, kv_dtype="int8", num_blocks=24)
    for i in range(3):                        # sequential: adopt each time
        eng.add_request(Request(base, max_new_tokens=6, req_id=i))
        eng.run()
    stats = eng.mgr.cache_stats
    assert stats.get("hit_blocks", 0) + stats.get("token_hits", 0) > 0
    eng.assert_quiescent()
    assert eng.kv.reconcile()["ok"]


def test_beam_search_int8_cow(model):
    """Beam fork + partial-block COW over the int8 pool (codes + scales
    forked together)."""
    rs = np.random.RandomState(6)
    p = rs.randint(1, 64, (7,))
    ref, _ = _run(model, [p], max_new=6)
    eng = _mk(model, kv_dtype="int8")
    eng.add_request(Request(p, max_new_tokens=6, num_beams=2))
    out = eng.run()
    assert len(list(out.values())[0]) == 6
    eng.assert_quiescent()


# -------------------------------------------- cross-replica handoff

def test_disaggregated_int8_matches_single_engine(model):
    """Every sequence crosses extract→ship→install with int8 codes and
    scale rows sealed + checksummed; fleet output == single int8
    engine, token for token."""
    rs = np.random.RandomState(7)
    prompts = _prompts(4, rs) + [rs.randint(1, 64, (19,))]
    ref, _ = _run(model, prompts, max_prompt_len=8, kv_dtype="int8")
    r = Router([Replica(_mk(model, max_prompt_len=8, kv_dtype="int8"),
                        role="prefill"),
                Replica(_mk(model, max_prompt_len=8, kv_dtype="int8"),
                        role="decode")])
    for p in prompts:
        r.add_request(Request(p, max_new_tokens=8))
    out = {rid: list(map(int, t)) for rid, t in r.run().items()}
    assert out == ref
    r.assert_quiescent()
    assert r.stats["transfers"] == 5


def _extract_one(model, prompt, **kw):
    src = _mk(model, prefill_only=True, **kw)
    src.add_request(Request(prompt, max_new_tokens=6, req_id=0))
    while 0 not in [int(x) for x in src.slot_req] or not src.active.any():
        src.step()
    return src, src.extract_sequence(0)


def test_payload_seal_covers_scales(model):
    rs = np.random.RandomState(8)
    src, payload = _extract_one(model, rs.randint(1, 64, (9,)),
                                kv_dtype="int8")
    assert payload.k_scale is not None and payload.expect["quant"]
    assert {"kssum", "vssum"} <= set(payload.expect)
    dst = _mk(model, kv_dtype="int8")
    validate_payload(DeviceKVTransfer().ship(payload, dst), dst)
    assert dst.install_sequence(payload)
    out = {rid: list(map(int, t)) for rid, t in dst.run().items()}
    assert len(out[0]) == 6
    src.assert_quiescent()
    dst.assert_quiescent()


def test_corrupted_scale_rejected(model):
    rs = np.random.RandomState(9)
    _, payload = _extract_one(model, rs.randint(1, 64, (9,)),
                              kv_dtype="int8")
    dst = _mk(model, kv_dtype="int8")
    payload.k_scale = payload.k_scale * 2.0       # silent rescale attempt
    with pytest.raises(KVTransferError, match="k-scale-checksum"):
        validate_payload(payload, dst)


def test_kv_dtype_mismatch_rejected(model):
    rs = np.random.RandomState(10)
    _, qpayload = _extract_one(model, rs.randint(1, 64, (9,)),
                               kv_dtype="int8")
    bf16_dst = _mk(model)
    with pytest.raises(KVTransferError, match="dtype mismatch"):
        validate_payload(qpayload, bf16_dst)
    with pytest.raises(ValueError, match="quantization"):
        bf16_dst.install_sequence(qpayload)
    _, bpayload = _extract_one(model, rs.randint(1, 64, (9,)))
    int8_dst = _mk(model, kv_dtype="int8")
    with pytest.raises(KVTransferError, match="dtype mismatch"):
        validate_payload(bpayload, int8_dst)


# --------------------------------------- trace-time kill-switch contract

def test_quant_kv_env_flip_needs_clear(model, monkeypatch):
    """PT_QUANT_KV is read when the quantized scatter TRACES: flipping
    it mid-process changes nothing (cached int8 programs keep running —
    the PR-10 contract), and after ``clear_jit_caches`` the retrace
    REFUSES to silently re-quantize, telling the caller to rebuild."""
    rs = np.random.RandomState(11)
    eng = _mk(model, kv_dtype="int8")
    pa._trace_events.clear()
    eng.add_request(Request(rs.randint(1, 64, (5,)), max_new_tokens=4))
    eng.run()
    assert "kv:int8-write" in pa._trace_events     # quantized scatter
    assert "decode:int8-kv" in pa._trace_events    # dequant-on-read

    monkeypatch.setenv("PT_QUANT_KV", "0")
    pa._trace_events.clear()
    eng.add_request(Request(rs.randint(1, 64, (5,)), max_new_tokens=4))
    eng.run()                       # cached traces: still the int8 path
    assert "kv:int8-write" not in pa._trace_events  # no retrace happened
    eng.assert_quiescent()

    clear_jit_caches()              # now the flip takes effect: retrace
    eng.add_request(Request(rs.randint(1, 64, (5,)), max_new_tokens=4))
    with pytest.raises(RuntimeError, match="PT_QUANT_KV"):
        eng.run()


def test_bf16_traces_carry_no_quant_breadcrumbs(model):
    rs = np.random.RandomState(12)
    pa._trace_events.clear()
    _run(model, _prompts(2, rs))
    assert not any("int8" in e for e in pa._trace_events)


# ------------------------------------------------- serving.kv_quant chaos

def test_chaos_kv_quant_exception_atomic(model):
    """An injected kv_quant fault must abort the tick BEFORE the
    quantize-on-write scatter: the engine survives, no blocks leak, no
    stale scale rows land, and the finished tokens match a clean run."""
    rs = np.random.RandomState(13)
    prompts = _prompts(3, rs)
    ref, _ = _run(model, prompts, kv_dtype="int8")
    eng = _mk(model, kv_dtype="int8")
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    fired = 0
    with FAULTS.scope("serving.kv_quant", on={1}, exc=InjectedFault):
        while eng.has_work():
            try:
                eng.step()
            except InjectedFault:
                fired += 1
    assert fired == 1
    out = {r: list(map(int, req.tokens))
           for r, req in eng.pop_finished().items()}
    assert out == ref
    eng.assert_quiescent()
    assert eng.kv.reconcile()["ok"]


def test_kv_quant_site_only_fires_for_int8_pools(model):
    rs = np.random.RandomState(14)
    eng = _mk(model)                      # bf16 pool: site never armed
    eng.add_request(Request(rs.randint(1, 64, (5,)), max_new_tokens=4))
    with FAULTS.scope("serving.kv_quant", exc=InjectedFault):
        eng.run()
    eng.assert_quiescent()
    assert FAULTS.hits["serving.kv_quant"] == 0
    FAULTS.clear()


# ------------------------------------------------ quantize_for_serving

def test_weight_only_roundtrip_and_quality(model):
    rs = np.random.RandomState(15)
    ids = jnp.asarray(rs.randint(1, 64, size=(2, 10)))
    ref = np.asarray(model(ids))
    m8 = quantize_for_serving(copy.deepcopy(model), "weight_only_int8")
    m4 = quantize_for_serving(copy.deepcopy(model), "weight_only_int4")
    assert m8._wo_bits == 8 and m4._wo_bits == 4
    att = m8.model.layers[0].self_attn
    assert isinstance(att.qkv_proj, QuantizedWeight)
    q8 = quant_quality(ref, m8(ids))
    q4 = quant_quality(ref, m4(ids))
    assert q8["logit_mse"] < q4["logit_mse"]       # int8 strictly tighter
    assert q8["greedy_match_rate"] >= 0.9
    assert METRICS.get("serving_quant_logit_mse").value() == \
        q4["logit_mse"]


def test_gptq_for_serving(model):
    rs = np.random.RandomState(16)
    ids = jnp.asarray(rs.randint(1, 64, size=(2, 12)))
    m = quantize_for_serving(copy.deepcopy(model), "gptq_int4",
                             calib_ids=ids)
    assert m._wo_bits == 4
    assert isinstance(m.model.layers[0].self_attn.qkv_proj,
                      QuantizedWeight)


def test_smooth_fold_is_function_preserving(model):
    rs = np.random.RandomState(17)
    ids = jnp.asarray(rs.randint(1, 64, size=(2, 10)))
    ref = np.asarray(model(ids))
    for kw in ({}, {"calib_ids": ids}):
        sm = smooth_for_serving(copy.deepcopy(model), **kw)
        assert np.abs(np.asarray(sm(ids)) - ref).max() < 1e-4


def test_quantize_moe_expert_stacks():
    pt.seed(3)
    mm = MixtralForCausalLM(MixtralConfig.tiny())
    rs = np.random.RandomState(18)
    ids = jnp.asarray(rs.randint(1, mm.cfg.vocab_size, size=(2, 8)))
    ref = np.asarray(mm(ids))
    mq = quantize_for_serving(copy.deepcopy(mm), "weight_only_int8",
                              smooth=True)
    ex = mq.layers[0].moe.experts
    assert isinstance(ex.gate_up, QuantizedExpertStack)
    assert ex.gate_up.q.dtype == jnp.int8
    assert mq.layers[0].moe.gate_w.dtype == jnp.float32  # router: never
    q = quant_quality(ref, mq(ids))
    assert q["greedy_match_rate"] >= 0.75
    # the quantized MoE also serves through the paged engine
    prompts = _prompts(3, rs, vocab=mm.cfg.vocab_size)
    out, _ = _run(mq, prompts, kv_dtype="int8")
    assert all(len(t) == 8 for t in out.values())


def test_expert_stack_int4_odd_k_roundtrip():
    rs = np.random.RandomState(19)
    w = jnp.asarray(rs.normal(size=(3, 5, 8)), jnp.float32)  # odd K=5
    qs = expert_stack_quantize(w, "weight_only_int4")
    assert qs.bits == 4 and qs.q.shape == (3, 3, 8)          # packed K
    err = np.abs(np.asarray(qs.dequantize()) - np.asarray(w)).max()
    assert err < float(jnp.abs(w).max()) / 7 + 1e-6          # 4-bit grid


def test_gptq_refuses_moe():
    pt.seed(4)
    mm = MixtralForCausalLM(MixtralConfig.tiny())
    with pytest.raises(NotImplementedError):
        quantize_for_serving(mm, "gptq_int8",
                             calib_ids=jnp.zeros((1, 4), jnp.int32))


# ------------------------------------------------ bytes-model satellites

def test_model_geometry_actual_dtypes():
    g = ModelGeometry(num_layers=2, hidden=32, intermediate=64, vocab=64,
                      heads=4, kv_heads=2, head_dim=64, dtype_bytes=2)
    gq = ModelGeometry(num_layers=2, hidden=32, intermediate=64, vocab=64,
                       heads=4, kv_heads=2, head_dim=64, dtype_bytes=2,
                       kv_dtype_bytes=1, kv_scale_bytes=4,
                       weight_dtype_bytes=1.0)
    assert kv_bytes_per_position(g) == 2 * 2 * 2 * 64 * 2
    # int8: 64 codes + 4 scale bytes per (position, head) vs 128 bf16
    assert kv_bytes_per_position(gq) / kv_bytes_per_position(g) \
        == pytest.approx(68 / 128)
    assert weight_bytes(gq) == weight_bytes(g) / 2


def test_engine_geom_and_gauge_read_actual_dtypes(model):
    qm = quantize_for_serving(copy.deepcopy(model), "weight_only_int8")
    eng = _mk(qm, kv_dtype="int8")
    assert eng._geom.kv_dtype_bytes == 1
    assert eng._geom.kv_scale_bytes == 4
    assert eng._geom.weight_dtype_bytes == 1.0
    bf16 = _mk(model)
    assert bf16._geom.kv_dtype_bytes == 0       # inherit dtype_bytes
    assert eng._kv_block_bytes() == cache_block_bytes(eng.cache)
    assert eng._kv_block_bytes() < bf16._kv_block_bytes()
