"""Vision zoo: every model builds, forwards at the right shape, and
backprops a finite loss on tiny inputs."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import vision
from paddle_tpu.models import resnet as R


def _check(model, x, num_classes=10, rng=False):
    out = model(x) if not rng else model(x, rng=jax.random.PRNGKey(0))
    assert out.shape == (x.shape[0], num_classes)
    assert bool(jnp.isfinite(out).all())
    return out


@pytest.mark.parametrize("name,size,kw", [
    ("LeNet", 28, {}),
    ("AlexNet", 71, {}),
    ("SqueezeNet", 65, {"version": "1.0"}),
    ("SqueezeNet", 65, {"version": "1.1"}),
    pytest.param(*("DenseNet", 64, {"layers": 121}), marks=pytest.mark.slow),
    pytest.param(*("GoogLeNet", 64, {}), marks=pytest.mark.slow),
    pytest.param(*("ShuffleNetV2", 64, {"scale": 0.5}), marks=pytest.mark.slow),
    ("MobileNetV1", 64, {"scale": 0.5}),
    pytest.param(*("MobileNetV3Small", 64, {}), marks=pytest.mark.slow),
    pytest.param(*("MobileNetV3Large", 64, {}), marks=pytest.mark.slow),
])
def test_zoo_forward(name, size, kw):
    pt.seed(0)
    cls = getattr(vision.models_extra, name)
    in_ch = 1 if name == "LeNet" else 3
    model = cls(num_classes=10, **kw).eval()
    x = jnp.asarray(np.random.RandomState(0).randn(2, in_ch, size, size),
                    jnp.float32)
    _check(model, x)


@pytest.mark.slow
def test_inception_v3_forward():
    pt.seed(0)
    model = vision.models_extra.InceptionV3(num_classes=10).eval()
    x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 299, 299), jnp.float32)
    _check(model, x)


@pytest.mark.slow
def test_resnext_and_wide():
    pt.seed(0)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 64, 64), jnp.float32)
    m = R.resnext50_32x4d(num_classes=10).eval()
    _check(m, x)
    w = R.wide_resnet50_2(num_classes=10).eval()
    _check(w, x)
    # grouped conv width: resnext bottleneck conv2 has 128 channels in 32 groups
    blk = m.layer1[0]
    assert blk.conv2.weight.shape == (128, 4, 3, 3)


@pytest.mark.slow
def test_zoo_trains():
    """One SGD step decreases loss on a fixed batch (ShuffleNet as probe)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.core.module import partition_trainable, combine

    pt.seed(0)
    model = vision.models_extra.ShuffleNetV2(0.25, num_classes=4)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 32, 32), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3])

    optimizer = opt.SGD(learning_rate=0.05)

    def loss_fn(m):
        return pt.nn.functional.cross_entropy(m(x), y)

    l0 = float(loss_fn(model))
    params, skel = partition_trainable(model)
    state = optimizer.init(params)
    for _ in range(3):
        grads = jax.grad(lambda p: loss_fn(combine(p, skel)))(params)
        params, state = optimizer.step(params, grads, state)
    l1 = float(loss_fn(combine(params, skel)))
    assert np.isfinite(l1) and l1 < l0, (l0, l1)


def test_vit_forward_and_grad():
    import paddle_tpu as pt
    from paddle_tpu.vision import vit
    import jax, jax.numpy as jnp, numpy as np

    pt.seed(0)
    net = vit.VisionTransformer(img_size=32, patch_size=8, embed_dim=64,
                                depth=2, num_heads=4, num_classes=10,
                                dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 3, 32, 32)),
                    jnp.float32)
    out = net(x)
    assert out.shape == (2, 10)
    assert np.isfinite(np.asarray(out)).all()

    # gradient flows to the patch conv and cls token
    from paddle_tpu.core.module import value_and_grad
    import paddle_tpu.nn.functional as F
    y = jnp.array([1, 3])
    loss, grads = value_and_grad(
        lambda m, x, y: F.cross_entropy(m(x), y))(net, x, y)
    g = np.asarray(grads.cls_token)
    assert np.abs(g).sum() > 0
    assert np.isfinite(float(loss))


def test_vit_configs_param_counts():
    from paddle_tpu.vision import vit
    import jax.numpy as jnp
    net = vit.vit_tiny_patch16_224(num_classes=10, dtype=jnp.float32)
    n = net.num_parameters()
    # ViT-Ti ~5.7M including head; sanity band
    assert 4e6 < n < 8e6


@pytest.mark.slow
def test_convnext_forward_grad():
    import paddle_tpu as pt
    from paddle_tpu.vision import convnext
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.core.module import value_and_grad
    import paddle_tpu.nn.functional as F

    pt.seed(0)
    net = convnext.ConvNeXt(depths=(1, 1, 2, 1), dims=(16, 32, 64, 128),
                            num_classes=7, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 3, 64, 64)),
                    jnp.float32)
    out = net(x)
    assert out.shape == (2, 7) and np.isfinite(np.asarray(out)).all()
    loss, grads = value_and_grad(
        lambda m, x, y: F.cross_entropy(m(x), y))(net, x, jnp.array([0, 3]))
    assert np.isfinite(float(loss))
    g = np.asarray(grads.stages[0][0].gamma)
    assert np.abs(g).sum() > 0


@pytest.mark.slow
def test_swin_forward_shapes_and_shift_mask():
    import paddle_tpu as pt
    from paddle_tpu.vision import swin
    import jax.numpy as jnp, numpy as np

    pt.seed(0)
    net = swin.SwinTransformer(img_size=32, patch_size=4, window_size=4,
                               embed_dim=24, depths=(2, 2), num_heads=(2, 4),
                               num_classes=5, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 3, 32, 32)),
                    jnp.float32)
    out = net(x)
    assert out.shape == (2, 5) and np.isfinite(np.asarray(out)).all()
    # stage 0 (res 8 > window 4): odd block is shifted with a blocking mask
    blk = net.stages[0][1]
    assert blk.shift > 0 and blk.attn_mask is not None
    m = np.asarray(blk.attn_mask)
    assert (m < -1e8).any() and (m == 0).any()
    # stage 1 (res 4 == window): whole map is one window — shift disabled
    assert all(b.shift == 0 for b in net.stages[1])
    assert all(b.window == 4 for b in net.stages[1])


def test_swin_window_roundtrip():
    from paddle_tpu.vision.swin import window_partition, window_reverse
    import jax.numpy as jnp, numpy as np
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 8, 8, 5)))
    w = window_partition(x, 4)
    assert w.shape == (2 * 4, 16, 5)
    back = window_reverse(w, 4, 8, 8)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))
