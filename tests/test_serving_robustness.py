"""Serving hardening + chaos tests (ISSUE 1 tentpole).

Deadlines, cancellation, backpressure, and graceful drain for
``LLMEngine`` — then seeded fault schedules (allocator failure, induced
preemption, tick exceptions) driven through full runs with the
invariants the production story needs:

  * zero leaked blocks (``assert_quiescent``: every block back, no
    standing reservations, no per-sequence tables)
  * no livelock (every run bounded in ticks)
  * expired requests finish with finish_reason == "timeout"
  * surviving outputs still EQUAL solo greedy (recovery never corrupts)
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (EngineDrainingError, LLMEngine,
                                QueueFullError, Request)
from paddle_tpu.utils.faults import FAULTS, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


class FakeClock:
    """Deterministic engine clock: tests advance it explicitly."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _solo(model, p, n):
    return np.asarray(generate(model, jnp.asarray(np.asarray(p)[None]),
                               max_new_tokens=n))[0, len(p):]


def _run_bounded(eng, max_ticks=400):
    ticks = 0
    while eng.has_work():
        eng.step()
        ticks += 1
        assert ticks < max_ticks, "livelock: engine did not drain"
    return ticks


# ------------------------------------------------------------- deadlines

def test_deadline_expires_inflight_request(model):
    clk = FakeClock()
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=64, clock=clk)
    rs = np.random.RandomState(0)
    slow = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                   max_new_tokens=30, deadline_s=5.0))
    fast = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                   max_new_tokens=30))
    while eng.has_work():
        eng.step()
        clk.t += 1.0          # 1s per tick: the deadline hits mid-decode
    r_slow, r_fast = eng.requests[slow], eng.requests[fast]
    assert r_slow.done and r_slow.finish_reason == "timeout"
    assert 0 < len(r_slow.tokens) < 30      # partial output survives
    assert r_fast.finish_reason == "length" and len(r_fast.tokens) == 30
    assert eng.stats["timeouts"] == 1
    eng.assert_quiescent()


def test_max_queue_s_times_out_waiting_request(model):
    clk = FakeClock()
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32, clock=clk)
    rs = np.random.RandomState(1)
    head = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                   max_new_tokens=12))
    waiter = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                     max_new_tokens=4, max_queue_s=3.0))
    while eng.has_work():
        eng.step()
        clk.t += 1.0
    assert eng.requests[waiter].finish_reason == "timeout"
    assert eng.requests[waiter].tokens == []     # never admitted
    assert eng.requests[head].finish_reason == "length"
    eng.assert_quiescent()


def test_deadline_already_expired_request_never_runs(model):
    clk = FakeClock()
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32, clock=clk)
    rid = eng.add_request(Request([1, 2, 3], max_new_tokens=4,
                                  deadline_s=1.0))
    clk.t = 2.0
    _run_bounded(eng)
    assert eng.requests[rid].finish_reason == "timeout"
    assert eng.requests[rid].tokens == []
    eng.assert_quiescent()


# ---------------------------------------------------------- cancellation

def test_cancel_queued_active_and_unknown(model):
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    rs = np.random.RandomState(2)
    active = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                     max_new_tokens=20))
    queued = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                     max_new_tokens=20))
    eng.step()                             # admit + first token
    assert eng.cancel(queued)              # still waiting: pulled from queue
    assert eng.cancel(active)              # mid-decode: slot + blocks freed
    assert not eng.cancel(active)          # double-cancel: no-op
    assert not eng.cancel(99999)           # unknown: no-op
    assert eng.requests[active].finish_reason == "cancelled"
    assert eng.requests[queued].finish_reason == "cancelled"
    assert eng.stats["cancelled"] == 2
    assert not eng.has_work()
    eng.assert_quiescent()


def test_cancel_beam_group_frees_all_slots(model):
    eng = LLMEngine(model, num_slots=4, block_size=4, max_prompt_len=16,
                    max_seq_len=32, eos_token_id=None)
    rs = np.random.RandomState(3)
    rid = eng.add_request(Request(rs.randint(0, 64, (7,)), max_new_tokens=8,
                                  num_beams=4))
    eng.step()                             # beam admitted: 4 slots live
    assert rid in eng.groups
    assert eng.cancel(rid)
    assert rid not in eng.groups and not eng.active.any()
    eng.assert_quiescent()


def test_cancel_chunk_prefilling_request(model):
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=48, prefix_caching=False)
    rs = np.random.RandomState(4)
    rid = eng.add_request(Request(rs.randint(0, 64, (24,)),
                                  max_new_tokens=4))
    eng.step()                             # claims slot, first chunk in
    assert rid in eng.prefilling
    assert eng.cancel(rid)
    assert rid not in eng.prefilling
    eng.assert_quiescent()


# ---------------------------------------------------------- backpressure

def test_bounded_queue_rejects_on_full(model):
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32, max_queue_len=2)
    rs = np.random.RandomState(5)
    eng.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=8))
    eng.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=8))
    with pytest.raises(QueueFullError):
        eng.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=8))
    assert eng.stats["rejected"] == 1
    eng.step()                             # head admitted -> queue has room
    eng.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=8))
    _run_bounded(eng)
    eng.assert_quiescent()


def test_drain_finishes_inflight_rejects_new(model):
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    rs = np.random.RandomState(6)
    prompts = [rs.randint(0, 64, (5,)) for _ in range(3)]
    rids = [eng.add_request(Request(p, max_new_tokens=6)) for p in prompts]
    eng.step()
    out = eng.drain()
    with pytest.raises(EngineDrainingError):
        eng.add_request(Request([1, 2], max_new_tokens=2))
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(np.asarray(out[rid]),
                                      _solo(model, p, 6))
    eng.assert_quiescent()


def test_drain_cancel_queued(model):
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    rs = np.random.RandomState(7)
    head = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                   max_new_tokens=6))
    tail = eng.add_request(Request(rs.randint(0, 64, (5,)),
                                   max_new_tokens=6))
    eng.step()                             # head holds the only slot
    eng.drain(cancel_queued=True)
    assert eng.requests[head].finish_reason == "length"
    assert eng.requests[tail].finish_reason == "cancelled"
    eng.assert_quiescent()


# ------------------------------------------------- preemption-order fix

def test_prefill_preemption_evicts_by_admission_order_not_req_id(model):
    """Round-5 advisor low: explicit req_ids are NOT monotonic with
    admission — the victim must be the LAST-ADMITTED prefill, even when
    it carries the numerically smallest id."""
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=48, preemption=True, prefix_caching=False)
    rs = np.random.RandomState(8)
    old = eng.add_request(Request(rs.randint(0, 64, (24,)),
                                  max_new_tokens=4, req_id=100))
    young = eng.add_request(Request(rs.randint(0, 64, (24,)),
                                    max_new_tokens=4, req_id=5))
    eng.step()                             # both claim slots, chunks land
    assert set(eng.prefilling) == {100, 5}
    assert eng._preempt_prefilling()
    # max(req_id) would have evicted 100; admission order evicts 5
    assert 100 in eng.prefilling
    assert 5 not in eng.prefilling and eng.queue[0].req_id == 5
    _run_bounded(eng)
    for rid, p in ((100, eng.requests[100].prompt),
                   (5, eng.requests[5].prompt)):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 4))
    eng.assert_quiescent()


# ----------------------------------------------------------- chaos runs

def test_chaos_allocator_failures_no_leaks_exact_outputs(model):
    """Seeded allocator-failure schedule under preemption: every injected
    MemoryError routes through preempt-and-retry; the run drains with
    zero leaked blocks and every output still equals solo greedy."""
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 12, 6)]
    FAULTS.schedule("serving.alloc", seed=42, p=0.25, horizon=200,
                    exc=MemoryError, times=20)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    ticks = 0
    while eng.has_work():
        try:
            eng.step()
        except MemoryError:
            # transient injected failure with nothing left to preempt:
            # the raise happens before any tick mutation — supervisor
            # retries the tick (a real dry pool would raise forever; the
            # tick bound below distinguishes the two)
            pass
        ticks += 1
        assert ticks < 400, "livelock under chaos"
    assert FAULTS.log, "schedule never fired — test is vacuous"
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 6),
            err_msg=f"request {rid} corrupted by chaos")
    eng.assert_quiescent()
    # fault counters agree with the chaos log (ISSUE 2)
    from paddle_tpu.observability import METRICS
    snap = METRICS.snapshot()["counters"]
    assert snap['faults_injected_total{site="serving.alloc"}'] == \
        len(FAULTS.log)


def test_chaos_induced_preemption_exact_outputs(model):
    """serving.preempt rule calls engine._preempt() on a seeded cadence —
    victims re-queue with their progress and still produce exact greedy
    outputs."""
    rs = np.random.RandomState(10)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 12, 4)]
    FAULTS.install("serving.preempt", every=5, times=6,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    _run_bounded(eng)
    assert eng.stats["preemptions"] > 0
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 6))
    eng.assert_quiescent()
    # the chaos run shows up in the metrics registry (ISSUE 2): every
    # induced preemption and injected firing is counted
    from paddle_tpu.observability import METRICS
    snap = METRICS.snapshot()["counters"]
    assert snap["serving_preemptions_total"] == eng.stats["preemptions"]
    assert snap['faults_injected_total{site="serving.preempt"}'] > 0


def test_chaos_tick_exception_engine_state_survives(model):
    """An exception at the top of step() (before any mutation) must leave
    the engine resumable: catch it, keep stepping, finish exactly."""
    rs = np.random.RandomState(11)
    p = rs.randint(0, 64, (6,))
    FAULTS.install("serving.tick", on={2, 4}, exc=InjectedFault)
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    eng.add_request(Request(p, max_new_tokens=6))
    ticks = 0
    while eng.has_work():
        try:
            eng.step()
        except InjectedFault:
            pass                           # supervisor catches and retries
        ticks += 1
        assert ticks < 100
    np.testing.assert_array_equal(np.asarray(eng.requests[0].tokens),
                                  _solo(model, p, 6))
    eng.assert_quiescent()


def test_chaos_deadlines_under_allocator_pressure(model):
    """Deadlines + chaos together: timed-out requests report "timeout",
    survivors stay exact, nothing leaks."""
    clk = FakeClock()
    rs = np.random.RandomState(12)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 10, 5)]
    FAULTS.schedule("serving.alloc", seed=7, p=0.15, horizon=150,
                    exc=MemoryError, times=10)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True, clock=clk)
    rids = [eng.add_request(Request(p, max_new_tokens=8,
                                    deadline_s=6.0 if i % 2 else None))
            for i, p in enumerate(prompts)]
    ticks = 0
    while eng.has_work():
        try:
            eng.step()
        except MemoryError:
            pass                           # transient injection: retry tick
        clk.t += 1.0
        ticks += 1
        assert ticks < 400, "livelock under chaos"
    for i, rid in enumerate(rids):
        r = eng.requests[rid]
        assert r.done
        if r.finish_reason == "timeout":
            continue                       # expired under pressure: fine
        np.testing.assert_array_equal(np.asarray(r.tokens),
                                      _solo(model, prompts[i], 8))
    eng.assert_quiescent()
