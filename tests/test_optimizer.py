"""Optimizer semantics vs torch reference steps (SURVEY.md §4;
ref test/legacy_test/test_adamw_op.py etc.)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _quadratic_setup():
    m = nn.Linear(4, 4, bias_attr=True)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    y = jnp.asarray(np.random.RandomState(1).randn(8, 4).astype(np.float32))

    def loss_fn(mod, x, y):
        return jnp.mean((mod(x) - y) ** 2)

    return m, x, y, loss_fn


def _run_steps(optimizer, n=20):
    m, x, y, loss_fn = _quadratic_setup()
    state = optimizer.init(m)
    losses = []
    for _ in range(n):
        loss, grads = pt.value_and_grad(loss_fn)(m, x, y)
        m, state = optimizer.step(m, grads, state)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("optimizer,factor", [
    (opt.SGD(learning_rate=0.1), 0.7),
    (opt.Momentum(learning_rate=0.05, momentum=0.9), 0.7),
    (opt.Adam(learning_rate=0.05), 0.7),
    (opt.AdamW(learning_rate=0.05, weight_decay=0.01), 0.7),
    (opt.Adagrad(learning_rate=0.3), 0.7),
    (opt.RMSProp(learning_rate=0.01), 0.7),
    (opt.Adadelta(learning_rate=1.0, rho=0.9), 0.95),  # slow starter by design
    (opt.Adamax(learning_rate=0.05), 0.7),
    (opt.Lamb(learning_rate=0.05), 0.7),
    (opt.Lion(learning_rate=0.01), 0.7),
], ids=lambda o: type(o).__name__ if isinstance(o, opt.Optimizer) else "")
def test_loss_decreases(optimizer, factor):
    losses = _run_steps(optimizer)
    assert losses[-1] < losses[0] * factor, losses


def _torch_compare(make_jax_opt, make_torch_opt, n=5, rtol=1e-4):
    import torch
    w0 = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    g_seq = [np.random.RandomState(i + 1).randn(4, 3).astype(np.float32) for i in range(n)]

    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = make_torch_opt([tw])
    for g in g_seq:
        tw.grad = torch.tensor(g)
        topt.step()

    jw = {"w": jnp.asarray(w0)}
    jopt = make_jax_opt()
    state = jopt.init(jw)
    for g in g_seq:
        jw, state = jopt.step(jw, {"w": jnp.asarray(g)}, state)
    np.testing.assert_allclose(np.asarray(jw["w"]), tw.detach().numpy(), rtol=rtol, atol=1e-5)


def test_sgd_matches_torch():
    import torch
    _torch_compare(lambda: opt.SGD(0.1), lambda p: torch.optim.SGD(p, lr=0.1))


def test_adam_matches_torch():
    import torch
    _torch_compare(lambda: opt.Adam(0.01),
                   lambda p: torch.optim.Adam(p, lr=0.01))


def test_adamw_matches_torch():
    import torch
    _torch_compare(lambda: opt.AdamW(0.01, weight_decay=0.1),
                   lambda p: torch.optim.AdamW(p, lr=0.01, weight_decay=0.1))


def test_momentum_matches_torch():
    import torch
    _torch_compare(lambda: opt.Momentum(0.1, momentum=0.9),
                   lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9))


def test_adamax_matches_torch():
    import torch
    _torch_compare(lambda: opt.Adamax(0.01),
                   lambda p: torch.optim.Adamax(p, lr=0.01))


def test_multi_precision_master_weights():
    m = nn.Linear(4, 4, dtype=jnp.bfloat16)
    x = jnp.ones((2, 4), jnp.bfloat16)

    def loss_fn(mod, x):
        return jnp.mean(mod(x).astype(jnp.float32) ** 2)

    o = opt.AdamW(learning_rate=1e-3, multi_precision=True)
    state = o.init(m)
    masters = [l for l in jax.tree_util.tree_leaves(state["master"]) if l is not None]
    assert all(l.dtype == jnp.float32 for l in masters)
    loss, grads = pt.value_and_grad(loss_fn)(m, x)
    m2, state = o.step(m, grads, state)
    assert m2.weight.dtype == jnp.bfloat16


def test_grad_clip_global_norm():
    grads = {"a": jnp.full((10,), 10.0), "b": jnp.full((10,), 10.0)}
    clipped = opt.ClipGradByGlobalNorm(1.0)(grads)
    n = float(opt.global_norm(clipped))
    np.testing.assert_allclose(n, 1.0, rtol=1e-5)
    # under the clip threshold -> unchanged
    small = {"a": jnp.full((2,), 0.01)}
    out = opt.ClipGradByGlobalNorm(1.0)(small)
    np.testing.assert_allclose(np.asarray(out["a"]), 0.01, rtol=1e-6)


def test_grad_clip_value_and_norm():
    g = {"a": jnp.array([5.0, -5.0, 0.5])}
    out = opt.ClipGradByValue(1.0)(g)
    np.testing.assert_allclose(np.asarray(out["a"]), [1.0, -1.0, 0.5])
    out2 = opt.ClipGradByNorm(1.0)(g)
    np.testing.assert_allclose(float(jnp.linalg.norm(out2["a"])), 1.0, rtol=1e-5)


def test_apply_decay_param_fun():
    m = nn.Linear(4, 4)
    o = opt.AdamW(0.1, weight_decay=0.5,
                  apply_decay_param_fun=lambda name: "bias" not in name)
    state = o.init(m)
    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p) if p is not None else None,
        pt.partition_trainable(m)[0], is_leaf=lambda x: x is None)
    # step with zero grads: only decayed params change
    m2, _ = o.step(m, pt.combine(zero_grads, pt.partition_trainable(m)[1]), state)
    assert not np.allclose(np.asarray(m2.weight), np.asarray(m.weight))
    np.testing.assert_allclose(np.asarray(m2.bias), np.asarray(m.bias))


def test_schedulers_pure_values():
    s = opt.NoamDecay(d_model=512, warmup_steps=100)
    v1 = float(s.value_at(jnp.asarray(50)))
    v2 = float(s.value_at(jnp.asarray(100)))
    v3 = float(s.value_at(jnp.asarray(10000)))
    assert v1 < v2 and v3 < v2
    c = opt.CosineAnnealingDecay(1.0, T_max=100)
    np.testing.assert_allclose(float(c.value_at(jnp.asarray(0))), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(c.value_at(jnp.asarray(100))), 0.0, atol=1e-6)
    w = opt.LinearWarmup(opt.CosineAnnealingDecay(1.0, 100), warmup_steps=10, start_lr=0.0)
    assert float(w.value_at(jnp.asarray(0))) == 0.0
    np.testing.assert_allclose(float(w.value_at(jnp.asarray(10))), 1.0, rtol=1e-5)
    p = opt.PiecewiseDecay([3, 6], [1.0, 0.5, 0.1])
    assert float(p.value_at(jnp.asarray(0))) == 1.0
    assert float(p.value_at(jnp.asarray(4))) == 0.5
    assert float(p.value_at(jnp.asarray(9))) == pytest.approx(0.1)


def test_scheduler_stateful_api():
    s = opt.StepDecay(1.0, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s.get_lr())
        s.step()
    np.testing.assert_allclose(lrs, [1.0, 1.0, 0.5, 0.5, 0.25], rtol=1e-6)


def test_scheduler_in_jit_train_step():
    m, x, y, loss_fn = _quadratic_setup()
    sched = opt.LinearWarmup(0.1, warmup_steps=5)
    o = opt.Adam(learning_rate=sched)
    state = o.init(m)

    @pt.jit
    def step(mod, st, x, y):
        loss, grads = pt.value_and_grad(loss_fn)(mod, x, y)
        mod, st = o.step(mod, grads, st)
        return mod, st, loss

    for _ in range(8):
        m, state, loss = step(m, state, x, y)
    assert int(state["step"]) == 8


def test_reduce_on_plateau():
    s = opt.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    s.step(1.0)
    s.step(1.0)
    s.step(1.0)  # no improvement for > patience steps -> halve
    assert s.get_lr() == 0.5


# -- Adafactor ---------------------------------------------------------------

class TestAdafactor:
    def test_slot_memory_is_factored(self):
        import paddle_tpu.optimizer as opt
        import jax.numpy as jnp
        params = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
        st = opt.Adafactor().init(params)
        assert st["vr"]["w"].shape == (64,)
        assert st["vc"]["w"].shape == (32,)
        assert st["vr"]["b"].shape == (32,)   # vectors keep full v

    def test_converges_on_quadratic(self):
        import paddle_tpu.optimizer as opt
        import jax, jax.numpy as jnp, numpy as np
        rng = np.random.default_rng(0)
        A = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        target = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        params = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
        o = opt.Adafactor(learning_rate=0.05, scale_parameter=False)
        st = o.init(params)
        loss = lambda p: jnp.mean((p["w"] * A - target) ** 2)
        l0 = float(loss(params))
        step = jax.jit(lambda p, s: o.step(p, jax.grad(loss)(p), s))
        for _ in range(300):
            params, st = step(params, st)
        assert float(loss(params)) < 0.2 * l0

    def test_beta1_and_fixed_lr(self):
        import paddle_tpu.optimizer as opt
        import jax, jax.numpy as jnp
        params = {"w": jnp.ones((8, 8))}
        o = opt.Adafactor(learning_rate=0.01, beta1=0.9, scale_parameter=False)
        st = o.init(params)
        assert "m" in st
        g = {"w": jnp.ones((8, 8))}
        p2, st2 = jax.jit(o.step)(params, g, st)
        assert float(jnp.abs(p2["w"] - params["w"]).max()) > 0
        assert int(st2["step"]) == 1

    def test_trains_llama_tiny(self):
        import paddle_tpu as pt
        import paddle_tpu.optimizer as opt
        import numpy as np
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.train import make_train_step
        from paddle_tpu.train.step import init_state

        pt.seed(0)
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        o = opt.Adafactor()
        state = init_state(model, o)
        step = make_train_step(lambda m, i, l: m.loss(i, l), o)
        rs = np.random.RandomState(0)
        ids = rs.randint(0, cfg.vocab_size, (2, 16))
        labels = np.concatenate([ids[:, 1:], -100 * np.ones((2, 1), ids.dtype)], 1)
        losses = []
        for _ in range(8):
            state, loss = step(state, ids, labels)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
