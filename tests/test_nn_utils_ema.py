"""nn.utils parameter helpers + LookAhead/EMA + recompute."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.nn import utils as U


def test_clip_grad_norm():
    grads = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([[0.0]])}
    clipped, total = U.clip_grad_norm_(grads, max_norm=1.0)
    assert np.isclose(float(total), 5.0)
    norm_after = np.sqrt(sum(float(jnp.sum(g ** 2))
                             for g in jax.tree_util.tree_leaves(clipped)))
    assert np.isclose(norm_after, 1.0, rtol=1e-5)
    # under the norm: unchanged
    c2, t2 = U.clip_grad_norm_(grads, max_norm=100.0)
    assert np.allclose(np.asarray(c2["a"]), [3.0, 4.0])


def test_clip_grad_value_and_vector_roundtrip():
    grads = {"w": jnp.asarray([[1.5, -2.5]]), "b": jnp.asarray([0.5])}
    c = U.clip_grad_value_(grads, 1.0)
    assert float(jnp.max(jnp.abs(c["w"]))) <= 1.0
    vec = U.parameters_to_vector(grads)
    assert vec.shape == (3,)
    back = U.vector_to_parameters(vec, grads)
    for k in grads:
        assert np.allclose(np.asarray(back[k]), np.asarray(grads[k]))


def test_weight_norm_roundtrip_and_spectral():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(6, 4).astype(np.float32))
    g, v = U.weight_norm(w, dim=0)
    fused = U.remove_weight_norm(g, v, dim=0)
    assert np.allclose(np.asarray(fused), np.asarray(w), atol=1e-5)
    wn = U.spectral_norm(w)
    s = np.linalg.svd(np.asarray(wn), compute_uv=False)
    assert abs(s[0] - 1.0) < 1e-3


def test_lookahead_syncs_slow_weights():
    pt.seed(0)
    params = {"w": jnp.asarray([10.0])}
    inner = opt.SGD(learning_rate=1.0)
    la = opt.LookAhead(inner, alpha=0.5, k=2)
    state = la.init(params)
    g = {"w": jnp.asarray([1.0])}
    # step 1: fast = 9, no sync
    params, state = la.step(params, g, state)
    assert np.isclose(float(params["w"][0]), 9.0)
    # step 2: fast = 8, sync: slow = 10 + 0.5*(8-10) = 9 -> params = 9
    params, state = la.step(params, g, state)
    assert np.isclose(float(params["w"][0]), 9.0)
    assert np.isclose(float(state["slow"]["w"][0]), 9.0)


def test_ema():
    ema = opt.ExponentialMovingAverage(decay=0.5)
    params = {"w": jnp.asarray([0.0])}
    shadow = ema.init(params)
    shadow = ema.update(shadow, {"w": jnp.asarray([4.0])})
    assert np.isclose(float(shadow["w"][0]), 2.0)
    shadow = ema.update(shadow, {"w": jnp.asarray([4.0])})
    assert np.isclose(float(shadow["w"][0]), 3.0)
    applied = ema.apply(shadow, params)
    assert np.isclose(float(applied["w"][0]), 3.0)


def test_recompute_matches_plain():
    from paddle_tpu.distributed import recompute

    def f(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    x = jnp.asarray(np.random.RandomState(1).randn(16).astype(np.float32))
    g_plain = jax.grad(f)(x)
    g_ckpt = jax.grad(lambda x: recompute(f, x))(x)
    assert np.allclose(np.asarray(g_plain), np.asarray(g_ckpt), atol=1e-6)
