"""Auto registry (ref: PaddleNLP AutoModel / HF AutoModelForCausalLM):
local-directory from_pretrained end-to-end and config mapping."""
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt

transformers = pytest.importorskip("transformers")


def test_auto_from_pretrained_llama_dir(tmp_path):
    """Save a tiny HF llama checkpoint to disk, auto-load it by
    config.json model_type, match HF logits."""
    import torch
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64,
                          attn_implementation="eager")).eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)

    from paddle_tpu.models.auto import auto_from_pretrained
    pt.seed(0)
    ours = auto_from_pretrained(str(tmp_path), dtype=jnp.float32)
    ours.cfg.remat = False
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 10))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_auto_from_config_types():
    """Every registered decoder type builds from a minimal HF-style
    config dict and runs a forward."""
    from paddle_tpu.models.auto import auto_from_config
    base = dict(vocab_size=64, hidden_size=32, num_hidden_layers=1,
                num_attention_heads=4, max_position_embeddings=32)
    cases = {
        "llama": dict(intermediate_size=64, num_key_value_heads=2),
        "gpt_neox": dict(intermediate_size=64, rotary_pct=0.25),
        "opt": dict(ffn_dim=64),
        "bloom": dict(n_layer=1, n_head=4),
        "falcon": dict(multi_query=True),
        "gpt2": dict(n_embd=32, n_layer=1, n_head=4, n_positions=32,
                     n_inner=None),
    }
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (1, 8)))
    for mt, extra in cases.items():
        pt.seed(0)
        cfgd = {**base, **extra, "model_type": mt, "dtype": jnp.float32,
                "remat": False}
        if mt == "bloom":
            cfgd.pop("hidden_size"); cfgd.pop("num_hidden_layers")
            cfgd.pop("num_attention_heads")
            cfgd["hidden_size"] = 32
        m = auto_from_config(cfgd)
        out = np.asarray(m(ids), np.float32)
        assert np.isfinite(out).all(), mt


def test_auto_unknown_type_raises(tmp_path):
    from paddle_tpu.models.auto import auto_from_pretrained
    (tmp_path / "config.json").write_text(json.dumps(
        {"model_type": "made_up_arch"}))
    with pytest.raises(ValueError, match="auto registry"):
        auto_from_pretrained(str(tmp_path))
