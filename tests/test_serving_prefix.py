"""Serving: cross-request prefix caching + preemption (VERDICT r3 item 4).

* two requests sharing a prompt prefix allocate the prefix blocks ONCE
  (pool accounting assertion), both concurrent and sequential
* parked (finished-request) blocks are reclaimed by LRU eviction when the
  free list runs dry — caching never reduces usable capacity
* preemption mode admits more concurrent work than worst-case reservation
  allows, preempts the youngest slot on out-of-blocks, and the victim
  resumes with recompute — all outputs stay exactly solo-greedy
Ref capability: PaddleNLP llm/predict block-attention serving (vLLM-style
hash-block reuse + recompute preemption).
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import PrefixCachingBlockManager
from paddle_tpu.serving import LLMEngine, Request


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _solo(model, p, n):
    return np.asarray(generate(model, jnp.asarray(np.asarray(p)[None]),
                               max_new_tokens=n))[0, len(p):]


# --------------------------------------------------------------- manager
def test_manager_park_match_adopt_evict():
    mgr = PrefixCachingBlockManager(num_blocks=6, block_size=4)
    toks = np.arange(10, dtype=np.int32)          # 2 full blocks + tail
    mgr.allocate(1, 10)
    mgr.commit_prefix(1, toks)
    t1 = list(mgr.tables[1])
    # full match capped at (len-1)//bs so the last token always prefills
    assert mgr.match_prefix(toks) == t1[:2]
    assert mgr.match_prefix(np.arange(9, dtype=np.int32)) == t1[:2]
    # a diverging second block only matches the first
    other = np.concatenate([np.arange(4), np.full(6, 63)]).astype(np.int32)
    assert mgr.match_prefix(other) == t1[:1]
    # free -> full blocks park (still matchable), unhashed tail block frees
    mgr.free(1)
    assert mgr.match_prefix(toks) == t1[:2]
    assert len(mgr._evictable) == 2
    assert mgr.free_blocks == 6                    # parked counts as free
    # adopt revives the parked blocks
    adopted = mgr.match_prefix(toks)
    mgr.adopt_prefix(2, adopted)
    assert all(b not in mgr._evictable for b in adopted)
    mgr.free(2)
    # exhaust the free list: eviction reclaims parked blocks LRU-first
    mgr.allocate(3, 24)                            # all 6 blocks
    assert mgr.cache_stats["evictions"] == 2
    assert mgr.match_prefix(toks) == []            # digests dropped


# ------------------------------------------------------- prefix caching
def test_concurrent_prefix_shared_once(model):
    rs = np.random.RandomState(3)
    pre = rs.randint(0, 64, (8,))
    p1 = np.concatenate([pre, rs.randint(0, 64, (4,))])
    p2 = np.concatenate([pre, rs.randint(0, 64, (4,))])
    eng = LLMEngine(model, num_slots=4, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    r1 = eng.add_request(Request(p1, max_new_tokens=5))
    r2 = eng.add_request(Request(p2, max_new_tokens=5))
    eng.step()                                     # both admitted this tick
    # pool accounting: the 2 full prefix blocks exist ONCE across tables
    t1, t2 = eng.mgr.tables[r1], eng.mgr.tables[r2]
    assert t1[:2] == t2[:2], "prefix blocks not shared"
    assert eng.mgr._rc[t1[0]] == 2 and eng.mgr._rc[t1[1]] == 2
    assert eng.mgr.cache_stats["hit_blocks"] == 2
    distinct = set(t1) | set(t2)
    assert len(distinct) == len(t1) + len(t2) - 2
    out = eng.run()
    np.testing.assert_array_equal(out[r1], _solo(model, p1, 5))
    np.testing.assert_array_equal(out[r2], _solo(model, p2, 5))


def test_sequential_prefix_reuse_after_finish(model):
    rs = np.random.RandomState(4)
    pre = rs.randint(0, 64, (9,))
    p1 = np.concatenate([pre, rs.randint(0, 64, (3,))])
    p2 = np.concatenate([pre, rs.randint(0, 64, (2,))])
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    r1 = eng.add_request(Request(p1, max_new_tokens=4))
    out1 = eng.run()
    np.testing.assert_array_equal(out1[r1], _solo(model, p1, 4))
    # r1 finished; its hashed prompt blocks are parked, then re-shared
    r2 = eng.add_request(Request(p2, max_new_tokens=4))
    out2 = eng.run()
    assert eng.mgr.cache_stats["hit_blocks"] == 2   # pre covers 2 blocks
    np.testing.assert_array_equal(out2[r2], _solo(model, p2, 4))


def test_long_prompt_chunked_prefix_reuse(model):
    """Chunked prefill (prompt > max_prompt_len) commits its prefix;
    an identical later prompt skips the cached chunks entirely."""
    rs = np.random.RandomState(5)
    p = rs.randint(0, 64, (20,))                   # > max_prompt_len=8
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32)
    r1 = eng.add_request(Request(p, max_new_tokens=4))
    out1 = eng.run()
    sol = _solo(model, p, 4)
    np.testing.assert_array_equal(out1[r1], sol)
    r2 = eng.add_request(Request(p.copy(), max_new_tokens=4))
    ticks = 0
    while eng.has_work():
        eng.step()
        ticks += 1
    # 4 of the 5 prompt blocks were cached ((20-1)//4 = 4): one chunk tick
    # covers the 4-token suffix, so first token lands on tick 1
    assert eng.mgr.cache_stats["hit_blocks"] >= 4
    np.testing.assert_array_equal(eng.requests[r2].tokens, sol)


def test_eviction_under_pressure_stays_correct(model):
    """Fill the pool with parked blocks, then admit work that needs them:
    eviction must reclaim transparently."""
    rs = np.random.RandomState(6)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=20, num_blocks=10)
    outs = {}
    prompts = {}
    for i in range(4):      # sequential: 3 hashed blocks park per request
        p = rs.randint(0, 64, (15,))
        rid = eng.add_request(Request(p, max_new_tokens=4))
        prompts[rid] = p
        outs.update(eng.run())
    assert eng.mgr.cache_stats["evictions"] > 0
    for rid, toks in outs.items():
        np.testing.assert_array_equal(toks, _solo(model, prompts[rid], 4))


# ----------------------------------------------------------- preemption
def test_preemption_oversubscribes_and_matches_solo(model):
    """Pool too small for both worst cases: worst-case admission would
    serialise; preemption runs them concurrently, evicts the youngest
    when blocks run out, and still reproduces solo greedy exactly."""
    rs = np.random.RandomState(7)
    p1 = rs.randint(0, 64, (7,))
    p2 = rs.randint(0, 64, (7,))
    n_new = 12
    # worst case each: ceil((7+12)/4) = 5 blocks; pool of 7 can't reserve 10
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=19, num_blocks=7, preemption=True)
    r1 = eng.add_request(Request(p1, max_new_tokens=n_new))
    r2 = eng.add_request(Request(p2, max_new_tokens=n_new))
    both_active = False
    while eng.has_work():
        eng.step()
        both_active |= bool(eng.active.sum() == 2)
    assert both_active, "preemption should admit both concurrently"
    assert eng.stats["preemptions"] >= 1
    np.testing.assert_array_equal(eng.requests[r1].tokens,
                                  _solo(model, p1, n_new))
    np.testing.assert_array_equal(eng.requests[r2].tokens,
                                  _solo(model, p2, n_new))
    # the victim's resume re-shared its own parked prompt block
    assert eng.mgr.cache_stats["hit_blocks"] >= 1


def test_worst_case_mode_never_runs_both(model):
    """Control for the test above: same sizes WITHOUT preemption keep the
    second request queued until the first finishes (and never preempt)."""
    rs = np.random.RandomState(7)
    p1 = rs.randint(0, 64, (7,))
    p2 = rs.randint(0, 64, (7,))
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=19, num_blocks=7)
    r1 = eng.add_request(Request(p1, max_new_tokens=12))
    r2 = eng.add_request(Request(p2, max_new_tokens=12))
    both = False
    while eng.has_work():
        eng.step()
        both |= bool(eng.active.sum() == 2)
    assert not both
    assert eng.stats["preemptions"] == 0
    np.testing.assert_array_equal(eng.requests[r1].tokens,
                                  _solo(model, p1, 12))
    np.testing.assert_array_equal(eng.requests[r2].tokens,
                                  _solo(model, p2, 12))


def test_preemption_many_requests_fcfs_progress(model):
    """6 long-running requests through 3 slots on a tight pool: everyone
    completes, all exactly solo-greedy, under repeated preemption."""
    rs = np.random.RandomState(8)
    prompts = [rs.randint(0, 64, (int(l),))
               for l in rs.randint(5, 12, size=6)]
    eng = LLMEngine(model, num_slots=3, block_size=4, max_prompt_len=16,
                    max_seq_len=24, num_blocks=12, preemption=True)
    rids = [eng.add_request(Request(p, max_new_tokens=8)) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(out[rid], _solo(model, p, 8),
                                      err_msg=f"request {rid}")


def test_prefix_caching_disabled_flag(model):
    """prefix_caching=False must behave exactly as before (no sharing)."""
    rs = np.random.RandomState(9)
    pre = rs.randint(0, 64, (8,))
    p1 = np.concatenate([pre, rs.randint(0, 64, (3,))])
    p2 = np.concatenate([pre, rs.randint(0, 64, (3,))])
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24, prefix_caching=False)
    r1 = eng.add_request(Request(p1, max_new_tokens=4))
    r2 = eng.add_request(Request(p2, max_new_tokens=4))
    out = eng.run()
    assert eng.mgr.cache_stats["hit_blocks"] == 0
    np.testing.assert_array_equal(out[r1], _solo(model, p1, 4))
    np.testing.assert_array_equal(out[r2], _solo(model, p2, 4))
