"""Zero-bubble (ZB-H1 style) pipeline schedule: parity vs sequential and
vs plain 1F1B, composition with dp, and the structural W-split property.

Ref: Fleet ``meta_parallel/pipeline_parallel.py`` (interleaved/zero-bubble
schedules); here ``pipeline_train_1f1b(zero_bubble=True)`` — drain-chain
hops compute dx only, deferred weight grads run in pp-1 tail ticks (see
``paddle_tpu/distributed/pipeline.py`` module docstring for the DAG cost
model).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.distributed.pipeline import (PipelineLayer,
                                             pipeline_train_step)

from tests.test_pipeline_1f1b import (_embed, _head_loss, _seq_ref, _setup)


@pytest.mark.parametrize("pp,M", [(2, 4), (4, 4), (4, 8)])
def test_zb1_matches_sequential(pp, M):
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    ref, refg = jax.value_and_grad(_seq_ref, argnums=(0, 1, 2))(
        pipe.stacked, emb_w, head_w, tokens, tlabels)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    loss, ds, de, dh = pipeline_train_step(
        pipe, mesh, tokens, tlabels, head_loss_fn=_head_loss,
        head_params=head_w, embed_fn=_embed, embed_params=emb_w,
        schedule="zb1")
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves((ds, de, dh)),
                    jax.tree_util.tree_leaves(refg)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-6)


def test_zb1_loss_bit_identical_to_1f1b():
    """The forward side is untouched by the W-split: losses match BITWISE;
    grads match to fp32 accumulation-order tolerance."""
    pp, M = 4, 4
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    kw = dict(head_loss_fn=_head_loss, head_params=head_w,
              embed_fn=_embed, embed_params=emb_w)
    l1, d1, e1, h1 = pipeline_train_step(pipe, mesh, tokens, tlabels, **kw)
    lz, dz, ez, hz = pipeline_train_step(pipe, mesh, tokens, tlabels,
                                         schedule="zb1", **kw)
    assert float(l1) == float(lz)
    for g, r in zip(jax.tree_util.tree_leaves((dz, ez, hz)),
                    jax.tree_util.tree_leaves((d1, e1, h1))):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-6, atol=1e-7)


def test_zb1_composes_with_dp():
    pp, dp, M = 2, 2, 4
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M, mb=4)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    ref, refg = jax.value_and_grad(_seq_ref, argnums=(0, 1, 2))(
        pipe.stacked, emb_w, head_w, tokens, tlabels)
    mesh = HybridMesh(dp=dp, pp=pp, devices=jax.devices()[:dp * pp])
    loss, ds, de, dh = pipeline_train_step(
        pipe, mesh, tokens, tlabels, head_loss_fn=_head_loss,
        head_params=head_w, embed_fn=_embed, embed_params=emb_w,
        batch_axes=("dp",), schedule="zb1")
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves((ds, de, dh)),
                    jax.tree_util.tree_leaves(refg)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-6)


def test_zb1_tail_ticks_and_wq_structure():
    """Structural: zb1 runs M + 3(pp-1) ticks (pp-1 W-only tail ticks past
    1F1B's M + 2(pp-1)) and carries a pp-slot (x, g) deferred-W queue."""
    pp, M, mb, width, seq = 4, 8, 2, 9, 5
    blocks, emb_w, head_w, tokens, tlabels = _setup(
        n_layers=4, width=width, M=M, mb=mb, seq=seq)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])

    def step(stacked, x, y, ep, hp, schedule):
        pipe.stacked = stacked
        return pipeline_train_step(pipe, mesh, x, y,
                                   head_loss_fn=_head_loss, head_params=hp,
                                   embed_fn=_embed, embed_params=ep,
                                   schedule=schedule)

    txt = str(jax.make_jaxpr(step, static_argnums=(5,))(
        pipe.stacked, tokens, tlabels, emb_w, head_w, "zb1")
    ).replace(" ", "")
    t_zb = M + 3 * (pp - 1)
    # the schedule scan iterates the tick index array [T]
    assert f"iota[dtype=int32shape=({t_zb},)" in txt or \
        f"i32[{t_zb}]" in txt, "expected M + 3(pp-1) ticks in zb1"
    # two [pp, mb, seq, width] queue buffers ride the carry
    assert txt.count(f"f32[{pp},{mb},{seq},{width}]") >= 2, \
        "expected the pp-slot deferred-W (x, g) queue in the carry"


def test_bad_schedule_name_raises():
    blocks, emb_w, head_w, tokens, tlabels = _setup()
    pipe = PipelineLayer(blocks, num_stages=2, num_microbatches=4)
    mesh = HybridMesh(pp=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        pipeline_train_step(pipe, mesh, tokens, tlabels,
                            head_loss_fn=_head_loss, head_params=head_w,
                            embed_fn=_embed, embed_params=emb_w,
                            schedule="gpipe")
