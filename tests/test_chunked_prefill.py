"""Chunked prefill (vLLM-style): prompts longer than max_prompt_len
stream in across engine ticks — interleaved with live decode — and the
result equals the solo greedy decode exactly."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import LLMEngine, Request


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def test_long_prompt_streams_in_and_matches_solo(model):
    rs = np.random.RandomState(0)
    long_p = rs.randint(0, 64, (19,))    # >> max_prompt_len=8: 3 chunks
    short_p = rs.randint(0, 64, (5,))
    new = 6
    ref_long = np.asarray(generate(model, long_p[None], max_new_tokens=new,
                                   eos_token_id=1))[0]
    ref_short = np.asarray(generate(model, short_p[None],
                                    max_new_tokens=new, eos_token_id=1))[0]

    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32, eos_token_id=1)
    r_short = eng.add_request(Request(short_p, max_new_tokens=new))
    r_long = eng.add_request(Request(long_p, max_new_tokens=new))
    ticks_with_decode_during_prefill = 0
    while eng.has_work():
        before = bool(eng.prefilling)
        out = eng.step()
        if before and any(rid == r_short for rid, _ in out):
            ticks_with_decode_during_prefill += 1
    out = {rid: r.tokens for rid, r in eng.requests.items()}

    def want(ref, p, got):
        w = [int(t) for t in ref[len(p): len(p) + len(got)]]
        assert got == w, (got, w)

    want(ref_long, long_p, out[r_long])
    want(ref_short, short_p, out[r_short])
    # the short request actually decoded WHILE the long prompt prefilled
    assert ticks_with_decode_during_prefill > 0
    assert eng.mgr.free_blocks == eng.mgr.num_blocks


def test_chunked_prefill_exact_boundary_and_oversubscription(model):
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, 64, (int(n),)) for n in (16, 9, 4, 21)]
    new = 5
    refs = [np.asarray(generate(model, p[None], max_new_tokens=new,
                                eos_token_id=1))[0] for p in prompts]
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32, eos_token_id=1)
    rids = [eng.add_request(Request(p, max_new_tokens=new))
            for p in prompts]
    out = eng.run()
    for rid, p, ref in zip(rids, prompts, refs):
        got = out[rid]
        assert got == [int(t) for t in ref[len(p): len(p) + len(got)]]
    assert eng.mgr.free_blocks == eng.mgr.num_blocks


def test_beam_plus_long_prompt_refused(model):
    eng = LLMEngine(model, num_slots=4, block_size=4, max_prompt_len=8,
                    max_seq_len=32)
    with pytest.raises(ValueError, match="chunked prefill"):
        eng.add_request(Request(np.arange(12), num_beams=2))


def test_chunk_kernel_logits_equal_one_shot_prefill(model):
    """Numeric (not just argmax) equivalence: chunk-prefilling a prompt
    into slot 1 — batch ROW 0 targeting SLOT 1, the row != slot case —
    yields the same final logits and pool contents as one-shot
    prefilling the same prompt, while slot 0 holds a SHORTER sequence
    whose lens must not bleed into the chunk mask."""
    from paddle_tpu.models.paged import (PagedKVCache, RefBlockManager,
                                         llama_prefill_chunk_paged,
                                         llama_prefill_paged)
    cfg = model.cfg
    rs = np.random.RandomState(7)
    short_p = rs.randint(0, 64, (5,))
    long_p = rs.randint(0, 64, (14,))
    bs, nb, mb, slots = 4, 16, 8, 2

    def fresh():
        return PagedKVCache.init(cfg.num_hidden_layers, nb, bs,
                                 cfg.num_key_value_heads,
                                 cfg.hidden_size // cfg.num_attention_heads,
                                 slots, mb, cfg.dtype)

    # reference: one-shot prefill of the long prompt alone
    mgr_r = RefBlockManager(nb, bs)
    t_ref = mgr_r.allocate("x", len(long_p))
    rows_r = np.full((1, mb), nb, np.int32)
    rows_r[0, :len(t_ref)] = t_ref
    ref_logits, _ = llama_prefill_paged(
        model, jnp.asarray(long_p[None]), jnp.asarray([len(long_p)]),
        fresh(), jnp.asarray([0], jnp.int32), jnp.asarray(rows_r))

    # engine-shaped: short seq occupies slot 0, long chunks into slot 1
    mgr = RefBlockManager(nb, bs)
    cache = fresh()
    t0 = mgr.allocate("s", len(short_p))
    rows0 = np.full((1, mb), nb, np.int32)
    rows0[0, :len(t0)] = t0
    _, cache = llama_prefill_paged(
        model, jnp.asarray(short_p[None]), jnp.asarray([len(short_p)]),
        cache, jnp.asarray([0], jnp.int32), jnp.asarray(rows0))
    off = 0
    for chunk in (long_p[:8], long_p[8:]):
        t1 = mgr.allocate("l", off + len(chunk))
        rows1 = np.full((1, mb), nb, np.int32)
        rows1[0, :len(t1)] = t1
        last, cache = llama_prefill_chunk_paged(
            model, jnp.asarray(chunk[None]),
            jnp.asarray([len(chunk)], jnp.int32),
            jnp.asarray([off], jnp.int32), cache,
            jnp.asarray([1], jnp.int32), jnp.asarray(rows1))
        off += len(chunk)
    np.testing.assert_allclose(np.asarray(last, np.float32),
                               np.asarray(ref_logits, np.float32),
                               rtol=2e-4, atol=2e-5)
