"""Host/device overlap tests (ISSUE 3): prefetch-to-device units, the
pipelined (deferred-sync) train loop's bit-exact equivalence to the
synchronous one, NaN attribution under pipelining, and the /metrics
pull endpoint. The conftest ``_no_leaked_threads`` fixture rides along
on every test here — a prefetch producer, checkpoint writer, or HTTP
thread that outlives its test fails that test."""
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io import DataLoader, TensorDataset, prefetch_to_device
from paddle_tpu.observability import METRICS, MetricsServer
from paddle_tpu.train.trainer import Trainer, TrainerArgs


# ------------------------------------------------------------- prefetch

def test_prefetch_preserves_order_and_lands_on_device():
    batches = [np.full((2, 2), i, np.float32) for i in range(10)]
    out = list(prefetch_to_device(iter(batches), depth=3))
    assert len(out) == 10
    for i, b in enumerate(out):
        assert isinstance(b, jax.Array)          # landed, not host numpy
        np.testing.assert_array_equal(np.asarray(b), batches[i])


def test_prefetch_handles_pytree_batches():
    def gen():
        for i in range(4):
            yield {"x": np.ones((2,), np.float32) * i,
                   "y": (np.zeros((1,), np.int32) + i, i)}
    out = list(prefetch_to_device(gen(), depth=2))
    assert len(out) == 4
    assert isinstance(out[3]["x"], jax.Array)
    assert float(out[3]["x"][0]) == 3.0
    assert int(out[2]["y"][0][0]) == 2
    assert int(out[2]["y"][1]) == 2              # scalar leaf lands too


def test_prefetch_propagates_iterator_exception_in_order():
    def bad_gen():
        yield np.ones((2,), np.float32)
        yield np.ones((2,), np.float32) * 2
        raise ValueError("source died")

    p = prefetch_to_device(bad_gen(), depth=4)
    assert float(next(p)[0]) == 1.0              # good batches come first
    assert float(next(p)[0]) == 2.0
    with pytest.raises(ValueError, match="source died"):
        next(p)
    with pytest.raises(StopIteration):           # terminal after the error
        next(p)


def test_prefetch_close_unblocks_full_queue_producer():
    produced = []

    def slow_to_drain():
        for i in range(1000):
            produced.append(i)
            yield np.full((1,), i, np.float32)

    p = prefetch_to_device(slow_to_drain(), depth=2)
    assert float(next(p)[0]) == 0.0
    time.sleep(0.1)                              # let the producer fill up
    p.close()                                    # must not deadlock
    assert not p._thread.is_alive()
    assert len(produced) < 1000                  # stopped early, not drained
    with pytest.raises(StopIteration):
        next(p)
    p.close()                                    # idempotent


def test_prefetch_context_manager_and_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        prefetch_to_device(iter([]), depth=0)
    with prefetch_to_device(iter([np.ones(2)] * 50), depth=2) as p:
        next(p)
    assert not p._thread.is_alive()              # __exit__ reaped it


def test_prefetch_queue_depth_and_stall_metrics():
    list(prefetch_to_device(iter([np.ones(2)] * 5), depth=2))
    snap = METRICS.snapshot()
    assert snap["gauges"]["io_prefetch_queue_depth"] == 0   # reset on drain
    # 6 gets (5 batches + the END marker) each timed a stall sample
    assert snap["histograms"]["io_prefetch_stall_seconds"]["count"] == 6


def test_dataloader_prefetch_wires_through():
    xs = np.arange(32, dtype=np.float32).reshape(16, 2)
    ys = np.arange(16, dtype=np.int64)
    dl = DataLoader(TensorDataset(xs, ys), batch_size=4)
    got = list(dl.prefetch(depth=2))
    assert len(got) == 4
    assert isinstance(got[0][0], jax.Array)
    np.testing.assert_array_equal(np.asarray(got[0][0]), xs[:4])
    np.testing.assert_array_equal(np.asarray(got[3][1]), ys[12:])


# ------------------------------------------- pipelined fit ≡ synchronous

def _fixed_batches(n=12, b=4, d=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, b, d)).astype(np.float32)
    W = np.array([[1.0], [-2.0], [0.5]], np.float32)
    return [(X[i], X[i] @ W) for i in range(n)]


def _make_trainer(max_steps, depth, seed=0, log_every=1, **kw):
    pt.seed(seed)
    net = nn.Sequential(nn.Linear(3, 8), nn.Tanh(), nn.Linear(8, 1))
    args = TrainerArgs(max_steps=max_steps, log_every=log_every,
                       pipeline_depth=depth, **kw)
    return Trainer(net, opt.SGD(learning_rate=0.05),
                   lambda m, x, y: nn.functional.mse_loss(m(x), y), args)


@pytest.mark.parametrize("depth,log_every", [(1, 1), (3, 1), (3, 3)])
def test_pipelined_fit_bit_identical_to_sync(depth, log_every):
    """log_every=1 checks every per-step loss; log_every=3 with depth=3
    actually keeps the window full between boundaries (a log boundary
    drains it, so per-step logging degenerates to near-sync)."""
    data = _fixed_batches()
    tr_sync = _make_trainer(12, 0, log_every=log_every)
    s_sync = tr_sync.fit(iter(data))
    tr_pipe = _make_trainer(12, depth, log_every=log_every)
    s_pipe = tr_pipe.fit(iter(data))

    assert int(s_pipe.step) == int(s_sync.step) == 12
    # the loss history (per-step at log_every=1) must agree BITWISE
    assert len(tr_pipe.history) == len(tr_sync.history) == 12 // log_every
    for ha, hb in zip(tr_sync.history, tr_pipe.history):
        assert ha["step"] == hb["step"]
        assert ha["loss"] == hb["loss"]          # bit-identical, no tolerance
        assert ha["lr"] == hb["lr"]
    # and so must every parameter
    for pa, pb in zip(jax.tree_util.tree_leaves(s_sync.model),
                      jax.tree_util.tree_leaves(s_pipe.model)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_pipelined_fit_with_prefetch_bit_identical():
    data = _fixed_batches()
    s_sync = _make_trainer(12, 0).fit(iter(data))
    tr = _make_trainer(12, 2)
    with prefetch_to_device(iter(data), depth=2) as p:
        s_pipe = tr.fit(p)
    for pa, pb in zip(jax.tree_util.tree_leaves(s_sync.model),
                      jax.tree_util.tree_leaves(s_pipe.model)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


@pytest.mark.chaos
def test_pipelined_nan_attribution_matches_sync():
    """A 2-step injected NaN storm: skip counts, streaks, metrics, and
    checkpoint cadence must match the synchronous loop — the host step
    mirror may lag the device but never diverge from it."""
    from paddle_tpu.utils.faults import FAULTS

    def run(depth, tmpdir):
        FAULTS.clear()
        FAULTS.install("train.loss", on={2, 3}, action=lambda c: float("nan"))
        tr = _make_trainer(8, depth, max_bad_steps=10,
                           ckpt_every=4, ckpt_dir=str(tmpdir))
        state = tr.fit(iter(_fixed_batches(8)))
        FAULTS.clear()
        return tr, state

    import tempfile
    with tempfile.TemporaryDirectory() as da, \
            tempfile.TemporaryDirectory() as db:
        tr_a, st_a = run(0, da)
        tr_b, st_b = run(3, db)
        assert int(st_a.step) == int(st_b.step) == 8
        assert tr_a.stats == tr_b.stats == {"nan_skips": 2,
                                            "bad_streak_max": 2}
        from paddle_tpu.train.checkpoint import CheckpointManager
        assert (CheckpointManager(da).all_steps()
                == CheckpointManager(db).all_steps() == [4, 8])
        for pa, pb in zip(jax.tree_util.tree_leaves(st_a.model),
                          jax.tree_util.tree_leaves(st_b.model)):
            np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_pipelined_fit_emits_drain_spans():
    from paddle_tpu.observability import TRACER
    TRACER.enable()
    _make_trainer(4, 2).fit(iter(_fixed_batches(4)))
    names = [e["name"] for e in TRACER.export()["traceEvents"]]
    assert names.count("train.step") == 4
    assert names.count("train.drain") == 4


@pytest.mark.parametrize("depth", [0, 2])
def test_device_double_buffer_bit_identical(depth):
    """device_double_buffer stages step N+1's microbatches while step N
    executes; the dispatch sequence is unchanged, so losses and params
    must be bit-identical to the plain loop — including at depth 0,
    where double-buffering alone routes through the pipelined loop."""
    data = _fixed_batches()
    s_ref = _make_trainer(12, depth).fit(iter(data))
    tr_ref = _make_trainer(12, depth)
    s_ref = tr_ref.fit(iter(data))
    tr_db = _make_trainer(12, depth, device_double_buffer=True)
    s_db = tr_db.fit(iter(data))
    assert int(s_db.step) == int(s_ref.step) == 12
    for ha, hb in zip(tr_ref.history, tr_db.history):
        assert ha["step"] == hb["step"]
        assert ha["loss"] == hb["loss"]          # bit-identical
    for pa, pb in zip(jax.tree_util.tree_leaves(s_ref.model),
                      jax.tree_util.tree_leaves(s_db.model)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_device_double_buffer_consumes_no_extra_batches():
    """The lookahead must stop one step early — exactly max_steps*accum
    batches are drawn, same as the synchronous loop (a finite iterator
    sized to the run must not StopIteration)."""
    data = _fixed_batches(n=8)               # exactly 8 steps of batches
    tr = _make_trainer(8, 0, device_double_buffer=True,
                       grad_accum_steps=1)
    state = tr.fit(iter(data))               # would raise if it over-read
    assert int(state.step) == 8


def test_device_double_buffer_with_grad_accum_bit_identical():
    data = _fixed_batches(n=12)
    tr_ref = _make_trainer(6, 0, grad_accum_steps=2)
    s_ref = tr_ref.fit(iter(data))
    tr_db = _make_trainer(6, 2, grad_accum_steps=2,
                          device_double_buffer=True)
    s_db = tr_db.fit(iter(data))
    for pa, pb in zip(jax.tree_util.tree_leaves(s_ref.model),
                      jax.tree_util.tree_leaves(s_db.model)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


def test_pipelined_async_ckpt_end_to_end(tmp_path):
    """pipeline_depth + async_ckpt together: fit() returning implies the
    final checkpoint is durable (fit calls mgr.wait() at exit)."""
    from paddle_tpu.train.checkpoint import CheckpointManager
    tr = _make_trainer(8, 2, ckpt_every=4, ckpt_dir=str(tmp_path),
                       async_ckpt=True)
    state = tr.fit(iter(_fixed_batches(8)))
    assert int(state.step) == 8
    mgr = CheckpointManager(tmp_path)
    assert mgr.latest_step() == 8
    restored = mgr.restore(tr.state)
    assert int(restored.step) == 8


@pytest.mark.slow
def test_pipelined_overlap_beats_sync_on_host_bound_iterator():
    """The acceptance bar: ≥20% steps/sec over sync when the host is the
    bottleneck. Calibrated — the iterator sleeps for one measured device
    step, so sync pays host+device serially while the pipelined loop
    overlaps them (kept out of tier-1: wall-clock assertions are
    machine-sensitive)."""
    rng = np.random.default_rng(0)
    data = [(rng.standard_normal((128, 128)).astype(np.float32),
             rng.standard_normal((128, 1)).astype(np.float32))
            for _ in range(30)]

    def make(depth):
        # a substantial device step (~10ms CPU): a too-cheap one would
        # leave the pipeline nothing to hide behind the host sleep
        pt.seed(0)
        net = nn.Sequential(nn.Linear(128, 512), nn.Tanh(),
                            nn.Linear(512, 512), nn.Tanh(),
                            nn.Linear(512, 1))
        return Trainer(net, opt.SGD(learning_rate=0.05),
                       lambda m, x, y: nn.functional.mse_loss(m(x), y),
                       TrainerArgs(max_steps=30, log_every=10,
                                   pipeline_depth=depth))

    def steady_sps(tr):
        # the first record pays the per-fit jit compile — drop it
        recs = tr.history[1:]
        return sum(r["steps_per_sec"] for r in recs) / len(recs)

    cal = make(0)
    cal.fit(iter(data))
    # sleep one measured steady-state device step per batch: sync pays
    # host+device (~2d) serially, the pipelined loop ~max(host, device)
    d_step = min(max(1.0 / steady_sps(cal), 0.005), 0.1)

    def slow_iter():
        for b in data:
            time.sleep(d_step)
            yield b

    def run(depth):
        tr = make(depth)
        if depth:
            with prefetch_to_device(slow_iter(), depth=depth) as p:
                tr.fit(p)
        else:
            tr.fit(slow_iter())
        return steady_sps(tr)

    sync_sps = run(0)
    pipe_sps = run(3)
    assert pipe_sps >= 1.2 * sync_sps, (sync_sps, pipe_sps)


# ------------------------------------------------------ /metrics endpoint

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def test_metrics_http_endpoint_serves_prometheus():
    METRICS.counter("overlap_test_hits_total", "endpoint test counter").inc(3)
    with MetricsServer(port=0, host="127.0.0.1") as srv:
        assert srv.port != 0                     # ephemeral port resolved
        status, ctype, body = _get(srv.url)
        assert status == 200
        assert "version=0.0.4" in ctype
        assert "overlap_test_hits_total 3" in body
        status, ctype, body = _get(srv.url + ".json")
        import json
        assert json.loads(body)["counters"]["overlap_test_hits_total"] == 3
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/nope")
        assert ei.value.code == 404
    # __exit__ stopped the server: socket closed, thread reaped
    assert not any(t.name == "pt-metrics-http" for t in threading.enumerate())
    with pytest.raises(urllib.error.URLError):
        _get(f"http://127.0.0.1:{srv.port}/metrics", timeout=0.5)


def test_metrics_server_module_default_start_stop():
    from paddle_tpu.observability import (start_metrics_server,
                                          stop_metrics_server)
    srv = start_metrics_server(port=0, host="127.0.0.1")
    assert start_metrics_server() is srv         # idempotent
    status, _, _ = _get(srv.url)
    assert status == 200
    stop_metrics_server()
    stop_metrics_server()                        # no-op when already down
