"""Child program for the 2-process multi-host integration test.

Launched twice by tests/test_multihost.py with COORDINATOR_ADDRESS /
NUM_PROCESSES / PROCESS_ID in the env (the paddle_tpu.distributed.launch
contract). Exercises, against a REAL second process:
  * launch.initialize_cluster (jax.distributed over the CPU backend)
  * a cross-process device collective through GSPMD (global-mesh sum)
  * collective.all_gather_object (pickled host data)
  * DistributedBatchSampler per-host disjoint sharding
  * TokenBinDataset per-host stream sharding (native C++ loader)
  * multi-host checkpoint: rank 0 writes, barrier, both ranks restore
Prints one "MULTIHOST_OK <json>" line on success (the parent asserts it).
"""
import json
import os
import sys
import tempfile

import numpy as np


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed import launch
    launch.initialize_cluster()
    assert jax.process_count() == 2, jax.process_count()
    pid = jax.process_index()
    results = {"pid": pid}

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    # -- cross-process device collective (GSPMD-inserted all-reduce) -------
    devs = np.asarray(jax.devices())            # 2 global devices
    mesh = Mesh(devs, ("dp",))
    local = jnp.asarray([np.float32(pid + 1)])  # host-local shard
    garr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp"))
    total = jax.jit(jnp.sum,
                    in_shardings=NamedSharding(mesh, P("dp")),
                    out_shardings=NamedSharding(mesh, P()))(garr)
    val = float(np.asarray(total.addressable_data(0)))
    assert val == 3.0, val  # 1 (rank0) + 2 (rank1)
    results["global_sum"] = val

    # -- all_gather_object --------------------------------------------------
    from paddle_tpu.distributed.collective import all_gather_object
    objs = all_gather_object({"rank": pid, "payload": list(range(pid + 2))})
    assert [o["rank"] for o in objs] == [0, 1], objs
    assert objs[1]["payload"] == [0, 1, 2]
    results["all_gather_object"] = True

    # -- per-host data sharding (DistributedBatchSampler) -------------------
    from paddle_tpu.io import DistributedBatchSampler
    ds = list(range(16))
    sampler = DistributedBatchSampler(ds, batch_size=2)  # auto rank/world
    local_idx = [i for batch in sampler for i in batch]
    gathered = all_gather_object(local_idx)
    flat = sorted(i for part in gathered for i in part)
    assert flat == list(range(16)), flat                 # full coverage
    assert not (set(gathered[0]) & set(gathered[1]))     # disjoint
    results["sampler_disjoint"] = True

    # -- token-bin stream sharding (native loader, per-host streams) --------
    shared_dir = os.environ["MULTIHOST_SHARED_DIR"]
    bin_path = os.path.join(shared_dir, "tokens.bin")
    if pid == 0:
        np.arange(4096, dtype=np.uint16).tofile(bin_path)
    multihost_utils.sync_global_devices("tokenbin_written")
    from paddle_tpu.io.token_bin import TokenBinDataset
    tb = TokenBinDataset(bin_path, batch_size=2, seq_len=16, seed=7,
                         num_batches=4)  # shard auto-detected
    mine = np.concatenate([x for x, _ in tb], axis=None)
    streams = all_gather_object(mine.tolist())
    assert streams[0] != streams[1], "host streams must differ"
    # same rank+seed reproduces its stream
    tb2 = TokenBinDataset(bin_path, batch_size=2, seq_len=16, seed=7,
                          num_batches=4)
    again = np.concatenate([x for x, _ in tb2], axis=None)
    assert streams[pid] == again.tolist()
    results["token_bin_sharded"] = True

    # -- multi-host checkpoint: rank 0 writes, everyone restores ------------
    from paddle_tpu.train.checkpoint import CheckpointManager
    state = {"w": jnp.full((4,), 2.0 + pid), "step": jnp.asarray(3)}
    ckdir = os.path.join(shared_dir, "ckpt")
    mgr = CheckpointManager(ckdir, max_to_keep=2)
    if pid == 0:
        mgr.save(3, state)
    multihost_utils.sync_global_devices("ckpt_saved")
    restored = mgr.restore(state)
    np.testing.assert_allclose(np.asarray(restored["w"]), 2.0)  # rank 0's
    assert int(restored["step"]) == 3
    results["checkpoint"] = True

    multihost_utils.sync_global_devices("done")
    print("MULTIHOST_OK " + json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
