"""Sort-based MoE dispatch (VERDICT r2 item 4).

* sparse route == dense GShard gate, including under saturation (same
  keep/drop decisions, same outputs)
* drop-rate counter observable; zero with ample capacity
* E=64 / T=16k dispatch traces without materialising any [T, E, C]-sized
  intermediate
* explicit shard_map all_to_all over the real ep mesh axis == single
  device, forward AND grads; composes with tp (MoE LLM loss equality)
Ref: python/paddle/incubate/distributed/models/moe/ (c_alltoall dispatch).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.mesh import HybridMesh
from paddle_tpu.distributed.moe import (MoELayer, expert_mlp_apply,
                                        sparse_combine, sparse_dispatch,
                                        top_k_gate, top_k_route)


def _dense_reference(moe, x):
    """The O(T·E·C) GShard einsum formulation as executable spec."""
    b, s, h = x.shape
    t = b * s
    cap = moe._capacity(t)
    xt = x.reshape(t, h)
    logits = xt.astype(jnp.float32) @ moe.gate_w
    dispatch, combine, aux = top_k_gate(logits, moe.k, cap)
    x_e = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
    y_e = expert_mlp_apply(x_e, moe.experts.gate_up, moe.experts.down)
    yt = jnp.einsum("tec,ech->th", combine.astype(x.dtype), y_e)
    return yt.reshape(b, s, h), aux


@pytest.mark.parametrize("capacity_factor", [1.25, 0.4])
def test_sparse_equals_dense(capacity_factor):
    """Same outputs as the dense GShard spec — ample AND saturated."""
    pt.seed(0)
    moe = MoELayer(hidden=16, intermediate=32, num_experts=8, k=2,
                   capacity_factor=capacity_factor, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 16), jnp.float32)
    ref, aux_ref = _dense_reference(moe, x)
    got, aux, metrics = moe(x, return_metrics=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)
    if capacity_factor < 1.0:
        assert float(metrics["drop_rate"]) > 0.0


def test_route_matches_gate_decisions():
    """keep/drop and slot positions identical to the dense gate."""
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(64, 8), jnp.float32)
    cap = 10  # saturating for 64*2/8 = 16 mean load
    dispatch, combine, _ = top_k_gate(logits, 2, cap)
    route, _, drop = top_k_route(logits, 2, cap)

    dense = np.asarray(dispatch)  # [T, E, C]
    r_tok = np.asarray(route["tok"])
    r_e = np.asarray(route["expert"])
    r_pos = np.asarray(route["pos"])
    r_keep = np.asarray(route["keep"])
    for i in range(len(r_tok)):
        if r_keep[i]:
            assert dense[r_tok[i], r_e[i], r_pos[i]]
    assert dense.sum() == r_keep.sum()
    assert float(drop) == pytest.approx(1.0 - r_keep.mean())


def test_drop_rate_zero_with_ample_capacity():
    pt.seed(0)
    moe = MoELayer(hidden=8, intermediate=16, num_experts=4, k=2,
                   capacity_factor=8.0, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 8), jnp.float32)
    _, _, metrics = moe(x, return_metrics=True)
    assert float(metrics["drop_rate"]) == 0.0


def test_no_dense_tec_intermediate_at_scale():
    """E=64, T=16k: the trace must not contain any [T,E,C]-sized buffer."""
    pt.seed(0)
    e, h, t = 64, 32, 16384
    moe = MoELayer(hidden=h, intermediate=2 * h, num_experts=e, k=2,
                   dtype=jnp.float32)
    x = jnp.zeros((8, t // 8, h), jnp.float32)
    jaxpr = jax.make_jaxpr(lambda m, v: m(v)[0])(moe, x)
    cap = moe._capacity(t)
    dense_size = t * e * cap
    biggest = 0
    for eqn in jaxpr.jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            if hasattr(v, "aval") and hasattr(v.aval, "size"):
                biggest = max(biggest, int(v.aval.size))
    # sparse path peak: [E*C, H] dispatch buffer / [N, H] gathers — orders
    # of magnitude under the dense [T, E, C] tensor
    assert biggest < dense_size / 100, (biggest, dense_size)


def test_ep_alltoall_matches_single_device():
    """shard_map all_to_all over the real ep axis == single device (fwd+bwd,
    no drops)."""
    pt.seed(0)
    moe = MoELayer(hidden=16, intermediate=32, num_experts=8, k=2,
                   capacity_factor=8.0, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 8, 16), jnp.float32)

    ref, aux_ref = moe(x)

    def loss(m, v):
        y, aux = m(v)
        return jnp.mean(y ** 2) + 0.01 * aux

    ref_loss, ref_grads = pt.value_and_grad(loss)(moe, x)

    mesh = HybridMesh(ep=8)
    with mesh:
        xs = jax.device_put(x, mesh.batch_sharding())
        out, aux = jax.jit(lambda m, v: m(v))(moe, xs)
        got_loss, got_grads = jax.jit(pt.value_and_grad(loss))(moe, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)
    np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(got_grads)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=1e-6)


def test_dp_times_ep_matches_single_device():
    """Tokens shard over dp AND ep; per-rank capacity accounts for both."""
    pt.seed(0)
    moe = MoELayer(hidden=16, intermediate=32, num_experts=4, k=2,
                   capacity_factor=8.0, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 8, 16), jnp.float32)
    ref, aux_ref = moe(x)
    mesh = HybridMesh(dp=2, ep=2, devices=jax.devices()[:4])
    with mesh:
        xs = jax.device_put(x, mesh.batch_sharding())
        out, aux = jax.jit(lambda m, v: m(v))(moe, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_ep_saturation_reports_drops():
    """Per-rank local capacity saturates -> drop_rate > 0 and finite out."""
    pt.seed(0)
    moe = MoELayer(hidden=8, intermediate=16, num_experts=4, k=2,
                   capacity_factor=0.3, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16, 8), jnp.float32)
    mesh = HybridMesh(ep=4, devices=jax.devices()[:4])
    with mesh:
        xs = jax.device_put(x, mesh.batch_sharding())
        y, _, metrics = jax.jit(
            lambda m, v: m(v, return_metrics=True))(moe, xs)
    assert float(metrics["drop_rate"]) > 0.0
    assert np.isfinite(np.asarray(y)).all()


def test_moe_llm_ep_times_tp_loss_equality():
    """The full MoE LLM trains under ep x tp with loss EQUAL to single
    device (attention tp-sharded, experts over the ep all_to_all)."""
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models.moe_llm import MoEConfig, MoEForCausalLM

    pt.seed(0)
    cfg = MoEConfig(base=LlamaConfig.tiny(), num_experts=4, top_k=2,
                    capacity_factor=8.0, moe_every=2)
    model = MoEForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)))
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((4, 1), ids.dtype)], axis=1)

    ref = float(model.loss(ids, labels))

    from paddle_tpu.distributed import shard_module
    mesh = HybridMesh(ep=2, tp=2, devices=jax.devices()[:4])
    with mesh:
        ms = shard_module(model, mesh, min_size=1)
        ids_s = jax.device_put(ids, mesh.batch_sharding())
        labels_s = jax.device_put(labels, mesh.batch_sharding())
        got = float(jax.jit(lambda m, i, l: m.loss(i, l))(ms, ids_s, labels_s))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_sparse_dispatch_combine_roundtrip():
    """identity experts -> combine(dispatch(x)) == sum_k gate * x = x."""
    rs = np.random.RandomState(2)
    t, h, e, cap = 32, 4, 4, 32
    xt = jnp.asarray(rs.randn(t, h), jnp.float32)
    logits = jnp.asarray(rs.randn(t, e), jnp.float32)
    route, _, drop = top_k_route(logits, 2, cap)
    assert float(drop) == 0.0
    x_e, dest = sparse_dispatch(xt, route, e, cap)
    yt = sparse_combine(x_e, route, dest, t)
    np.testing.assert_allclose(np.asarray(yt), np.asarray(xt),
                               rtol=1e-5, atol=1e-6)
