"""Paged KV cache + continuous batched decode (VERDICT r1 missing #4):
kernel parity vs gather reference, ragged-batch generation parity vs the
static-cache generate(), block recycling, and the Σ-lengths memory bound."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import (BlockManager, PagedKVCache,
                                     llama_prefill_paged, paged_generate)
from paddle_tpu.ops.pallas.paged_attention import (
    paged_decode_attention_pallas, paged_decode_attention_xla)


def test_paged_kernel_matches_gather_reference():
    rs = np.random.RandomState(0)
    b, h, hkv, d, nb, bs, mb = 3, 4, 2, 16, 8, 8, 3
    q = jnp.asarray(rs.randn(b, h, d).astype(np.float32))
    k_pool = jnp.asarray(rs.randn(nb, bs, hkv, d).astype(np.float32))
    v_pool = jnp.asarray(rs.randn(nb, bs, hkv, d).astype(np.float32))
    tables = jnp.asarray([[0, 3, 5], [1, 2, nb], [4, nb, nb]], jnp.int32)
    lens = jnp.asarray([20, 11, 3], jnp.int32)
    ref = paged_decode_attention_xla(q, k_pool, v_pool, tables, lens)
    got = paged_decode_attention_pallas(q, k_pool, v_pool, tables, lens,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_block_manager_alloc_free_recycle():
    mgr = BlockManager(num_blocks=6, block_size=4)
    t0 = mgr.allocate(0, 9)     # 3 blocks
    t1 = mgr.allocate(1, 8)     # 2 blocks
    assert len(t0) == 3 and len(t1) == 2 and mgr.free_blocks == 1
    assert set(t0).isdisjoint(t1)
    mgr.allocate(1, 12)         # grow to 3 blocks
    assert mgr.free_blocks == 0
    with pytest.raises(MemoryError):
        mgr.allocate(0, 16)     # would need a 4th block, none free
    mgr.free(1)
    assert mgr.free_blocks == 3
    t2 = mgr.allocate(2, 4)     # must recycle one of seq 1's freed blocks
    assert t2[0] in set(t1)


def _tiny_model(seed=0):
    pt.seed(seed)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def test_paged_generate_matches_static_cache_uniform():
    from paddle_tpu.models.decoding import generate
    model = _tiny_model()
    rs = np.random.RandomState(1)
    b, s, new = 2, 12, 8
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    ref = generate(model, ids, max_new_tokens=new)          # greedy
    got, _ = paged_generate(model, ids, np.full((b,), s), max_new_tokens=new,
                            block_size=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_paged_generate_ragged_matches_per_row():
    """Each ragged row must equal generating that row alone."""
    from paddle_tpu.models.decoding import generate
    model = _tiny_model()
    rs = np.random.RandomState(2)
    lens = [10, 6, 3]
    b, smax, new = len(lens), max(lens), 6
    rows = [rs.randint(0, 64, (n,)) for n in lens]
    padded = np.zeros((b, smax), np.int64)
    for i, r in enumerate(rows):
        padded[i, :len(r)] = r
    got, cache = paged_generate(model, jnp.asarray(padded),
                                np.asarray(lens), max_new_tokens=new,
                                block_size=4)
    for i, r in enumerate(rows):
        ref = generate(model, jnp.asarray(r[None]), max_new_tokens=new)
        np.testing.assert_array_equal(
            np.asarray(got[i, : lens[i] + new]), np.asarray(ref[0]),
            err_msg=f"row {i} (len {lens[i]}) diverged from solo decode")


def test_paged_generate_sliding_window_matches_static():
    """Mistral-style sliding window: decode masks to the last W positions,
    matching prefill semantics and the static ring-cache generate()."""
    from paddle_tpu.models.decoding import generate
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, sliding_window=6)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(7)
    b, s, new = 2, 10, 8  # generation runs well past the window
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    ref = generate(model, ids, max_new_tokens=new)
    got, _ = paged_generate(model, ids, np.full((b,), s), max_new_tokens=new,
                            block_size=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_paged_memory_bound_is_sum_of_lengths():
    """Pool capacity ≈ Σ(len_i + new), NOT B × max_len."""
    model = _tiny_model()
    rs = np.random.RandomState(3)
    lens = [40, 4, 4, 4]
    b, smax, new, bs = len(lens), max(lens), 4, 4
    padded = np.zeros((b, smax), np.int64)
    for i, n in enumerate(lens):
        padded[i, :n] = rs.randint(0, 64, (n,))
    got, cache = paged_generate(model, jnp.asarray(padded), np.asarray(lens),
                                max_new_tokens=new, block_size=bs)
    ragged_bound = sum(-(-(n + new) // bs) * bs for n in lens)
    dense_bound = b * (smax + new)
    assert cache.pool_tokens() == ragged_bound
    assert cache.pool_tokens() < dense_bound, (
        f"pool {cache.pool_tokens()} should undercut dense {dense_bound}")


def test_paged_generate_eos_frees_blocks():
    """A row hitting EOS stops and its blocks are recyclable: a pool sized
    for the RAGGED bound still serves all rows (no corruption of others)."""
    from paddle_tpu.models.decoding import generate
    model = _tiny_model()
    rs = np.random.RandomState(4)
    b, s, new = 2, 8, 6
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    ref = generate(model, ids, max_new_tokens=new)
    # pick the token the reference generates FIRST for row 0 as "EOS":
    eos = int(np.asarray(ref)[0, s])
    got, _ = paged_generate(model, ids, np.full((b,), s), max_new_tokens=new,
                            block_size=4, eos_token_id=eos)
    g = np.asarray(got)
    r = np.asarray(ref)
    # row 0 froze right after EOS (padded with the same token)
    assert g[0, s] == eos and np.all(g[0, s:] == eos)
    # other rows keep decoding exactly as the reference until/unless EOS
    row1_ref = r[1]
    stop = np.nonzero(row1_ref[s:] == eos)[0]
    upto = s + (stop[0] + 1 if len(stop) else new)
    np.testing.assert_array_equal(g[1, :upto], row1_ref[:upto])
