"""Checkpoint crash-safety chaos tests (ISSUE 1 tentpole + satellite).

The durability contract under kill-at-any-point:

  * :func:`save` is atomic — a crash before/at the rename leaves the
    previous complete file untouched (tmp + fsync + ``os.replace``)
  * :func:`load` verifies per-array CRCs — damage raises
    :class:`CheckpointCorruptError`, never restores garbage
  * ``CheckpointManager``'s ``latest`` pointer advances only AFTER the
    durable rename, so a kill during save never leaves an unloadable
    latest; ``restore`` falls back past corrupt checkpoints
  * end-to-end: ``run_elastic`` survives an injected kill mid-save and
    still finishes training
"""
import json

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.train.checkpoint import (CheckpointCorruptError,
                                         CheckpointManager, load, save)
from paddle_tpu.train.elastic import ElasticRunner
from paddle_tpu.train.trainer import Trainer, TrainerArgs
from paddle_tpu.utils.faults import FAULTS, InjectedCrash

pytestmark = pytest.mark.chaos


def _state(v: float):
    return {"w": np.full((4,), v, np.float32), "step": int(v)}


# ----------------------------------------------------------- atomic save

@pytest.mark.parametrize("site", ["ckpt.write", "ckpt.rename"])
def test_kill_during_save_preserves_previous_file(tmp_path, site):
    """A crash at EITHER window — before the tmp write or between the tmp
    write and the rename — must leave the prior complete checkpoint
    loadable and byte-identical."""
    path = tmp_path / "ck.npz"
    save(_state(1.0), path)
    FAULTS.install(site, on={0}, exc=InjectedCrash)
    with pytest.raises(InjectedCrash):
        save(_state(2.0), path)
    FAULTS.clear()
    got = load(path, target=_state(0.0))
    np.testing.assert_array_equal(np.asarray(got["w"]), _state(1.0)["w"])
    assert got["step"] == 1
    # a retried save (the crash window now clear) supersedes cleanly,
    # stale .tmp or not
    save(_state(2.0), path)
    assert load(path, target=_state(0.0))["step"] == 2


def test_save_is_atomic_even_first_time(tmp_path):
    """Crash on the very first save: no final file may exist at all —
    half-written checkpoints must be invisible to readers."""
    path = tmp_path / "ck.npz"
    FAULTS.install("ckpt.rename", on={0}, exc=InjectedCrash)
    with pytest.raises(InjectedCrash):
        save(_state(1.0), path)
    assert not path.exists()
    with pytest.raises(FileNotFoundError):
        load(path)


# ------------------------------------------------------------ CRC verify

def test_truncated_file_raises_corrupt(tmp_path):
    path = tmp_path / "ck.npz"
    save(_state(3.0), path)
    raw = path.read_bytes()
    path.write_bytes(raw[: len(raw) // 2])
    with pytest.raises(CheckpointCorruptError):
        load(path)


def test_crc_mismatch_raises_corrupt(tmp_path):
    """Bit-rot that the zip container misses: rewrite the archive with a
    stored CRC that no longer matches the payload — the meta-level CRC
    check must catch it (and ``verify=False`` must skip it)."""
    path = tmp_path / "ck.npz"
    save(_state(4.0), path)
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    for lm in meta["leaves"]:
        if lm.get("kind") == "array":
            lm["crc"] ^= 0xDEADBEEF
    np.savez(str(path), __meta__=json.dumps(meta), **arrays)
    with pytest.raises(CheckpointCorruptError, match="CRC mismatch"):
        load(path)
    got = load(path, target=_state(0.0), verify=False)   # explicit opt-out
    np.testing.assert_array_equal(np.asarray(got["w"]), _state(4.0)["w"])


# ----------------------------------------------------- manager + pointer

def test_latest_pointer_survives_kill_during_save(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=3)
    mgr.save(1, _state(1.0))
    assert mgr.latest_step() == 1
    FAULTS.install("ckpt.rename", on={0}, exc=InjectedCrash)
    with pytest.raises(InjectedCrash):
        mgr.save(2, _state(2.0))
    FAULTS.clear()
    # pointer never advanced: latest is still the previous GOOD step,
    # and it restores
    assert mgr.latest_step() == 1
    got = mgr.restore(_state(0.0))
    assert got["step"] == 1 and mgr.last_restored_step == 1


def test_restore_falls_back_past_corrupt_latest(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=5)
    for s in (1, 2, 3):
        mgr.save(s, _state(float(s)))
    p3 = mgr._step_path(3)
    p3.write_bytes(p3.read_bytes()[:40])          # rot the newest
    with pytest.warns(UserWarning, match="fell back"):
        got = mgr.restore(_state(0.0))
    assert got["step"] == 2 and mgr.last_restored_step == 2
    # strict modes refuse to time-travel silently
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(_state(0.0), step=3)
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(_state(0.0), fallback=False)


def test_restore_raises_when_nothing_loadable(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=5)
    for s in (1, 2):
        mgr.save(s, _state(float(s)))
    for s in (1, 2):
        mgr._step_path(s).write_bytes(b"not a checkpoint")
    with pytest.raises(CheckpointCorruptError, match="no loadable"):
        mgr.restore(_state(0.0))


def test_retention_never_deletes_latest_target(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, _state(float(s)))
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest_step() == 4
    assert mgr.restore(_state(0.0))["step"] == 4


def test_damaged_pointer_falls_back_to_glob(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=3)
    mgr.save(5, _state(5.0))
    (tmp_path / "latest").write_text("garbage")
    assert mgr.latest_step() == 5
    (tmp_path / "latest").write_text("999")       # dangling reference
    assert mgr.latest_step() == 5


# ---------------------------------------------------- async (background) save

def test_async_save_is_durable_after_wait(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=True)
    mgr.save(1, _state(1.0))       # returns once the snapshot is taken
    mgr.wait()                     # ...and THIS is the durability point
    assert mgr.latest_step() == 1
    got = mgr.restore(_state(0.0))
    assert got["step"] == 1


def test_async_writer_death_preserves_previous_checkpoint(tmp_path):
    """ISSUE 3 crash-safety: the writer dies between the snapshot and the
    rename (injected at the ckpt.rename window, which now fires on the
    writer thread). ``latest`` must never advance, the failure must
    surface at wait(), and restore must land on the previous durable
    step."""
    mgr = CheckpointManager(tmp_path, async_save=True)
    mgr.save(1, _state(1.0))
    mgr.wait()
    FAULTS.install("ckpt.rename", on={0}, exc=InjectedCrash)
    mgr.save(2, _state(2.0))       # returns fine — the crash is in-flight
    with pytest.raises(InjectedCrash):
        mgr.wait()
    FAULTS.clear()
    assert mgr.latest_step() == 1
    got = mgr.restore(_state(0.0))
    assert got["step"] == 1 and mgr.last_restored_step == 1
    mgr.save(2, _state(2.0))       # retry supersedes cleanly
    mgr.wait()
    assert mgr.latest_step() == 2


def test_async_writer_failure_surfaces_at_next_save(tmp_path):
    """A caller that never wait()s between saves still sees the failure:
    save N+1 first drains save N and re-raises its exception."""
    mgr = CheckpointManager(tmp_path, async_save=True)
    FAULTS.install("ckpt.write", on={0}, exc=InjectedCrash)
    mgr.save(1, _state(1.0))
    with pytest.raises(InjectedCrash):
        mgr.save(2, _state(2.0))
    FAULTS.clear()
    mgr.wait()                     # exception already consumed — clean now


def test_async_save_snapshots_before_mutation(tmp_path):
    """The device→host copy happens on the caller's thread BEFORE save
    returns: mutating (or donating) the live buffers afterwards must not
    corrupt the bytes on disk. A stall injected in the write window keeps
    the writer busy while the caller scribbles over the source array."""
    mgr = CheckpointManager(tmp_path, async_save=True)
    live = _state(7.0)
    FAULTS.install("ckpt.write", on={0}, stall_s=0.2)
    mgr.save(1, live)
    live["w"][:] = -1.0            # donation stand-in: buffer reused
    mgr.wait()
    got = mgr.restore(_state(0.0))
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.full((4,), 7.0, np.float32))


def test_async_save_at_most_one_in_flight(tmp_path):
    """A second save must wait out the first writer, never overlap it."""
    import threading as _threading
    mgr = CheckpointManager(tmp_path, async_save=True)
    FAULTS.install("ckpt.write", on={0}, stall_s=0.3)
    mgr.save(1, _state(1.0))
    first_writer = mgr._writer
    mgr.save(2, _state(2.0))       # blocks until save 1 is durable
    assert not first_writer.is_alive()
    assert mgr.latest_step() in (1, 2)   # 1 definitely durable; 2 racing
    mgr.wait()
    assert mgr.latest_step() == 2
    assert sum(t.name == "pt-ckpt-writer"
               for t in _threading.enumerate() if t.is_alive()) == 0


# ------------------------------------------------------ elastic end-to-end

def test_elastic_survives_kill_during_save(tmp_path):
    """Kill the trainer mid-save (between tmp-write and rename) via the
    fault registry: the elastic restart restores the previous durable
    step and finishes all 8 steps; at no point is ``latest`` unloadable."""
    pt.seed(0)

    def loss_fn(m, x, y):
        return nn.functional.mse_loss(m(x), y)

    def make_trainer():
        pt.seed(0)
        net = nn.Sequential(nn.Linear(3, 8), nn.Tanh(), nn.Linear(8, 1))
        return Trainer(net, opt.SGD(learning_rate=0.05), loss_fn,
                       TrainerArgs(max_steps=8, log_every=0, ckpt_every=2,
                                   ckpt_dir=str(tmp_path), nan_guard=False))

    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 3)).astype(np.float32)
    Y = (X @ np.array([[1.0], [-2.0], [0.5]], np.float32))

    def data_fn():
        def gen():
            i = 0
            while True:
                sl = slice((i * 4) % 64, (i * 4) % 64 + 4)
                yield X[sl], Y[sl]
                i += 1
        return gen()

    # saves land at steps 2,4,6,8 -> rename hits 0,1,2,3. Kill hit 1
    # (the step-4 save): restart resumes from step 2, the step-4 save
    # retries clean (hit 2), training runs through step 8.
    FAULTS.install("ckpt.rename", on={1}, exc=InjectedCrash)
    runner = ElasticRunner(make_trainer, max_restarts=2, backoff_s=0.0)
    state = runner.run(data_fn)
    assert int(state.step) == 8
    assert runner.restarts == 1
    assert any("InjectedCrash" in f for f in runner.failures)
    mgr = CheckpointManager(tmp_path)
    assert mgr.latest_step() == 8


def test_elastic_recovers_from_injected_step_exception(tmp_path):
    """train.step chaos site: a one-shot injected exception inside the
    fit loop rides the same restart net as a real device error."""
    pt.seed(0)

    def make_trainer():
        pt.seed(0)
        net = nn.Sequential(nn.Linear(2, 4), nn.Tanh(), nn.Linear(4, 1))
        return Trainer(net, opt.SGD(learning_rate=0.05),
                       lambda m, x, y: nn.functional.mse_loss(m(x), y),
                       TrainerArgs(max_steps=6, log_every=0, ckpt_every=2,
                                   ckpt_dir=str(tmp_path), nan_guard=False))

    rng = np.random.default_rng(1)
    X = rng.standard_normal((32, 2)).astype(np.float32)
    Y = X.sum(1, keepdims=True)

    def data_fn():
        def gen():
            i = 0
            while True:
                sl = slice((i * 4) % 32, (i * 4) % 32 + 4)
                yield X[sl], Y[sl]
                i += 1
        return gen()

    FAULTS.install("train.step", on={4}, exc=InjectedCrash)
    runner = ElasticRunner(make_trainer, max_restarts=2, backoff_s=0.0)
    state = runner.run(data_fn)
    assert int(state.step) == 6
    assert runner.restarts == 1
