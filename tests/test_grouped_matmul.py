"""Grouped (ragged) GEMM vs the dense einsum reference — ISSUE 6.

Covers both implementations (the Pallas kernel through its interpret CPU
path, and the XLA tile-batch lowering) over ragged group partitions
including EMPTY experts and single-token groups, forward and backward,
plus the dropless-mode token-conservation property of the refactored
MoELayer and the PT_GROUPED_GEMM=0 kill switch (bit-compatible dense
path).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.moe import (
    MoELayer,
    expert_mlp_apply,
    grouped_forward,
    sparse_combine,
    sparse_dispatch,
    top_k_route,
)
from paddle_tpu.ops.pallas.grouped_matmul import (
    grouped_gemm_enabled,
    grouped_matmul,
    grouped_matmul_reference,
)

RAGGED_CASES = [
    # (experts, k_dim, n_dim, group_sizes) — empty + single-token groups
    (4, 32, 64, [5, 0, 1, 10]),
    (8, 16, 32, [0, 0, 3, 1, 0, 7, 1, 0]),
    (1, 8, 128, [9]),
    (6, 64, 48, [128, 0, 1, 300, 1, 2]),     # n not a multiple of 128
    (3, 16, 16, [0, 0, 4]),                  # leading empty experts
]


def _case(e, k, n, sizes):
    m = sum(sizes)
    lhs = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32)
    rhs = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.float32)
    return lhs, rhs, jnp.asarray(sizes, jnp.int32)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("case", RAGGED_CASES)
def test_matches_dense_reference(impl, case):
    lhs, rhs, gs = _case(*case)
    ref = grouped_matmul_reference(lhs, rhs, gs)
    out = jax.jit(lambda a, b, g: grouped_matmul(a, b, g, impl=impl))(
        lhs, rhs, gs)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("case", RAGGED_CASES[:3])
def test_gradients_match_dense_reference(impl, case):
    lhs, rhs, gs = _case(*case)

    def f(a, b):
        return jnp.sum(jnp.sin(grouped_matmul(a, b, gs, impl=impl)))

    def fr(a, b):
        return jnp.sum(jnp.sin(grouped_matmul_reference(a, b, gs)))

    da, db = jax.jit(jax.grad(f, argnums=(0, 1)))(lhs, rhs)
    ra, rb = jax.jit(jax.grad(fr, argnums=(0, 1)))(lhs, rhs)
    np.testing.assert_allclose(da, ra, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(db, rb, rtol=1e-4, atol=1e-5)


def test_group_sizes_is_nondiff():
    """Integer group sizes must flow float0 cotangents, not crash."""
    lhs, rhs, gs = _case(*RAGGED_CASES[0])

    def f(a):
        return jnp.sum(grouped_matmul(a, rhs, gs, impl="pallas") ** 2)

    g = jax.grad(f)(lhs)
    assert g.shape == lhs.shape


def test_kill_switch_routes_to_dense(monkeypatch):
    monkeypatch.setenv("PT_GROUPED_GEMM", "0")
    assert not grouped_gemm_enabled()
    lhs, rhs, gs = _case(*RAGGED_CASES[0])
    ref = grouped_matmul_reference(lhs, rhs, gs)
    out = grouped_matmul(lhs, rhs, gs, impl="pallas")  # impl overridden
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_moe_layer_kill_switch_bit_compatible(monkeypatch):
    """PT_GROUPED_GEMM=0 must restore the capacity-padded dispatch path
    bit-for-bit (same ops in the same order as the pre-grouped layer)."""
    import paddle_tpu as pt
    pt.seed(0)
    layer = MoELayer(32, 64, 4, k=2, capacity_factor=1.25)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 32), jnp.float32)
    monkeypatch.setenv("PT_GROUPED_GEMM", "0")
    y_off, aux_off = jax.jit(layer)(x)

    # the dense path, composed manually — must be IDENTICAL
    t = 2 * 16
    cap = layer._capacity(t)
    xt = x.reshape(t, 32)
    logits = xt.astype(jnp.float32) @ layer.gate_w
    route, aux, _ = top_k_route(logits, 2, cap)
    x_e, dest = sparse_dispatch(xt, route, 4, cap)
    y_e = expert_mlp_apply(x_e, layer.experts.gate_up, layer.experts.down)
    yt = sparse_combine(y_e, route, dest, t)
    np.testing.assert_array_equal(np.asarray(y_off),
                                  np.asarray(yt.reshape(2, 16, 32)))
    np.testing.assert_array_equal(np.asarray(aux_off), np.asarray(aux))


def test_grouped_forward_equals_capacity_path():
    """The sorted grouped forward must reproduce the capacity path's
    results exactly in semantics (same kept/dropped set, same weights) —
    including under SATURATION, where dropped assignments must contribute
    zero."""
    import paddle_tpu as pt
    pt.seed(0)
    e, h, inter, k, t = 4, 32, 64, 2, 48
    layer = MoELayer(h, inter, e, k=k, capacity_factor=0.4)  # saturated
    x = jax.random.normal(jax.random.PRNGKey(5), (1, t, h), jnp.float32)
    xt = x.reshape(t, h)
    cap = layer._capacity(t)
    logits = xt.astype(jnp.float32) @ layer.gate_w
    route, _, drop = top_k_route(logits, k, cap)
    assert float(drop) > 0, "case must actually saturate"
    x_e, dest = sparse_dispatch(xt, route, e, cap)
    y_dense = sparse_combine(
        expert_mlp_apply(x_e, layer.experts.gate_up, layer.experts.down),
        route, dest, t)
    y_grp = grouped_forward(xt, route, layer.experts.gate_up,
                            layer.experts.down, t)
    np.testing.assert_allclose(y_grp, y_dense, rtol=1e-5, atol=1e-6)


def test_dropless_token_conservation():
    """capacity_factor=None (dropless): nothing is ever dropped and, with
    renormalised gates, each token's combine weights sum to 1 — expert
    outputs are a convex combination, so routing conserves tokens: no
    assignment mass is lost to capacity."""
    import paddle_tpu as pt
    pt.seed(0)
    e, h, k, t = 8, 16, 2, 64
    layer = MoELayer(h, 32, e, k=k, capacity_factor=None)
    x = jax.random.normal(jax.random.PRNGKey(7), (2, t // 2, h), jnp.float32)
    y, aux, m = layer(x, return_metrics=True)
    assert float(m["drop_rate"]) == 0.0

    xt = x.reshape(t, h)
    logits = xt.astype(jnp.float32) @ layer.gate_w
    route, _, _ = top_k_route(logits, k, layer._capacity(t))
    assert bool(jnp.all(route["keep"]))
    # per-expert segment sizes cover every assignment exactly once
    assert int(jnp.sum(route["counts"])) == t * k
    # combine weights per source token sum to 1 (renormalised top-k)
    wsum = jnp.zeros((t,)).at[route["tok"]].add(route["gate"])
    np.testing.assert_allclose(wsum, np.ones(t), rtol=1e-5)
    # identity check: output equals the per-token explicit expert mix
    probs = jax.nn.softmax(logits, axis=-1)
    gv, gi = jax.lax.top_k(probs, k)
    gv = gv / jnp.sum(gv, -1, keepdims=True)
    ref = jnp.zeros_like(xt)
    for j in range(k):
        xe = expert_mlp_apply(xt[:, None, :],
                              layer.experts.gate_up[gi[:, j]],
                              layer.experts.down[gi[:, j]])[:, 0]
        ref = ref + gv[:, j][:, None] * xe
    np.testing.assert_allclose(y.reshape(t, h), ref, rtol=2e-4, atol=1e-5)
