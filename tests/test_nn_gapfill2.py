"""Second nn gap-fill round: transpose convs 1/3-D, generic pad,
hsigmoid, triplet-with-distance, SyncBatchNorm conversion, containers."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_conv1d_transpose_matches_torch():
    import torch
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 10).astype(np.float32)
    w = rs.randn(3, 4, 5).astype(np.float32)  # [in, out, k]
    got = F.conv1d_transpose(jnp.asarray(x), jnp.asarray(w), stride=2,
                             padding=1, output_padding=1)
    want = torch.nn.functional.conv_transpose1d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1,
        output_padding=1).numpy()
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_conv3d_transpose_matches_torch():
    import torch
    rs = np.random.RandomState(1)
    x = rs.randn(1, 2, 4, 5, 6).astype(np.float32)
    w = rs.randn(2, 3, 3, 3, 3).astype(np.float32)
    got = F.conv3d_transpose(jnp.asarray(x), jnp.asarray(w), stride=2,
                             padding=1)
    want = torch.nn.functional.conv_transpose3d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1).numpy()
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_conv_transpose_layers():
    pt.seed(0)
    y1 = nn.Conv1DTranspose(3, 6, 4, stride=2)(jnp.zeros((2, 3, 8)))
    assert y1.shape == (2, 6, 18)
    y3 = nn.Conv3DTranspose(2, 4, 3, stride=2)(jnp.zeros((1, 2, 4, 4, 4)))
    assert y3.shape == (1, 4, 9, 9, 9)


def test_generic_pad_matches_torch():
    import torch
    rs = np.random.RandomState(2)
    x = rs.randn(2, 3, 4, 5).astype(np.float32)
    for mode in ["constant", "reflect", "replicate", "circular"]:
        got = F.pad(jnp.asarray(x), [1, 2, 2, 1], mode=mode, value=3.0)
        want = torch.nn.functional.pad(
            torch.tensor(x), [1, 2, 2, 1], mode=mode.replace("constant", "constant"),
            value=3.0 if mode == "constant" else 0.0).numpy()
        np.testing.assert_allclose(np.asarray(got), want, err_msg=mode)
    # full-length pad: per-dim pairs in dim order
    got = F.pad(jnp.asarray(x), [0, 0, 0, 0, 1, 1, 2, 2])
    assert got.shape == (2, 3, 6, 9)


def test_zeropad2d_and_adaptive_max_pool3d():
    x = jnp.ones((1, 2, 3, 3))
    y = F.zeropad2d(x, [1, 2, 3, 4])
    assert y.shape == (1, 2, 10, 6) and float(y[0, 0, 0, 0]) == 0.0
    z = jnp.asarray(np.random.RandomState(0).randn(1, 2, 4, 6, 8), jnp.float32)
    out = F.adaptive_max_pool3d(z, (2, 3, 4))
    assert out.shape == (1, 2, 2, 3, 4)
    np.testing.assert_allclose(
        np.asarray(out[0, 0, 0, 0, 0]),
        np.asarray(z[0, 0, :2, :2, :2]).max())
    assert nn.AdaptiveMaxPool3D((2, 3, 4))(z).shape == (1, 2, 2, 3, 4)


def test_softmax_with_cross_entropy():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(4, 7).astype(np.float32))
    label = jnp.asarray(rs.randint(0, 7, (4, 1)))
    loss, sm = F.softmax_with_cross_entropy(logits, label, return_softmax=True)
    assert loss.shape == (4, 1) and sm.shape == (4, 7)
    want = -np.log(np.asarray(sm)[np.arange(4), np.asarray(label)[:, 0]])
    np.testing.assert_allclose(np.asarray(loss)[:, 0], want, rtol=1e-5)


def test_triplet_margin_with_distance_loss():
    rs = np.random.RandomState(0)
    a, p, n = (jnp.asarray(rs.randn(4, 8).astype(np.float32)) for _ in range(3))
    default = float(F.triplet_margin_with_distance_loss(a, p, n))
    l1 = float(F.triplet_margin_with_distance_loss(
        a, p, n, distance_function=lambda u, v: jnp.sum(jnp.abs(u - v), -1)))
    assert np.isfinite(default) and np.isfinite(l1) and default != l1
    layer = nn.TripletMarginWithDistanceLoss(margin=0.5)
    assert np.isfinite(float(layer(a, p, n)))


def test_hsigmoid_loss_trains():
    """HSigmoid must be a trainable classifier proxy: loss decreases and
    beats chance on a separable toy problem."""
    pt.seed(0)
    rs = np.random.RandomState(0)
    num_classes, dim, n = 8, 16, 64
    labels = rs.randint(0, num_classes, n)
    x = rs.randn(n, dim).astype(np.float32) * 0.1
    x += np.eye(num_classes)[labels] @ rs.randn(num_classes, dim).astype(np.float32)
    layer = nn.HSigmoidLoss(dim, num_classes)
    xs, ys = jnp.asarray(x), jnp.asarray(labels)

    def loss_fn(m):
        return jnp.mean(m(xs, ys))

    import paddle_tpu.optimizer as opt
    o = opt.Adam(learning_rate=0.1)
    state = o.init(layer)
    l0 = float(loss_fn(layer))
    for _ in range(30):
        grads = jax.grad(loss_fn)(layer)
        layer, state = o.step(layer, grads, state)
    assert float(loss_fn(layer)) < l0 * 0.5, (l0, float(loss_fn(layer)))


def test_sync_batchnorm_convert_and_forward():
    m = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4), nn.ReLU())
    m2 = nn.SyncBatchNorm.convert_sync_batchnorm(m)
    assert isinstance(m2.layers[1], nn.SyncBatchNorm)
    out = m2(jnp.zeros((2, 3, 8, 8)))
    assert out.shape == (2, 4, 6, 6)


def test_parameter_list():
    pl = nn.ParameterList([jnp.ones((2,)), jnp.zeros((3,))])
    pl.append(jnp.ones((4,)))
    assert len(pl) == 3 and pl[2].shape == (4,)
    # registered as pytree leaves
    leaves = jax.tree_util.tree_leaves(pl)
    assert sum(l.size for l in leaves) == 9


def test_upsampling_layers():
    x = jnp.asarray(np.random.RandomState(0).randn(1, 2, 4, 4), jnp.float32)
    up_n = nn.UpsamplingNearest2D(scale_factor=2)(x)
    up_b = nn.UpsamplingBilinear2D(scale_factor=2)(x)
    assert up_n.shape == up_b.shape == (1, 2, 8, 8)
    import torch
    want = torch.nn.UpsamplingBilinear2d(scale_factor=2)(
        torch.tensor(np.asarray(x))).numpy()
    np.testing.assert_allclose(np.asarray(up_b), want, rtol=1e-4, atol=1e-5)


def test_log_sigmoid_layer():
    x = jnp.asarray([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(np.asarray(nn.LogSigmoid()(x)),
                               np.asarray(F.log_sigmoid(x)))


def test_rnn_cell_base_exported():
    assert issubclass(nn.LSTMCell, nn.RNNCellBase)
