"""1F1B pipeline schedule: loss+grad parity vs sequential, LLaMA stages,
and the bounded-residual-memory property (ring of 2*pp-1 slots, not M).

Ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
(1F1B); here the SPMD shifted-buffer formulation in
``paddle_tpu/distributed/pipeline.py``.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.distributed.pipeline import (PipelineLayer,
                                             pipeline_train_step)


def _mlp(width):
    return nn.Sequential(nn.Linear(width, width * 2), nn.GELU(),
                         nn.Linear(width * 2, width))


def _embed(ep, ids):
    return ep[ids]


def _head_loss(hp, y, labels):
    logits = y @ hp
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))


def _setup(n_layers=8, width=8, vocab=13, M=4, mb=2, seq=6):
    pt.seed(0)
    rs = np.random.RandomState(0)
    blocks = [_mlp(width) for _ in range(n_layers)]
    emb_w = jnp.asarray(rs.randn(vocab, width).astype(np.float32) * 0.1)
    head_w = jnp.asarray(rs.randn(width, vocab).astype(np.float32) * 0.1)
    tokens = jnp.asarray(rs.randint(0, vocab, (M * mb, seq)))
    tlabels = jnp.asarray(rs.randint(0, vocab, (M * mb, seq)))
    return blocks, emb_w, head_w, tokens, tlabels


def _seq_ref(stacked, ep, hp, ids, labels):
    h = _embed(ep, ids)
    out, _ = lax.scan(lambda hh, lyr: (lyr(hh), None), h, stacked)
    return _head_loss(hp, out, labels)


@pytest.mark.parametrize("pp", [1, 2, 4])
def test_1f1b_matches_sequential(pp):
    M = 4
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    ref, refg = jax.value_and_grad(_seq_ref, argnums=(0, 1, 2))(
        pipe.stacked, emb_w, head_w, tokens, tlabels)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    loss, ds, de, dh = pipeline_train_step(
        pipe, mesh, tokens, tlabels, head_loss_fn=_head_loss,
        head_params=head_w, embed_fn=_embed, embed_params=emb_w)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves((ds, de, dh)),
                    jax.tree_util.tree_leaves(refg)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-6)


def test_1f1b_microbatch_count_exceeds_stages():
    # steady state holds more microbatches than a fill-drain wave: M >> pp
    pp, M = 2, 8
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    ref, refg = jax.value_and_grad(_seq_ref, argnums=(0, 1, 2))(
        pipe.stacked, emb_w, head_w, tokens, tlabels)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    loss, ds, de, dh = pipeline_train_step(
        pipe, mesh, tokens, tlabels, head_loss_fn=_head_loss,
        head_params=head_w, embed_fn=_embed, embed_params=emb_w)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves((ds, de, dh)),
                    jax.tree_util.tree_leaves(refg)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-6)


def test_1f1b_residual_memory_bounded_by_pp_not_m():
    """The schedule's saved-activation window is a ring of 2*pp-1 slots.

    Structural proof on the jaxpr: with M=16 microbatches and a distinctive
    activation width, the only [M, ...] float buffers in the program are the
    (int) token/label streams — no per-microbatch activation stash exists;
    the scan carry holds a [2*pp-1, mb, seq, width] residual ring instead.
    """
    pp, M, mb, width, seq = 4, 16, 2, 9, 5
    blocks, emb_w, head_w, tokens, tlabels = _setup(
        n_layers=4, width=width, M=M, mb=mb, seq=seq)
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])

    def step(stacked, x, y, ep, hp):
        pipe.stacked = stacked
        return pipeline_train_step(pipe, mesh, x, y,
                                   head_loss_fn=_head_loss, head_params=hp,
                                   embed_fn=_embed, embed_params=ep)

    text = str(jax.make_jaxpr(step)(pipe.stacked, tokens, tlabels,
                                    emb_w, head_w))
    ring_shape = f"{2 * pp - 1},{mb},{seq},{width}"
    assert f"f32[{ring_shape}]" in text.replace(" ", ""), \
        "expected the 2*pp-1 residual ring in the scan carry"
    stash_shape = f"f32[{M},{mb},{seq},{width}]"
    assert stash_shape not in text.replace(" ", ""), \
        "found a per-microbatch activation stash — schedule is not 1F1B"


def test_1f1b_composes_with_dp():
    """pp x dp: each dp member pipelines its batch shard; loss+grads equal
    the single-device run (dp-averaged inside the schedule)."""
    pp, dp, M = 2, 2, 4
    blocks, emb_w, head_w, tokens, tlabels = _setup(M=M, mb=4)  # mb div dp
    pipe = PipelineLayer(blocks, num_stages=pp, num_microbatches=M)
    ref, refg = jax.value_and_grad(_seq_ref, argnums=(0, 1, 2))(
        pipe.stacked, emb_w, head_w, tokens, tlabels)
    mesh = HybridMesh(dp=dp, pp=pp, devices=jax.devices()[:dp * pp])
    loss, ds, de, dh = pipeline_train_step(
        pipe, mesh, tokens, tlabels, head_loss_fn=_head_loss,
        head_params=head_w, embed_fn=_embed, embed_params=emb_w,
        batch_axes=("dp",))
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for g, r in zip(jax.tree_util.tree_leaves((ds, de, dh)),
                    jax.tree_util.tree_leaves(refg)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=5e-4, atol=1e-6)


def test_1f1b_optimizer_integrated_training_matches_adamw():
    """make_llama_pp_train_step: the jitted pp(+dp) train loop tracks the
    non-pipelined AdamW trajectory."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         init_llama_pp_state,
                                         make_llama_pp_train_step)
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    pp, M, mb, seq = 4, 4, 2, 16
    cfg = LlamaConfig.tiny(num_hidden_layers=4, hidden_size=32,
                           num_attention_heads=2, num_key_value_heads=2,
                           vocab_size=64, tie_word_embeddings=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (M * mb, seq)))
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((M * mb, 1), ids.dtype)], axis=1)

    # init_llama_pp_state copies every leaf, so neither the reference
    # step's donation nor the pp step's donation can delete shared buffers
    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    params, opt_state = init_llama_pp_state(model, opt.AdamW(learning_rate=1e-3))

    # reference: plain AdamW on the whole module
    optimizer = opt.AdamW(learning_rate=1e-3)
    ref_state = init_state(model, optimizer)
    ref_step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
    ref_losses = []
    for _ in range(3):
        ref_state, l = ref_step(ref_state, ids, labels)
        ref_losses.append(float(l))
    pp_opt = opt.AdamW(learning_rate=1e-3)
    step = make_llama_pp_train_step(model, mesh, pp_opt,
                                    num_microbatches=M)
    pp_losses = []
    for _ in range(3):
        params, opt_state, l = step(params, opt_state, ids, labels)
        pp_losses.append(float(l))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-3, atol=1e-4)
    assert pp_losses[-1] < pp_losses[0]


@pytest.mark.parametrize("dp", [1, 2])
@pytest.mark.slow
def test_1f1b_composes_with_tp(dp):
    """Full hybrid: tensor parallelism INSIDE the 1F1B pipeline (pp x tp,
    and pp x tp x dp): Megatron-interleaved fused projections, explicit
    row-parallel psums, loss+grads == single device."""
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_pipeline_train_step,
                                         tp_shuffle_llama_params)

    pt.seed(0)
    pp, tp, M, mb, seq = 2, 2, 2, 2 * dp, 16
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, tie_word_embeddings=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (M * mb, seq)))
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((M * mb, 1), ids.dtype)], axis=1)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda m: m.loss(ids, labels))(model)

    mesh = HybridMesh(dp=dp, pp=pp, tp=tp,
                      devices=jax.devices()[:dp * pp * tp])
    loss, grads = llama_pipeline_train_step(
        model, mesh, ids, labels, num_microbatches=M,
        batch_axes=("dp",) if dp > 1 else ())
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)

    # grads come back in the tp-interleaved layout — invert it
    grads = tp_shuffle_llama_params(grads, cfg, tp, inverse=True)
    from paddle_tpu.distributed.pipeline import stack_layers
    ref_stacked = stack_layers(ref_grads.model.layers)
    for g, r in zip(jax.tree_util.tree_leaves(grads["layers"]),
                    jax.tree_util.tree_leaves(ref_stacked)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["embed_tokens"]),
                               np.asarray(ref_grads.model.embed_tokens),
                               rtol=1e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["lm_head"]),
                               np.asarray(ref_grads.lm_head),
                               rtol=1e-3, atol=2e-5)
    # final-norm grad exercises the auto-psum-of-replicated-partials path
    np.testing.assert_allclose(np.asarray(grads["norm_weight"]),
                               np.asarray(ref_grads.model.norm.weight),
                               rtol=1e-3, atol=2e-5)
    # wrong-layout params must be REJECTED, not silently mis-split
    from paddle_tpu.models.llama import _pp_params, _pp_loss_and_grads
    bad = _pp_params(model, copy=False)  # canonical layout, tp_layout=1
    with pytest.raises(ValueError):
        _pp_loss_and_grads(cfg, 2, mesh, bad, ids, labels, M,
                           ("dp",) if dp > 1 else ())


def test_1f1b_tp_jitted_optimizer_loop_with_qkv_bias():
    """The machinery PpParams exists for: the layout tag must survive jit
    tracing, donation, and optimizer tree_maps in the tp>1 TRAINING loop —
    with attention_bias=True so the qkv_bias permutation/spec/local-add
    path runs too. Trajectory matches plain AdamW."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         init_llama_pp_state,
                                         make_llama_pp_train_step)
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    pp, tp, M, mb, seq = 2, 2, 2, 2, 16
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, tie_word_embeddings=False,
                           attention_bias=True)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (M * mb, seq)))
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((M * mb, 1), ids.dtype)], axis=1)

    mesh = HybridMesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
    params, opt_state = init_llama_pp_state(
        model, opt.AdamW(learning_rate=1e-3), mesh=mesh)

    optimizer = opt.AdamW(learning_rate=1e-3)
    ref_state = init_state(model, optimizer)
    ref_step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
    ref_losses = []
    for _ in range(3):
        ref_state, l = ref_step(ref_state, ids, labels)
        ref_losses.append(float(l))

    step = make_llama_pp_train_step(model, mesh, opt.AdamW(learning_rate=1e-3),
                                    num_microbatches=M)
    pp_losses = []
    for _ in range(3):
        params, opt_state, l = step(params, opt_state, ids, labels)
        pp_losses.append(float(l))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-3, atol=1e-4)


def test_tp_shuffle_layout_guards():
    """Double-shuffling or wrong-direction unshuffling must raise."""
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         _pp_params, tp_shuffle_llama_params)
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, tie_word_embeddings=False)
    model = LlamaForCausalLM(cfg)
    canonical = _pp_params(model, copy=False)
    shuffled = tp_shuffle_llama_params(canonical, cfg, 2)
    assert shuffled.tp_layout == 2
    with pytest.raises(ValueError):
        tp_shuffle_llama_params(shuffled, cfg, 2)          # double shuffle
    with pytest.raises(ValueError):
        tp_shuffle_llama_params(canonical, cfg, 2, inverse=True)
    back = tp_shuffle_llama_params(shuffled, cfg, 2, inverse=True)
    assert back.tp_layout == 1
    for a, b in zip(jax.tree_util.tree_leaves(back["layers"]),
                    jax.tree_util.tree_leaves(canonical["layers"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_1f1b_llama_stages_match_model_loss():
    """Full LLaMA under the pipeline: loss equals model.loss, grads match."""
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_pipeline_train_step)

    pt.seed(0)
    pp, M, mb, seq = 4, 4, 2, 16
    cfg = LlamaConfig.tiny(num_hidden_layers=4, hidden_size=32,
                           num_attention_heads=2, num_key_value_heads=2,
                           vocab_size=64, tie_word_embeddings=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (M * mb, seq)))
    # same -100 tail per row -> every microbatch masks the same count, so
    # mean-of-microbatch-losses == the global masked mean
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((M * mb, 1), ids.dtype)], axis=1)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda m: m.loss(ids, labels))(model)

    mesh = HybridMesh(pp=pp, devices=jax.devices()[:pp])
    loss, grads = llama_pipeline_train_step(model, mesh, ids, labels,
                                            num_microbatches=M)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)

    # stacked layer grads vs per-layer reference grads
    from paddle_tpu.distributed.pipeline import stack_layers
    ref_stacked = stack_layers(ref_grads.model.layers)
    for g, r in zip(jax.tree_util.tree_leaves(grads["layers"]),
                    jax.tree_util.tree_leaves(ref_stacked)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["embed_tokens"]),
                               np.asarray(ref_grads.model.embed_tokens),
                               rtol=1e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["norm_weight"]),
                               np.asarray(ref_grads.model.norm.weight),
                               rtol=1e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["lm_head"]),
                               np.asarray(ref_grads.lm_head),
                               rtol=1e-3, atol=2e-5)
