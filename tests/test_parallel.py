"""Parallelism correctness on the 8-device CPU mesh (SURVEY.md §4; ref
test/collective/fleet/). The gold standard: every parallel form must equal
the single-device computation."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import (
    ColumnParallelLinear,
    HybridMesh,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_cross_entropy,
    partition_specs,
    shard_module,
)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state


def _llama_setup(batch=4, seq=16):
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((batch, 1), ids.dtype)], axis=1)
    return cfg, model, ids, labels


def test_tp_matches_single_device():
    cfg, model, ids, labels = _llama_setup()
    ref_loss = float(model.loss(ids, labels))
    mesh = HybridMesh(tp=8)
    with mesh:
        sharded = shard_module(model, mesh, min_size=1)
        loss = jax.jit(lambda m, i, l: m.loss(i, l))(sharded, ids, labels)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-4)


def test_fsdp_matches_single_device():
    cfg, model, ids, labels = _llama_setup(batch=8)
    ref_loss = float(model.loss(ids, labels))
    mesh = HybridMesh(fsdp=8)
    with mesh:
        sharded = shard_module(model, mesh, min_size=1)
        ids_s = jax.device_put(ids, mesh.batch_sharding())
        labels_s = jax.device_put(labels, mesh.batch_sharding())
        loss = jax.jit(lambda m, i, l: m.loss(i, l))(sharded, ids_s, labels_s)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-4)


def test_tp_logits_match_single_device():
    """LOGITS-level (not loss-level) parity under tp: catches errors that
    loss reduction could cancel out (r1 verdict weak #7)."""
    cfg, model, ids, labels = _llama_setup()
    ref = np.asarray(model(ids), np.float32)
    mesh = HybridMesh(tp=8)
    with mesh:
        sharded = shard_module(model, mesh, min_size=1)
        got = np.asarray(jax.jit(lambda m, i: m(i))(sharded, ids),
                         np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_fsdp_logits_match_single_device():
    cfg, model, ids, labels = _llama_setup(batch=8)
    ref = np.asarray(model(ids), np.float32)
    mesh = HybridMesh(fsdp=8)
    with mesh:
        sharded = shard_module(model, mesh, min_size=1)
        ids_s = jax.device_put(ids, mesh.batch_sharding())
        got = np.asarray(jax.jit(lambda m, i: m(i))(sharded, ids_s),
                         np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_tp_grads_match_single_device():
    """GRADIENT-level parity under tp — the strongest cancellation check:
    every parameter's gradient must match the single-device gradient."""
    cfg, model, ids, labels = _llama_setup()
    ref_grads = jax.grad(lambda m: m.loss(ids, labels))(model)
    mesh = HybridMesh(tp=8)
    with mesh:
        sharded = shard_module(model, mesh, min_size=1)
        got_grads = jax.jit(jax.grad(lambda m: m.loss(ids, labels)))(sharded)
    for (pr, r), (pg, g) in zip(
            jax.tree_util.tree_flatten_with_path(ref_grads)[0],
            jax.tree_util.tree_flatten_with_path(got_grads)[0]):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=5e-4, atol=5e-4, err_msg=f"grad mismatch at {pr}")


def test_hybrid_training_matches_single_device():
    """dp2 x fsdp2 x tp2 training trajectory == single-device trajectory."""
    cfg, model, ids, labels = _llama_setup(batch=8)
    optimizer = opt.AdamW(learning_rate=1e-3)

    # single-device trajectory
    state = init_state(model, optimizer)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, donate=False)
    losses_ref = []
    s = state
    for _ in range(3):
        s, loss = step(s, ids, labels)
        losses_ref.append(float(loss))

    # sharded trajectory
    mesh = HybridMesh(dp=2, fsdp=2, tp=2)
    with mesh:
        s2 = init_state(model, optimizer, mesh)
        ids_s = jax.device_put(ids, mesh.batch_sharding())
        labels_s = jax.device_put(labels, mesh.batch_sharding())
        step2 = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, donate=False)
        losses_par = []
        for _ in range(3):
            s2, loss = step2(s2, ids_s, labels_s)
            losses_par.append(float(loss))
    np.testing.assert_allclose(losses_par, losses_ref, rtol=3e-4)


def test_column_row_parallel_match_dense():
    pt.seed(1)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    ref = (jax.nn.relu(col(x))) @ np.asarray(row.weight) + np.asarray(row.bias)
    mesh = HybridMesh(tp=8)
    with mesh:
        col_s = shard_module(col, mesh, min_size=1)
        row_s = shard_module(row, mesh, min_size=1)
        out = jax.jit(lambda c, r, x: r(jax.nn.relu(c(x))))(col_s, row_s, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_vocab_parallel_embedding():
    pt.seed(2)
    emb = VocabParallelEmbedding(64, 8)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 10)))
    ref = emb(ids)
    mesh = HybridMesh(tp=8)
    with mesh:
        emb_s = shard_module(emb, mesh, min_size=1)
        out = jax.jit(lambda e, i: e(i))(emb_s, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_parallel_cross_entropy_matches_dense():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 32).astype(np.float32))
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 32, (4,)))
    import paddle_tpu.nn.functional as F
    ref = F.cross_entropy(logits, labels, reduction="none")
    got = parallel_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)


def test_partition_specs_respect_tp_annotations():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    specs = partition_specs(model, stage=3, min_size=1, fsdp_size=2)
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or isinstance(x, jax.sharding.PartitionSpec))
    named = [s for s in flat if s is not None and any(a is not None for a in s)]
    assert named, "no sharded leaves"
    tp_specs = [s for s in named if "tp" in jax.tree_util.tree_leaves(tuple(s))]
    assert tp_specs, "tp annotations not propagated"


def test_collectives_shard_map():
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import PartitionSpec as P
    import paddle_tpu.distributed as dist

    mesh = HybridMesh(dp=8)
    x = jnp.arange(8.0)

    f = shard_map(lambda v: dist.all_reduce(v, axis_name="dp"),
                  mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))

    g = shard_map(lambda v: dist.all_gather(v, axis_name="dp"),
                  mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    gathered = g(x)  # each member holds the full gather; global shape 8*8
    assert gathered.shape == (64,)
    np.testing.assert_allclose(np.asarray(gathered)[:8], np.arange(8.0))

    h = shard_map(lambda v: dist.shift(v, 1, axis_name="dp"),
                  mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    np.testing.assert_allclose(np.asarray(h(x)), np.roll(np.arange(8.0), 1))
