"""RoPE scaling (linear/NTK/dynamic), speculative decoding, and paged
sampling (PaddleNLP llm parity round 2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.ops.attention import rope_cos_sin


def test_rope_scaling_linear_is_position_interpolation():
    d = 16
    cos, sin = rope_cos_sin(8, d, scaling={"type": "linear", "factor": 4.0})
    cos_ref, sin_ref = rope_cos_sin(8, d, position_ids=jnp.arange(8) / 4.0)
    np.testing.assert_allclose(np.asarray(cos), np.asarray(cos_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sin), np.asarray(sin_ref), rtol=1e-6)


def test_rope_scaling_ntk_raises_base():
    d = 16
    cos, _ = rope_cos_sin(8, d, base=10000.0,
                          scaling={"type": "ntk", "factor": 2.0})
    cos_ref, _ = rope_cos_sin(8, d, base=10000.0 * 2.0 ** (d / (d - 2)))
    np.testing.assert_allclose(np.asarray(cos), np.asarray(cos_ref), rtol=1e-6)


def test_rope_scaling_dynamic_only_beyond_trained_length():
    d = 16
    # within the trained window: identical to unscaled
    c1, _ = rope_cos_sin(8, d, scaling={"type": "dynamic", "factor": 2.0},
                         max_position_embeddings=16)
    c0, _ = rope_cos_sin(8, d)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0), rtol=1e-6)
    # beyond it: base grows (frequencies shrink)
    c2, _ = rope_cos_sin(32, d, scaling={"type": "dynamic", "factor": 2.0},
                         max_position_embeddings=16)
    c3, _ = rope_cos_sin(32, d)
    assert not np.allclose(np.asarray(c2), np.asarray(c3))


def test_llama_rope_scaling_consistent_between_forward_and_decode():
    """Model forward and the KV-cache decode path must rotate identically
    under rope_scaling (linear)."""
    from paddle_tpu.models.decoding import generate

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64,
                           rope_scaling={"type": "linear", "factor": 2.0})
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (1, 10)))
    # teacher-forced check: decode-path logits at the last prompt position
    # equal the full-forward logits there
    full = model(ids)
    from paddle_tpu.models.decoding import KVCache, llama_forward_with_cache
    cache = KVCache.init(cfg.num_hidden_layers, 1, 16,
                         cfg.num_key_value_heads,
                         cfg.hidden_size // cfg.num_attention_heads,
                         cfg.dtype)
    dec, _ = llama_forward_with_cache(model, ids, cache, 0)
    np.testing.assert_allclose(np.asarray(dec[:, -1]),
                               np.asarray(full[:, -1]),
                               rtol=2e-4, atol=2e-5)
    # generation runs end-to-end
    out = generate(model, ids, max_new_tokens=4)
    assert out.shape == (1, 14)


def _pair(seed_t=0, seed_d=1):
    cfg = dict(num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
               num_key_value_heads=2, vocab_size=64)
    pt.seed(seed_t)
    target = LlamaForCausalLM(LlamaConfig.tiny(**cfg))
    pt.seed(seed_d)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        **{**cfg, "num_hidden_layers": 1}))
    return target, draft


def test_speculative_equals_target_greedy():
    """Output must be EXACTLY the target's own greedy decode, whatever the
    draft proposes."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import speculative_generate

    target, draft = _pair()
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 64, (1, 8)))
    new = 10
    ref = generate(target, ids, max_new_tokens=new)
    got, stats = speculative_generate(target, draft, ids,
                                      max_new_tokens=new, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert stats["rounds"] >= 1 and 0.0 <= stats["acceptance_rate"] <= 1.0


def test_speculative_perfect_draft_accepts_everything():
    """Draft == target: every proposal accepted, so the target runs
    ~max_new/(gamma+1) verification forwards instead of max_new."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import speculative_generate

    cfgkw = dict(num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
                 num_key_value_heads=2, vocab_size=64)
    pt.seed(0)
    target = LlamaForCausalLM(LlamaConfig.tiny(**cfgkw))
    pt.seed(0)
    draft = LlamaForCausalLM(LlamaConfig.tiny(**cfgkw))
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 64, (1, 8)))
    new, gamma = 12, 3
    ref = generate(target, ids, max_new_tokens=new)
    got, stats = speculative_generate(target, draft, ids,
                                      max_new_tokens=new, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert stats["acceptance_rate"] == 1.0
    assert stats["rounds"] <= -(-new // (gamma + 1)) + 1


def test_speculative_eos_matches_generate_exactly():
    """With an eos token, the output buffer must equal generate()'s —
    including the zero padding after the first EOS."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import speculative_generate

    target, draft = _pair()
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(0, 64, (1, 8)))
    new = 10
    ref_plain = generate(target, ids, max_new_tokens=new)
    # pick a token the target actually emits early as "EOS"
    eos = int(np.asarray(ref_plain)[0, 8 + 1])
    ref = generate(target, ids, max_new_tokens=new, eos_token_id=eos)
    got, _ = speculative_generate(target, draft, ids, max_new_tokens=new,
                                  gamma=3, eos_token_id=eos)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_paged_generate_sampling_reproducible():
    from paddle_tpu.models.paged import paged_generate

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(4)
    b, s, new = 2, 8, 6
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    kw = dict(max_new_tokens=new, block_size=4, temperature=0.8, top_k=8,
              top_p=0.9)
    out1, _ = paged_generate(model, ids, np.full((b,), s),
                             rng=jax.random.PRNGKey(7), **kw)
    out2, _ = paged_generate(model, ids, np.full((b,), s),
                             rng=jax.random.PRNGKey(7), **kw)
    out3, _ = paged_generate(model, ids, np.full((b,), s),
                             rng=jax.random.PRNGKey(8), **kw)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))
    assert np.asarray(out1).max() < 64 and np.asarray(out1).min() >= 0


def test_speculative_batched_ragged_equals_solo_greedy():
    """BATCHED speculation (VERDICT r2 item 6): every ragged row's output
    == its solo greedy decode, rows advancing at their own acceptance."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import speculative_generate_batched

    target, draft = _pair()
    rs = np.random.RandomState(5)
    lens = [8, 5, 11, 3]
    b, smax, new = len(lens), max(lens), 9
    padded = np.zeros((b, smax), np.int64)
    rows = []
    for i, n in enumerate(lens):
        rows.append(rs.randint(0, 64, (n,)))
        padded[i, :n] = rows[-1]
    got, stats = speculative_generate_batched(
        target, draft, padded, prompt_lens=np.asarray(lens),
        max_new_tokens=new, gamma=3)
    got = np.asarray(got)
    for i, r in enumerate(rows):
        ref = np.asarray(generate(target, jnp.asarray(r[None]),
                                  max_new_tokens=new))[0]
        np.testing.assert_array_equal(got[i, : lens[i] + new], ref,
                                      err_msg=f"row {i}")
    assert stats["rounds"] >= 1


def test_speculative_batched_eos_per_row():
    """Rows hit EOS at different times; finished rows freeze (zeros past
    EOS, the single-sequence convention) while others continue exactly."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import speculative_generate_batched

    target, draft = _pair()
    rs = np.random.RandomState(6)
    b, s, new = 3, 6, 8
    ids = rs.randint(0, 64, (b, s))
    refs = [np.asarray(generate(target, jnp.asarray(ids[i][None]),
                                max_new_tokens=new))[0] for i in range(b)]
    eos = int(refs[0][s + 1])     # row 0 finishes early (maybe others too)
    got, _ = speculative_generate_batched(
        target, draft, ids, max_new_tokens=new, gamma=3, eos_token_id=eos)
    got = np.asarray(got)
    for i in range(b):
        gen = refs[i][s:]
        stop = np.nonzero(gen == eos)[0]
        keep = int(stop[0]) + 1 if len(stop) else new
        np.testing.assert_array_equal(got[i, s: s + keep], gen[:keep],
                                      err_msg=f"row {i}")
        assert (got[i, s + keep:] == 0).all()


def test_dynamic_ntk_decode_matches_generate():
    """dynamic-NTK now rides fixed-shape decode as TRACED data (it used
    to raise): paged decode == static-cache generate beyond the trained
    window, and within the window dynamic == unscaled exactly."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.paged import paged_generate

    mk = dict(num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
              num_key_value_heads=2, vocab_size=64,
              max_position_embeddings=8)
    pt.seed(0)
    dyn = LlamaForCausalLM(LlamaConfig.tiny(
        **mk, rope_scaling={"type": "dynamic", "factor": 2.0}))
    rs = np.random.RandomState(11)
    b, s, new = 2, 6, 10          # decode runs well past trained=8
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))

    ref = generate(dyn, ids, max_new_tokens=new)
    assert np.isfinite(np.asarray(dyn(ids))).all()
    got, _ = paged_generate(dyn, ids, np.full((b,), s), max_new_tokens=new,
                            block_size=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    # within the trained window the clamp makes dynamic == unscaled
    pt.seed(0)
    plain = LlamaForCausalLM(LlamaConfig.tiny(**mk))
    pt.seed(0)
    dyn2 = LlamaForCausalLM(LlamaConfig.tiny(
        **mk, rope_scaling={"type": "dynamic", "factor": 2.0}))
    short = generate(plain, ids, max_new_tokens=2)   # total 8 == trained
    short_d = generate(dyn2, ids, max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(short_d), np.asarray(short))


def test_dynamic_ntk_chunked_prefill_matches_forward():
    """Chunked cache prefill (cur_len = L traced) == the full forward's
    static dynamic-NTK base at the last position."""
    from paddle_tpu.models.decoding import KVCache, llama_forward_with_cache

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, max_position_embeddings=8,
                           rope_scaling={"type": "dynamic", "factor": 2.0})
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(12)
    ids = jnp.asarray(rs.randint(0, 64, (1, 12)))    # past trained=8
    full = model(ids)
    cache = KVCache.init(cfg.num_hidden_layers, 1, 16,
                         cfg.num_key_value_heads,
                         cfg.hidden_size // cfg.num_attention_heads,
                         cfg.dtype)
    dec, _ = llama_forward_with_cache(model, ids, cache, 0)
    np.testing.assert_allclose(np.asarray(dec[:, -1]),
                               np.asarray(full[:, -1]),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_speculative_dynamic_ntk_stays_lossless():
    """Speculative chunk verify under dynamic-NTK rotates each position
    with ITS current length (like one-at-a-time decode) — output still
    exactly equals the target's own greedy decode past the window."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.speculative import (speculative_generate,
                                               speculative_generate_batched)

    dyn = dict(num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
               num_key_value_heads=2, vocab_size=64,
               max_position_embeddings=8,
               rope_scaling={"type": "dynamic", "factor": 2.0})
    pt.seed(0)
    target = LlamaForCausalLM(LlamaConfig.tiny(**dyn))
    pt.seed(1)
    draft = LlamaForCausalLM(LlamaConfig.tiny(
        **{**dyn, "num_hidden_layers": 1}))
    rs = np.random.RandomState(13)
    ids = jnp.asarray(rs.randint(0, 64, (1, 6)))
    new = 10                       # well past trained=8
    ref = generate(target, ids, max_new_tokens=new)
    got, _ = speculative_generate(target, draft, ids, max_new_tokens=new,
                                  gamma=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    gotb, _ = speculative_generate_batched(target, draft,
                                           np.asarray(ids),
                                           max_new_tokens=new, gamma=3)
    np.testing.assert_array_equal(np.asarray(gotb), np.asarray(ref))

    # LONG prompt (12 > trained 8): the dynamic-NTK prefill must use the
    # chunk-end base alpha(prompt_len) like generate()'s prefill — the
    # per-position verify bases apply only to post-prompt chunks
    ids_long = jnp.asarray(rs.randint(0, 64, (1, 12)))
    ref_l = generate(target, ids_long, max_new_tokens=new)
    got_l, _ = speculative_generate(target, draft, ids_long,
                                    max_new_tokens=new, gamma=3)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(ref_l))
    # batched ragged long prompts: rows prefill with alpha(len[r]) each
    idsb = np.zeros((2, 12), np.int64)
    idsb[0] = np.asarray(ids_long)[0]
    idsb[1, :9] = rs.randint(0, 64, (9,))
    lens = np.asarray([12, 9])
    refs = [generate(target, jnp.asarray(idsb[r:r + 1, :lens[r]]),
                     max_new_tokens=new) for r in range(2)]
    gotb_l, _ = speculative_generate_batched(target, draft, idsb,
                                             prompt_lens=lens,
                                             max_new_tokens=new, gamma=3)
    gb = np.asarray(gotb_l)
    for r in range(2):
        sol = np.asarray(refs[r])[0]
        np.testing.assert_array_equal(gb[r, :len(sol)], sol)
