"""Selective-remat policy tests (ISSUE 1 satellite: the ``remat_policy``
flag was parsed but never reached ``jax.checkpoint`` — VERDICT r4 item 1).

Assert the policy is ACTUALLY applied, not just accepted: the residuals
jax saves across the per-layer checkpoint must grow as the policy keeps
more named activations, the ffn_gu tensor must appear exactly when a
policy names it, and — remat being a pure memory/recompute trade —
loss and gradients must be bit-identical across every policy.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.core.module import value_and_grad
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

try:
    from jax._src.ad_checkpoint import saved_residuals
except ImportError:                        # pragma: no cover
    saved_residuals = None

# hidden=32, intermediate=48: the fused gate_up ("ffn_gu") activation has
# last dim 2*48=96 — unique in the net, so its presence in the saved
# residuals identifies the policy unambiguously
_B, _S, _H, _I = 1, 8, 32, 48


@pytest.fixture(scope="module")
def setup():
    pt.seed(0)
    cfg = LlamaConfig.tiny(remat=True, num_hidden_layers=2, hidden_size=_H,
                           intermediate_size=_I, num_attention_heads=4,
                           num_key_value_heads=2, vocab_size=64,
                           scan_layers=False)
    model = LlamaForCausalLM(cfg)
    ids = np.arange(_S, dtype=np.int32)[None]
    labels = np.concatenate(
        [ids[:, 1:], -100 * np.ones((_B, 1), np.int32)], axis=1)
    return model, jnp.asarray(ids), jnp.asarray(labels)


def _residual_shapes(model, ids, labels):
    res = saved_residuals(lambda m: m.loss(ids, labels), model)
    # drop arguments (params/inputs are always live) — count only what
    # the checkpoint policy chose to SAVE from the forward
    return [tuple(a.shape) for a, d in res if "argument" not in d]


@pytest.mark.skipif(saved_residuals is None,
                    reason="jax saved_residuals unavailable")
def test_policy_monotonically_grows_saved_residuals(setup):
    model, ids, labels = setup
    counts = {}
    for pol in [None, "hidden", "no_ffn", "dots"]:
        model.cfg.remat_policy = pol
        counts[pol] = len(_residual_shapes(model, ids, labels))
    assert counts[None] < counts["hidden"] < counts["no_ffn"] < counts["dots"]


@pytest.mark.skipif(saved_residuals is None,
                    reason="jax saved_residuals unavailable")
def test_ffn_gu_saved_exactly_when_policy_names_it(setup):
    model, ids, labels = setup
    gu_shape = (_B, _S, 2 * _I)
    model.cfg.remat_policy = "dots"        # names "ffn_gu"
    assert gu_shape in _residual_shapes(model, ids, labels)
    model.cfg.remat_policy = "no_ffn"      # does not
    assert gu_shape not in _residual_shapes(model, ids, labels)


def test_loss_and_grads_identical_across_policies(setup):
    model, ids, labels = setup
    ref = None
    for pol in [None, "full", "hidden", "no_ffn", "dots"]:
        model.cfg.remat_policy = pol
        loss, grads = value_and_grad(
            lambda m, i, l: m.loss(i, l))(model, ids, labels)
        flat = [np.asarray(g) for g in jax.tree_util.tree_leaves(grads)
                if g is not None]
        if ref is None:
            ref = (float(loss), flat)
            continue
        assert float(loss) == ref[0], f"loss drifted under {pol!r}"
        for a, b in zip(flat, ref[1]):
            np.testing.assert_allclose(a, b, rtol=0, atol=0,
                                       err_msg=f"grad drifted under {pol!r}")


def test_unknown_policy_raises(setup):
    model, ids, labels = setup
    model.cfg.remat_policy = "everything"
    with pytest.raises(ValueError, match="remat_policy"):
        model(ids)
    model.cfg.remat_policy = None
