"""Test config: force an 8-device virtual CPU mesh so distributed tests run
without TPU hardware (SURVEY.md §4).

Note: the axon TPU-tunnel plugin is registered by sitecustomize at
interpreter startup (it imports jax internals), so JAX_PLATFORMS in the
environment is already consumed — the override must go through
jax.config.update, and XLA_FLAGS must be set before the CPU client is
instantiated (it is created lazily, so doing it here is early enough).
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests — seeded "
        "schedules, CPU-safe, run in tier-1 (no slow marker)")
    config.addinivalue_line("markers", "slow: long-running; excluded from "
                            "the tier-1 '-m not slow' run")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    pt.seed(0)
    yield


@pytest.fixture(autouse=True)
def _clear_faults():
    """Chaos hygiene: no fault rule ever leaks across tests."""
    from paddle_tpu.utils.faults import FAULTS
    FAULTS.clear()
    yield
    FAULTS.clear()


@pytest.fixture(autouse=True)
def _no_leaked_threads():
    """Background-thread hygiene: every paddle_tpu helper thread carries a
    ``pt-`` name prefix (prefetch producers, the async checkpoint writer,
    the metrics HTTP server, stall watchdogs). None may outlive the test
    that started it. A short grace join absorbs threads that are already
    winding down (e.g. a prefetch producer observing its closed flag)."""
    import threading
    import time
    yield
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name.startswith("pt-") and t.is_alive()]
        if not leaked:
            return
        time.sleep(0.02)
    assert not leaked, f"leaked background threads: {[t.name for t in leaked]}"


@pytest.fixture(autouse=True)
def _clear_observability():
    """Telemetry hygiene: every test starts with zeroed metric series,
    an empty span buffer, the tracer disabled (its default), and an
    empty flight-recorder ring with NO dump directory — a chaos test
    that crashes a trainer must not scatter flight_*.json into the
    repo. Tests that want dumps set FLIGHT.dir (or pass directory=)
    themselves; capacity/dir are restored afterwards either way. The
    request tracker (ISSUE 9) gets the same treatment: cleared and
    disabled (its default) on both sides, capacity restored. The SLO
    layer (ISSUE 19) too: the goodput ledger's metering sink is
    detached so a tracker built in one test never bills another's
    tokens, and the tenant label-cardinality seen-set resets."""
    from paddle_tpu.observability import FLIGHT, GOODPUT, METRICS, \
        REQUESTS, TRACER

    def _reset_slo_state():
        GOODPUT.attach_sink(None)
        # serving.telemetry pulls in jax via the engine stack; only
        # reset the seen-set if some test already imported it
        tel = sys.modules.get("paddle_tpu.serving.telemetry")
        if tel is not None:
            tel.reset_tenant_labels()

    _reset_slo_state()
    METRICS.reset()
    METRICS.enable()
    TRACER.disable()
    TRACER.clear()
    FLIGHT.clear()
    REQUESTS.disable()
    REQUESTS.clear()
    saved_dir, saved_cap = FLIGHT.dir, FLIGHT.capacity
    saved_rcap = REQUESTS.capacity
    FLIGHT.dir = None
    yield
    METRICS.reset()
    METRICS.enable()
    TRACER.disable()
    TRACER.clear()
    FLIGHT.clear()
    REQUESTS.disable()
    REQUESTS.clear()
    _reset_slo_state()
    FLIGHT.dir = saved_dir
    if FLIGHT.capacity != saved_cap:
        FLIGHT.set_capacity(saved_cap)
    if REQUESTS.capacity != saved_rcap:
        REQUESTS.set_capacity(saved_rcap)
