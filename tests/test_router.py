"""Multi-replica router (ISSUE 7): greedy identity single vs routed vs
disaggregated prefill/decode (chunked prefill and spec decode included),
least-outstanding-requests dispatch, session affinity, health-gated
dispatch, drain-aware rebalancing (the requeue-before-drain deadlock
fix), the three router chaos sites, and the PT_ROUTER_DISAGG kill
switch. Every chaos path must leave the fleet quiescent — no block
leaks on any replica, dead ones included."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability.health import (HEALTH, HealthEvaluator,
                                             gauge_imbalance)
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.serving import (EngineDrainingError, LLMEngine, Replica,
                                Request, Router)
from paddle_tpu.utils.faults import FAULTS, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module", autouse=True)
def _preserve_global_rng():
    """Later test modules build models off the global key stream without
    reseeding; leave that stream exactly where this module found it."""
    from paddle_tpu.core import random as _prng
    saved = None if _prng._global is None else _prng._global.key
    yield
    if saved is None:
        _prng._global = None
    else:
        _prng.seed(0)
        _prng._global.key = saved


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=4, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14):
    return [rs.randint(0, 64, (int(l),)) for l in rs.randint(lo, hi, size=n)]


def _reference(model, prompts, max_new=10, **ekw):
    eng = _mk(model, **ekw)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    return {rid: list(map(int, t)) for rid, t in eng.run().items()}


def _route(router, prompts, max_new=10, **rkw):
    for p in prompts:
        router.add_request(Request(p, max_new_tokens=max_new, **rkw))
    out = router.run()
    return {rid: list(map(int, t)) for rid, t in out.items()}


def _requeues_by_label():
    """router_requeues_total broken down as {(replica, why): count}."""
    from paddle_tpu.observability import METRICS
    inst = METRICS.get("router_requeues_total")
    if inst is None:
        return {}
    return {key: cell[0] for key, cell in inst._series.items()}


# --------------------------------------------------- greedy identity

def test_routed_two_replicas_matches_single_engine(model):
    """The router is transparent: 2-replica LOR output == one engine."""
    rs = np.random.RandomState(0)
    prompts = _prompts(8, rs)
    ref = _reference(model, prompts)
    r = Router([_mk(model), _mk(model)])
    out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["dispatched"] == 8 and r.stats["requeues"] == 0


def test_disaggregated_matches_single_engine(model):
    """1 prefill + 1 decode replica: every sequence crosses the KV
    transfer seam, and output is still token-for-token identical —
    including a prompt long enough for chunked prefill on the
    prefill-role replica (19 tokens > max_prompt_len=8 → 3 chunks)."""
    rs = np.random.RandomState(1)
    prompts = _prompts(5, rs) + [rs.randint(0, 64, (19,))]
    ref = _reference(model, prompts, max_prompt_len=8)
    r = Router([Replica(_mk(model, max_prompt_len=8), role="prefill"),
                Replica(_mk(model, max_prompt_len=8), role="decode")])
    assert r.disagg
    out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["transfers"] == 6          # every request crossed over
    assert not r.replicas[0].engine.has_work()


def test_disagg_spec_decode_on_decode_replica(model, draft):
    """Speculative decoding runs on the DECODE replica over installed
    (transferred) KV state: greedy output still equals the plain
    single-engine run."""
    rs = np.random.RandomState(2)
    prompts = _prompts(4, rs)
    ref = _reference(model, prompts, max_new=8)
    r = Router([
        Replica(_mk(model), role="prefill"),
        Replica(_mk(model, draft_model=draft, spec_k=2), role="decode"),
    ])
    out = _route(r, prompts, max_new=8)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["transfers"] == 4


def test_disagg_kill_switch(model, monkeypatch):
    """PT_ROUTER_DISAGG=0 collapses a disaggregated topology to plain
    replication: no transfers, roles coerced to 'both', output intact."""
    monkeypatch.setenv("PT_ROUTER_DISAGG", "0")
    rs = np.random.RandomState(3)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    r = Router([Replica(_mk(model), role="prefill"),
                Replica(_mk(model), role="decode")])
    assert not r.disagg
    assert all(rep.role == "both" for rep in r.replicas)
    out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["transfers"] == 0


@pytest.mark.slow
def test_parallel_run_matches_sequential(model):
    """run(parallel=True) — one driver thread per replica — produces
    the same greedy tokens as orchestrated sequential stepping."""
    rs = np.random.RandomState(4)
    prompts = _prompts(8, rs)
    ref = _reference(model, prompts)
    r = Router([_mk(model), _mk(model)])
    for p in prompts:
        r.add_request(Request(p, max_new_tokens=10))
    out = {rid: list(map(int, t))
           for rid, t in r.run(parallel=True).items()}
    assert out == ref
    r.assert_quiescent()


# ------------------------------------------------- dispatch policy

def test_lor_prefers_least_loaded_replica(model):
    """Skewed lengths: once the short request finishes, its replica has
    the fewest outstanding requests and MUST win the next dispatch."""
    rs = np.random.RandomState(5)
    r = Router([_mk(model), _mk(model)])
    long_rid = r.add_request(Request(rs.randint(0, 64, (5,)),
                                     max_new_tokens=24))
    short_rid = r.add_request(Request(rs.randint(0, 64, (5,)),
                                      max_new_tokens=2))
    assert r._where[long_rid] == 0 and r._where[short_rid] == 1
    while not r.requests[short_rid].done:
        r.step()
    nxt = r.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=2))
    assert r._where[nxt] == 1          # r1 idle, r0 still decoding
    r.run()
    r.assert_quiescent()


def test_session_affinity_sticks_to_one_replica(model):
    """Requests sharing a session_id land on one replica (their prefix
    blocks live there); distinct sessions still spread by LOR."""
    rs = np.random.RandomState(6)
    r = Router([_mk(model), _mk(model)])
    alice = [r.add_request(Request(rs.randint(0, 64, (6,)),
                                   max_new_tokens=6, session_id="alice"))
             for _ in range(3)]
    bob = [r.add_request(Request(rs.randint(0, 64, (6,)),
                                 max_new_tokens=6, session_id="bob"))
           for _ in range(3)]
    assert len({r._where[rid] for rid in alice}) == 1
    assert len({r._where[rid] for rid in bob}) == 1
    assert r._where[alice[0]] != r._where[bob[0]]
    r.run()
    r.assert_quiescent()


def test_crit_replica_receives_nothing(model):
    """Health gating: a replica whose evaluator verdicts CRIT is
    excluded from dispatch entirely."""
    rs = np.random.RandomState(7)
    bad = Replica(_mk(model))
    bad.health.rule("always_on_fire", lambda: 99.0, warn=1.0, crit=2.0)
    r = Router([bad, Replica(_mk(model))])
    prompts = _prompts(5, rs)
    ref = _reference(model, prompts)
    out = _route(r, prompts)
    assert out == ref
    assert bad.engine.stats["ticks"] == 0    # never even stepped
    r.assert_quiescent()


def test_imbalance_health_rule_installed_and_fires(model):
    """Router construction installs the stock imbalance rule on the
    global evaluator; the gauge_imbalance getter flags a skewed fleet."""
    Router([_mk(model), _mk(model)])
    assert any(rule.name == "router_replica_imbalance"
               for rule in HEALTH.rules)
    reg = MetricsRegistry()
    g = reg.gauge("router_replica_outstanding", "t", labelnames=("replica",))
    get = gauge_imbalance("router_replica_outstanding", registry=reg)
    g.set(10.0, replica="a")
    assert np.isnan(get())            # one series: nothing to compare
    g.set(0.0, replica="b")
    assert get() == pytest.approx(2.0)   # (10-0)/max(mean=5, 1)
    g.set(10.0, replica="b")
    assert get() == pytest.approx(0.0)


# ----------------------------------------------------- drain/rebalance

def test_drain_replica_rebalances_without_deadlock(model):
    """Satellite (f): draining a replica while the router holds queued
    work for it must requeue-then-drain, not deadlock. Engines are
    sized so the fleet backs up into the router queue first."""
    rs = np.random.RandomState(8)
    prompts = _prompts(10, rs)
    ref = _reference(model, prompts, max_new=6)
    r = Router([_mk(model, num_slots=2, max_queue_len=2),
                _mk(model, num_slots=2, max_queue_len=2)])
    for p in prompts:
        r.add_request(Request(p, max_new_tokens=6))
    assert len(r._queue) > 0           # fleet full: router is holding work
    r.drain_replica("r0")              # must return, not spin
    assert r.replicas[0].draining
    out = {rid: list(map(int, t)) for rid, t in r.run().items()}
    assert out == ref
    r.assert_quiescent()
    # nothing new landed on r0 after the drain call finished it
    assert all(i != 0 for i in r._where.values())
    assert r.stats["requeues"] >= 1    # engine-queued work was rebalanced
    # every requeue carries the drained replica + the drain cause
    by = _requeues_by_label()
    assert by and all(k == ("r0", "drain") for k in by)
    assert sum(by.values()) == r.stats["requeues"]


def test_drain_prefill_replica_flushes_handoffs(model):
    """Draining a prefill-role replica mid-CHUNKED-prefill drives the
    extract/install loop to completion (a prefill-only engine can't
    finish slots by itself — plain engine.drain() would spin)."""
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, 64, (19,))] + _prompts(3, rs, hi=8)
    ref = _reference(model, prompts, max_new=6, max_prompt_len=8)
    r = Router([Replica(_mk(model, max_prompt_len=8), role="prefill"),
                Replica(_mk(model, max_prompt_len=8), role="decode")])
    for p in prompts:
        r.add_request(Request(p, max_new_tokens=6))
    r.step()                    # 19-token prompt is now mid-chunk on r0
    r.drain_replica("r0")
    assert not r.replicas[0].engine.has_work()
    out = {rid: list(map(int, t)) for rid, t in r.run().items()}
    assert out == ref
    r.assert_quiescent()


# ------------------------------------------------------- chaos sites

def test_chaos_dispatch_requeues_and_recovers(model):
    """router.dispatch fault fires BEFORE the engine sees the request:
    nothing leaks, the request stays with the router and goes out on a
    later attempt; output identical."""
    rs = np.random.RandomState(10)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    r = Router([_mk(model), _mk(model)])
    with FAULTS.scope("router.dispatch", exc=InjectedFault, on={0, 2}):
        out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["requeues"] == 2
    assert r.stats["dispatched"] == 6
    assert sum(n for (rep, why), n in _requeues_by_label().items()
               if why == "dispatch_fault") == 2


def test_chaos_kv_transfer_requeues_no_leak(model):
    """router.kv_transfer fault during the prefill→decode handoff:
    exception-atomic — the sequence is pulled back, requeued, and
    re-prefilled elsewhere; no blocks leak on either replica and greedy
    output is unchanged."""
    rs = np.random.RandomState(11)
    prompts = _prompts(5, rs)
    ref = _reference(model, prompts)
    r = Router([Replica(_mk(model), role="prefill"),
                Replica(_mk(model), role="decode")])
    with FAULTS.scope("router.kv_transfer", exc=InjectedFault, on={1, 3}):
        out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["requeues"] == 2
    # the faults fired on the prefill replica's extraction path
    assert _requeues_by_label() == {("r0", "kv_transfer"): 2}


def test_chaos_replica_death_requeues_exactly_once(model):
    """Kill r0 mid-decode: its live requests are pulled back and
    re-dispatched to r1 EXACTLY once each; finished work survives; the
    dead replica's pool shows no leaks; greedy output is unchanged."""
    rs = np.random.RandomState(12)
    prompts = _prompts(6, rs)
    ref = _reference(model, prompts)
    r = Router([_mk(model), _mk(model)])
    seen = {"r0": 0}

    def kill_r0(ctx):
        if ctx["replica"] == "r0":
            seen["r0"] += 1
            if seen["r0"] == 3:       # a few steps in: requests mid-decode
                raise InjectedFault("induced r0 death")

    with FAULTS.scope("router.replica_death", action=kill_r0):
        out = _route(r, prompts)
    assert out == ref
    r.assert_quiescent()
    assert r.stats["deaths"] == 1
    assert not r.replicas[0].alive
    assert r.stats["requeues"] == len(r._requeued) >= 1
    by = _requeues_by_label()
    assert by and all(k == ("r0", "replica_death") for k in by)
    assert sum(by.values()) == r.stats["requeues"]


def test_replica_death_twice_marks_request_failed(model):
    """A request whose SECOND replica also dies is not requeued again —
    it finishes with finish_reason='replica_death' (exactly-once
    requeue); survivors complete on the remaining replica and the whole
    fleet stays quiescent."""
    rs = np.random.RandomState(13)
    prompts = _prompts(6, rs)
    r = Router([_mk(model), _mk(model), _mk(model)])
    seen = {"r0": 0, "r1": 0}

    def kill_two(ctx):
        name = ctx["replica"]
        if name in seen:
            seen[name] += 1
            if (name, seen[name]) in (("r0", 2), ("r1", 6)):
                raise InjectedFault(f"induced {name} death")

    ref = _reference(model, prompts)
    with FAULTS.scope("router.replica_death", action=kill_two):
        for p in prompts:
            r.add_request(Request(p, max_new_tokens=10))
        out = r.run()
    assert r.stats["deaths"] == 2
    for rid, req in r.requests.items():
        assert req.done
        if req.finish_reason == "replica_death":
            continue                   # gave up after the second death
        assert list(map(int, out[rid])) == ref[rid]
    # exactly-once: every requeue is a distinct request
    assert r.stats["requeues"] == len(r._requeued)
    r.assert_quiescent()


def test_all_replicas_down_rejects_new_requests(model):
    rs = np.random.RandomState(14)
    r = Router([_mk(model)])
    r.replicas[0].alive = False
    with pytest.raises(EngineDrainingError):
        r.add_request(Request(rs.randint(0, 64, (5,)), max_new_tokens=4))


# ------------------------------------------------------ import surface

def test_serving_import_surface_unchanged():
    """The package split must not break a single pre-existing import."""
    import paddle_tpu.serving as S
    for name in ("LLMEngine", "Request", "QueueFullError",
                 "EngineDrainingError", "_BeamGroup", "_SAMPLE_ROWS_JIT",
                 "_MOE_DROPPED", "KVCache", "_sample_rows", "PagedKVCache",
                 "PrefixCachingBlockManager", "_beam_finalize",
                 "_BEAM_GROUP_UPDATE_JIT", "_BEAM_SELECT_JIT",
                 "_PREFILL_CHUNK_JIT", "_PREFILL_JIT", "_REWIND_LENS_JIT",
                 "_TICK_JIT", "_VERIFY_CHUNK_JIT", "greedy_accept_length",
                 "is_moe_model", "stochastic_accept_row", "_FWD_ROWS_JIT",
                 "METRICS", "_span", "FLIGHT", "fault_point",
                 "Router", "Replica", "Scheduler", "KVManager",
                 "ModelExecutor", "KVTransfer", "DeviceKVTransfer",
                 "KVPayload"):
        assert hasattr(S, name), f"paddle_tpu.serving lost {name}"
