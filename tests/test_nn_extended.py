"""Extended nn surface: new losses (incl. CTC vs torch), fold/shuffle,
adaptive pools, interpolate modes — golden-checked against torch CPU."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def _np(x):
    return np.asarray(x, np.float32)


def test_ctc_loss_vs_torch():
    rs = np.random.RandomState(0)
    T, B, C, L = 12, 3, 5, 4
    logits = rs.randn(T, B, C).astype(np.float32)
    log_probs = tF.log_softmax(torch.tensor(logits), dim=-1)
    labels = rs.randint(1, C, (B, L)).astype(np.int32)
    input_lengths = np.array([12, 10, 8], np.int32)
    label_lengths = np.array([4, 3, 2], np.int32)

    want = tF.ctc_loss(log_probs, torch.tensor(labels.astype(np.int64)),
                       torch.tensor(input_lengths.astype(np.int64)),
                       torch.tensor(label_lengths.astype(np.int64)),
                       blank=0, reduction="mean").item()
    got = F.ctc_loss(jnp.asarray(log_probs.numpy()), jnp.asarray(labels),
                     jnp.asarray(input_lengths), jnp.asarray(label_lengths))
    assert np.allclose(float(got), want, rtol=1e-4), (float(got), want)

    # zero-length label edge case
    ll0 = np.array([4, 3, 0], np.int32)
    want0 = tF.ctc_loss(log_probs, torch.tensor(labels.astype(np.int64)),
                        torch.tensor(input_lengths.astype(np.int64)),
                        torch.tensor(ll0.astype(np.int64)),
                        blank=0, reduction="sum").item()
    got0 = F.ctc_loss(jnp.asarray(log_probs.numpy()), jnp.asarray(labels),
                      jnp.asarray(input_lengths), jnp.asarray(ll0),
                      reduction="sum")
    assert np.allclose(float(got0), want0, rtol=1e-4), (float(got0), want0)


@pytest.mark.parametrize("name,args", [
    ("soft_margin", {}),
    ("multi_label_soft_margin", {}),
    ("poisson_nll", {}),
    ("gaussian_nll", {}),
    ("multi_margin", {}),
])
def test_extra_losses_vs_torch(name, args):
    rs = np.random.RandomState(1)
    x = rs.randn(8, 6).astype(np.float32)
    if name == "soft_margin":
        y = rs.choice([-1.0, 1.0], (8, 6)).astype(np.float32)
        want = tF.soft_margin_loss(torch.tensor(x), torch.tensor(y)).item()
        got = F.soft_margin_loss(jnp.asarray(x), jnp.asarray(y))
    elif name == "multi_label_soft_margin":
        y = rs.randint(0, 2, (8, 6)).astype(np.float32)
        want = tF.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(y)).item()
        got = F.multi_label_soft_margin_loss(jnp.asarray(x), jnp.asarray(y))
    elif name == "poisson_nll":
        y = rs.poisson(3.0, (8, 6)).astype(np.float32)
        want = tF.poisson_nll_loss(torch.tensor(x), torch.tensor(y), full=True).item()
        got = F.poisson_nll_loss(jnp.asarray(x), jnp.asarray(y), full=True)
    elif name == "gaussian_nll":
        y = rs.randn(8, 6).astype(np.float32)
        var = np.abs(rs.randn(8, 6)).astype(np.float32) + 0.1
        want = tF.gaussian_nll_loss(torch.tensor(x), torch.tensor(y),
                                    torch.tensor(var)).item()
        got = F.gaussian_nll_loss(jnp.asarray(x), jnp.asarray(y), jnp.asarray(var))
    else:  # multi_margin
        y = rs.randint(0, 6, (8,))
        want = tF.multi_margin_loss(torch.tensor(x), torch.tensor(y)).item()
        got = F.multi_margin_loss(jnp.asarray(x), jnp.asarray(y.astype(np.int32)))
    assert np.allclose(float(got), want, rtol=1e-4, atol=1e-5), (name, float(got), want)


def test_fold_inverts_unfold():
    rs = np.random.RandomState(2)
    x = rs.randn(2, 3, 10, 8).astype(np.float32)
    cols = F.unfold(jnp.asarray(x), kernel_size=3, stride=2, padding=1)
    got = F.fold(cols, (10, 8), 3, strides=2, paddings=1)
    want = tF.fold(tF.unfold(torch.tensor(x), 3, stride=2, padding=1),
                   (10, 8), 3, stride=2, padding=1).numpy()
    assert np.allclose(_np(got), want, atol=1e-5)


def test_pixel_and_channel_shuffle():
    rs = np.random.RandomState(3)
    x = rs.randn(2, 8, 4, 6).astype(np.float32)
    assert np.allclose(_np(F.pixel_unshuffle(jnp.asarray(x), 2)),
                       tF.pixel_unshuffle(torch.tensor(x), 2).numpy())
    assert np.allclose(_np(F.channel_shuffle(jnp.asarray(x), 4)),
                       tF.channel_shuffle(torch.tensor(x), 4).numpy())
    # unshuffle inverts shuffle
    y = F.pixel_shuffle(jnp.asarray(x), 2)
    assert np.allclose(_np(F.pixel_unshuffle(y, 2)), x)


def test_adaptive_pools_nondivisible():
    rs = np.random.RandomState(4)
    x = rs.randn(2, 3, 11).astype(np.float32)
    got = F.adaptive_avg_pool1d(jnp.asarray(x), 4)
    want = tF.adaptive_avg_pool1d(torch.tensor(x), 4).numpy()
    assert np.allclose(_np(got), want, atol=1e-5)
    got = F.adaptive_max_pool1d(jnp.asarray(x), 4)
    want = tF.adaptive_max_pool1d(torch.tensor(x), 4).numpy()
    assert np.allclose(_np(got), want, atol=1e-5)
    x3 = rs.randn(2, 3, 5, 7, 9).astype(np.float32)
    got = F.adaptive_avg_pool3d(jnp.asarray(x3), (2, 3, 4))
    want = tF.adaptive_avg_pool3d(torch.tensor(x3), (2, 3, 4)).numpy()
    assert np.allclose(_np(got), want, atol=1e-5)


@pytest.mark.parametrize("mode,align", [
    ("nearest", False), ("bilinear", False), ("bilinear", True), ("area", False),
    ("bicubic", False), ("bicubic", True),
])
def test_interpolate_2d_vs_torch(mode, align):
    rs = np.random.RandomState(5)
    x = rs.randn(2, 3, 7, 9).astype(np.float32)
    kw = {} if mode in ("nearest", "area") else {"align_corners": align}
    want = tF.interpolate(torch.tensor(x), size=(13, 5), mode=mode, **kw).numpy()
    got = F.interpolate(jnp.asarray(x), size=(13, 5), mode=mode, align_corners=align)
    assert np.allclose(_np(got), want, atol=1e-5), (mode, align)


def test_interpolate_3d_5d():
    rs = np.random.RandomState(6)
    x1 = rs.randn(2, 3, 11).astype(np.float32)
    want = tF.interpolate(torch.tensor(x1), size=5, mode="linear").numpy()
    got = F.interpolate(jnp.asarray(x1), size=5, mode="linear")
    assert np.allclose(_np(got), want, atol=1e-5)
    x2 = rs.randn(1, 2, 4, 5, 6).astype(np.float32)
    want = tF.interpolate(torch.tensor(x2), size=(8, 3, 4), mode="trilinear").numpy()
    got = F.interpolate(jnp.asarray(x2), size=(8, 3, 4), mode="trilinear")
    assert np.allclose(_np(got), want, atol=1e-5)


def test_distance_layers():
    rs = np.random.RandomState(7)
    a = rs.randn(4, 8).astype(np.float32)
    b = rs.randn(4, 8).astype(np.float32)
    want = tF.cosine_similarity(torch.tensor(a), torch.tensor(b), dim=1).numpy()
    got = nn.CosineSimilarity(axis=1)(jnp.asarray(a), jnp.asarray(b))
    assert np.allclose(_np(got), want, atol=1e-5)
    want = torch.nn.PairwiseDistance()(torch.tensor(a), torch.tensor(b)).numpy()
    got = nn.PairwiseDistance()(jnp.asarray(a), jnp.asarray(b))
    assert np.allclose(_np(got), want, atol=1e-4)


def test_spectral_norm_layer():
    rs = np.random.RandomState(8)
    w = jnp.asarray(rs.randn(6, 4).astype(np.float32))
    sn = nn.SpectralNorm(w.shape, dim=0, power_iters=30)
    wn = sn(w)
    # largest singular value of the normalised weight ~= 1
    s = np.linalg.svd(_np(wn), compute_uv=False)
    assert abs(s[0] - 1.0) < 1e-3
    # u/v persist across calls: power_iters=1 converges over repeated calls
    sn1 = nn.SpectralNorm(w.shape, dim=0, power_iters=1)
    for _ in range(30):
        wn1 = sn1(w)
    s1 = np.linalg.svd(_np(wn1), compute_uv=False)
    assert abs(s1[0] - 1.0) < 1e-3


def test_scale_factor_and_int_padding():
    x = jnp.ones((1, 2, 4, 4))
    assert F.interpolate(x, scale_factor=2.0, mode="bilinear").shape == (1, 2, 8, 8)
    assert F.interpolate(x, scale_factor=0.5, mode="nearest").shape == (1, 2, 2, 2)
    assert nn.ZeroPad2D(1)(x).shape == (1, 2, 6, 6)
    assert nn.Pad3D(2)(jnp.ones((1, 2, 3, 3, 3))).shape == (1, 2, 7, 7, 7)
    assert nn.Pad1D(1)(jnp.ones((1, 2, 3))).shape == (1, 2, 5)
    # stability: large-magnitude soft margin stays finite
    out = F.soft_margin_loss(jnp.asarray([90.0]), jnp.asarray([-1.0]))
    assert np.isfinite(float(out))


def test_misc_new_layers():
    x = jnp.asarray(np.random.RandomState(9).randn(2, 6, 4, 4).astype(np.float32))
    assert nn.ZeroPad2D([1, 1, 2, 2])(x).shape == (2, 6, 8, 6)
    assert nn.Unflatten(1, (2, 3))(x).shape == (2, 2, 3, 4, 4)
    assert nn.ChannelShuffle(3)(x).shape == x.shape
    assert nn.InstanceNorm1D(6)(x[..., 0]).shape == (2, 6, 4)
    assert nn.AdaptiveAvgPool3D(2)(jnp.ones((1, 2, 4, 4, 4))).shape == (1, 2, 2, 2, 2)
    loss = nn.CTCLoss()
    out = loss(jnp.zeros((5, 2, 4)), jnp.ones((2, 2), jnp.int32),
               jnp.array([5, 5]), jnp.array([2, 2]))
    assert np.isfinite(float(out))
