"""Detection ops vs hand-rolled numpy references (mirroring the reference
PHI kernels' algorithms) plus torch golden where torch has the op."""
import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu.vision import ops as V
from paddle_tpu.nn import functional as F


# -- numpy references --------------------------------------------------------

def np_nms(boxes, scores, thresh):
    order = np.argsort(-scores, kind="stable")
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            # iou
            x1 = max(boxes[i, 0], boxes[j, 0]); y1 = max(boxes[i, 1], boxes[j, 1])
            x2 = min(boxes[i, 2], boxes[j, 2]); y2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(x2 - x1, 0) * max(y2 - y1, 0)
            a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            b = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / (a + b - inter) > thresh:
                suppressed[j] = True
    return np.array(keep, np.int64)


def np_roi_align(x, boxes, bidx, out, scale, ratio, aligned):
    R = len(boxes)
    C, H, W = x.shape[1:]
    ph = pw = out
    res = np.zeros((R, C, ph, pw), np.float32)
    for r in range(R):
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = boxes[r] * scale - off
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bw, bh = rw / pw, rh / ph
        sh = ratio if ratio > 0 else max(int(np.ceil(rh / ph)), 1)
        sw = ratio if ratio > 0 else max(int(np.ceil(rw / pw)), 1)
        img = x[bidx[r]]
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(C, np.float32)
                for si in range(sh):
                    for sj in range(sw):
                        yy = y1 + i * bh + (si + 0.5) * bh / sh
                        xx = x1 + j * bw + (sj + 0.5) * bw / sw
                        if yy < -1.0 or yy > H or xx < -1.0 or xx > W:
                            continue
                        yy = min(max(yy, 0), H - 1)
                        xx = min(max(xx, 0), W - 1)
                        y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                        y1i, x1i = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                        ly, lx = yy - y0, xx - x0
                        acc += (img[:, y0, x0] * (1 - ly) * (1 - lx) +
                                img[:, y0, x1i] * (1 - ly) * lx +
                                img[:, y1i, x0] * ly * (1 - lx) +
                                img[:, y1i, x1i] * ly * lx)
                res[r, :, i, j] = acc / (sh * sw)
    return res


def np_roi_pool(x, boxes, bidx, out, scale):
    R = len(boxes)
    C, H, W = x.shape[1:]
    res = np.zeros((R, C, out, out), np.float32)
    for r in range(R):
        x1, y1, x2, y2 = np.round(boxes[r] * scale)
        rh = max(y2 - y1 + 1, 1.0)
        rw = max(x2 - x1 + 1, 1.0)
        bh, bw = rh / out, rw / out
        for i in range(out):
            for j in range(out):
                hs = int(np.clip(np.floor(i * bh) + y1, 0, H))
                he = int(np.clip(np.ceil((i + 1) * bh) + y1, 0, H))
                ws = int(np.clip(np.floor(j * bw) + x1, 0, W))
                we = int(np.clip(np.ceil((j + 1) * bw) + x1, 0, W))
                if he > hs and we > ws:
                    res[r, :, i, j] = x[bidx[r]][:, hs:he, ws:we].max(axis=(1, 2))
    return res


def test_nms_matches_numpy():
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 50, (40, 2)).astype(np.float32)
    wh = rng.uniform(5, 30, (40, 2)).astype(np.float32)
    boxes = np.concatenate([xy, xy + wh], 1)
    scores = rng.uniform(size=40).astype(np.float32)
    got = np.asarray(V.nms(boxes, 0.4, scores=scores))
    ref = np_nms(boxes, scores, 0.4)
    np.testing.assert_array_equal(got, ref)


def test_nms_categories_never_cross_suppress():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1])
    got = np.asarray(V.nms(boxes, 0.1, scores=scores, category_idxs=cats,
                           categories=[0, 1]))
    assert set(got.tolist()) == {0, 1}
    got2 = np.asarray(V.nms(boxes, 0.1, scores=scores))
    assert got2.tolist() == [0]


def test_nms_top_k_and_empty():
    boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [10, 10, 11, 11]], np.float32)
    scores = np.array([0.1, 0.9, 0.5], np.float32)
    got = np.asarray(V.nms(boxes, 0.5, scores=scores, top_k=2))
    assert got.tolist() == [1, 2]
    assert V.nms(np.zeros((0, 4), np.float32), 0.5).shape == (0,)


@pytest.mark.parametrize("ratio,aligned", [(2, True), (2, False), (-1, True)])
def test_roi_align_matches_numpy(ratio, aligned):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 3, 12, 16)).astype(np.float32)
    boxes = np.array([[1, 1, 10, 8], [0.5, 2.2, 15.7, 11.1], [3, 3, 4, 4.5]],
                     np.float32)
    boxes_num = [2, 1]
    got = np.asarray(V.roi_align(x, boxes, boxes_num, 5, spatial_scale=0.5,
                                 sampling_ratio=ratio, aligned=aligned))
    ref = np_roi_align(x, boxes, [0, 0, 1], 5, 0.5, ratio, aligned)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_roi_pool_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 4, 10, 10)).astype(np.float32)
    boxes = np.array([[0, 0, 6, 6], [2, 2, 9, 9], [1, 0, 3, 8]], np.float32)
    got = np.asarray(V.roi_pool(x, boxes, [1, 2], 3, spatial_scale=1.0))
    ref = np_roi_pool(x, boxes, [0, 1, 1], 3, 1.0)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_psroi_pool_shapes_and_average():
    # uniform image → every bin average equals the channel constant
    ph = pw = 2
    C_out = 3
    x = np.arange(C_out * ph * pw, dtype=np.float32).reshape(1, -1, 1, 1)
    x = np.tile(x, (1, 1, 8, 8))
    boxes = np.array([[0, 0, 7, 7]], np.float32)
    out = np.asarray(V.psroi_pool(x, boxes, [1], (ph, pw), 1.0))
    assert out.shape == (1, C_out, ph, pw)
    for c in range(C_out):
        for i in range(ph):
            for j in range(pw):
                assert abs(out[0, c, i, j] - (c * ph * pw + i * pw + j)) < 1e-5


def test_deform_conv2d_zero_offset_equals_conv2d():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 4, 9, 9)).astype(np.float32)
    w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    offset = np.zeros((2, 2 * 1 * 9, 9, 9), np.float32)
    got = np.asarray(V.deform_conv2d(x, offset, w, b, stride=1, padding=1))
    ref = np.asarray(F.conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                              stride=1, padding=1))
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_deform_conv2d_integer_shift():
    # offset of exactly (0, +1) shifts sampling one pixel right = conv on
    # shifted input (interior pixels)
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, 2, 8, 8)).astype(np.float32)
    w = rng.standard_normal((3, 2, 3, 3)).astype(np.float32)
    offset = np.zeros((1, 2 * 9, 6, 6), np.float32)
    offset[:, 1::2] = 1.0  # dx = +1 for every tap
    got = np.asarray(V.deform_conv2d(x, offset, w, None, stride=1, padding=0))
    xs = np.roll(x, -1, axis=3)
    ref = np.asarray(F.conv2d(jnp.asarray(xs), jnp.asarray(w), None,
                              stride=1, padding=0))
    np.testing.assert_allclose(got[..., :-1], ref[..., :-1], atol=1e-3)


def test_deform_conv2d_mask_modulation():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    w = rng.standard_normal((2, 2, 3, 3)).astype(np.float32)
    offset = np.zeros((1, 18, 4, 4), np.float32)
    mask0 = np.zeros((1, 9, 4, 4), np.float32)
    out0 = np.asarray(V.deform_conv2d(x, offset, w, None, mask=mask0))
    np.testing.assert_allclose(out0, 0.0, atol=1e-6)
    mask1 = np.ones((1, 9, 4, 4), np.float32)
    out1 = np.asarray(V.deform_conv2d(x, offset, w, None, mask=mask1))
    ref = np.asarray(V.deform_conv2d(x, offset, w, None))
    np.testing.assert_allclose(out1, ref, atol=1e-5)


def test_box_coder_roundtrip():
    rng = np.random.default_rng(6)
    priors = np.abs(rng.uniform(1, 20, (5, 4))).astype(np.float32)
    priors[:, 2:] += priors[:, :2] + 1
    targets = np.abs(rng.uniform(1, 20, (3, 4))).astype(np.float32)
    targets[:, 2:] += targets[:, :2] + 1
    var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    enc = V.box_coder(priors, var, targets, "encode_center_size")
    assert enc.shape == (3, 5, 4)
    dec = V.box_coder(priors, var, enc, "decode_center_size", axis=0)
    for m in range(5):
        np.testing.assert_allclose(np.asarray(dec[:, m]), targets, rtol=1e-3,
                                   atol=1e-3)


def test_yolo_box_shapes_and_range():
    rng = np.random.default_rng(7)
    an, cls, H, W = 3, 4, 5, 5
    x = rng.standard_normal((2, an * (5 + cls), H, W)).astype(np.float32)
    img_size = np.array([[160, 160], [320, 160]], np.int32)
    boxes, scores = V.yolo_box(x, img_size, [10, 13, 16, 30, 33, 23], cls,
                               conf_thresh=0.0)
    assert boxes.shape == (2, H * W * an, 4)
    assert scores.shape == (2, H * W * an, cls)
    b = np.asarray(boxes)
    assert b[..., 0].min() >= 0 and b[0, :, 2].max() <= 159.001
    s = np.asarray(scores)
    assert s.min() >= 0 and s.max() <= 1


def test_yolo_box_anchor_major_and_iou_aware():
    rng = np.random.default_rng(12)
    an, cls, H, W = 2, 3, 4, 4
    x = rng.standard_normal((1, an * (5 + cls), H, W)).astype(np.float32)
    img_size = np.array([[128, 128]], np.int32)
    anchors = [10, 13, 16, 30]
    boxes, scores = V.yolo_box(x, img_size, anchors, cls, conf_thresh=0.0)
    # anchor-major: first H*W entries come from anchor 0 — check one decoded
    # box against hand math for anchor 1, cell (0, 0) → flat index H*W
    feat = x.reshape(1, an, 5 + cls, H, W)
    sig = lambda v: 1 / (1 + np.exp(-v))
    cx = sig(feat[0, 1, 0, 0, 0]) / W * 128
    bw = np.exp(feat[0, 1, 2, 0, 0]) * anchors[2] / (32 * W) * 128
    expect_x1 = np.clip(cx - bw / 2, 0, 127)
    np.testing.assert_allclose(np.asarray(boxes)[0, H * W, 0], expect_x1,
                               rtol=1e-4, atol=1e-4)
    # iou_aware: leading an-channel IoU block, conf blended by factor
    x2 = np.concatenate([rng.standard_normal((1, an, H, W)).astype(np.float32),
                         x], axis=1)
    b2, s2 = V.yolo_box(x2, img_size, anchors, cls, conf_thresh=0.0,
                        iou_aware=True, iou_aware_factor=0.5)
    assert b2.shape == boxes.shape and s2.shape == scores.shape
    conf = sig(feat[0, :, 4])
    iou = sig(x2[0, :an].reshape(an, H, W))
    blended = conf ** 0.5 * iou ** 0.5
    probs = sig(feat[0, :, 5:]) * blended[:, None]
    np.testing.assert_allclose(np.asarray(s2)[0].reshape(an, H, W, cls),
                               probs.transpose(0, 2, 3, 1), atol=1e-4)


def test_nms_categories_filter():
    boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30], [40, 40, 50, 50]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    cats = np.array([0, 1, 2])
    got = np.asarray(V.nms(boxes, 0.5, scores=scores, category_idxs=cats,
                           categories=[0, 2]))
    assert set(got.tolist()) == {0, 2}


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small → low level
                     [0, 0, 500, 500],    # big → high level
                     [0, 0, 224, 224]], np.float32)
    multi, restore, num = V.distribute_fpn_proposals(rois, 2, 5, 4, 224,
                                                     rois_num=[2, 1])
    assert len(multi) == 4
    total = sum(int(m.shape[0]) for m in multi)
    assert total == 3
    # restore maps concatenated-by-level order back to input order
    cat = np.concatenate([np.asarray(m) for m in multi if m.shape[0]], 0)
    np.testing.assert_allclose(cat[np.asarray(restore)], rois)
    assert [int(x.sum()) for x in num] == [1, 1, 1, 0] or sum(int(x.sum()) for x in num) == 3


def test_grid_sample_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    rng = np.random.default_rng(8)
    x = rng.standard_normal((2, 3, 6, 7)).astype(np.float32)
    g = rng.uniform(-1.2, 1.2, (2, 4, 5, 2)).astype(np.float32)
    for mode in ("bilinear", "nearest"):
        for pad in ("zeros", "border", "reflection"):
            for ac in (True, False):
                ref = TF.grid_sample(torch.tensor(x), torch.tensor(g),
                                     mode=mode, padding_mode=pad,
                                     align_corners=ac).numpy()
                got = np.asarray(F.grid_sample(jnp.asarray(x), jnp.asarray(g),
                                               mode=mode, padding_mode=pad,
                                               align_corners=ac))
                np.testing.assert_allclose(got, ref, atol=2e-4)


def test_affine_grid_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    rng = np.random.default_rng(9)
    for ac in (True, False):
        th = rng.standard_normal((2, 2, 3)).astype(np.float32)
        ref = TF.affine_grid(torch.tensor(th), (2, 3, 5, 7), align_corners=ac).numpy()
        got = np.asarray(F.affine_grid(jnp.asarray(th), (2, 3, 5, 7), align_corners=ac))
        np.testing.assert_allclose(got, ref, atol=1e-5)
        th3 = rng.standard_normal((2, 3, 4)).astype(np.float32)
        ref = TF.affine_grid(torch.tensor(th3), (2, 3, 4, 5, 6), align_corners=ac).numpy()
        got = np.asarray(F.affine_grid(jnp.asarray(th3), (2, 3, 4, 5, 6), align_corners=ac))
        np.testing.assert_allclose(got, ref, atol=1e-5)


def test_grid_sample_5d_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF
    rng = np.random.default_rng(10)
    x = rng.standard_normal((1, 2, 4, 5, 6)).astype(np.float32)
    g = rng.uniform(-1.1, 1.1, (1, 3, 4, 5, 3)).astype(np.float32)
    for mode in ("bilinear", "nearest"):
        ref = TF.grid_sample(torch.tensor(x), torch.tensor(g), mode=mode,
                             padding_mode="zeros", align_corners=True).numpy()
        got = np.asarray(F.grid_sample(jnp.asarray(x), jnp.asarray(g),
                                       mode=mode, padding_mode="zeros",
                                       align_corners=True))
        np.testing.assert_allclose(got, ref, atol=2e-4)


def test_layer_wrappers():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((1, 4, 8, 8)).astype(np.float32)
    layer = V.DeformConv2D(4, 6, 3, padding=1)
    off = np.zeros((1, 18, 8, 8), np.float32)
    assert layer(jnp.asarray(x), jnp.asarray(off)).shape == (1, 6, 8, 8)
    boxes = np.array([[0, 0, 4, 4]], np.float32)
    assert V.RoIAlign(3)(x, boxes, [1]).shape == (1, 4, 3, 3)
    assert V.RoIPool(3)(x, boxes, [1]).shape == (1, 4, 3, 3)
    x2 = rng.standard_normal((1, 4 * 4, 8, 8)).astype(np.float32)
    assert V.PSRoIPool(2)(x2, boxes, [1]).shape == (1, 4, 2, 2)


# -- transforms (host-side) --------------------------------------------------

class TestTransforms:
    def _img(self):
        return np.random.default_rng(3).uniform(0, 255, (16, 20, 3)).astype(np.uint8)

    def test_flip_involution_and_chw(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        np.testing.assert_array_equal(T.vflip(T.vflip(img)), img)
        np.testing.assert_array_equal(T.hflip(T.hflip(img)), img)
        chw = np.transpose(img, (2, 0, 1))
        assert T.vflip(chw).shape == chw.shape
        np.testing.assert_array_equal(
            np.transpose(T.vflip(chw), (1, 2, 0)), T.vflip(img))

    def test_pad_and_crop(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        p = T.pad(img, (1, 2, 3, 4))  # l, t, r, b
        assert p.shape == (16 + 2 + 4, 20 + 1 + 3, 3)
        np.testing.assert_array_equal(T.crop(p, 2, 1, 16, 20), img)

    def test_adjustments_identity_at_one(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        np.testing.assert_array_equal(T.adjust_brightness(img, 1.0), img)
        np.testing.assert_allclose(T.adjust_contrast(img, 1.0), img, atol=1)
        np.testing.assert_allclose(T.adjust_saturation(img, 1.0), img, atol=1)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=2)
        # brightness 0.5 halves values
        np.testing.assert_allclose(T.adjust_brightness(img, 0.5),
                                   (img * 0.5).astype(np.uint8), atol=1)

    def test_rotation_identity_and_90(self):
        from paddle_tpu.vision import transforms as T
        img = self._img().astype(np.float32)[:16, :16]  # square for 90°
        np.testing.assert_allclose(T.rotate(img, 0), img, atol=1e-3)
        r90 = T.rotate(img, 90)
        # 90° CCW of HWC = np.rot90 on the spatial axes
        np.testing.assert_allclose(r90, np.rot90(img, 1, (0, 1)), atol=1e-2)

    def test_grayscale_and_erasing(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        g3 = T.Grayscale(3)(img)
        assert g3.shape == img.shape
        np.testing.assert_array_equal(g3[..., 0], g3[..., 1])
        e = T.RandomErasing(prob=1.0, value=7, seed=0)(img)
        assert (e == 7).any() and e.shape == img.shape

    def test_random_resized_crop_and_jitter_shapes(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        out = T.RandomResizedCrop((10, 12), seed=1)(img)
        assert out.shape == (10, 12, 3)
        out = T.ColorJitter(0.3, 0.3, 0.3, 0.1, seed=1)(img)
        assert out.shape == img.shape and out.dtype == img.dtype

    def test_compose_pipeline(self):
        from paddle_tpu.vision import transforms as T
        pipe = T.Compose([T.RandomHorizontalFlip(seed=0), T.Resize(8),
                          T.ToTensor(),
                          T.Normalize([0.5] * 3, [0.5] * 3)])
        out = pipe(self._img())
        assert out.shape == (3, 8, 8)
        assert out.min() >= -1.001 and out.max() <= 1.001

    def test_adjust_ops_chw_and_grayscale(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        chw = np.transpose(img, (2, 0, 1))
        # contrast must agree across layouts
        a = T.adjust_contrast(img, 0.5)
        b = np.transpose(T.adjust_contrast(chw, 0.5), (1, 2, 0))
        np.testing.assert_allclose(a.astype(int), b.astype(int), atol=1)
        # hue on grayscale is a no-op, not a crash
        gray = img[..., 0]
        np.testing.assert_array_equal(T.adjust_hue(gray, 0.3), gray)

    def test_rotate_expand(self):
        from paddle_tpu.vision import transforms as T
        img = self._img().astype(np.float32)
        out = T.rotate(img, 45, expand=True)
        assert out.shape[0] > img.shape[0] and out.shape[1] > img.shape[1]
        # content preserved: sum of a rotated constant image stays ~constant
        ones = np.ones((10, 10, 1), np.float32)
        r = T.rotate(ones, 45, expand=True)
        np.testing.assert_allclose(r.sum(), 100.0, rtol=0.05)
