"""Beam search + generation constraints (ref PaddleNLP GenerationMixin)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.decoding import beam_search, generate


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    return LlamaForCausalLM(cfg).eval()


def _seq_logprob(model, seq, prompt_len):
    """Sum log p(token | prefix) over generated positions."""
    logits = model(seq[None, :])  # [1, L, V]
    logp = jax.nn.log_softmax(np.asarray(logits, np.float32), axis=-1)
    total = 0.0
    for t in range(prompt_len, seq.shape[0]):
        total += logp[0, t - 1, int(seq[t])]
    return total


def test_beam1_equals_greedy(model):
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, model.cfg.vocab_size, (2, 4)))
    greedy = generate(model, prompt, max_new_tokens=6, temperature=0.0)
    beam, _ = beam_search(model, prompt, max_new_tokens=6, num_beams=1)
    assert np.array_equal(np.asarray(greedy), np.asarray(beam))


def test_beam_score_is_exact_and_beats_greedy(model):
    rs = np.random.RandomState(1)
    prompt = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (2, 3)))
    n_new = 5
    seqs, scores = beam_search(model, prompt, max_new_tokens=n_new, num_beams=4)
    assert seqs.shape == (2, 3 + n_new)
    greedy = generate(model, prompt, max_new_tokens=n_new, temperature=0.0)
    for bi in range(2):
        want_lp = _seq_logprob(model, np.asarray(seqs[bi]), 3)
        got = float(scores[bi]) * n_new  # length_penalty=1.0
        assert abs(want_lp - got) < 5e-2, (want_lp, got)
        greedy_lp = _seq_logprob(model, np.asarray(greedy[bi]), 3)
        assert want_lp >= greedy_lp - 1e-3  # beam can't be worse than greedy


def test_beam_eos_finishes(model):
    rs = np.random.RandomState(2)
    prompt = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (1, 3)))
    eos = 7
    seqs, scores = beam_search(model, prompt, max_new_tokens=8, num_beams=3,
                               eos_token_id=eos)
    assert seqs.shape == (1, 11)
    assert np.isfinite(float(scores[0]))


def test_repetition_penalty_reduces_repeats(model):
    rs = np.random.RandomState(3)
    prompt = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (1, 4)))
    plain = np.asarray(generate(model, prompt, max_new_tokens=12, temperature=0.0))
    pen = np.asarray(generate(model, prompt, max_new_tokens=12, temperature=0.0,
                              repetition_penalty=5.0))

    def repeats(x):
        gen = x[0, 4:]
        return len(gen) - len(set(gen.tolist()))

    assert repeats(pen) <= repeats(plain)
    assert not np.array_equal(plain, pen) or repeats(plain) == 0


def test_min_new_tokens_blocks_eos(model):
    rs = np.random.RandomState(4)
    prompt = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (1, 3)))
    # find the greedy first token, use it as "eos" so it would stop instantly
    g = np.asarray(generate(model, prompt, max_new_tokens=1, temperature=0.0))
    eos = int(g[0, 3])
    out = np.asarray(generate(model, prompt, max_new_tokens=6, temperature=0.0,
                              eos_token_id=eos, min_new_tokens=4))
    gen = out[0, 3:]
    assert not np.any(gen[:3] == eos), gen  # eos suppressed below min length
