"""geometric segment/message-passing ops, callbacks, summary/flops."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import geometric as G
from paddle_tpu import callbacks as C


# -- geometric ---------------------------------------------------------------

def test_segment_ops_match_numpy():
    rng = np.random.default_rng(0)
    data = rng.standard_normal((10, 3)).astype(np.float32)
    seg = np.array([0, 0, 1, 1, 1, 3, 3, 3, 3, 0])
    n = 5  # segment 2 and 4 empty
    s = np.zeros((n, 3), np.float32)
    for i, sid in enumerate(seg):
        s[sid] += data[i]
    np.testing.assert_allclose(np.asarray(G.segment_sum(data, seg, n)), s, atol=1e-5)
    cnt = np.bincount(seg, minlength=n)[:, None]
    mean = s / np.maximum(cnt, 1)
    np.testing.assert_allclose(np.asarray(G.segment_mean(data, seg, n)), mean, atol=1e-5)
    mx = np.full((n, 3), -np.inf, np.float32)
    mn = np.full((n, 3), np.inf, np.float32)
    for i, sid in enumerate(seg):
        mx[sid] = np.maximum(mx[sid], data[i])
        mn[sid] = np.minimum(mn[sid], data[i])
    mx[cnt[:, 0] == 0] = 0
    mn[cnt[:, 0] == 0] = 0
    np.testing.assert_allclose(np.asarray(G.segment_max(data, seg, n)), mx, atol=1e-5)
    np.testing.assert_allclose(np.asarray(G.segment_min(data, seg, n)), mn, atol=1e-5)


def test_segment_ops_infer_num_segments():
    data = np.ones((4, 2), np.float32)
    seg = np.array([0, 1, 1, 2])
    out = np.asarray(G.segment_sum(data, seg))
    assert out.shape == (3, 2)
    np.testing.assert_allclose(out[1], [2, 2])


def test_send_u_recv_reductions():
    x = np.array([[1.0], [2.0], [4.0]], np.float32)
    src = np.array([0, 1, 2, 2])
    dst = np.array([1, 2, 2, 0])
    got = np.asarray(G.send_u_recv(x, src, dst, "sum"))
    np.testing.assert_allclose(got, [[4], [1], [6]])
    got = np.asarray(G.send_u_recv(x, src, dst, "max"))
    np.testing.assert_allclose(got, [[4], [1], [4]])
    got = np.asarray(G.send_u_recv(x, src, dst, "mean"))
    np.testing.assert_allclose(got, [[4], [1], [3]])


def test_send_ue_recv_and_send_uv():
    x = np.array([[1.0], [2.0], [3.0]], np.float32)
    e = np.array([[10.0], [20.0]], np.float32)
    src = np.array([0, 1])
    dst = np.array([2, 2])
    got = np.asarray(G.send_ue_recv(x, e, src, dst, "add", "sum"))
    np.testing.assert_allclose(got, [[0], [0], [33]])
    got = np.asarray(G.send_ue_recv(x, e, src, dst, "mul", "max"))
    np.testing.assert_allclose(got, [[0], [0], [40]])
    y = np.array([[5.0], [6.0], [7.0]], np.float32)
    got = np.asarray(G.send_uv(x, y, src, dst, "add"))
    np.testing.assert_allclose(got, [[8], [9]])


def test_send_u_recv_under_jit():
    x = jnp.ones((4, 2))
    src = jnp.array([0, 1, 2, 3])
    dst = jnp.array([1, 1, 0, 0])
    f = jax.jit(lambda x: G.send_u_recv(x, src, dst, "sum", out_size=4))
    np.testing.assert_allclose(np.asarray(f(x))[0], [2, 2])


def test_reindex_graph():
    x = np.array([10, 20])
    nbr = np.array([30, 20, 10, 40])
    cnt = np.array([2, 2])
    src, dst, nodes = G.reindex_graph(x, nbr, cnt)
    nodes = np.asarray(nodes)
    assert nodes[0] == 10 and nodes[1] == 20  # input nodes keep their slots
    # edge endpoints decode back to the original ids
    np.testing.assert_array_equal(nodes[np.asarray(src)], nbr)
    np.testing.assert_array_equal(np.asarray(dst), [0, 0, 1, 1])


def test_sample_neighbors():
    # CSC: node 0 has nbrs [1,2,3], node 1 has [0]
    colptr = np.array([0, 3, 4])
    row = np.array([1, 2, 3, 0])
    nbrs, cnt = G.sample_neighbors(row, colptr, [0, 1], sample_size=2, seed=0)
    assert np.asarray(cnt).tolist() == [2, 1]
    assert set(np.asarray(nbrs)[:2]).issubset({1, 2, 3})
    w = np.array([0.1, 0.1, 10.0, 1.0])
    nbrs, cnt = G.weighted_sample_neighbors(row, colptr, w, [0], sample_size=1,
                                            seed=1)
    assert np.asarray(cnt).tolist() == [1]


# -- callbacks ---------------------------------------------------------------

class _Recorder(C.Callback):
    def __init__(self):
        super().__init__()
        self.events = []

    def on_train_begin(self, logs=None): self.events.append("tb")
    def on_epoch_begin(self, e, logs=None): self.events.append(f"eb{e}")
    def on_train_batch_end(self, s, logs=None): self.events.append(f"be{s}")
    def on_epoch_end(self, e, logs=None): self.events.append(f"ee{e}")
    def on_train_end(self, logs=None): self.events.append("te")


def _fit_tiny(callbacks, epochs=3):
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.1),
              loss=lambda out, y: nn.functional.cross_entropy(out, y))
    rng = np.random.default_rng(0)
    data = [(rng.standard_normal((8, 4)).astype(np.float32),
             rng.integers(0, 2, 8)) for _ in range(4)]
    hist = m.fit(data, epochs=epochs, verbose=0, callbacks=callbacks)
    return m, hist


def test_callback_event_order():
    rec = _Recorder()
    _fit_tiny([rec], epochs=2)
    assert rec.events[0] == "tb" and rec.events[-1] == "te"
    assert rec.events[1] == "eb0" and "ee1" in rec.events
    assert rec.events.index("ee0") < rec.events.index("eb1")


def test_early_stopping_stops():
    class Spike(C.Callback):
        # force the monitored loss upward so patience trips
        def on_epoch_end(self, epoch, logs=None):
            logs["loss"] = 1.0 + epoch

    rec = _Recorder()
    es = C.EarlyStopping(monitor="loss", patience=1, verbose=0)
    _fit_tiny([Spike(), es, rec], epochs=10)
    seen_epochs = [e for e in rec.events if e.startswith("ee")]
    assert len(seen_epochs) < 10


def test_model_checkpoint(tmp_path):
    mc = C.ModelCheckpoint(save_freq=1, save_dir=str(tmp_path))
    _fit_tiny([mc], epochs=2)
    import os
    names = os.listdir(str(tmp_path))
    assert any(n.startswith("final") for n in names)
    assert any(n.startswith("0") for n in names)  # per-epoch save


def test_lr_scheduler_callback_steps_epoch_schedule():
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    sched = opt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=sched),
              loss=lambda out, y: nn.functional.cross_entropy(out, y))
    rng = np.random.default_rng(0)
    data = [(rng.standard_normal((4, 4)).astype(np.float32),
             rng.integers(0, 2, 4))]
    lr0 = sched.get_lr()
    m.fit(data, epochs=2, verbose=0, callbacks=[C.LRSchedulerCallback()])
    assert sched.get_lr() < lr0  # epoch-end stepping actually fired


def test_early_stopping_reusable():
    es = C.EarlyStopping(monitor="loss", patience=0, verbose=0)
    es.stop_training = True  # stale state from a previous fit
    es.on_train_begin()
    assert es.stop_training is False


def test_nms_categories_filter_all_removed():
    from paddle_tpu.vision.ops import nms
    boxes = np.array([[0, 0, 1, 1]], np.float32)
    got = nms(boxes, 0.5, scores=np.array([0.9], np.float32),
              category_idxs=np.array([0]), categories=[1])
    assert got.shape == (0,)


def test_summary_and_flops():
    import paddle_tpu.nn as nn
    pt.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    out = []
    res = pt.summary(net, (None, 16), print_fn=out.append)
    assert res["total_params"] == 16 * 32 + 32 + 32 * 4 + 4
    assert res["output_shape"] == (1, 4)
    assert "Linear" in out[0]
    n = pt.flops(net, (1, 16), print_fn=None)
    # 2*16*32 + 2*32*4 MACs-ish; cost model may fold bias — just sanity-band
    assert n == 0 or 500 < n < 50_000
