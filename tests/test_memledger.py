"""KV memory ledger (ISSUE 13): per-block state accounting reconciled
against the block manager tick-for-tick.

* chaos reconciliation: after EVERY engine tick — under injected
  allocator failures (``serving.alloc``), induced preemption
  (``serving.preempt``), spec-verify faults (``serving.spec_verify``),
  and the radix + spec + chunked-prefill combination —
  ``reconcile()["ok"]`` holds and the five states sum to the pool size
* ``serving.prefix_evict`` chaos at the manager choke point: the
  exception-atomic fault leaves the ledger agreeing block-for-block
* ``PT_MEM_LEDGER=0``: bit-identical outputs, zeroed counts, hooks
  reduced to one bool read
* ``GET /memory`` endpoint shape; per-request peak attribution in
  ``req.trace_summary``; admission-stall arithmetic
  (``serving_kv_stall_total{blocked_on}`` == ``ledger.stall_counts``)
* ``assert_quiescent`` violations carry the ledger breakdown and land
  in the flight ring
"""
import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import RadixPrefixBlockManager
from paddle_tpu.observability import FLIGHT, METRICS, REQUESTS
from paddle_tpu.observability.httpd import MetricsServer
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _solo(model, p, n):
    return np.asarray(generate(model, jnp.asarray(np.asarray(p)[None]),
                               max_new_tokens=n))[0, len(p):]


def _assert_reconciled(eng):
    r = eng.kv.reconcile()
    assert r["ok"], r["diffs"]
    assert sum(r["counts"].values()) == eng.kv.num_blocks, r["counts"]


def _run_reconciled(eng, catch=(), max_ticks=400):
    """Drive the engine to drain, asserting the ledger↔manager identity
    after every tick (including ticks that raised a caught chaos
    exception mid-flight)."""
    ticks = 0
    while eng.has_work():
        try:
            eng.step()
        except catch:
            pass                       # transient injection: retry tick
        _assert_reconciled(eng)
        ticks += 1
        assert ticks < max_ticks, "livelock under chaos"
    _assert_reconciled(eng)
    return ticks


# ------------------------------------------------- chaos reconciliation

@pytest.mark.chaos
def test_reconcile_every_tick_under_alloc_chaos(model):
    """Seeded allocator failures + preemption: the ledger agrees with
    the manager after every tick AND the run still drains exactly."""
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 12, 6)]
    FAULTS.schedule("serving.alloc", seed=42, p=0.25, horizon=200,
                    exc=MemoryError, times=20)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    _run_reconciled(eng, catch=(MemoryError,))
    assert FAULTS.log, "schedule never fired — test is vacuous"
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 6),
            err_msg=f"request {rid} corrupted by chaos")
    eng.assert_quiescent()
    # drained pool: everything is parked (radix) or free, nothing active
    c = eng.kv.ledger.counts()
    assert c["active"] == 0 and c["cow_pending"] == 0 and c["reserved"] == 0


@pytest.mark.chaos
def test_reconcile_every_tick_under_induced_preemption(model):
    """serving.preempt rule kicks victims out on a cadence — table_drop
    must retire their rows without disturbing the block mirrors."""
    rs = np.random.RandomState(10)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 12, 4)]
    FAULTS.install("serving.preempt", every=5, times=6,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    _run_reconciled(eng)
    assert eng.stats["preemptions"] > 0
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 6))
    eng.assert_quiescent()


@pytest.mark.chaos
def test_reconcile_every_tick_under_spec_verify_chaos(model):
    """Spec decode with injected verify faults: rewinds, fallbacks, and
    multi-token commits all keep the mirrors block-exact."""
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(3, 12, 5)]
    FAULTS.install("serving.spec_verify", every=2, times=4)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    _run_reconciled(eng)
    assert eng.stats["spec_fallbacks"] > 0, "fault never fired"
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 8))
    eng.assert_quiescent()


@pytest.mark.chaos
def test_reconcile_under_prefix_evict_chaos():
    """The serving.prefix_evict fault site is exception-atomic at the
    manager; the ledger must agree block-for-block before, during (the
    caught raise), and after the retried eviction."""
    mgr = RadixPrefixBlockManager(num_blocks=2, block_size=4)

    def ok():
        r = mgr.ledger.reconcile(mgr)
        assert r["ok"], r["diffs"]
        assert sum(r["counts"].values()) == mgr.num_blocks

    toks = np.arange(8, dtype=np.int32)
    mgr.allocate(1, 8)
    mgr.commit_prefix(1, toks)
    ok()
    mgr.free(1)                                    # pool fully parked
    ok()
    assert mgr.ledger.counts()["parked"] == 2
    with FAULTS.scope("serving.prefix_evict", exc=InjectedFault,
                      every=1, times=1):
        with pytest.raises(InjectedFault):
            mgr.allocate(2, 4)
    mgr.tables.pop(2, None)                        # caller cleanup on fail
    ok()                                           # pre-mutation: untouched
    assert mgr.ledger.counts()["parked"] == 2
    mgr.allocate(2, 4)                             # retried: evicts one
    ok()
    assert mgr.cache_stats["evictions"] == 1
    mgr.free(2)
    ok()


def test_reconcile_radix_spec_chunked_prefill(model):
    """The acceptance combination: radix sharing (common prefixes) +
    spec decode + chunked prefill (prompts >> max_prompt_len) in one
    engine, reconciled after every tick."""
    rs = np.random.RandomState(3)
    base = rs.randint(0, 64, (14,))
    prompts = [base,
               np.concatenate([base[:10], rs.randint(0, 64, (9,))]),
               np.concatenate([base, rs.randint(0, 64, (5,))]),
               rs.randint(0, 64, (5,))]
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=2,
                    block_size=4, max_prompt_len=8, max_seq_len=40)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    _run_reconciled(eng)
    for rid, p in enumerate(prompts):
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), _solo(model, p, 6))
    eng.assert_quiescent()
    # the radix trie kept shared blocks parked — visible in the ledger
    assert eng.kv.ledger.counts()["parked"] > 0


# ------------------------------------------------------ the kill switch

def test_disabled_is_noop_and_bit_identical(model, monkeypatch):
    rs = np.random.RandomState(5)
    prompts = [rs.randint(0, 64, (int(n),)) for n in rs.randint(4, 12, 4)]

    def run(eng):
        for p in prompts:
            eng.add_request(Request(p, max_new_tokens=6))
        out = eng.run()
        return {rid: list(map(int, t)) for rid, t in out.items()}

    kw = dict(num_slots=2, block_size=4, max_prompt_len=16, max_seq_len=32,
              preemption=True)
    base = run(LLMEngine(model, **kw))
    monkeypatch.setenv("PT_MEM_LEDGER", "0")
    eng = LLMEngine(model, **kw)
    assert not eng.kv.ledger.enabled
    off = run(eng)
    assert off == base                             # bit-identical behavior
    led = eng.kv.ledger
    assert led.counts() == dict.fromkeys(led.STATES, 0)
    assert led.fragmentation() == 0.0
    assert led.describe() == "disabled (PT_MEM_LEDGER=0)"
    assert led.take_peak(0) == 0                   # finish paths still call
    r = eng.kv.reconcile()
    assert r == {"ok": True, "skipped": True, "diffs": [],
                 "counts": dict.fromkeys(led.STATES, 0), "walk": None}
    eng.assert_quiescent()                         # message path still works


# --------------------------------------------------- /memory endpoint

def test_memory_endpoint_shape(model):
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    eng.add_request(Request(np.arange(6) % 64, max_new_tokens=4))
    eng.run()
    srv = MetricsServer(port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/memory", timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
    finally:
        srv.stop()
    assert "device" in doc                         # HBM stats (or error)
    pools = doc["pools"]
    assert pools, "engine pool not registered"
    mine = [p for p in pools if p["num_blocks"] == eng.kv.num_blocks]
    assert mine
    for p in pools:
        assert set(p["states"]) == set(eng.kv.ledger.STATES)
        assert sum(p["states"].values()) == p["num_blocks"]
        for key in ("pool", "enabled", "block_size", "fragmentation",
                    "bytes_per_token", "stalls", "top_holders",
                    "reserved_promised"):
            assert key in p, key


# ----------------------------------------------- peak-block attribution

def test_request_peak_blocks_in_trace_summary(model):
    REQUESTS.enable()
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    p = np.arange(9) % 64
    rid = eng.add_request(Request(p, max_new_tokens=6))
    eng.run()
    req = eng.requests[rid]
    peak = req.trace_summary["kv_peak_blocks"]
    # 9 prompt + 6 new = 15 tokens over block_size=4 → 4 blocks at peak
    assert peak == -(-(len(p) + 6) // eng.block_size)
    # the per-seq entry was consumed at finish — nothing accumulates
    assert eng.kv.take_peak(rid) == 0


def test_peak_survives_preemption(model):
    """A preempted-and-replayed request reports its lifetime peak, not
    the post-replay segment's."""
    REQUESTS.enable()
    FAULTS.install("serving.preempt", every=3, times=4,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, preemption=True)
    rids = [eng.add_request(Request(np.arange(8) % 64, max_new_tokens=6))
            for _ in range(3)]
    eng.run()
    assert eng.stats["preemptions"] > 0
    for rid in rids:
        peak = eng.requests[rid].trace_summary["kv_peak_blocks"]
        assert peak == -(-(8 + 6) // eng.block_size)


# ------------------------------------------------------ stall forensics

def test_stall_arithmetic_counter_matches_ledger(model):
    """A pool-starved admission stalls the queue head; the metrics
    counter and the ledger's own tally agree label-for-label, and the
    blamed state is the one actually holding the blocks."""
    # each request's worst case is 3 blocks (6 prompt + 4 new over
    # block_size=4); a 5-block pool admits one and stalls the other —
    # distinct prompts so the radix cache can't quietly share the cost
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24, num_blocks=5)
    eng.add_request(Request(np.arange(6) % 64, max_new_tokens=4))
    eng.add_request(Request(63 - np.arange(6) % 64, max_new_tokens=4))
    eng.run()
    led = eng.kv.ledger
    assert led.stall_counts, "no stall was ever recorded"
    # every stall blamed a held state (parked/free never block admission)
    assert set(led.stall_counts) <= {"active", "reserved", "cow_pending",
                                     "slots", "capacity"}
    snap = METRICS.snapshot()["counters"]
    for label, n in led.stall_counts.items():
        key = f'serving_kv_stall_total{{blocked_on="{label}"}}'
        assert snap[key] == n, (label, snap)
    assert not [k for k in snap
                if k.startswith("serving_kv_stall_total")
                and k not in {f'serving_kv_stall_total{{blocked_on="{s}"}}'
                              for s in led.stall_counts}]
    eng.assert_quiescent()


# ------------------------------------------- quiescence + OOM forensics

def test_quiescent_violation_carries_ledger_breakdown(model):
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    eng.mgr.allocate(99, 5)                        # leak two blocks
    with pytest.raises(AssertionError, match=r"kv ledger: active=2"):
        eng.assert_quiescent()
    # the violation landed in the flight ring with the state breakdown
    ev = [e for e in FLIGHT.events()
          if e["kind"] == "serving.quiescence_violation"]
    assert ev and ev[-1]["states"]["active"] == 2
    eng.mgr.free(99)
    eng.assert_quiescent()
