"""MoE serving through LLMEngine — ISSUE 6.

Mixtral/Qwen2-MoE decode through the paged engine (the structure-agnostic
adapters in ``models/paged.py``), greedy token identity between the
grouped-GEMM path and the dense capacity path (``PT_GROUPED_GEMM=0``),
expert-parallel serving under an ``ep`` mesh, the ``serving.moe_dispatch``
chaos site's exception-atomicity, and the prefix-cache metrics export.
"""
import os

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
from paddle_tpu.models.paged import clear_jit_caches, is_moe_model
from paddle_tpu.models.qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS, InjectedFault


def _mixtral():
    pt.seed(0)
    return MixtralForCausalLM(MixtralConfig.tiny())


def _engine(model, **kw):
    ekw = dict(num_slots=4, block_size=8, max_prompt_len=16, max_seq_len=48)
    ekw.update(kw)
    return LLMEngine(model, **ekw)


def _prompts(vocab, n=3, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (int(l),))
            for l in rs.randint(3, 12, size=n)]


def _run(model, prompts, max_new=10, **kw):
    eng = _engine(model, **kw)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    out = eng.run()
    eng.assert_quiescent()
    return {r: list(map(int, t)) for r, t in out.items()}


def test_moe_model_detection():
    assert is_moe_model(_mixtral())
    pt.seed(0)
    assert is_moe_model(Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny()))
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    assert not is_moe_model(LlamaForCausalLM(LlamaConfig.tiny()))


@pytest.mark.parametrize("family", ["mixtral", "qwen2moe"])
def test_moe_engine_decodes(family):
    if family == "mixtral":
        model = _mixtral()
    else:
        pt.seed(0)
        model = Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny())
    out = _run(model, _prompts(model.cfg.vocab_size))
    assert all(len(t) == 10 for t in out.values())


def test_grouped_vs_dense_greedy_identity(monkeypatch):
    """PT_GROUPED_GEMM=0 must restore the dense path bit-compatibly:
    greedy decode emits identical tokens either way. The env flag is read
    at trace time, so the module-level jit caches are cleared around the
    flip."""
    model = _mixtral()
    prompts = _prompts(model.cfg.vocab_size, n=4)
    clear_jit_caches()
    try:
        on = _run(model, prompts)
        monkeypatch.setenv("PT_GROUPED_GEMM", "0")
        clear_jit_caches()
        off = _run(model, prompts)
    finally:
        clear_jit_caches()
    assert on == off


def test_moe_dispatch_chaos_aborts_tick_atomically():
    """An injected moe_dispatch fault (dead expert shard) must abort the
    tick exception-atomically: the engine survives, every block is
    reclaimed, and assert_quiescent stays clean."""
    model = _mixtral()
    eng = _engine(model)
    for p in _prompts(model.cfg.vocab_size):
        eng.add_request(Request(p, max_new_tokens=8))
    fired = 0
    with FAULTS.scope("serving.moe_dispatch", on={1}, exc=InjectedFault):
        while eng.has_work():
            try:
                eng.step()
            except InjectedFault:
                fired += 1
    assert fired == 1
    out = {r: list(map(int, req.tokens))
           for r, req in eng.pop_finished().items()}
    assert all(len(t) == 8 for t in out.values())
    eng.assert_quiescent()
    # faulted run produced the same tokens as a clean one (the aborted
    # tick mutated nothing)
    assert out == _run(_mixtral(), _prompts(model.cfg.vocab_size),
                       max_new=8)


def test_moe_dispatch_site_only_fires_for_moe_models():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    eng = _engine(model)
    eng.add_request(Request(np.array([1, 2, 3]), max_new_tokens=4))
    with FAULTS.scope("serving.moe_dispatch", exc=InjectedFault):
        eng.run()          # dense model: the site must never fire
    eng.assert_quiescent()
    assert FAULTS.hits["serving.moe_dispatch"] == 0
    FAULTS.clear()


def test_expert_parallel_serving_matches_single_device():
    """LLMEngine traced under a mesh with ep>1 routes MoE layers through
    the shard_map all_to_all path — greedy outputs must match the
    single-device engine exactly."""
    from paddle_tpu.distributed.mesh import HybridMesh
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    model = _mixtral()
    prompts = _prompts(model.cfg.vocab_size, n=3)
    clear_jit_caches()
    try:
        single = _run(model, prompts)
        clear_jit_caches()
        mesh = HybridMesh(ep=2, devices=jax.devices()[:2])
        with mesh:
            ep_out = _run(model, prompts)
    finally:
        clear_jit_caches()
    assert ep_out == single


def test_prefix_cache_metrics_exported():
    from paddle_tpu.observability import METRICS
    model = _mixtral()
    shared = np.arange(1, 17)            # two full shared 8-token blocks
    eng = _engine(model)
    eng.add_request(Request(shared, max_new_tokens=4))
    eng.run()
    before = METRICS.snapshot()["counters"].get(
        "serving_prefix_hit_blocks_total", 0)
    eng.add_request(Request(shared, max_new_tokens=4))
    eng.run()
    snap = METRICS.snapshot()
    hits = snap["counters"].get("serving_prefix_hit_blocks_total", 0)
    assert hits - before >= 1            # second request adopted blocks
    assert snap["gauges"].get("serving_prefix_hit_rate", 0.0) > 0.0
