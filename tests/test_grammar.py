"""Grammar-constrained decoding (ISSUE 14).

* regex parser + lazy DFA agree with Python's ``re`` over exhaustive
  short strings for a spread of patterns (classes, counters, alts)
* TokenMaskAutomaton surface: bias is exactly 0 / -1e30, EOS is legal
  iff accepting (with the no-continuation escape hatch), illegal
  ``advance`` raises
* ``json_schema_regex`` end-to-end: masked decoding can only spell
  canonical instances of the schema
* engine level: greedy, temperature>0, and SPECULATIVE decoding emit
  only mask-legal tokens; spec greedy under a grammar is token-for-token
  identical to non-spec greedy under the same grammar
"""
import json
import re
import string

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.serving.grammar import (TokenMaskAutomaton,
                                        json_schema_regex, regex_escape)

# 63 single-char tokens + one empty-string EOS token = vocab_size 64
CHARS = (string.digits + string.ascii_lowercase
         + string.ascii_uppercase[:19] + '{}":,-._')
VOCAB = list(CHARS) + [""]
EOS = 63
assert len(VOCAB) == 64 and len(set(CHARS)) == 63

ENG = dict(num_slots=3, block_size=4, max_prompt_len=16, max_seq_len=24)


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def aut_for(pattern):
    return TokenMaskAutomaton(pattern, vocab=VOCAB, eos_token_id=EOS)


def dfa_accepts(aut, s):
    """Drive the automaton one char-token at a time; legality must agree
    with the mask at every step."""
    sid = aut.start_state
    for ch in s:
        tid = VOCAB.index(ch)
        if not aut.mask(sid)[tid]:
            return False
        sid = aut.advance(sid, tid)
    return aut.accepting(sid)


# -------------------------------------------------------- parser vs re
@pytest.mark.parametrize("pattern", [
    "ab|ac", "a(b|c)*d", "[a-c]{2,3}", "a?b+c*", "\\d+",
    "-?\\d+(\\.\\d+)?", "[^ab]c", "(ab){2}", "a{2,}b",
])
def test_dfa_agrees_with_re(pattern):
    aut = aut_for(pattern)
    gold = re.compile(pattern)
    alphabet = "abcd01."
    pool = [""]
    for _ in range(4):
        pool = [s + c for s in pool for c in alphabet] + pool
    for s in set(pool):
        assert dfa_accepts(aut, s) == bool(gold.fullmatch(s)), (pattern, s)


def test_regex_escape_literal_roundtrip():
    raw = 'a.b{c}"d-e'
    aut = aut_for(regex_escape(raw))
    assert dfa_accepts(aut, raw)
    assert not dfa_accepts(aut, 'azb{c}"d-e')   # '.' escaped: not a wildcard


# ----------------------------------------------------- automaton surface
def test_bias_values_and_mask_consistency():
    aut = aut_for("[ab]{2}")
    b = aut.bias(aut.start_state)
    m = aut.mask(aut.start_state)
    assert b.dtype == np.float32 and b.shape == (64,)
    assert set(np.unique(b)) <= {np.float32(0.0), np.float32(-1e30)}
    np.testing.assert_array_equal(b == 0.0, m)
    legal = {VOCAB.index("a"), VOCAB.index("b")}
    assert set(np.nonzero(m)[0]) == legal          # EOS illegal: not accepting


def test_eos_iff_accepting_and_illegal_advance_raises():
    aut = aut_for("ab")
    s0 = aut.start_state
    assert not aut.mask(s0)[EOS]
    s1 = aut.advance(s0, VOCAB.index("a"))
    assert not aut.mask(s1)[EOS]
    s2 = aut.advance(s1, VOCAB.index("b"))
    assert aut.accepting(s2) and aut.mask(s2)[EOS]
    assert aut.advance(s2, EOS) == s2              # EOS keeps the state
    with pytest.raises(ValueError, match="illegal"):
        aut.advance(s0, VOCAB.index("b"))


def test_eos_escape_hatch_when_vocab_cannot_continue():
    # '~' is spellable by no token: after 'a' the state is live but
    # stuck, so EOS becomes the only way out
    aut = aut_for("a~")
    s1 = aut.advance(aut.start_state, VOCAB.index("a"))
    m = aut.mask(s1)
    assert m[EOS] and m.sum() == 1


def test_empty_and_impossible_patterns():
    with pytest.raises(ValueError):
        aut_for("[b-a]")
    with pytest.raises(ValueError):
        TokenMaskAutomaton(vocab=VOCAB)            # neither regex nor schema
    with pytest.raises(ValueError):
        TokenMaskAutomaton("a", json_schema={"type": "string"}, vocab=VOCAB)


# ------------------------------------------------------------ JSON schema
def test_json_schema_regex_shapes():
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "b": {"enum": ["x", "y"]},
                             "ok": {"type": "boolean"}}}
    aut = TokenMaskAutomaton(json_schema=schema, vocab=VOCAB,
                             eos_token_id=EOS)
    good = '{"a":-12,"b":"y","ok":true}'
    assert dfa_accepts(aut, good)
    assert json.loads(good) == {"a": -12, "b": "y", "ok": True}
    assert not dfa_accepts(aut, '{"b":"y","a":-12,"ok":true}')   # key order
    assert not dfa_accepts(aut, '{"a":1.5,"b":"x","ok":true}')   # not int
    assert not dfa_accepts(aut, '{"a":1,"b":"z","ok":true}')     # enum miss


def test_json_schema_standalone_leaves():
    aut = TokenMaskAutomaton(json_schema={"type": "number"}, vocab=VOCAB,
                             eos_token_id=EOS)
    assert dfa_accepts(aut, "-3.25") and dfa_accepts(aut, "7")
    assert not dfa_accepts(aut, "3.")
    with pytest.raises(ValueError):
        json_schema_regex({"type": "array"})


# -------------------------------------------------------------- engine
def _replay_legal(aut, tokens):
    """Every emitted token must be mask-legal from the replayed state."""
    sid = aut.start_state
    for t in tokens:
        assert aut.mask(sid)[int(t)], (t, VOCAB[int(t)])
        sid = aut.advance(sid, int(t))
    return sid


def _decode(tokens):
    return "".join(VOCAB[int(t)] for t in tokens if int(t) != EOS)


def test_engine_greedy_respects_grammar(model):
    p = np.arange(1, 6, dtype=np.int32)
    free = LLMEngine(model, eos_token_id=EOS, **ENG)
    rid = free.add_request(Request(p, max_new_tokens=6))
    unconstrained = free.run()[rid]

    aut = aut_for("[ab]{3}")
    eng = LLMEngine(model, eos_token_id=EOS, **ENG)
    rid = eng.add_request(Request(p, max_new_tokens=6, grammar=aut))
    out = eng.run()[rid]
    eng.assert_quiescent()
    sid = _replay_legal(aut, out)
    assert aut.accepting(sid)
    assert re.fullmatch("[ab]{3}", _decode(out))
    assert eng.requests[rid].finish_reason == "eos"  # exact counter: forced
    assert out != unconstrained                      # the mask actually bound


def test_engine_sampled_respects_grammar(model):
    p = np.arange(2, 8, dtype=np.int32)
    aut = aut_for("[ab]{8}")
    eng = LLMEngine(model, eos_token_id=EOS, **ENG)
    rids = [eng.add_request(Request(p, max_new_tokens=5, grammar=aut,
                                    temperature=1.0, top_p=0.9))
            for _ in range(3)]
    out = eng.run()
    eng.assert_quiescent()
    for rid in rids:
        _replay_legal(aut, out[rid])
        assert len(out[rid]) == 5                    # never accepting: no EOS


def test_engine_spec_decode_respects_grammar_and_matches_nonspec(model):
    """Spec decoding under a grammar: drafts violating the mask must be
    rejected before the accept law, so greedy output is token-for-token
    the non-spec grammar-constrained stream."""
    from paddle_tpu.serving.telemetry import _GRAMMAR_SPEC_REJECTS
    p = np.arange(3, 9, dtype=np.int32)
    aut = aut_for("(ab|ba){4}")
    plain = LLMEngine(model, eos_token_id=EOS, **ENG)
    r0 = plain.add_request(Request(p, max_new_tokens=6, grammar=aut))
    want = plain.run()[r0]

    before = _GRAMMAR_SPEC_REJECTS.value()
    eng = LLMEngine(model, draft_model=model, spec_k=4, eos_token_id=EOS,
                    **ENG)
    r1 = eng.add_request(Request(p, max_new_tokens=6, grammar=aut))
    got = eng.run()[r1]
    eng.assert_quiescent()
    assert got == want
    _replay_legal(aut, got)
    assert eng.stats["spec_ticks"] > 0
    assert _GRAMMAR_SPEC_REJECTS.value() >= before   # counter never regresses


def test_engine_mixed_grammar_and_free_rows(model):
    """A grammar row and free rows decode in the same ticks; the free
    rows are untouched by the neighbour's bias."""
    p = np.arange(1, 6, dtype=np.int32)
    free = LLMEngine(model, eos_token_id=EOS, **ENG)
    rf = free.add_request(Request(p, max_new_tokens=4))
    want_free = free.run()[rf]

    aut = aut_for("[ab]{8}")
    eng = LLMEngine(model, eos_token_id=EOS, **ENG)
    rg = eng.add_request(Request(p, max_new_tokens=4, grammar=aut))
    rf2 = eng.add_request(Request(p, max_new_tokens=4))
    out = eng.run()
    eng.assert_quiescent()
    assert out[rf2] == want_free
    _replay_legal(aut, out[rg])


def test_add_request_validates_grammar(model):
    eng = LLMEngine(model, **ENG)
    short = TokenMaskAutomaton("[ab]*", vocab=VOCAB[:32], eos_token_id=31)
    with pytest.raises(ValueError):
        eng.add_request(Request(np.arange(3), grammar=short))  # vocab size
    with pytest.raises(ValueError):
        eng.add_request(Request(np.arange(3), grammar=aut_for("[ab]*"),
                                num_beams=2))
