"""Multi-tenant batched LoRA serving (ISSUE 14).

* AdapterStore: strict registration, device-cache LRU eviction /
  hot-swap, pin exhaustion, pinned re-register refused
* null-adapter identity: an engine carrying an AdapterStore but serving
  only base requests is bit-exact with a storeless engine, and
  ``PT_MULTILORA=0`` forces the base path even for adapter requests
* mixed continuous batch: every request's stream equals a dedicated
  single-adapter engine's — heterogeneous adapters batched through the
  grouped ragged path change nothing per-tenant
* cross-tenant isolation: the radix prefix cache never matches across
  adapter identities, even for byte-identical prompts
* fair admission: a saturating tenant cannot starve a light tenant
* ``serving.adapter_swap`` chaos: exception-atomic at the store, and a
  deferred admission is retried (not dropped) by the scheduler
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import RadixPrefixBlockManager
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.serving.adapters import AdapterStore
from paddle_tpu.serving.telemetry import (_ADAPTER_DEFERRALS,
                                          _ADAPTER_EVICTIONS)
from paddle_tpu.utils.faults import FAULTS, InjectedFault

ENG = dict(num_slots=3, block_size=4, max_prompt_len=16, max_seq_len=24)


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def make_adapter(model, seed, r=4):
    """A visible (non-zero-B) adapter state_dict on qkv/o projections."""
    import jax
    from paddle_tpu.peft import lora_init, lora_state_dict
    tree = lora_init(model, jax.random.PRNGKey(seed), r=r, alpha=8,
                     target_modules=("qkv_proj", "o_proj"))
    sd = lora_state_dict(tree)
    rs = np.random.RandomState(seed)
    for k in list(sd):
        if k.endswith(".lora_B"):
            sd[k] = rs.randn(*np.shape(sd[k])).astype(np.float32) * 0.05
    return sd


@pytest.fixture(scope="module")
def store(model):
    s = AdapterStore(model, capacity=2, max_rank=4)
    s.register("t1", make_adapter(model, 1))
    s.register("t2", make_adapter(model, 2, r=2))   # heterogeneous rank
    return s


def _run_one(model, store, prompt, n, adapter_id=None):
    eng = LLMEngine(model, adapter_store=store, **ENG)
    rid = eng.add_request(Request(prompt, max_new_tokens=n,
                                  adapter_id=adapter_id))
    out = eng.run()[rid]
    eng.assert_quiescent()
    return out


# ------------------------------------------------------------ store unit
def test_store_register_strict_and_known(model, store):
    assert store.known("t1") and store.known("t2")
    assert not store.known("nope")
    with pytest.raises(ValueError):
        store.register(None, make_adapter(model, 3))
    sd = make_adapter(model, 3)
    sd.pop(next(k for k in sd if k.endswith(".lora_A")))
    with pytest.raises(ValueError, match="missing"):
        AdapterStore(model, capacity=2, max_rank=4).register("bad", sd)
    sd2 = make_adapter(model, 3)
    sd2["totally.bogus.lora_A"] = np.zeros((1, 1), np.float32)
    with pytest.raises(ValueError, match="unexpected"):
        AdapterStore(model, capacity=2, max_rank=4).register("bad", sd2)


def test_store_rank_over_max_refused(model):
    s = AdapterStore(model, capacity=2, max_rank=2)
    with pytest.raises(ValueError):
        s.register("fat", make_adapter(model, 1, r=4))


def test_store_lru_eviction_and_hot_swap(model):
    s = AdapterStore(model, capacity=2, max_rank=4)
    for i in (1, 2, 3):
        s.register(f"a{i}", make_adapter(model, i))
    i1, i2 = s.ensure("a1"), s.ensure("a2")
    assert {i1, i2} == {0, 1}
    before = _ADAPTER_EVICTIONS.value()
    s.ensure("a1")                       # touch: a2 becomes LRU
    i3 = s.ensure("a3")                  # evicts a2, reuses its slot
    assert i3 == i2
    assert _ADAPTER_EVICTIONS.value() == before + 1
    assert s.index_of("a1") == i1        # survivor untouched
    with pytest.raises(KeyError):
        s.index_of("a2")                 # evicted: not resident
    assert s.ensure("a2") == i1          # re-upload evicts the new LRU (a1)


def test_store_pins_block_eviction_and_reregister(model):
    s = AdapterStore(model, capacity=1, max_rank=4)
    s.register("a1", make_adapter(model, 1))
    s.register("a2", make_adapter(model, 2))
    s.acquire("a1")
    with pytest.raises(RuntimeError, match="exhausted"):
        s.acquire("a2")                  # sole slot pinned
    with pytest.raises(ValueError, match="pinned"):
        s.register("a1", make_adapter(model, 5))   # pinned: no re-register
    s.release("a1")
    assert s.acquire("a2") == 0          # hot-swap into the freed slot
    s.release("a2")
    s.assert_quiescent()


# ----------------------------------------------------- engine: identity
def test_null_adapter_and_kill_switch_identity(model, store, monkeypatch):
    p = np.arange(1, 6, dtype=np.int32)
    base_eng = LLMEngine(model, **ENG)
    rb = base_eng.add_request(Request(p, max_new_tokens=4))
    base = base_eng.run()[rb]
    # store attached, request base: bit-exact (lora arg never built)
    assert _run_one(model, store, p, 4) == base
    # kill switch: even an adapter request takes the base path
    monkeypatch.setenv("PT_MULTILORA", "0")
    assert _run_one(model, store, p, 4, adapter_id="t1") == base
    monkeypatch.delenv("PT_MULTILORA")
    # and with it off again, the adapter visibly changes the stream
    assert _run_one(model, store, p, 4, adapter_id="t1") != base


def test_mixed_batch_matches_dedicated_engines(model, store):
    """Base + two heterogeneous adapters in ONE continuous batch emit
    exactly what three dedicated engines emit (radix cache active)."""
    p = np.arange(1, 6, dtype=np.int32)
    eng = LLMEngine(model, adapter_store=store, **ENG)
    r0 = eng.add_request(Request(p, max_new_tokens=4))
    r1 = eng.add_request(Request(p, max_new_tokens=4, adapter_id="t1",
                                 tenant_id="a"))
    r2 = eng.add_request(Request(p, max_new_tokens=4, adapter_id="t2",
                                 tenant_id="b"))
    out = eng.run()
    eng.assert_quiescent()
    store.assert_quiescent()
    assert out[r0] == _run_one(model, None, p, 4)
    assert out[r1] == _run_one(model, store, p, 4, adapter_id="t1")
    assert out[r2] == _run_one(model, store, p, 4, adapter_id="t2")
    assert out[r1] != out[r0] and out[r2] != out[r0]
    assert out[r1] != out[r2]


# ------------------------------------------------- cross-tenant isolation
def test_radix_never_matches_across_adapters():
    mgr = RadixPrefixBlockManager(num_blocks=8, block_size=4)
    toks = np.arange(10, dtype=np.int32)
    mgr.allocate(1, 10)
    mgr.commit_prefix(1, toks, adapter="t1")
    assert mgr.match_prefix(toks, adapter="t1").token_count > 0
    assert mgr.match_prefix(toks, adapter="t2").token_count == 0
    assert mgr.match_prefix(toks).token_count == 0          # base trie
    mgr.free(1)


def test_same_prompt_sequential_tenants_no_contamination(model, store):
    """Byte-identical prompts under different adapters, served one after
    another through the SAME engine (t1's blocks are parked in the radix
    cache when t2 arrives) — each stream still equals its dedicated
    engine, and the base request is untouched by either."""
    p = np.arange(2, 9, dtype=np.int32)
    eng = LLMEngine(model, adapter_store=store, **ENG)
    outs = {}
    for aid in ("t1", "t2", None, "t1"):
        rid = eng.add_request(Request(p, max_new_tokens=4, adapter_id=aid))
        outs[(aid, rid)] = eng.run()[rid]
    eng.assert_quiescent()
    for (aid, _), got in outs.items():
        assert got == _run_one(model, store, p, 4, adapter_id=aid), aid


# --------------------------------------------------------- fair admission
def test_fair_admission_light_tenant_not_starved(model):
    """One slot, four queued requests from a saturating tenant plus one
    from a light tenant enqueued LAST. Deficit-weighted admission serves
    the light tenant well before the heavy backlog drains (pure FCFS
    would serve it dead last)."""
    order = []

    def track(req, tok):
        if len(req.tokens) == 1:
            order.append(req.tenant_id)

    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    for i in range(4):
        eng.add_request(Request(np.arange(1 + i, 6 + i, dtype=np.int32),
                                max_new_tokens=3, tenant_id="heavy",
                                stream=track))
    eng.add_request(Request(np.arange(9, 14, dtype=np.int32),
                            max_new_tokens=3, tenant_id="light",
                            stream=track))
    eng.run()
    eng.assert_quiescent()
    assert len(order) == 5
    assert order.index("light") <= 2, order    # FCFS would put it at 4
    assert order[-1] == "heavy"


def test_tenant_weight_validation(model):
    eng = LLMEngine(model, **ENG)
    eng.sched.set_tenant_weight("gold", 4.0)
    assert eng.sched.tenant_weights["gold"] == 4.0
    with pytest.raises(ValueError):
        eng.sched.set_tenant_weight("bad", 0.0)


# ------------------------------------------------------------------ chaos
def test_adapter_swap_fault_is_exception_atomic(model):
    s = AdapterStore(model, capacity=2, max_rank=4)
    s.register("a1", make_adapter(model, 1))
    with FAULTS.scope("serving.adapter_swap", exc=InjectedFault):
        with pytest.raises(InjectedFault):
            s.ensure("a1")
        assert "a1" not in s._resident   # host copy stays, no residency
        assert len(s._free) == 2         # no slot leaked
    idx = s.ensure("a1")                 # clean retry succeeds
    assert idx in (0, 1)
    s.assert_quiescent()


def test_adapter_swap_fault_defers_admission_then_retries(model):
    """A one-shot upload fault makes the scheduler defer the admission;
    the next tick retries and the request completes with the exact
    no-fault stream (nothing dropped, nothing leaked)."""
    p = np.arange(3, 10, dtype=np.int32)
    s = AdapterStore(model, capacity=2, max_rank=4)
    s.register("t1", make_adapter(model, 1))
    want = _run_one(model, s, p, 4, adapter_id="t1")

    s2 = AdapterStore(model, capacity=2, max_rank=4)
    s2.register("t1", make_adapter(model, 1))
    eng = LLMEngine(model, adapter_store=s2, **ENG)
    before = _ADAPTER_DEFERRALS.value()
    with FAULTS.scope("serving.adapter_swap", exc=InjectedFault, on={0}):
        rid = eng.add_request(Request(p, max_new_tokens=4,
                                      adapter_id="t1"))
        out = eng.run()
    assert out[rid] == want
    assert _ADAPTER_DEFERRALS.value() == before + 1
    eng.assert_quiescent()
    s2.assert_quiescent()


# -------------------------------------------------------------- intake
def test_add_request_validates_adapter(model, store):
    p = np.arange(1, 5, dtype=np.int32)
    eng = LLMEngine(model, adapter_store=store, **ENG)
    with pytest.raises(ValueError):
        eng.add_request(Request(p, adapter_id="unregistered"))
    no_store = LLMEngine(model, **ENG)
    with pytest.raises(ValueError):
        no_store.add_request(Request(p, adapter_id="t1"))
