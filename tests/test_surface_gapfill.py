"""Tests for the round-1 gap-fill surface: pooling masks/unpool, full
Transformer, RNN/BiRNN cell drivers, gather_tree, Viterbi decode,
nan-reductions, as_strided, folder/text datasets."""
import io
import os
import tarfile
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


# -- pooling with mask + unpool ---------------------------------------------

def test_max_pool2d_return_mask_matches_torch():
    import torch
    x = np.random.RandomState(0).randn(2, 3, 8, 10).astype(np.float32)
    out, mask = F.max_pool2d(jnp.asarray(x), 2, stride=2, return_mask=True)
    t_out, t_idx = torch.nn.functional.max_pool2d(
        torch.tensor(x), 2, stride=2, return_indices=True)
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(mask), t_idx.numpy())


def test_max_pool2d_return_mask_padded():
    import torch
    x = np.random.RandomState(1).randn(1, 2, 7, 7).astype(np.float32)
    out, mask = F.max_pool2d(jnp.asarray(x), 3, stride=2, padding=1,
                             return_mask=True)
    t_out, t_idx = torch.nn.functional.max_pool2d(
        torch.tensor(x), 3, stride=2, padding=1, return_indices=True)
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(mask), t_idx.numpy())


def test_max_unpool2d_roundtrip():
    import torch
    x = np.random.RandomState(2).randn(2, 2, 6, 6).astype(np.float32)
    out, mask = F.max_pool2d(jnp.asarray(x), 2, return_mask=True)
    up = F.max_unpool2d(out, mask, 2)
    t_out, t_idx = torch.nn.functional.max_pool2d(
        torch.tensor(x), 2, return_indices=True)
    t_up = torch.nn.functional.max_unpool2d(t_out, t_idx, 2)
    np.testing.assert_allclose(np.asarray(up), t_up.numpy(), rtol=1e-6)


def test_max_unpool1d_and_layers():
    x = jnp.asarray(np.random.RandomState(3).randn(2, 3, 8).astype(np.float32))
    pool = nn.MaxPool1D(2, return_mask=True)
    out, mask = pool(x)
    up = nn.MaxUnPool1D(2)(out, mask)
    assert up.shape == x.shape
    # every kept value appears at its original position
    np.testing.assert_allclose(np.asarray(up).max(-1), np.asarray(out).max(-1))


def test_max_pool3d_and_unpool3d():
    x = jnp.asarray(np.random.RandomState(4).randn(1, 2, 4, 4, 4).astype(np.float32))
    out, mask = F.max_pool3d(x, 2, return_mask=True)
    assert out.shape == (1, 2, 2, 2, 2)
    up = F.max_unpool3d(out, mask, 2)
    assert up.shape == x.shape
    np.testing.assert_allclose(np.asarray(up).sum(), np.asarray(out).sum(), rtol=1e-5)


# -- transformer / rnn -------------------------------------------------------

def test_full_transformer_forward():
    m = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                       num_decoder_layers=2, dim_feedforward=32)
    m.eval()
    src = jnp.asarray(np.random.RandomState(0).randn(2, 5, 16), jnp.float32)
    tgt = jnp.asarray(np.random.RandomState(1).randn(2, 4, 16), jnp.float32)
    out = m(src, tgt)
    assert out.shape == (2, 4, 16)
    mask = nn.Transformer.generate_square_subsequent_mask(4)
    assert mask.shape == (4, 4) and np.isneginf(np.asarray(mask)[0, 1])
    out2 = m(src, tgt, tgt_mask=mask)
    assert out2.shape == (2, 4, 16)


def test_rnn_wrapper_matches_manual_scan():
    cell = nn.LSTMCell(4, 6)
    x = jnp.asarray(np.random.RandomState(0).randn(3, 5, 4), jnp.float32)
    out, (h, c) = nn.RNN(cell)(x)
    assert out.shape == (3, 5, 6) and h.shape == (3, 6)
    # manual unroll
    hh = jnp.zeros((3, 6)); cc = jnp.zeros((3, 6))
    for t in range(5):
        o, (hh, cc) = cell(x[:, t], (hh, cc))
    np.testing.assert_allclose(np.asarray(out[:, -1]), np.asarray(o), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(hh), rtol=1e-5)


def test_birnn_concat_shapes():
    fw, bw = nn.GRUCell(4, 5), nn.GRUCell(4, 5)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 7, 4), jnp.float32)
    out, (hf, hb) = nn.BiRNN(fw, bw)(x)
    assert out.shape == (2, 7, 10)
    # reverse branch equals running the reversed sequence forward
    out_r, hr = nn.RNN(bw)(x[:, ::-1])
    np.testing.assert_allclose(np.asarray(out[:, :, 5:]),
                               np.asarray(out_r[:, ::-1]), rtol=1e-5)


# -- beam utils / viterbi ----------------------------------------------------

def test_gather_tree():
    ids = jnp.asarray([[[2, 5]], [[6, 1]], [[3, 9]]])       # [T=3, B=1, beam=2]
    parents = jnp.asarray([[[0, 0]], [[1, 0]], [[0, 1]]])
    out = np.asarray(F.gather_tree(ids, parents))
    # beam 0 at t=2 came from parent 0 (t=1) which came from parent 1 (t=0)
    assert out[:, 0, 0].tolist() == [5, 6, 3]
    assert out[:, 0, 1].tolist() == [2, 1, 9]


def _brute_viterbi(pot, trans, length, bos_eos):
    import itertools
    n = pot.shape[-1]
    best, path = -np.inf, None
    for seq in itertools.product(range(n), repeat=length):
        s = pot[0, seq[0]] + (trans[-1, seq[0]] if bos_eos else 0)
        for t in range(1, length):
            s += trans[seq[t - 1], seq[t]] + pot[t, seq[t]]
        if bos_eos:
            s += trans[seq[length - 1], -2]
        if s > best:
            best, path = s, seq
    return best, list(path)


@pytest.mark.parametrize("bos_eos", [True, False])
def test_viterbi_decode_matches_bruteforce(bos_eos):
    from paddle_tpu.text import viterbi_decode
    rs = np.random.RandomState(0)
    pot = rs.randn(2, 4, 3).astype(np.float32)
    trans = rs.randn(3, 3).astype(np.float32)
    lengths = np.array([4, 2])
    scores, paths = viterbi_decode(pot, trans, lengths, bos_eos)
    for b in range(2):
        s, p = _brute_viterbi(pot[b], trans, int(lengths[b]), bos_eos)
        assert abs(float(scores[b]) - s) < 1e-4
        assert np.asarray(paths)[b, :lengths[b]].tolist() == p
        assert np.all(np.asarray(paths)[b, lengths[b]:] == 0)


def test_viterbi_decoder_layer():
    from paddle_tpu.text import ViterbiDecoder
    dec = ViterbiDecoder(np.eye(3, dtype=np.float32))
    scores, paths = dec(np.zeros((1, 3, 3), np.float32), np.array([3]))
    assert paths.shape == (1, 3)


# -- tensor gap-fill ---------------------------------------------------------

def test_nanmedian_nanquantile():
    x = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 7.0]], np.float32)
    np.testing.assert_allclose(np.asarray(pt.nanmedian(jnp.asarray(x))),
                               np.nanmedian(x))
    np.testing.assert_allclose(
        np.asarray(pt.nanquantile(jnp.asarray(x), 0.5, axis=1)),
        np.nanquantile(x, 0.5, axis=1))


def test_as_strided():
    x = jnp.arange(12.0)
    out = pt.as_strided(x, [3, 4], [4, 1])
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(12.0).reshape(3, 4))
    # overlapping windows
    win = pt.as_strided(x, [5, 3], [2, 1])
    expect = np.lib.stride_tricks.as_strided(
        np.arange(12.0), (5, 3), (16, 8))
    np.testing.assert_array_equal(np.asarray(win), expect)


# -- datasets ----------------------------------------------------------------

def test_dataset_folder_and_image_folder(tmp_path):
    from PIL import Image
    for cls in ["cat", "dog"]:
        d = tmp_path / "root" / cls
        d.mkdir(parents=True)
        for i in range(2):
            Image.fromarray(
                np.full((4, 4, 3), 100 + i, np.uint8)).save(d / f"{i}.png")
    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder
    ds = DatasetFolder(str(tmp_path / "root"))
    assert len(ds) == 4 and ds.classes == ["cat", "dog"]
    img, label = ds[0]
    assert img.shape == (4, 4, 3) and label == 0
    ifo = ImageFolder(str(tmp_path / "root"))
    assert len(ifo) == 4 and ifo[0][0].shape == (4, 4, 3)


def test_uci_housing(tmp_path):
    rs = np.random.RandomState(0)
    data = rs.rand(50, 14)
    path = tmp_path / "housing.data"
    np.savetxt(path, data)
    from paddle_tpu.text.datasets import UCIHousing
    tr = UCIHousing(str(path), mode="train")
    te = UCIHousing(str(path), mode="test")
    assert len(tr) == 40 and len(te) == 10
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_imdb(tmp_path):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for split in ["train", "test"]:
            for sent, docs in [("pos", ["a great movie", "great fun film"]),
                               ("neg", ["terrible boring movie", "awful bad"])]:
                for i, text in enumerate(docs):
                    data = text.encode()
                    info = tarfile.TarInfo(f"aclImdb/{split}/{sent}/{i}.txt")
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
    p = tmp_path / "aclImdb_v1.tar.gz"
    p.write_bytes(buf.getvalue())
    from paddle_tpu.text.datasets import Imdb
    ds = Imdb(str(p), mode="train", cutoff=1)
    assert len(ds) == 4
    doc, label = ds[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    assert "great" in ds.word_idx


def test_imikolov(tmp_path):
    buf = io.BytesIO()
    text = "\n".join(["the quick fox", "the lazy dog", "the quick dog"])
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name in ["ptb.train.txt", "ptb.valid.txt"]:
            data = text.encode()
            info = tarfile.TarInfo(f"./simple-examples/data/{name}")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    p = tmp_path / "simple-examples.tgz"
    p.write_bytes(buf.getvalue())
    from paddle_tpu.text.datasets import Imikolov
    ds = Imikolov(str(p), data_type="NGRAM", window_size=3, mode="train",
                  min_word_freq=1)
    assert len(ds) > 0 and ds[0].shape == (3,)
    seq = Imikolov(str(p), data_type="SEQ", mode="test", min_word_freq=1)
    assert seq[0][0] == seq.word_idx["<s>"]


def test_movielens(tmp_path):
    p = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("ml-1m/users.dat", "1::M::25::4::90210\n2::F::35::7::10001\n")
        zf.writestr("ml-1m/movies.dat",
                    "10::Toy Story (1995)::Animation|Comedy\n"
                    "20::Heat (1995)::Action\n")
        zf.writestr("ml-1m/ratings.dat",
                    "1::10::5::978300760\n2::20::3::978300761\n"
                    "1::20::4::978300762\n")
    from paddle_tpu.text.datasets import Movielens
    ds = Movielens(str(p), mode="train", test_ratio=0.0)
    assert len(ds) == 3
    item = ds[0]
    assert item[-1] in (3.0, 4.0, 5.0)


def test_wmt16(tmp_path):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, lines in [("wmt16/train.en", "a b c\nd e f\n"),
                            ("wmt16/train.de", "x y\nz w\n")]:
            data = lines.encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    p = tmp_path / "wmt16.tar.gz"
    p.write_bytes(buf.getvalue())
    from paddle_tpu.text.datasets import WMT16
    ds = WMT16(str(p), mode="train")
    assert len(ds) == 2
    src, trg_in, trg_out = ds[0]
    assert trg_in[0] == 0 and trg_out[-1] == 1  # <s> prefix / <e> suffix


def test_conll05st(tmp_path):
    import gzip as _gz
    words = "The\ncat\nsat\n\nDogs\nbark\n"
    props = "-\t*\nsit\t(V*)\n-\t*\n\nbark\t(V*)\n-\t*\n"
    props = props.replace("\t", " ")
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, text in [("conll05st/test.wsj.words.gz", words),
                           ("conll05st/test.wsj.props.gz", props)]:
            data = _gz.compress(text.encode())
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    p = tmp_path / "conll05st.tar.gz"
    p.write_bytes(buf.getvalue())
    from paddle_tpu.text.datasets import Conll05st
    ds = Conll05st(str(p))
    assert len(ds) == 2
    wids, pred, lids = ds[0]
    assert wids.shape == (3,) and lids.shape == (3,)


def test_conll05_bio_nested_brackets():
    from paddle_tpu.text.datasets import Conll05st
    # token opening two spans: B- names the innermost, ')' pops one level
    assert Conll05st._bio(['(A1(V*)', '*', '*)']) == ['B-V', 'I-A1', 'I-A1']
    assert Conll05st._bio(['(A0*)', '(V*)', '(A1*', '*)']) == \
        ['B-A0', 'B-V', 'B-A1', 'I-A1']


# -- top-level alias gap-fill (round-1 audit) --------------------------------

def test_toplevel_alias_ops():
    import jax.numpy as jnp
    import torch
    # unfold matches torch Tensor.unfold
    x = np.arange(20.0).reshape(4, 5).astype(np.float32)
    got = np.asarray(pt.unfold(jnp.asarray(x), 1, 2, 2))
    want = torch.tensor(x).unfold(1, 2, 2).numpy()
    np.testing.assert_allclose(got, want)
    # unflatten with inferred dim
    assert pt.unflatten(jnp.arange(24.0).reshape(2, 12), 1, (3, -1)).shape == (2, 3, 4)
    # crop / scatter_nd / shard_index
    c = pt.crop(jnp.arange(25.0).reshape(5, 5), shape=[2, 2], offsets=[1, 2])
    np.testing.assert_allclose(np.asarray(c), [[7.0, 8.0], [12.0, 13.0]])
    s = pt.scatter_nd(jnp.asarray([[1], [1], [3]]), jnp.asarray([1.0, 2.0, 3.0]), [5])
    np.testing.assert_allclose(np.asarray(s), [0, 3, 0, 3, 0])
    si = pt.shard_index(jnp.asarray([0, 5, 9, 12]), 16, 2, 1)
    assert np.asarray(si).tolist() == [-1, -1, 1, 4]
    # multiplex picks rows by index
    a = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    b = jnp.asarray([[5.0, 6.0], [7.0, 8.0]])
    out = pt.multiplex([a, b], jnp.asarray([[1], [0]]))
    np.testing.assert_allclose(np.asarray(out), [[5.0, 6.0], [3.0, 4.0]])
    # sgn on complex = unit phase
    z = pt.sgn(jnp.asarray([3 + 4j, 0j]))
    np.testing.assert_allclose(np.asarray(z), [0.6 + 0.8j, 0], atol=1e-7)
    # misc predicates / aliases
    assert pt.is_tensor(jnp.zeros(2)) and not pt.is_tensor([1])
    assert pt.is_floating_point(jnp.zeros(2))
    assert pt.is_integer(jnp.zeros(2, jnp.int32))
    assert pt.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert pt.tolist(jnp.asarray([1, 2])) == [1, 2]
    assert int(pt.rank(jnp.zeros((2, 3)))) == 2
    np.testing.assert_allclose(np.asarray(pt.logspace(0, 2, 3)), [1, 10, 100])
    np.testing.assert_allclose(
        np.asarray(pt.add_n([jnp.ones(2), jnp.ones(2), jnp.ones(2)])), [3.0, 3.0])
    assert pt.tril_indices(3).shape[0] == 2 and pt.triu_indices(3).shape[0] == 2


def test_incubate_fused_ops_and_fleet_sparse_parity():
    import paddle_tpu.incubate.nn as inn
    import paddle_tpu.sparse as sp
    from paddle_tpu.distributed import fleet
    for n in ["swiglu", "fused_bias_dropout_residual_layer_norm",
              "fused_multi_head_attention", "fused_feedforward",
              "masked_multihead_attention"]:
        assert hasattr(inn.functional, n), n
    assert fleet.distributed_optimizer("opt") == "opt"  # parity passthrough
    assert hasattr(fleet.utils, "recompute")
    x = sp.sparse_coo_tensor(jnp.asarray([[0, 1], [1, 0]]),
                             jnp.asarray([-1.0, 2.0]), (2, 2))
    assert sp.is_same_shape(x, x)
    y = sp.nn.ReLU()(x)
    np.testing.assert_allclose(np.asarray(y.todense()),
                               [[0.0, 0.0], [2.0, 0.0]])


def test_fused_mha_matches_unfused():
    import paddle_tpu.incubate.nn as inn
    from paddle_tpu.ops.attention import xla_attention
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 4, 8).astype(np.float32))
    w_qkv = jnp.asarray(rs.randn(8, 24).astype(np.float32)) * 0.1
    w_out = jnp.asarray(rs.randn(8, 8).astype(np.float32)) * 0.1
    got = inn.functional.fused_multi_head_attention(
        x, w_qkv, None, w_out, None, num_heads=2, causal=True)
    qkv = x @ w_qkv
    q, k, v = jnp.split(qkv, 3, axis=-1)
    ref = xla_attention(q.reshape(2, 4, 2, 4), k.reshape(2, 4, 2, 4),
                        v.reshape(2, 4, 2, 4), is_causal=True)
    # reference block: residual add then post-LN (default affine)
    core = ref.reshape(2, 4, 8) @ w_out
    ref_out = F.layer_norm(core + x, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-6)
    # add_residual=False drops the residual (LN still applies)
    got2 = inn.functional.fused_multi_head_attention(
        x, w_qkv, None, w_out, None, num_heads=2, causal=True,
        add_residual=False)
    np.testing.assert_allclose(np.asarray(got2),
                               np.asarray(F.layer_norm(core, 8)),
                               rtol=1e-5, atol=1e-6)


def test_final_tensor_audit_ops():
    import torch
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    y = np.full(4, 9.0, np.float32)
    np.testing.assert_allclose(
        np.asarray(pt.diagonal_scatter(jnp.asarray(x), jnp.asarray(y))),
        torch.diagonal_scatter(torch.tensor(x), torch.tensor(y)).numpy())
    t = torch.tensor(x.copy()); t.fill_diagonal_(7.0)
    np.testing.assert_allclose(
        np.asarray(pt.fill_diagonal(jnp.asarray(x), 7.0)), t.numpy())
    a = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(
        np.asarray(pt.block_diag(a, a)),
        torch.block_diag(torch.tensor(np.asarray(a)),
                         torch.tensor(np.asarray(a))).numpy())
    ip = pt.index_put(jnp.zeros((3, 3)),
                      (jnp.asarray([0, 1]), jnp.asarray([1, 2])), 5.0)
    assert float(ip[0, 1]) == 5.0 and float(ip[1, 2]) == 5.0
    assert pt.view(a, [3, 2]).shape == (3, 2)
    assert pt.view_as(a, jnp.zeros((6,))).shape == (6,)
    assert pt.column_stack([jnp.ones(3), jnp.zeros(3)]).shape == (3, 2)
    assert pt.row_stack([jnp.ones(3), jnp.zeros(3)]).shape == (2, 3)
    h, e = pt.histogramdd(jnp.asarray(np.random.rand(20, 2)), bins=4)
    assert h.shape == (4, 4) and len(e) == 2
    np.testing.assert_allclose(
        np.asarray(pt.take_along_dim(a, jnp.asarray([[0], [2]]), 1)),
        np.take_along_axis(np.asarray(a), np.array([[0], [2]]), 1))
