"""Request-scoped tracing + goodput accounting (ISSUE 9): the tracker
ring bound, disabled-is-a-no-op, cross-replica timeline stitching over a
disaggregated 2-replica run (flow events + greedy identity + fleet
quiescence), goodput arithmetic under spec-reject / preemption-replay /
chaos-abort, the ``/requests`` endpoint, the flight-recorder excerpt,
and the generated metrics reference."""
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import (FLIGHT, GOODPUT, METRICS,
                                      MetricsServer, REQUESTS, TRACER)
from paddle_tpu.observability.requests import RequestTracker
from paddle_tpu.serving import LLMEngine, Replica, Request, Router
from paddle_tpu.utils.faults import FAULTS, InjectedFault

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module", autouse=True)
def _preserve_global_rng():
    from paddle_tpu.core import random as _prng
    saved = None if _prng._global is None else _prng._global.key
    yield
    if saved is None:
        _prng._global = None
    else:
        _prng.seed(0)
        _prng._global.key = saved


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=4, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14):
    return [rs.randint(0, 64, (int(l),)) for l in rs.randint(lo, hi, size=n)]


def _tokens_total():
    inst = METRICS.get("serving_tokens_total")
    return float(sum(cell[0] for cell in inst._series.values())) \
        if inst is not None else 0.0


# ----------------------------------------------------------- ring bound

def test_ring_bound_evicts_oldest():
    """The tracker keeps at most ``capacity`` timelines; the oldest is
    evicted (and counted) when the ring wraps."""
    trk = RequestTracker(capacity=4)
    trk.enable()
    reqs = [Request([1, 2, 3], req_id=i) for i in range(10)]
    for r in reqs:
        trk.submit(r)
    assert len(trk) == 4
    assert trk.evicted == 6
    # newest four survive, oldest six are gone
    assert trk.timeline(reqs[0].trace_id) is None
    assert trk.timeline(reqs[9].trace_id) is not None
    doc = trk.to_doc()
    assert doc["tracked"] == 4 and doc["evicted"] == 6


def test_event_cap_counts_drops():
    trk = RequestTracker(capacity=2, event_cap=5)
    trk.enable()
    req = Request([1, 2], req_id=0)
    trk.submit(req)
    for i in range(20):
        trk.event(req, "prefill_chunk", offset=i)
    line_doc = trk.timeline(req.trace_id)
    assert len(line_doc["events"]) == 5          # submitted + 4 appends
    assert line_doc["dropped_events"] == 16


def test_disabled_tracker_is_noop(model):
    """Tracking off (the default): no trace ids are minted, nothing is
    recorded, and request objects stay untouched."""
    assert not REQUESTS.enabled
    eng = _mk(model)
    rid = eng.add_request(Request([1, 2, 3], max_new_tokens=3))
    eng.run()
    req = eng.requests[rid]
    assert req.trace_id is None and req.trace_summary is None
    assert len(REQUESTS) == 0
    assert REQUESTS.to_doc()["requests"] == []


# --------------------------------------- single-engine greedy identity

def test_tracking_enabled_leaves_greedy_output_unchanged(model):
    """Measured no-op: the same prompts produce token-identical output
    with tracking off and on, and the tracked run's summaries agree
    with the finished requests."""
    rs = np.random.RandomState(0)
    prompts = _prompts(5, rs)
    eng = _mk(model)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    ref = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    eng.assert_quiescent()

    # the reference run counted tokens too (goodput is ungated by the
    # tracker) — zero the registry so the traced run reconciles alone
    METRICS.reset()
    REQUESTS.enable()
    eng2 = _mk(model)
    for p in prompts:
        eng2.add_request(Request(p, max_new_tokens=8))
    out = {rid: list(map(int, t)) for rid, t in eng2.run().items()}
    assert out == ref
    eng2.assert_quiescent()
    for rid, req in eng2.requests.items():
        s = req.trace_summary
        assert s is not None and s["ok"] and s["finish_reason"] in (
            "eos", "length")
        assert s["tokens"] == len(req.tokens)
        assert s["ttft_s"] >= s["breakdown"]["queue_s"] >= 0.0
        # colocated serving: no handoff legs in the breakdown
        assert s["breakdown"]["handoff_s"] == 0.0
        assert s["breakdown"]["first_decode_s"] == 0.0
    # goodput reconciles with the token counter (no waste sources here)
    assert GOODPUT.good_total() == _tokens_total() == \
        sum(len(r.tokens) for r in eng2.requests.values())


# --------------------------------------------- disaggregated stitching

def test_disagg_two_replicas_stitched_timelines(model):
    """The acceptance run: 2-replica disaggregated serving exports one
    stitched timeline per request crossing BOTH replicas, the Chrome
    trace carries s→t→f flow arrows over named replica tracks, the
    goodput ledger reconciles with serving_tokens_total, and /requests
    serves exactly the summary each finished request carries."""
    rs = np.random.RandomState(1)
    prompts = _prompts(5, rs) + [rs.randint(0, 64, (19,))]
    eng = _mk(model, max_prompt_len=8)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    ref = {rid: list(map(int, t)) for rid, t in eng.run().items()}

    METRICS.reset()          # drop the reference run's token counts
    REQUESTS.enable()
    TRACER.enable()
    r = Router([Replica(_mk(model, max_prompt_len=8), role="prefill"),
                Replica(_mk(model, max_prompt_len=8), role="decode")])
    for p in prompts:
        r.add_request(Request(p, max_new_tokens=8))
    out = {rid: list(map(int, t)) for rid, t in r.run().items()}
    assert out == ref                       # zero change to greedy output
    r.assert_quiescent()

    # one timeline per request, each crossing both replicas
    doc = REQUESTS.to_doc()
    assert doc["tracked"] == len(prompts)
    for rid, req in r.requests.items():
        s = req.trace_summary
        assert s is not None and s["ok"]
        assert s["replicas"] == ["r0", "r1"]
        line = REQUESTS.timeline(req.trace_id)
        kinds = [e["kind"] for e in line["events"]]
        for k in ("submitted", "dispatched", "admitted", "first_token",
                  "kv_extract", "kv_ship", "kv_install", "decode_resume",
                  "finished"):
            assert k in kinds, (k, kinds)
        # handoff/first-decode legs are measured, not zeroed
        assert s["breakdown"]["handoff_s"] >= 0.0
        assert s["total_s"] >= s["ttft_s"] >= 0.0
        # /requests serves the summary the finish result carries
        match = [q for q in doc["requests"]
                 if q["trace_id"] == req.trace_summary["trace_id"]]
        assert match == [req.trace_summary]

    # flow stitching: every request's arrow is s → t(s) → f on the named
    # replica tracks
    trace = TRACER.export()["traceEvents"]
    flows = [e for e in trace if e.get("cat") == "flow"]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    track_tids = {e["tid"]: e["args"]["name"] for e in trace
                  if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert set(track_tids.values()) >= {"r0", "r1"}
    summaries = {req.trace_summary["trace_id"] for req in
                 r.requests.values()}
    assert set(by_id) == summaries
    for fid, evs in by_id.items():
        phases = [e["ph"] for e in evs]
        assert phases[0] == "s" and phases[-1] == "f"
        assert all(p == "t" for p in phases[1:-1])
        assert evs[-1]["bp"] == "e"
        # the arrow visits both replica tracks
        assert {track_tids[e["tid"]] for e in evs} == {"r0", "r1"}

    # goodput reconciles with the token counter across the fleet
    assert GOODPUT.good_total() == _tokens_total() == \
        sum(len(r_.tokens) for r_ in r.requests.values())


# -------------------------------------------------- goodput arithmetic

def test_goodput_spec_reject_arithmetic(model, draft):
    """Speculative serving: waste{spec_rejected} == proposed - accepted,
    pad_rows counts the verify batch's sentinel rows, and goodput still
    equals serving_tokens_total."""
    REQUESTS.enable()
    rs = np.random.RandomState(2)
    prompts = _prompts(3, rs)
    eng = _mk(model, draft_model=draft, spec_k=3)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    eng.run()
    eng.assert_quiescent()
    assert eng.stats["spec_proposed"] > 0
    waste = GOODPUT.waste_by_why()
    assert waste["spec_rejected"] == (eng.stats["spec_proposed"]
                                      - eng.stats["spec_accepted"])
    assert GOODPUT.good_total() == _tokens_total()
    assert 0.0 < GOODPUT.ratio() <= 1.0
    # per-request spec counters roll up to the engine totals
    sp = sum(r.trace_summary["spec_proposed"]
             for r in eng.requests.values())
    sa = sum(r.trace_summary["spec_accepted"]
             for r in eng.requests.values())
    assert (sp, sa) == (eng.stats["spec_proposed"],
                        eng.stats["spec_accepted"])


def test_goodput_replay_prefill_on_preemption(model):
    """Chaos-induced preemption: the replayed re-prefill tokens land in
    waste{replay_prefill}, the timeline records preempted/replayed, and
    goodput still reconciles with the token counter."""
    REQUESTS.enable()
    rs = np.random.RandomState(3)
    prompts = _prompts(4, rs, lo=4, hi=12)
    FAULTS.install("serving.preempt", every=5, times=4,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = _mk(model, num_slots=2, preemption=True)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    eng.run()
    eng.assert_quiescent()
    assert eng.stats["preemptions"] > 0
    waste = GOODPUT.waste_by_why()
    assert waste.get("replay_prefill", 0) > 0
    assert GOODPUT.good_total() == _tokens_total()
    preempted = [r for r in eng.requests.values()
                 if r.trace_summary["preemptions"] > 0]
    assert preempted
    for req in preempted:
        kinds = [e["kind"]
                 for e in REQUESTS.timeline(req.trace_id)["events"]]
        assert "preempted" in kinds and "replayed" in kinds


def test_goodput_chaos_abort_counts_drafted_tokens(model, draft):
    """An injected spec-verify fault burns that round's drafted tokens:
    they land in waste{chaos_abort} and the engine still finishes with
    exact greedy output (covered elsewhere) and a reconciled ledger."""
    REQUESTS.enable()
    rs = np.random.RandomState(4)
    prompts = _prompts(3, rs)
    FAULTS.install("serving.spec_verify", on={1, 3}, exc=InjectedFault)
    eng = _mk(model, draft_model=draft, spec_k=3)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    eng.run()
    eng.assert_quiescent()
    assert eng.stats["spec_fallbacks"] == 2
    waste = GOODPUT.waste_by_why()
    assert waste.get("chaos_abort", 0) > 0
    assert GOODPUT.good_total() == _tokens_total()
    # the health rule reads the same ledger: with mostly-good traffic the
    # stock serving_waste_ratio rule stays below CRIT
    from paddle_tpu.observability.health import HEALTH
    rule = [x for x in HEALTH.evaluate()["rules"]
            if x["name"] == "serving_waste_ratio"]
    assert rule and rule[0]["status"] in ("OK", "WARN")


# ------------------------------------------------- endpoint + artifacts

def test_requests_endpoint_serves_tracker_doc(model):
    REQUESTS.enable()
    eng = _mk(model)
    rid = eng.add_request(Request([5, 6, 7], max_new_tokens=4))
    eng.run()
    req = eng.requests[rid]
    srv = MetricsServer(port=0, host="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{srv.port}/requests"
        with urllib.request.urlopen(url, timeout=5) as resp:
            doc = json.loads(resp.read().decode())
    finally:
        srv.stop()
    assert doc["enabled"] is True
    assert doc["tracked"] == 1 and doc["evicted"] == 0
    # the endpoint serves the same summary the finish result carries
    # (json round-trip normalises tuples to lists; summaries are built
    # JSON-safe so equality holds exactly)
    assert doc["requests"] == [req.trace_summary]
    assert doc["timelines"][0]["summary"] == req.trace_summary


def test_flight_dump_embeds_slowest_and_failed(model, tmp_path):
    REQUESTS.enable()
    eng = _mk(model)
    ok_rid = eng.add_request(Request([1, 2, 3], max_new_tokens=4))
    eng.run()
    bad_rid = eng.add_request(Request([4, 5, 6], max_new_tokens=4))
    eng.cancel(bad_rid)
    path = FLIGHT.dump(reason="test", directory=str(tmp_path))
    doc = json.loads(open(path).read())
    assert "requests" in doc
    failed = doc["requests"]["failed"]
    assert [l["summary"]["finish_reason"] for l in failed] == ["cancelled"]
    slow = doc["requests"]["slowest"]
    assert {l["req_id"] for l in slow} == {ok_rid, bad_rid}


def test_metrics_reference_lists_every_instrument():
    """``python -m paddle_tpu.observability`` renders the registry —
    every instrument name present, nothing failed to import."""
    from paddle_tpu.observability.__main__ import metrics_reference
    text = metrics_reference()
    assert "## import failures" not in text
    for name in ("serving_goodput_tokens_total", "serving_waste_total",
                 "serving_goodput_ratio", "router_requeues_total",
                 "serving_tokens_total", "train_steps_total"):
        assert f"`{name}`" in text
