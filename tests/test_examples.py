"""Examples smoke tests: each example script runs end-to-end at tiny scale."""
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(script, *args):
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / script), *args],
        capture_output=True, text=True, timeout=600,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": "/root", "PYTHONUNBUFFERED": "1"})
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def test_train_llama_example(tmp_path):
    out = _run("train_llama.py", "--steps", "6", "--batch", "2", "--seq", "32",
               "--ckpt-dir", str(tmp_path / "ck"))
    assert "loss" in out and "saved checkpoint" in out
    losses = [float(l.rsplit(" ", 1)[1]) for l in out.splitlines()
              if l.startswith("step")]
    assert losses[-1] < losses[0]  # trains


@pytest.mark.slow
def test_train_resnet_example():
    out = _run("train_resnet.py", "--steps", "4", "--batch", "4")
    assert "loss" in out


def test_train_multichip_example():
    out = _run("train_multichip.py", "--devices", "8", "--steps", "2")
    assert "mesh dp=2 fsdp=2 tp=2" in out
    losses = [float(l.split("loss ")[1].split(" ")[0])
              for l in out.splitlines() if l.startswith("step")]
    assert np.isfinite(losses).all()


def test_generate_example():
    out = _run("generate.py", "--model", "mistral", "--strategy", "greedy",
               "--max-new-tokens", "4")
    assert "mistral/greedy" in out


@pytest.mark.slow
def test_long_context_example():
    out = _run("long_context.py", "--mode", "ring", "--steps", "2",
               "--seq", "64")
    assert "step 1" in out
