"""paddle_tpu.sparse (BCOO-backed) vs dense golden values."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu.sparse as sp


def _mk():
    dense = np.array([[0, 2.0, 0], [3.0, 0, 4.0], [0, 0, 0], [5.0, 0, 0]],
                     np.float32)
    nz = np.nonzero(dense)
    indices = np.stack(nz)  # [2, nnz]
    values = dense[nz]
    return dense, indices, values


def test_coo_create_and_dense():
    dense, idx, vals = _mk()
    s = sp.sparse_coo_tensor(idx, vals, dense.shape)
    assert sp.is_sparse(s)
    assert sp.nnz(s) == 4
    assert np.allclose(np.asarray(sp.to_dense(s)), dense)
    s2 = sp.to_sparse_coo(jnp.asarray(dense))
    assert np.allclose(np.asarray(sp.to_dense(s2)), dense)


def test_csr_create():
    dense, _, _ = _mk()
    # CSR of the same matrix
    crows = np.array([0, 1, 3, 3, 4])
    cols = np.array([1, 0, 2, 0])
    vals = np.array([2.0, 3.0, 4.0, 5.0], np.float32)
    s = sp.sparse_csr_tensor(crows, cols, vals, dense.shape)
    assert np.allclose(np.asarray(sp.to_dense(s)), dense)


def test_elementwise_and_activation():
    dense, idx, vals = _mk()
    s = sp.sparse_coo_tensor(idx, -vals, dense.shape)
    assert np.allclose(np.asarray(sp.to_dense(sp.relu(s))), np.maximum(-dense, 0))
    assert np.allclose(np.asarray(sp.to_dense(sp.abs(s))), np.abs(dense))
    assert np.allclose(np.asarray(sp.to_dense(sp.neg(s))), dense)
    assert np.allclose(np.asarray(sp.to_dense(sp.multiply(s, 2.0))), -2 * dense)
    t = sp.sparse_coo_tensor(idx, vals, dense.shape)
    assert np.allclose(np.asarray(sp.to_dense(sp.add(s, t))), np.zeros_like(dense))
    assert np.allclose(np.asarray(sp.to_dense(sp.tanh(t))), np.tanh(dense))


def test_matmul_and_masked():
    dense, idx, vals = _mk()
    s = sp.sparse_coo_tensor(idx, vals, dense.shape)
    w = np.random.RandomState(0).randn(3, 5).astype(np.float32)
    out = sp.matmul(s, jnp.asarray(w))
    assert np.allclose(np.asarray(out), dense @ w, atol=1e-5)
    # SDDMM: (a @ b) sampled at s's pattern
    a = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    b = np.random.RandomState(2).randn(6, 3).astype(np.float32)
    got = sp.masked_matmul(jnp.asarray(a), jnp.asarray(b), s)
    want = (a @ b) * (dense != 0)
    assert np.allclose(np.asarray(sp.to_dense(got)), want, atol=1e-4)


def test_transpose_sum_cast():
    dense, idx, vals = _mk()
    s = sp.sparse_coo_tensor(idx, vals, dense.shape)
    assert np.allclose(np.asarray(sp.to_dense(sp.transpose(s))), dense.T)
    assert np.allclose(float(sp.sum(s)), dense.sum())
    assert np.allclose(np.asarray(sp.to_dense(sp.sum(s, axis=1))), dense.sum(1))
    assert sp.sum(s, axis=1, keepdim=True).shape == (4, 1)
    assert sp.sum(s, keepdim=True).shape == (1, 1)
    assert sp.cast(s, jnp.bfloat16).data.dtype == jnp.bfloat16


def test_divide_same_pattern():
    dense, idx, vals = _mk()
    a = sp.sparse_coo_tensor(idx, vals, dense.shape)
    b = sp.sparse_coo_tensor(idx, vals * 2, dense.shape)
    q = sp.divide(a, b)
    got = np.asarray(sp.to_dense(q))
    assert np.all(np.isfinite(got))
    assert np.allclose(got[dense != 0], 0.5)
    assert np.allclose(got[dense == 0], 0.0)  # structural zeros stay zero
    # mismatched pattern rejected
    other_idx = idx.copy()
    other_idx[1, 0] = (other_idx[1, 0] + 1) % 3
    c = sp.sparse_coo_tensor(other_idx, vals, dense.shape)
    import pytest
    with pytest.raises(ValueError):
        sp.divide(a, c)


def test_sparse_ops_under_jit():
    import jax
    dense, idx, vals = _mk()
    a = sp.sparse_coo_tensor(idx, vals, dense.shape)
    b = sp.sparse_coo_tensor(idx, vals * 3, dense.shape)
    out = jax.jit(lambda x, y: sp.to_dense(sp.add(x, y)))(a, b)
    assert np.allclose(np.asarray(out), 4 * dense)
    q = jax.jit(lambda x, y: sp.to_dense(sp.divide(x, y)))(a, b)
    assert np.allclose(np.asarray(q)[dense != 0], 1 / 3, atol=1e-6)
    mm = jax.jit(lambda x: sp.matmul(x, jnp.ones((3, 2))))(a)
    assert np.allclose(np.asarray(mm), dense @ np.ones((3, 2)))


def test_pylayer_multi_output():
    import jax
    import paddle_tpu.autograd as ag

    class Split(ag.PyLayer):
        @staticmethod
        def forward(ctx, x):
            return 2.0 * x, 3.0 * x

        @staticmethod
        def backward(ctx, ga, gb):
            return 2.0 * ga + 3.0 * gb

    a, b = Split.apply(jnp.asarray(1.0))
    assert float(a) == 2.0 and float(b) == 3.0
    g = jax.grad(lambda x: sum(Split.apply(x)))(jnp.asarray(1.0))
    assert float(g) == 5.0


def test_hybrid_to_sparse_coo():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    h = sp.to_sparse_coo(x, sparse_dim=1)
    assert h.n_dense == 1
    assert np.allclose(np.asarray(sp.to_dense(h)), np.asarray(x))
