"""Spec-draft reuse from the radix frontier (ISSUE 11, closing PR 9's
REMAINING): a radix prefix hit used to pay a draft-side re-prefill of
the whole adopted span, counted as ``replay_prefill`` waste. The engine
now seeds ``draft_cur`` from the slot's resident draft cache, so the
catch-up feed embeds only the un-adopted suffix — asserted through the
goodput ledger, the reuse counter, output identity, and the
PT_DRAFT_REUSE kill switch."""
import numpy as np
import pytest

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability.goodput import GOODPUT
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.serving.telemetry import _SPEC_DRAFT_REUSE

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _run(eng, prompts, max_new=8):
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new))
    return {r: list(map(int, t)) for r, t in eng.run().items()}


def _kw(model, **kw):
    # one slot: the second request is guaranteed to land on the slot
    # whose draft cache holds the first request's prefix
    base = dict(num_slots=1, block_size=8, max_prompt_len=16,
                max_seq_len=96, draft_model=model, prefix_caching=True)
    base.update(kw)
    return base


def _two_phase(model, rs, **ekw):
    """Two sequential requests sharing a 24-token prefix; returns
    (outputs, reuse tokens, replay_prefill waste of phase 2)."""
    shared = rs.randint(0, 64, (24,))
    p1 = np.concatenate([shared, rs.randint(0, 64, (4,))])
    p2 = np.concatenate([shared, rs.randint(0, 64, (4,))])
    eng = LLMEngine(model, **_kw(model, **ekw))
    o1 = _run(eng, [p1])
    w0 = GOODPUT.waste_by_why().get("replay_prefill", 0)
    r0 = _SPEC_DRAFT_REUSE.value()
    o2 = _run(eng, [p2])
    replay = GOODPUT.waste_by_why().get("replay_prefill", 0) - w0
    reuse = _SPEC_DRAFT_REUSE.value() - r0
    return {**o1, **o2}, reuse, replay


def test_radix_hit_seeds_draft_and_kills_replay_waste(model, monkeypatch):
    """With reuse on, the adopted span's draft re-embed disappears; with
    PT_DRAFT_REUSE=0 it comes back token for token — and the outputs are
    identical either way (reuse can only change speed, never tokens)."""
    out_on, reuse_on, replay_on = _two_phase(
        model, np.random.RandomState(3))
    assert reuse_on > 0

    monkeypatch.setenv("PT_DRAFT_REUSE", "0")
    out_off, reuse_off, replay_off = _two_phase(
        model, np.random.RandomState(3))
    assert reuse_off == 0
    assert list(out_on.values()) == list(out_off.values())
    # every reused position is exactly one replay_prefill unit saved
    assert replay_off - replay_on == reuse_on
    assert replay_off >= 24        # the kill-switch run re-embeds the span


def test_unrelated_prompt_reuses_nothing(model):
    """No shared prefix → no radix adoption → seeding must stay at 0
    even though the slot's resident draft cache is warm."""
    rs = np.random.RandomState(4)
    eng = LLMEngine(model, **_kw(model))
    _run(eng, [rs.randint(0, 64, (24,))])
    r0 = _SPEC_DRAFT_REUSE.value()
    _run(eng, [rs.randint(0, 64, (24,))])
    assert _SPEC_DRAFT_REUSE.value() == r0


def test_reuse_with_unrelated_draft_model(model):
    """A near-zero-acceptance draft stresses the rollback/snapshot path:
    resident snapshots must track the COMMITTED prefix, so the second
    request still reuses and still matches the no-reuse outputs."""
    dcfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=64)
    draft = LlamaForCausalLM(dcfg)
    out_on, reuse, _ = _two_phase(model, np.random.RandomState(5),
                                  draft_model=draft)
    assert reuse > 0
    # identity vs a spec-less engine: reuse composes with rejection
    rs = np.random.RandomState(5)
    shared = rs.randint(0, 64, (24,))
    p1 = np.concatenate([shared, rs.randint(0, 64, (4,))])
    p2 = np.concatenate([shared, rs.randint(0, 64, (4,))])
    plain = LLMEngine(model, **{**_kw(model), "draft_model": None})
    base = {**_run(plain, [p1]), **_run(plain, [p2])}
    assert list(out_on.values()) == list(base.values())


def test_goodput_reconciliation_still_exact(model):
    """saved/waste are side ledgers: reuse accounting must not break the
    good-token vs serving_tokens_total reconciliation."""
    from paddle_tpu.serving.telemetry import _TOKENS
    rs = np.random.RandomState(6)
    shared = rs.randint(0, 64, (24,))
    prompts = [np.concatenate([shared, rs.randint(0, 64, (4,))])
               for _ in range(3)]
    t0 = _TOKENS.value()
    g0 = GOODPUT.good_total()
    eng = LLMEngine(model, **_kw(model))
    out = _run(eng, prompts)
    good = GOODPUT.good_total() - g0
    emitted = sum(len(t) for t in out.values())
    assert good == emitted == _TOKENS.value() - t0
