"""Continuous-batching serving engine + paged beam (VERDICT r2 items 2/6).

* 3x more requests than slots all complete; every output equals its
  single-request greedy reference
* queued requests are admitted MID-FLIGHT into freed slots (prefill
  interleaved with decode ticks)
* pool block usage tracks Σ live lengths (lazy allocation), never the
  dense bound
* per-request streaming callbacks fire in decode order
* beam search in the paged path == the static-cache beam, with prompt
  blocks SHARED across beams (refcount fork, partial-tail copy)
Ref: PaddleNLP llm/predict/predictor.py block-attention serving.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import beam_search, generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import RefBlockManager, paged_beam_search
from paddle_tpu.serving import LLMEngine, Request


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _prompts(n, rs):
    return [rs.randint(0, 64, (int(l),))
            for l in rs.randint(3, 14, size=n)]


def test_engine_oversubscribed_matches_solo_greedy(model):
    """6 requests through 2 slots: all complete, each == solo greedy."""
    rs = np.random.RandomState(0)
    prompts = _prompts(6, rs)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=6))
    out = eng.run()
    assert len(out) == 6
    for rid, toks in out.items():
        p = prompts[rid]
        ref = np.asarray(generate(model, jnp.asarray(p[None]),
                                  max_new_tokens=6))[0, len(p):]
        np.testing.assert_array_equal(np.asarray(toks), ref,
                                      err_msg=f"request {rid}")


def test_engine_admits_mid_flight(model):
    """A queued request must enter a slot while others are mid-decode —
    not after the whole first wave drains."""
    rs = np.random.RandomState(1)
    prompts = _prompts(4, rs)
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32, eos_token_id=None)
    # first two run long, second two are queued behind them
    for i, p in enumerate(prompts):
        eng.add_request(Request(p, max_new_tokens=10 if i < 2 else 4))
    first_tick_of = {}
    tick = 0
    while eng.has_work():
        for rid, _ in eng.step():
            first_tick_of.setdefault(rid, tick)
        tick += 1
    # requests 2/3 started strictly after 0/1 but before the run ended
    assert first_tick_of[2] > first_tick_of[0]
    assert first_tick_of[2] < tick - 1
    # outputs still exact
    for rid in range(4):
        p = prompts[rid]
        n = 10 if rid < 2 else 4
        ref = np.asarray(generate(model, jnp.asarray(p[None]),
                                  max_new_tokens=n))[0, len(p):]
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), ref)


def test_engine_eos_frees_slot_for_queue(model):
    """EOS finishes a request early; its slot and blocks serve the queue."""
    rs = np.random.RandomState(2)
    prompts = _prompts(4, rs)
    refs = {}
    eos = None
    for rid, p in enumerate(prompts):
        r = np.asarray(generate(model, jnp.asarray(p[None]),
                                max_new_tokens=8))[0, len(p):]
        refs[rid] = r
    # choose the first generated token of request 0 as EOS
    eos = int(refs[0][0])
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24, eos_token_id=eos)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    out = eng.run()
    for rid in range(4):
        got = np.asarray(out[rid])
        ref = refs[rid]
        stop = np.nonzero(ref == eos)[0]
        expect = ref[: int(stop[0]) + 1] if len(stop) else ref
        np.testing.assert_array_equal(got, expect, err_msg=f"req {rid}")
        fin = eng.requests[rid].finish_reason
        assert fin == ("eos" if len(stop) else "length")


def test_engine_pool_usage_tracks_live_lengths(model):
    """Lazy allocation: blocks in use ≈ Σ ceil(live_len/bs), and the peak
    stays far under slots × max_blocks when requests are short."""
    rs = np.random.RandomState(3)
    prompts = _prompts(6, rs)
    eng = LLMEngine(model, num_slots=3, block_size=4, max_prompt_len=16,
                    max_seq_len=64)   # roomy tables; usage must stay lazy
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=5))
    peak = 0
    while eng.has_work():
        eng.step()
        used = eng.mgr.num_blocks - eng.mgr.free_blocks
        live = [int(eng.cur[s]) + 1 for s in range(eng.num_slots)
                if eng.slot_req[s] >= 0]
        bound = sum(-(-n // eng.block_size) for n in live)
        assert used <= bound + eng.num_slots  # ≤ one growth block per slot
        peak = max(peak, used)
    assert peak <= 3 * (-(-(16 + 5) // 4))   # ≈ Σ active, not table width
    assert eng.mgr.free_blocks == eng.mgr.num_blocks  # all recycled


def test_engine_streaming_callbacks(model):
    rs = np.random.RandomState(4)
    p = rs.randint(0, 64, (5,))
    seen = []
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=8,
                    max_seq_len=16)
    eng.add_request(Request(p, max_new_tokens=5,
                            stream=lambda r, t: seen.append(t)))
    out = eng.run()
    assert seen == out[0] and len(seen) == 5


def test_engine_sampling_seeded(model):
    """temperature > 0: engine runs, tokens in-vocab, reproducible."""
    rs = np.random.RandomState(5)
    prompts = _prompts(3, rs)

    def run():
        eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                        max_seq_len=24, temperature=0.8, top_k=8, seed=7)
        for p in prompts:
            eng.add_request(Request(p, max_new_tokens=6))
        return eng.run()

    a, b = run(), run()
    assert all(len(v) == 6 for v in a.values())
    assert all(0 <= t < 64 for v in a.values() for t in v)
    assert a == b


def test_engine_sliding_window_recycles_blocks(model):
    """Mistral-style window: outputs equal the static ring-cache generate
    AND live blocks per sequence stay O(window), not O(length)."""
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, sliding_window=6)
    wmodel = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, 64, (n,)) for n in (10, 4)]
    new = 16   # decode far past the window

    eng = LLMEngine(wmodel, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=32)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=new))
    peak_live = 0
    while eng.has_work():
        eng.step()
        for s in range(eng.num_slots):
            if eng.slot_req[s] >= 0:
                peak_live = max(peak_live,
                                eng._live_blocks(int(eng.slot_req[s])))
    for rid, p in enumerate(prompts):
        ref = np.asarray(generate(wmodel, jnp.asarray(p[None]),
                                  max_new_tokens=new))[0, len(p):]
        np.testing.assert_array_equal(
            np.asarray(eng.requests[rid].tokens), ref, err_msg=f"req {rid}")
    # window 6 @ bs 4: live span ≤ window + 2*bs tokens -> 4 blocks; the
    # un-recycled bound for row 0 would be ceil((10+16)/4) = 7
    assert peak_live <= 4, peak_live


def test_engine_request_validation_and_eviction(model):
    eng = LLMEngine(model, num_slots=1, block_size=4, max_prompt_len=8,
                    max_seq_len=16)
    with pytest.raises(ValueError, match="at least one token"):
        eng.generate([])
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.generate([1, 2], max_new_tokens=0)
    rid = eng.add_request(Request([1, 2, 3], max_new_tokens=2, req_id=5))
    assert rid == 5
    with pytest.raises(ValueError, match="already exists"):
        eng.add_request(Request([4], max_new_tokens=2, req_id=5))
    auto = eng.generate([7, 8], max_new_tokens=2)
    assert auto > 5                      # auto ids skip explicit ones
    eng.run()
    done = eng.pop_finished()
    assert set(done) == {5, auto} and all(r.done for r in done.values())
    assert eng.requests == {}            # evicted — no unbounded growth


# ------------------------------------------------------------------- beam

def test_paged_beam_matches_static_beam(model):
    rs = np.random.RandomState(6)
    prompt = rs.randint(0, 64, (7,))
    ref_seq, ref_score = beam_search(model, jnp.asarray(prompt[None]),
                                     max_new_tokens=8, num_beams=4)
    got_seq, got_score = paged_beam_search(model, prompt, max_new_tokens=8,
                                           num_beams=4, block_size=4)
    np.testing.assert_array_equal(np.asarray(got_seq),
                                  np.asarray(ref_seq)[0])
    assert abs(float(got_score) - float(ref_score[0])) < 1e-5


def test_paged_beam_with_eos_matches_static(model):
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, 64, (6,))
    probe, _ = beam_search(model, jnp.asarray(prompt[None]),
                           max_new_tokens=8, num_beams=4)
    eos = int(np.asarray(probe)[0, len(prompt) + 2])
    ref_seq, ref_score = beam_search(model, jnp.asarray(prompt[None]),
                                     max_new_tokens=8, num_beams=4,
                                     eos_token_id=eos)
    got_seq, got_score = paged_beam_search(model, prompt, max_new_tokens=8,
                                           num_beams=4, block_size=4,
                                           eos_token_id=eos)
    np.testing.assert_array_equal(np.asarray(got_seq),
                                  np.asarray(ref_seq)[0])
    assert abs(float(got_score) - float(ref_score[0])) < 1e-5


def test_paged_beam_shares_prompt_blocks(model):
    """K beams over a long prompt must NOT use K x prompt blocks: full
    prompt blocks are refcount-shared, only tails are private."""
    rs = np.random.RandomState(8)
    prompt = rs.randint(0, 64, (12,))   # 3 full blocks at bs=4
    K, bs = 4, 4
    pool = K * (-(-(len(prompt) + 4) // bs))
    seq, _ = paged_beam_search(model, prompt, max_new_tokens=4,
                               num_beams=K, block_size=bs, num_blocks=pool)
    assert len(np.asarray(seq)) == len(prompt) + 4
    # direct manager-level check of the sharing arithmetic
    mgr = RefBlockManager(num_blocks=pool, block_size=bs)
    mgr.allocate(0, len(prompt))
    base = mgr.num_blocks - mgr.free_blocks
    for j in range(1, K):
        assert mgr.fork(0, j, len(prompt)) is None   # aligned: no copy
    assert mgr.num_blocks - mgr.free_blocks == base  # fully shared
    mgr2 = RefBlockManager(num_blocks=pool, block_size=bs)
    mgr2.allocate(0, 10)                              # partial tail
    used0 = mgr2.num_blocks - mgr2.free_blocks
    assert mgr2.fork(0, 1, 10) is not None            # tail copied
    assert mgr2.num_blocks - mgr2.free_blocks == used0 + 1
    mgr2.free(1)
    assert mgr2.num_blocks - mgr2.free_blocks == used0
