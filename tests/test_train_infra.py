"""Trainer / checkpoint / amp / watchdog / fleet tests (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import amp
from paddle_tpu.distributed import fleet
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.train import TrainState, make_train_step
from paddle_tpu.train.checkpoint import CheckpointManager, load, save
from paddle_tpu.train.step import init_state
from paddle_tpu.train.trainer import Trainer, TrainerArgs
from paddle_tpu.utils.watchdog import StallWatchdog, WatchdogTrip, check_finite


def _lm_data(cfg, n=100, b=2, s=16):
    rs = np.random.RandomState(0)
    while True:
        ids = rs.randint(0, cfg.vocab_size, (b, s))
        labels = np.concatenate([ids[:, 1:], -100 * np.ones((b, 1), ids.dtype)], axis=1)
        yield ids, labels


def test_trainer_runs_and_logs():
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    tr = Trainer(model, opt.AdamW(1e-3), lambda m, i, l: m.loss(i, l),
                 TrainerArgs(max_steps=6, log_every=2))
    state = tr.fit(_lm_data(cfg))
    assert int(state.step) == 6
    assert len(tr.history) >= 2
    assert all(np.isfinite(h["loss"]) for h in tr.history)


def test_trainer_grad_accum_matches_large_batch():
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (4, 16))
    labels = np.concatenate([ids[:, 1:], -100 * np.ones((4, 1), ids.dtype)], axis=1)

    # accum=2 over half-batches
    tr = Trainer(model, opt.SGD(0.1), lambda m, i, l: m.loss(i, l),
                 TrainerArgs(max_steps=1, log_every=0, grad_accum_steps=2))
    def half_batches():
        yield ids[:2], labels[:2]
        yield ids[2:], labels[2:]
    state_a = tr.fit(half_batches())

    # full batch, 1 step — equal token counts per microbatch → same update
    pt.seed(0)
    model2 = LlamaForCausalLM(cfg)
    tr2 = Trainer(model2, opt.SGD(0.1), lambda m, i, l: m.loss(i, l),
                  TrainerArgs(max_steps=1, log_every=0))
    state_b = tr2.fit(iter([(ids, labels)]))
    wa = np.asarray(state_a.model.lm_head, dtype=np.float32)
    wb = np.asarray(state_b.model.lm_head, dtype=np.float32)
    np.testing.assert_allclose(wa, wb, rtol=1e-4, atol=1e-6)


def test_checkpoint_roundtrip_exact(tmp_path):
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(1e-3)
    state = init_state(model, optimizer)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, donate=False)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 16)))
    labels = jnp.asarray(np.concatenate(
        [np.asarray(ids)[:, 1:], -100 * np.ones((2, 1), np.asarray(ids).dtype)], axis=1))
    for _ in range(3):
        state, _ = step(state, ids, labels)

    mgr = CheckpointManager(tmp_path / "ck")
    mgr.save(3, state)
    assert mgr.latest_step() == 3

    # continue original 2 more steps
    cont = state
    for _ in range(2):
        cont, loss_a = step(cont, ids, labels)

    # restore and continue — identical trajectory
    pt.seed(1)  # different rng state; restore must not depend on it
    model_r = LlamaForCausalLM(cfg)
    state_r = init_state(model_r, optimizer)
    state_r = mgr.restore(state_r)
    assert int(state_r.step) == 3
    for _ in range(2):
        state_r, loss_b = step(state_r, ids, labels)
    np.testing.assert_allclose(float(loss_b), float(loss_a), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cont.model.lm_head),
                                  np.asarray(state_r.model.lm_head))


def test_checkpoint_retention(tmp_path):
    mgr = CheckpointManager(tmp_path / "ck", max_to_keep=2)
    for s in (1, 2, 3):
        mgr.save(s, {"x": jnp.ones((2,)) * s})
    import os
    files = sorted(os.listdir(tmp_path / "ck"))
    ckpts = [f for f in files if f.startswith("ckpt_")]
    assert len(ckpts) == 2 and "ckpt_00000003.npz" in ckpts
    # the durable latest pointer rides along and tracks the newest save
    assert "latest" in files and mgr.latest_step() == 3


def test_save_load_plain_tree(tmp_path):
    tree = {"a": jnp.arange(4.0), "b": {"c": jnp.ones((2, 2))}, "n": None}
    save(tree, tmp_path / "t")
    back = load(tmp_path / "t", target={"a": jnp.zeros(4), "b": {"c": jnp.zeros((2, 2))},
                                        "n": None})
    np.testing.assert_allclose(np.asarray(back["a"]), np.arange(4.0))


def test_amp_policy_and_scaler():
    pol = amp.O2()
    m = nn.Linear(4, 4)
    m16 = pol.cast_to_param(m)
    assert m16.weight.dtype == jnp.bfloat16
    sc = amp.GradScaler(enable=True, init_loss_scaling=8.0, incr_every_n_steps=2)
    st = sc.init()
    assert float(sc.scale(jnp.asarray(2.0), st)) == 16.0
    g = {"w": jnp.asarray([8.0])}
    np.testing.assert_allclose(np.asarray(sc.unscale(g, st)["w"]), [1.0])
    # inf grads shrink the scale
    st2 = sc.update(st, jnp.asarray(True))
    assert float(st2["scale"]) == 4.0
    # good steps grow it after incr_every
    st3 = sc.update(sc.update(st, jnp.asarray(False)), jnp.asarray(False))
    assert float(st3["scale"]) == 16.0
    assert bool(sc.found_inf({"a": jnp.asarray([jnp.inf])}))
    assert not bool(sc.found_inf({"a": jnp.asarray([1.0])}))


def test_nan_guard_skips_poisoned_update():
    pt.seed(0)
    m = nn.Linear(4, 4)
    w0 = np.asarray(m.weight).copy()
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x: jnp.sum(mod(x)) / jnp.sum(x * 0.0),  # nan loss
                 TrainerArgs(max_steps=1, log_every=0, max_bad_steps=5))
    tr.fit(iter([(np.ones((2, 4), np.float32),)]))
    np.testing.assert_array_equal(np.asarray(tr.state.model.weight), w0)


@pytest.mark.chaos
def test_injected_nan_losses_counted_and_skipped():
    """train.loss chaos site: inject a 3-step NaN storm mid-run — the
    trainer counts the skips, tracks the worst streak, recovers, and
    finishes all steps."""
    from paddle_tpu.utils.faults import FAULTS
    pt.seed(0)
    m = nn.Linear(4, 1)
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                 TrainerArgs(max_steps=8, log_every=0, max_bad_steps=10))
    FAULTS.install("train.loss", on={2, 3, 4}, action=lambda c: float("nan"))
    rs = np.random.RandomState(0)
    data = ((rs.randn(2, 4).astype(np.float32),
             rs.randn(2, 1).astype(np.float32)) for _ in range(8))
    state = tr.fit(data)
    assert int(state.step) == 8
    assert tr.stats["nan_skips"] == 3
    assert tr.stats["bad_streak_max"] == 3
    assert tr._bad_steps == 0              # streak reset by the good tail
    # the same run is visible in the metrics registry (ISSUE 2): every
    # injected loss override and every skip landed in a counter
    from paddle_tpu.observability import METRICS
    snap = METRICS.snapshot()["counters"]
    assert snap["train_nan_skips_total"] == 3
    assert snap['faults_injected_total{site="train.loss"}'] == 3
    assert snap["train_steps_total"] == 8


@pytest.mark.chaos
def test_nan_storm_trips_watchdog():
    """An unbroken injected NaN storm must trip after max_bad_steps —
    feeding the elastic restart path instead of burning steps forever."""
    from paddle_tpu.utils.faults import FAULTS
    pt.seed(0)
    m = nn.Linear(4, 1)
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                 TrainerArgs(max_steps=50, log_every=0, max_bad_steps=3))
    FAULTS.install("train.loss", every=1, action=lambda c: float("nan"))
    rs = np.random.RandomState(1)
    data = ((rs.randn(2, 4).astype(np.float32),
             rs.randn(2, 1).astype(np.float32)) for _ in range(50))
    with pytest.raises(WatchdogTrip, match="non-finite"):
        tr.fit(data)
    assert tr.stats["nan_skips"] == 3


@pytest.mark.chaos
def test_nan_backoff_sleeps_exponentially():
    from paddle_tpu.utils.faults import FAULTS
    import time as _time
    pt.seed(0)
    m = nn.Linear(4, 1)
    tr = Trainer(m, opt.SGD(0.1),
                 lambda mod, x, y: nn.functional.mse_loss(mod(x), y),
                 TrainerArgs(max_steps=4, log_every=0, max_bad_steps=10,
                             nan_backoff_s=0.05))
    FAULTS.install("train.loss", on={1, 2}, action=lambda c: float("nan"))
    rs = np.random.RandomState(2)
    data = ((rs.randn(2, 4).astype(np.float32),
             rs.randn(2, 1).astype(np.float32)) for _ in range(4))
    t0 = _time.monotonic()
    tr.fit(data)
    # streak 1 sleeps 0.05, streak 2 doubles to 0.10
    assert _time.monotonic() - t0 >= 0.14


def test_watchdog_trips():
    w = StallWatchdog(timeout_s=0.2).start()
    import time
    time.sleep(0.5)
    with pytest.raises(WatchdogTrip):
        w.poke()
    w.stop()
    assert check_finite({"a": jnp.ones(3)})
    assert not check_finite({"a": jnp.asarray([np.nan])})


def test_fleet_facade():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"mp_degree": 2, "sharding_degree": 2}
    mesh = fleet.init(is_collective=True, strategy=strategy)
    assert mesh.tp == 2 and mesh.fsdp == 2 and mesh.dp == 2  # 8 devices
    assert fleet.get_hybrid_communicate_group() is mesh
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    with mesh:
        ms = fleet.distributed_model(m, min_size=1)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (4, 16)))
        out = jax.jit(lambda mm, i: mm(i))(ms, ids)
    assert out.shape == (4, 16, cfg.vocab_size)


def test_jit_save_load_roundtrip(tmp_path):
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32))
    ref = np.asarray(net(x))
    assert net.training  # fresh modules are in train mode
    p = pt.jit.save(net, str(tmp_path / "net"), example_args=x)
    assert net.training  # save() must not leave the module in eval mode
    f = pt.jit.load(p)
    np.testing.assert_allclose(np.asarray(f(x)), ref, atol=1e-6)
    # InputSpec None dims export as symbolic: any batch size works
    p2 = pt.jit.save(net, str(tmp_path / "net2"),
                     input_spec=[pt.jit.InputSpec((None, 8))])
    f2 = pt.jit.load(p2)
    assert f2(jnp.ones((1, 8))).shape == (1, 4)
    assert f2(jnp.ones((5, 8))).shape == (5, 4)


def test_evaluate_restores_train_mode():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Dropout(0.5), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.1),
              loss=lambda out, y: nn.functional.cross_entropy(out, y))
    rng = np.random.default_rng(0)
    data = [(rng.standard_normal((4, 4)).astype(np.float32),
             rng.integers(0, 2, 4))]
    m.fit(data, eval_data=data, epochs=2, verbose=0)
    assert all(s.training for s in m._state.model.sublayers(include_self=True))
    m.predict(data)
    assert all(s.training for s in m._state.model.sublayers(include_self=True))


def test_elastic_restarts_and_resumes(tmp_path):
    """Inject a crash mid-training; elastic must restore the latest
    checkpoint and finish all steps."""
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import Trainer, TrainerArgs, run_elastic

    pt.seed(0)
    crashes = {"left": 1}

    def loss_fn(m, x, y):
        return nn.functional.mse_loss(m(x), y)

    def make_trainer():
        pt.seed(0)  # deterministic init; resume() restores real progress
        net = nn.Sequential(nn.Linear(3, 8), nn.Tanh(), nn.Linear(8, 1))
        return Trainer(net, opt.SGD(learning_rate=0.05), loss_fn,
                       TrainerArgs(max_steps=12, log_every=2, ckpt_every=2,
                                   ckpt_dir=str(tmp_path), nan_guard=False))

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 3)).astype(np.float32)
    Y = (X @ np.array([[1.0], [-2.0], [0.5]], np.float32)).astype(np.float32)

    def data_fn():
        def gen():
            i = 0
            while True:
                if crashes["left"] and i == 6:
                    crashes["left"] -= 1
                    raise RuntimeError("injected failure")
                sl = slice((i * 16) % 240, (i * 16) % 240 + 16)
                yield X[sl], Y[sl]
                i += 1
        return gen()

    state = run_elastic(make_trainer, data_fn, max_restarts=2, backoff_s=0.0)
    assert int(state.step) >= 12
    assert crashes["left"] == 0  # the injected crash actually fired

    # the crashed+resumed trajectory must equal an uncrashed one: resume
    # fast-forwards the fresh stream, so the trained batch sequence matches
    import shutil
    shutil.rmtree(tmp_path)
    crashes["left"] = 0
    ref_state = run_elastic(make_trainer, data_fn, max_restarts=0,
                            backoff_s=0.0)
    w_crashed = np.asarray(state.model[0].weight, np.float32)
    w_clean = np.asarray(ref_state.model[0].weight, np.float32)
    np.testing.assert_allclose(w_crashed, w_clean, rtol=1e-5, atol=1e-6)


def test_elastic_gives_up(tmp_path):
    import numpy as np
    import pytest as _pytest
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import Trainer, TrainerArgs, run_elastic

    pt.seed(0)

    def make_trainer():
        net = nn.Sequential(nn.Linear(2, 1))
        return Trainer(net, opt.SGD(learning_rate=0.1),
                       lambda m, x, y: nn.functional.mse_loss(m(x), y),
                       TrainerArgs(max_steps=5, ckpt_every=0,
                                   ckpt_dir=str(tmp_path)))

    def data_fn():
        def gen():
            raise RuntimeError("always broken")
            yield  # pragma: no cover
        return gen()

    with _pytest.raises(RuntimeError, match="gave up"):
        run_elastic(make_trainer, data_fn, max_restarts=1, backoff_s=0.0)


def test_amp_debugging():
    import jax.numpy as jnp
    import numpy as np
    import pytest as _pytest
    from paddle_tpu.amp import debugging as D

    assert D.check_numerics({"a": jnp.ones(3)}) is True
    with _pytest.raises(FloatingPointError, match="1 NaN"):
        D.check_numerics({"a": jnp.asarray([1.0, np.nan])})

    stats = D.collect_operator_stats(
        lambda x, w: (x @ w).astype(jnp.bfloat16) @ w.T.astype(jnp.bfloat16),
        jnp.ones((4, 8)), jnp.ones((8, 8)), print_fn=None)
    dots = {k: v for k, v in stats.items() if k[0] == "dot_general"}
    assert sum(dots.values()) == 2
    assert any(dt == "bf16" for (_, dt) in dots)

    ok, rep = D.compare_accuracy(
        lambda x: x * 2.0,
        lambda x: (x.astype(jnp.bfloat16) * 2.0).astype(jnp.float32),
        jnp.linspace(0, 1, 16), print_fn=None)
    assert ok and len(rep) == 1
