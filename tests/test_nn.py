"""Layer fwd/bwd semantics; golden checks vs torch CPU where APIs are 1:1
(SURVEY.md §4; ref test/legacy_test/test_layers.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_matches_torch():
    import torch
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    lin = nn.Linear(8, 3)
    w = np.asarray(lin.weight)
    b = np.asarray(lin.bias)
    t = torch.nn.functional.linear(torch.tensor(x), torch.tensor(w.T), torch.tensor(b))
    np.testing.assert_allclose(np.asarray(lin(jnp.asarray(x))), t.numpy(), rtol=1e-5)


def test_conv2d_matches_torch():
    import torch
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    conv = nn.Conv2D(3, 5, 3, stride=2, padding=1)
    t = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(np.asarray(conv.weight)),
                                   torch.tensor(np.asarray(conv.bias)), stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(conv(jnp.asarray(x))), t.numpy(), rtol=1e-4, atol=1e-5)


def test_conv_groups_dilation():
    import torch
    x = np.random.RandomState(1).randn(1, 4, 10, 10).astype(np.float32)
    conv = nn.Conv2D(4, 8, 3, groups=2, dilation=2)
    t = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(np.asarray(conv.weight)),
                                   torch.tensor(np.asarray(conv.bias)), groups=2, dilation=2)
    np.testing.assert_allclose(np.asarray(conv(jnp.asarray(x))), t.numpy(), rtol=1e-4, atol=1e-5)


def test_conv2d_transpose_matches_torch():
    import torch
    x = np.random.RandomState(2).randn(1, 4, 7, 7).astype(np.float32)
    ct = nn.Conv2DTranspose(4, 6, 3, stride=2, padding=1, output_padding=1)
    t = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(np.asarray(ct.weight)),
        torch.tensor(np.asarray(ct.bias)), stride=2, padding=1, output_padding=1)
    np.testing.assert_allclose(np.asarray(ct(jnp.asarray(x))), t.numpy(), rtol=1e-4, atol=1e-5)


def test_layer_norm_matches_torch():
    import torch
    x = np.random.RandomState(0).randn(4, 6, 16).astype(np.float32)
    ln = nn.LayerNorm(16)
    t = torch.nn.functional.layer_norm(torch.tensor(x), (16,),
                                       torch.tensor(np.asarray(ln.weight)),
                                       torch.tensor(np.asarray(ln.bias)))
    np.testing.assert_allclose(np.asarray(ln(jnp.asarray(x))), t.numpy(), rtol=1e-4, atol=1e-5)


def test_batch_norm_train_and_eval():
    bn = nn.BatchNorm2D(3, momentum=0.9)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 5, 5).astype(np.float32))
    y = bn(x)  # training: normalised by batch stats
    np.testing.assert_allclose(np.asarray(y.mean(axis=(0, 2, 3))), np.zeros(3), atol=1e-5)
    assert not np.allclose(np.asarray(bn._mean), 0.0)  # running stats updated
    bn.eval()
    y2 = bn(x)
    assert y2.shape == x.shape


def test_rms_norm():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 32).astype(np.float32))
    rn = nn.RMSNorm(32)
    y = rn(x)
    expected = np.asarray(x) / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-4, atol=1e-5)


def test_group_norm_matches_torch():
    import torch
    x = np.random.RandomState(0).randn(2, 8, 4, 4).astype(np.float32)
    gn = nn.GroupNorm(2, 8)
    t = torch.nn.functional.group_norm(torch.tensor(x), 2,
                                       torch.tensor(np.asarray(gn.weight)),
                                       torch.tensor(np.asarray(gn.bias)))
    np.testing.assert_allclose(np.asarray(gn(jnp.asarray(x))), t.numpy(), rtol=1e-4, atol=1e-5)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    out = emb(jnp.array([[0, 1, 2]]))
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.zeros(4))
    assert not np.allclose(np.asarray(out[0, 1]), 0.0)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = jnp.ones((100, 100))
    y = d(x, rng=jax.random.PRNGKey(0))
    frac = float((y == 0).mean())
    assert 0.4 < frac < 0.6
    d.eval()
    np.testing.assert_allclose(np.asarray(d(x)), np.asarray(x))


def test_pools_match_torch():
    import torch
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    mp = F.max_pool2d(jnp.asarray(x), 2)
    t = torch.nn.functional.max_pool2d(torch.tensor(x), 2)
    np.testing.assert_allclose(np.asarray(mp), t.numpy(), rtol=1e-5, atol=1e-6)
    ap = F.avg_pool2d(jnp.asarray(x), 2)
    t2 = torch.nn.functional.avg_pool2d(torch.tensor(x), 2)
    np.testing.assert_allclose(np.asarray(ap), t2.numpy(), rtol=1e-5, atol=1e-6)
    aa = F.adaptive_avg_pool2d(jnp.asarray(x), 2)
    t3 = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), 2)
    np.testing.assert_allclose(np.asarray(aa), t3.numpy(), rtol=1e-4, atol=1e-5)


def test_activations_match_torch():
    import torch
    x = np.linspace(-3, 3, 50).astype(np.float32)
    tx = torch.tensor(x)
    jx = jnp.asarray(x)
    pairs = [
        (F.relu, torch.nn.functional.relu),
        (F.silu, torch.nn.functional.silu),
        (lambda v: F.gelu(v), lambda v: torch.nn.functional.gelu(v)),
        (F.softplus, torch.nn.functional.softplus),
        (F.sigmoid, torch.sigmoid),
        (lambda v: F.leaky_relu(v, 0.1), lambda v: torch.nn.functional.leaky_relu(v, 0.1)),
        (F.hardswish, torch.nn.functional.hardswish),
        (F.mish, torch.nn.functional.mish),
        (lambda v: F.elu(v), torch.nn.functional.elu),
    ]
    for jf, tf in pairs:
        np.testing.assert_allclose(np.asarray(jf(jx)), tf(tx).numpy(), rtol=1e-4, atol=1e-5)


def test_cross_entropy_matches_torch():
    import torch
    logits = np.random.RandomState(0).randn(8, 10).astype(np.float32)
    labels = np.random.RandomState(1).randint(0, 10, (8,))
    got = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    want = torch.nn.functional.cross_entropy(torch.tensor(logits), torch.tensor(labels))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    # ignore_index
    labels2 = labels.copy()
    labels2[:4] = -100
    got2 = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels2))
    want2 = torch.nn.functional.cross_entropy(torch.tensor(logits), torch.tensor(labels2))
    np.testing.assert_allclose(float(got2), float(want2), rtol=1e-5)
    # label smoothing
    got3 = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels), label_smoothing=0.1)
    want3 = torch.nn.functional.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                                              label_smoothing=0.1)
    np.testing.assert_allclose(float(got3), float(want3), rtol=1e-5)


def test_bce_losses_match_torch():
    import torch
    logits = np.random.RandomState(0).randn(8).astype(np.float32)
    labels = np.random.RandomState(1).randint(0, 2, (8,)).astype(np.float32)
    got = F.binary_cross_entropy_with_logits(jnp.asarray(logits), jnp.asarray(labels))
    want = torch.nn.functional.binary_cross_entropy_with_logits(
        torch.tensor(logits), torch.tensor(labels))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_mha_causal_matches_manual():
    mha = nn.MultiHeadAttention(16, 2).eval()
    x = jnp.asarray(np.random.RandomState(0).randn(1, 6, 16).astype(np.float32))
    out = mha(x, is_causal=True)
    assert out.shape == (1, 6, 16)
    # causal: changing future tokens must not affect past outputs
    x2 = x.at[:, -1].set(99.0)
    out2 = mha(x2, is_causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :5]), np.asarray(out2[:, :5]), atol=1e-5)


def test_mha_gqa():
    mha = nn.MultiHeadAttention(16, 4, num_kv_heads=2).eval()
    x = jnp.ones((2, 5, 16))
    assert mha(x).shape == (2, 5, 16)


def test_rnn_shapes_and_grad():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = jnp.ones((2, 6, 4))
    out, states = lstm(x)
    assert out.shape == (2, 6, 8)

    def loss(m, x):
        return jnp.sum(m(x)[0] ** 2)

    _, g = pt.value_and_grad(loss)(lstm, x)
    gl = [l for l in jax.tree_util.tree_leaves(g) if l is not None]
    assert all(np.isfinite(np.asarray(l)).all() for l in gl)


def test_gru_matches_torch():
    import torch
    gru = nn.GRU(3, 5)
    cell = gru.cells[0]
    x = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
    tg = torch.nn.GRU(3, 5, batch_first=True)
    with torch.no_grad():
        tg.weight_ih_l0.copy_(torch.tensor(np.asarray(cell.weight_ih).T))
        tg.weight_hh_l0.copy_(torch.tensor(np.asarray(cell.weight_hh).T))
        tg.bias_ih_l0.copy_(torch.tensor(np.asarray(cell.bias_ih)))
        tg.bias_hh_l0.copy_(torch.tensor(np.asarray(cell.bias_hh)))
        want, _ = tg(torch.tensor(x))
    got, _ = gru(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-4, atol=1e-5)


def test_sequential_and_state_dict():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = m.state_dict()
    assert len(sd) == 4
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(sd)
    x = jnp.ones((1, 4))
    np.testing.assert_allclose(np.asarray(m(x)), np.asarray(m2(x)), rtol=1e-6)


def test_transformer_encoder_decoder():
    enc = nn.TransformerEncoder(lambda: nn.TransformerEncoderLayer(16, 2, 32), 2).eval()
    x = jnp.ones((2, 5, 16))
    assert enc(x).shape == (2, 5, 16)


def test_initializers():
    import paddle_tpu.nn.initializer as I
    w = I.XavierUniform()((100, 100))
    fan = 100
    limit = np.sqrt(6.0 / (fan + fan))
    assert float(jnp.max(jnp.abs(w))) <= limit + 1e-6
    k = I.KaimingNormal()((64, 64))
    assert 0.05 < float(jnp.std(k)) < 0.35
    c = I.Constant(3.0)((4,))
    np.testing.assert_allclose(np.asarray(c), 3.0)


def test_interpolate_modes():
    x = jnp.ones((1, 2, 4, 4))
    assert F.interpolate(x, scale_factor=2, mode="nearest").shape == (1, 2, 8, 8)
    assert F.interpolate(x, size=(6, 6), mode="bilinear").shape == (1, 2, 6, 6)
    assert F.pixel_shuffle(jnp.ones((1, 8, 2, 2)), 2).shape == (1, 2, 4, 4)
