"""Fused ragged chunk attention (ISSUE 11): interpret-Pallas vs XLA
gather parity over GQA/MHA, mid-block offsets, degenerate chunk_lens,
sliding windows, and OOB-sentinel table slots; the cached per-process
Pallas fallback (counter + single warning, no silent per-call retry);
the PT_PAGED_CHUNK kill switch actually changing the traced path only
through ``clear_jit_caches``; and engine-level greedy identity with the
kernel on, off, and interpreted — incl. spec decode, chunked prefill,
and preempt-replay."""
import warnings

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import clear_jit_caches
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS


@pytest.fixture(autouse=True)
def _fresh_jits():
    # PT_PAGED_CHUNK is read at trace time: tests that flip it must not
    # inherit (or leak) traced programs keyed on another test's mode
    clear_jit_caches()
    yield
    clear_jit_caches()


# ------------------------------------------------------------ parity

def _ragged_case(rng, a, c, h, h_kv, d, bs, mb, n, offs, cls):
    """Pool with garbage everywhere, distinct permuted live blocks per
    row, sentinel (= n) padding on unused table slots."""
    q = jnp.asarray(rng.normal(size=(a, c, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n, bs, h_kv, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n, bs, h_kv, d)), jnp.float32)
    tables = np.full((a, mb), n, np.int32)
    offs = np.asarray(offs, np.int32)
    cls = np.asarray(cls, np.int32)
    for i in range(a):
        need = -(-int(offs[i] + cls[i]) // bs)
        tables[i, :need] = rng.choice(n, size=need, replace=False)
    return q, kp, vp, jnp.asarray(tables), offs, cls


def _assert_live_parity(out_p, out_x, cls, tol=2e-5):
    # dead rows diverge by design (kernel emits 0, the dense path a
    # uniform average over fully-masked logits) — compare live rows only
    for i, cl in enumerate(np.asarray(cls)):
        cl = int(cl)
        if cl == 0:
            assert np.allclose(np.asarray(out_p)[i], 0.0)
            continue
        err = np.abs(np.asarray(out_p)[i, :cl]
                     - np.asarray(out_x)[i, :cl]).max()
        assert err < tol, f"row {i}: {err}"


@pytest.mark.parametrize("h,h_kv", [(8, 2), (4, 4)])
def test_chunk_parity_ragged(h, h_kv):
    """GQA and MHA over mid-block offsets with chunk_lens 0 and 1."""
    rng = np.random.default_rng(0)
    case = _ragged_case(rng, 4, 6, h, h_kv, 16, 8, 6, 32,
                        offs=[0, 5, 13, 3], cls=[6, 1, 0, 4])
    q, kp, vp, tables, offs, cls = case
    out_p = pa.paged_chunk_attention_pallas(q, kp, vp, tables, offs, cls,
                                            interpret=True)
    out_x = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    _assert_live_parity(out_p, out_x, cls)


def test_chunk_parity_sliding_window():
    rng = np.random.default_rng(1)
    q, kp, vp, tables, offs, cls = _ragged_case(
        rng, 3, 7, 8, 4, 16, 8, 8, 40, offs=[20, 0, 37], cls=[7, 7, 5])
    out_p = pa.paged_chunk_attention_pallas(q, kp, vp, tables, offs, cls,
                                            window=10, interpret=True)
    out_x = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls,
                                         window=10)
    _assert_live_parity(out_p, out_x, cls)


def test_chunk_parity_multi_tile_with_padding():
    """cg = 13*3 = 39 folded rows at q_tile=16 → a 3-tile grid with 9
    padding rows in the last tile."""
    rng = np.random.default_rng(2)
    q, kp, vp, tables, offs, cls = _ragged_case(
        rng, 2, 13, 6, 2, 16, 8, 9, 40, offs=[7, 22], cls=[13, 9])
    out_p = pa.paged_chunk_attention_pallas(q, kp, vp, tables, offs, cls,
                                            q_tile=16, interpret=True)
    out_x = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    _assert_live_parity(out_p, out_x, cls)


def test_chunk_parity_verify_shape():
    """The spec-verify batch shape: C = k+1 queries appended at a deep
    offset, every row a different live length."""
    rng = np.random.default_rng(3)
    q, kp, vp, tables, offs, cls = _ragged_case(
        rng, 4, 5, 8, 2, 32, 8, 10, 48, offs=[17, 40, 0, 63],
        cls=[5, 5, 5, 5])
    out_p = pa.paged_chunk_attention_pallas(q, kp, vp, tables, offs, cls,
                                            interpret=True)
    out_x = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    _assert_live_parity(out_p, out_x, cls)


# ----------------------------------------------- dispatch + fallback

def test_dispatch_kill_switch_forces_xla(monkeypatch):
    """PT_PAGED_CHUNK=0 must route to the gather path and leave a
    breadcrumb, never touching the Pallas wrapper."""
    monkeypatch.setenv("PT_PAGED_CHUNK", "0")
    monkeypatch.setattr(pa, "paged_chunk_attention_pallas",
                        lambda *a, **k: pytest.fail("pallas path taken"))
    rng = np.random.default_rng(4)
    q, kp, vp, tables, offs, cls = _ragged_case(
        rng, 2, 4, 4, 2, 16, 8, 4, 16, offs=[0, 9], cls=[4, 3])
    pa._trace_events.clear()
    out = pa.paged_chunk_attention(q, kp, vp, tables, offs, cls)
    ref = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    assert np.array_equal(np.asarray(out), np.asarray(ref))
    assert "chunk:xla-forced" in pa._trace_events


def test_dispatch_interpret_mode(monkeypatch):
    monkeypatch.setenv("PT_PAGED_CHUNK", "interpret")
    rng = np.random.default_rng(5)
    q, kp, vp, tables, offs, cls = _ragged_case(
        rng, 2, 4, 4, 2, 16, 8, 4, 16, offs=[0, 9], cls=[4, 3])
    pa._trace_events.clear()
    out = pa.paged_chunk_attention(q, kp, vp, tables, offs, cls)
    ref = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    _assert_live_parity(out, ref, cls)
    assert "chunk:pallas-interpret" in pa._trace_events


@pytest.mark.parametrize("kernel", ["decode", "chunk"])
def test_pallas_failure_cached_per_process(monkeypatch, kernel):
    """A Pallas trace failure must warn ONCE, bump the fallback counter,
    and pin the process to the XLA path — no silent per-call retry."""
    monkeypatch.setattr(pa, "_pallas_disabled", {})
    monkeypatch.setattr(pa.jax, "default_backend", lambda: "tpu")
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise RuntimeError("mosaic says no")

    rng = np.random.default_rng(6)
    if kernel == "chunk":
        monkeypatch.setattr(pa, "paged_chunk_attention_pallas", boom)
        q, kp, vp, tables, offs, cls = _ragged_case(
            rng, 2, 4, 4, 2, 16, 8, 4, 16, offs=[0, 9], cls=[4, 3])
        call = lambda: pa.paged_chunk_attention(q, kp, vp, tables, offs,
                                                cls)
        ref = pa.paged_chunk_attention_xla(q, kp, vp, tables, offs, cls)
    else:
        monkeypatch.setattr(pa, "paged_decode_attention_pallas", boom)
        q = jnp.asarray(rng.normal(size=(2, 4, 16)), jnp.float32)
        kp = jnp.asarray(rng.normal(size=(16, 8, 2, 16)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(16, 8, 2, 16)), jnp.float32)
        tables = jnp.asarray([[0, 1, 16, 16], [2, 3, 16, 16]], jnp.int32)
        lens = jnp.asarray([10, 13], jnp.int32)
        call = lambda: pa.paged_decode_attention(q, kp, vp, tables, lens)
        ref = pa.paged_decode_attention_xla(q, kp, vp, tables, lens)

    c0 = pa._PALLAS_FALLBACK.value(kernel=kernel)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out1 = call()
        out2 = call()
    assert len(calls) == 1, "fallback decision not cached"
    assert kernel in pa._pallas_disabled
    assert pa._PALLAS_FALLBACK.value(kernel=kernel) == c0 + 1
    warned = [x for x in w if "Pallas kernel failed" in str(x.message)]
    assert len(warned) == 1
    for out in (out1, out2):
        assert np.array_equal(np.asarray(out), np.asarray(ref))


# --------------------------------------- traced-path flip via jit cache

def _eng_kw(**kw):
    base = dict(num_slots=4, block_size=8, max_prompt_len=8,
                max_seq_len=64)
    base.update(kw)
    return base


def _run(eng, prompts, max_new=8, **kw):
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new, **kw))
    return {r: list(map(int, t)) for r, t in eng.run().items()}


def _prompts(n, rs, lo=12, hi=24):
    # longer than max_prompt_len=8: every prompt takes the chunk program
    return [rs.randint(0, 64, (int(l),))
            for l in rs.randint(lo, hi, size=n)]


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def test_env_flip_needs_clear_jit_caches(model, monkeypatch):
    """PT_PAGED_CHUNK is read when the chunk program TRACES: flipping it
    mid-process changes nothing until ``clear_jit_caches`` drops the
    traced programs, after which the new mode's path is taken."""
    rs = np.random.RandomState(7)
    prompts = _prompts(2, rs)
    pa._trace_events.clear()
    _run(LLMEngine(model, **_eng_kw()), prompts)
    assert "chunk:xla" in pa._trace_events          # CPU default path

    monkeypatch.setenv("PT_PAGED_CHUNK", "interpret")
    pa._trace_events.clear()
    _run(LLMEngine(model, **_eng_kw()), prompts)
    # same shapes -> jit cache hit -> the dispatch never re-ran
    assert "chunk:pallas-interpret" not in pa._trace_events

    clear_jit_caches()
    pa._trace_events.clear()
    _run(LLMEngine(model, **_eng_kw()), prompts)
    assert "chunk:pallas-interpret" in pa._trace_events


# --------------------------------------------- engine greedy identity

@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_engine_identity_chunked_prefill(model, monkeypatch, mode):
    rs = np.random.RandomState(8)
    prompts = _prompts(5, rs)
    base = _run(LLMEngine(model, **_eng_kw()), prompts)
    monkeypatch.setenv("PT_PAGED_CHUNK", mode)
    clear_jit_caches()
    assert _run(LLMEngine(model, **_eng_kw()), prompts) == base


@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_engine_identity_spec_decode(model, monkeypatch, mode):
    """Spec verify rides the same chunk program — identity must hold
    with a draft in the loop (draft == target: the all-accept extreme)."""
    rs = np.random.RandomState(9)
    prompts = _prompts(4, rs)
    kw = _eng_kw(draft_model=model)
    base = _run(LLMEngine(model, **kw), prompts)
    monkeypatch.setenv("PT_PAGED_CHUNK", mode)
    clear_jit_caches()
    assert _run(LLMEngine(model, **kw), prompts) == base


def test_engine_identity_preempt_replay_interpret(model, monkeypatch):
    """Interpreted kernel under preemption chaos: replay re-prefills
    through the chunk program and must still match the baseline."""
    rs = np.random.RandomState(10)
    prompts = _prompts(4, rs, lo=10, hi=18)
    kw = _eng_kw(num_blocks=24, preemption=True)
    base = _run(LLMEngine(model, **kw), prompts)
    monkeypatch.setenv("PT_PAGED_CHUNK", "interpret")
    clear_jit_caches()
    FAULTS.install("serving.preempt", every=3, times=4,
                   action=lambda ctx: ctx["engine"]._preempt())
    try:
        out = _run(LLMEngine(model, **kw), prompts)
    finally:
        FAULTS.clear()
    assert out == base
