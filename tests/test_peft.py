"""LoRA (ref: paddlenlp.peft LoRAModel): functional adapter tree merged
into the base inside the jitted loss — base frozen, adapters trainable,
zero-init equivalence, deployment merge, checkpoint round-trip."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.peft import (lora_init, lora_load_state_dict, lora_merge,
                             lora_num_parameters, lora_state_dict,
                             lora_targets)


def _model():
    pt.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, vocab_size=64))


def test_zero_init_is_identity_and_targets():
    m = _model()
    tg = lora_targets(m)
    assert any("qkv_proj" in t for t in tg)
    assert any("o_proj" in t for t in tg)
    lora = lora_init(m, jax.random.PRNGKey(0), r=4)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (2, 8)))
    np.testing.assert_allclose(np.asarray(lora_merge(m, lora)(ids)),
                               np.asarray(m(ids)), rtol=1e-6, atol=1e-6)
    # rank-r adapters are a tiny fraction of the base
    assert lora_num_parameters(lora) < 0.2 * m.num_parameters()


def test_lora_training_moves_only_adapters():
    m = _model()
    lora = lora_init(m, jax.random.PRNGKey(1), r=4)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, 64, (4, 8)))
    labels = jnp.asarray(rs.randint(0, 64, (4, 8)))
    base_before = jax.tree_util.tree_leaves(m)

    @jax.jit
    def loss_fn(lora):
        return lora_merge(m, lora).loss(ids, labels)

    l0 = float(loss_fn(lora))
    g = jax.grad(loss_fn)(lora)
    # scale is a hyperparameter, not trained
    lora = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, lora, g)
    l1 = float(loss_fn(lora))
    assert l1 < l0, (l0, l1)
    for a, b in zip(base_before, jax.tree_util.tree_leaves(m)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_merge_and_checkpoint_roundtrip():
    m = _model()
    lora = lora_init(m, jax.random.PRNGKey(2), r=4)
    # make the adapters non-trivial
    lora = jax.tree_util.tree_map(
        lambda p: p + 0.01 if p.ndim == 2 else p, lora)
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 64, (1, 8)))
    merged = lora_merge(m, lora)
    ref = np.asarray(merged(ids))
    assert np.abs(ref - np.asarray(m(ids))).max() > 1e-5  # really adapted
    sd = lora_state_dict(lora)
    lora2 = lora_load_state_dict(lora_init(m, jax.random.PRNGKey(9), r=4),
                                 sd)
    np.testing.assert_allclose(np.asarray(lora_merge(m, lora2)(ids)), ref,
                               rtol=1e-6, atol=1e-6)


def test_make_lora_train_step_with_adamw():
    from paddle_tpu.optimizer import AdamW
    m = _model()
    lora = lora_init(m, jax.random.PRNGKey(3), r=4)
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 64, (4, 8)))
    labels = jnp.asarray(rs.randint(0, 64, (4, 8)))

    from paddle_tpu.peft import make_lora_train_step
    step, adapters, opt_state = make_lora_train_step(
        m, lora, AdamW(learning_rate=1e-2),
        lambda mm, x, y: mm.loss(x, y))
    losses = []
    for _ in range(5):
        adapters, opt_state, loss = step(adapters, opt_state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # the full tree flows out: _scale rides along untouched (no weight
    # decay), and the trained tree works with the other peft helpers
    assert float(adapters["_scale"]) == float(lora["_scale"])
    lora_merge(m, adapters)(ids)
    rt = lora_load_state_dict(adapters, lora_state_dict(adapters))
    assert float(rt["_scale"]) == float(lora["_scale"])
    # the caller's ORIGINAL tree survived the donating loop
    lora_merge(m, lora)(ids)
