"""REAL multi-host exercise (VERDICT r1 missing #6): fork two processes
that bring up jax.distributed over the CPU backend via
``paddle_tpu.distributed.launch`` and run collectives, object gathers,
per-host data sharding, token-bin stream sharding, and a coordinated
checkpoint against each other. See tests/_multihost_child.py."""
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_multihost(tmp_path):
    port = _free_port()
    child = os.path.join(os.path.dirname(__file__), "_multihost_child.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(child)))
    procs = []
    for pid in range(2):
        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": repo,
            "JAX_PLATFORMS": "cpu",
            # the launch.py env contract
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(pid),
            "MULTIHOST_SHARED_DIR": str(tmp_path),
        }
        procs.append(subprocess.Popen(
            [sys.executable, child], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost child timed out (coordination deadlock?)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "MULTIHOST_OK" in out, out
