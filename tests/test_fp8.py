"""fp8 training path with delayed scaling (VERDICT r1 missing #5):
quantized matmul numerics, overwrite-with-gradient meta plumbing through
the optimizer, and tiny-scale LLaMA loss parity vs the bf16/f32 path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.amp.fp8 import Fp8Linear, fp8_matmul, new_fp8_meta


def test_fp8_matmul_close_to_fp32():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rs.randn(16, 4).astype(np.float32))
    meta = new_fp8_meta()
    y = fp8_matmul(x, w, meta)
    ref = x @ w
    # e4m3 has ~2 mantissa-ish bits of relative precision
    err = np.abs(np.asarray(y) - np.asarray(ref)).max()
    assert err < 0.35 * np.abs(np.asarray(ref)).max(), err
    # with a calibrated history (scale amplifies small values) it tightens
    meta2 = dict(meta)
    meta2["amax_x"] = meta["amax_x"].at[0].set(jnp.abs(x).max())
    meta2["amax_w"] = meta["amax_w"].at[0].set(jnp.abs(w).max())
    y2 = fp8_matmul(x, w, meta2)
    err2 = np.abs(np.asarray(y2) - np.asarray(ref)).max()
    assert err2 <= err + 1e-6


def test_fp8_matmul_grads_and_meta_cotangent():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(8, 3).astype(np.float32))
    meta = new_fp8_meta()

    def loss(x, w, meta):
        return jnp.sum(fp8_matmul(x, w, meta) ** 2)

    (dx, dw, dmeta) = jax.grad(loss, argnums=(0, 1, 2))(x, w, meta)
    rx, rw = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                      argnums=(0, 1))(x, w)
    # quantized grads approximate the fp32 ones
    assert np.abs(np.asarray(dx) - np.asarray(rx)).max() < \
        0.35 * np.abs(np.asarray(rx)).max()
    assert np.abs(np.asarray(dw) - np.asarray(rw)).max() < \
        0.35 * np.abs(np.asarray(rw)).max()
    # the meta "gradient" is the UPDATED meta: history rolled with amaxes
    np.testing.assert_allclose(float(dmeta["amax_x"][0]),
                               float(jnp.abs(x).max()), rtol=1e-6)
    np.testing.assert_allclose(float(dmeta["amax_w"][0]),
                               float(jnp.abs(w).max()), rtol=1e-6)
    assert float(dmeta["amax_g"][0]) > 0


def test_fp8_linear_optimizer_overwrites_meta():
    """The optimizer must OVERWRITE fp8_meta leaves with their 'gradient'
    (new value), not apply the update rule, and must exclude them from
    global-norm clipping."""
    pt.seed(0)
    layer = Fp8Linear(8, 4, dtype=jnp.float32)
    o = opt.SGD(learning_rate=0.1,
                grad_clip=opt.ClipGradByGlobalNorm(1e-6))  # brutal clip
    state = o.init(layer)
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(16, 8).astype(np.float32))

    def loss_fn(m, x):
        return jnp.mean(m(x) ** 2)

    step = jax.jit(lambda m, x, s: (
        lambda g: o.step(m, g, s))(jax.grad(loss_fn)(m, x)))
    new_layer, state = step(layer, x, state)
    # meta overwritten with the rolled amax history — NOT scaled by the
    # clip (1e-6 would crush it) nor by lr
    np.testing.assert_allclose(float(new_layer.fp8_meta["amax_x"][0]),
                               float(jnp.abs(x).max()), rtol=1e-6)
    # weights DID get the clipped update (clip worked on real grads)
    w_delta = np.abs(np.asarray(new_layer.weight - layer.weight)).max()
    assert 0 < w_delta < 1e-5  # crushed by the 1e-6 norm clip


def test_fp8_llama_loss_parity_tiny():
    """cfg.fp8=True trains within tolerance of the fp32 tiny model."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    losses = {}
    for fp8 in (False, True):
        pt.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                               num_attention_heads=4, num_key_value_heads=2,
                               vocab_size=64, fp8=fp8)
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3)
        state = init_state(model, optimizer)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, 64, (4, 16)))
        labels = jnp.concatenate(
            [ids[:, 1:], -100 * jnp.ones((4, 1), ids.dtype)], axis=1)
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
        trace = []
        for _ in range(6):
            state, loss = step(state, ids, labels)
            trace.append(float(loss))
        losses[fp8] = trace
    # both train (loss decreases) and fp8 tracks fp32 loosely
    assert losses[True][-1] < losses[True][0]
    for a, b in zip(losses[False], losses[True]):
        assert abs(a - b) < 0.15 * abs(a) + 0.05, (losses[False], losses[True])
