"""Ring attention == full attention; pipeline == sequential; MoE dispatch
conservation (SURVEY.md §4)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from paddle_tpu.distributed._compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.distributed.moe import MoELayer, top_k_gate
from paddle_tpu.distributed.pipeline import PipelineLayer, stack_layers
from paddle_tpu.distributed.ring_attention import make_ring_attention, ring_attention
from paddle_tpu.ops.attention import xla_attention


@pytest.mark.parametrize(
    "causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_attention_matches_full(causal):
    b, s, h, d = 2, 32, 2, 8
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=causal)
    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=causal)
        out = attend(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_grad_matches_full():
    b, s, h, d = 1, 16, 2, 4
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))

    ref_grads = jax.grad(lambda q, k, v: jnp.sum(xla_attention(q, k, v, is_causal=True) ** 2),
                         argnums=(0, 1, 2))(q, k, v)
    mesh = HybridMesh(sp=8)
    with mesh:
        attend = make_ring_attention(mesh, causal=True)
        got_grads = jax.grad(lambda q, k, v: jnp.sum(attend(q, k, v) ** 2),
                             argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_got in zip(ref_grads, got_grads):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref), rtol=5e-4, atol=5e-5)


def _mlp_block(width):
    return nn.Sequential(nn.Linear(width, width * 2), nn.GELU(), nn.Linear(width * 2, width))


def test_pipeline_matches_sequential():
    pt.seed(0)
    width = 16
    blocks = [_mlp_block(width) for _ in range(8)]
    x = jnp.asarray(np.random.RandomState(0).randn(8, width).astype(np.float32))

    ref = x
    for blk in blocks:
        ref = blk(ref)

    pipe = PipelineLayer(blocks, num_stages=4, num_microbatches=4)
    # no-mesh path (plain scan)
    out0 = pipe(x)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(ref), rtol=1e-4, atol=1e-5)

    mesh = HybridMesh(pp=4, devices=jax.devices()[:4])
    out = pipe(x, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_pipeline_grad_matches_sequential():
    pt.seed(0)
    width = 8
    blocks = [_mlp_block(width) for _ in range(4)]
    x = jnp.asarray(np.random.RandomState(0).randn(4, width).astype(np.float32))

    def seq_loss(stacked, x):
        pipe = PipelineLayer.__new__(PipelineLayer)  # reuse scan path via stacked tree
        from jax import lax
        def body(h, lyr):
            return lyr(h), None
        out, _ = lax.scan(body, x, stacked)
        return jnp.sum(out ** 2)

    stacked = stack_layers(blocks)
    ref_grad = jax.grad(seq_loss)(stacked, x)

    mesh = HybridMesh(pp=4, devices=jax.devices()[:4])
    pipe = PipelineLayer(blocks, num_stages=4, num_microbatches=2)

    def pipe_loss(stacked_params, x):
        p2 = PipelineLayer.__new__(PipelineLayer)
        object.__setattr__(p2, "_buffers", set()); object.__setattr__(p2, "_pspecs", {})
        object.__setattr__(p2, "_dyn_names", set()); object.__setattr__(p2, "training", True)
        p2.stacked = stacked_params
        p2.num_stages = 4; p2.num_microbatches = 2
        p2.layers_per_stage = 1; p2.n_layers = 4; p2.remat = True
        p2.template = blocks[0]
        return jnp.sum(p2(x, mesh=mesh) ** 2)

    got_grad = jax.jit(jax.grad(pipe_loss))(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grad), jax.tree_util.tree_leaves(got_grad)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=2e-3, atol=1e-4)


def test_top_k_gate_conservation():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(64, 8).astype(np.float32))
    dispatch, combine, aux = top_k_gate(logits, k=2, capacity=16)
    # each token lands in at most k slots; each (expert, slot) used at most once
    per_slot = np.asarray(dispatch).sum(axis=0).reshape(-1)
    assert per_slot.max() <= 1
    per_token = np.asarray(dispatch).sum(axis=(1, 2))
    assert per_token.max() <= 2
    # combine weights for a routed token sum to ~1 (both choices kept)
    cw = np.asarray(combine).sum(axis=(1, 2))
    routed = per_token == 2
    np.testing.assert_allclose(cw[routed], 1.0, rtol=1e-5)
    assert float(aux) > 0


def test_moe_layer_forward_backward():
    pt.seed(0)
    moe = MoELayer(hidden=16, intermediate=32, num_experts=4, k=2)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
    y, aux = moe(x)
    assert y.shape == x.shape
    def loss(m, x):
        y, aux = m(x)
        return jnp.mean(y ** 2) + 0.01 * aux
    lv, grads = pt.value_and_grad(loss)(moe, x)
    leaves = [l for l in jax.tree_util.tree_leaves(grads) if l is not None]
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    # expert weights get gradient (tokens actually routed)
    assert float(jnp.abs(grads.experts.gate_up).max()) > 0


def test_moe_expert_parallel_matches_single():
    pt.seed(0)
    moe = MoELayer(hidden=16, intermediate=32, num_experts=8, k=2)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 8, 16).astype(np.float32))
    ref, _ = moe(x)
    mesh = HybridMesh(dp=2, fsdp=4)
    from paddle_tpu.distributed import shard_module
    with mesh:
        moe_s = shard_module(moe, mesh, min_size=1)
        xs = jax.device_put(x, mesh.batch_sharding())
        out, _ = jax.jit(lambda m, v: m(v))(moe_s, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_ring_attention_matches_full():
    """Zigzag layout + ring == full causal attention (after inverse perm)."""
    import numpy as np
    import jax, jax.numpy as jnp
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.distributed.ring_attention import (
        zigzag_inverse_permutation, zigzag_permutation, zigzag_ring_attention)
    from paddle_tpu.ops.attention import xla_attention

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 2, 32, 2, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)

    ref = np.asarray(xla_attention(q, k, v, is_causal=True))

    perm = zigzag_permutation(S, 4)
    inv = zigzag_inverse_permutation(S, 4)
    qz, kz, vz = q[:, perm], k[:, perm], v[:, perm]

    spec = P(None, "sp", None, None)
    attend = shard_map(
        lambda a, b, c: zigzag_ring_attention(a, b, c, axis_name="sp"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = np.asarray(jax.jit(attend)(qz, kz, vz))[:, inv]
    np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)


def test_zigzag_permutation_roundtrip():
    import numpy as np
    from paddle_tpu.distributed.ring_attention import (
        zigzag_inverse_permutation, zigzag_permutation)
    perm = zigzag_permutation(24, 3)
    inv = zigzag_inverse_permutation(24, 3)
    x = np.arange(24)
    np.testing.assert_array_equal(x[perm][inv], x)
    # rank 0 holds chunks 0 and 5 (of 6): first local half is 0..3
    np.testing.assert_array_equal(perm[:4], [0, 1, 2, 3])
    np.testing.assert_array_equal(perm[4:8], [20, 21, 22, 23])
