"""Quantization: fake-quant STE, int8 linear accuracy, QAT/PTQ passes."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.quantization as Q


def test_fake_quant_values_and_ste():
    x = jnp.asarray([-1.5, -0.5, 0.0, 0.4, 0.9, 2.0])
    scale = jnp.asarray(1.0)
    y = Q.fake_quant(x, scale)
    # values snap to the 127-level grid, clipped to [-128/127, 1]
    assert np.allclose(np.asarray(y),
                       np.clip(np.round(np.asarray(x) * 127) / 127,
                               -128 / 127, 1.0), atol=1e-6)
    g = jax.grad(lambda x: Q.fake_quant(x, scale).sum())(x)
    # STE passes grad where |x/scale| <= 1, blocks outside
    assert np.allclose(np.asarray(g), [0, 1, 1, 1, 1, 0])


def test_quantize_weight_roundtrip():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    q, scale = Q.quantize_weight(w, axis=1)
    assert q.dtype == jnp.int8 and scale.shape == (1, 16)
    back = Q.dequantize(q, scale)
    assert float(jnp.abs(back - w).max()) < float(jnp.abs(w).max()) / 100


def test_quantized_linear_close_to_fp():
    pt.seed(0)
    lin = nn.Linear(64, 32, dtype=jnp.float32)
    qlin = Q.quant_linear(lin)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 64).astype(np.float32))
    want = np.asarray(lin(x))
    got = np.asarray(qlin(x))
    rel = np.abs(got - want).mean() / np.abs(want).mean()
    assert rel < 0.02, rel  # int8 dynamic quant ~1% mean error


def test_qat_trains_and_ptq_converts():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    x = jnp.asarray(np.random.RandomState(2).randn(8, 16).astype(np.float32))
    y = jnp.asarray(np.arange(8) % 4)

    qat_model = Q.QAT().quantize(model)
    assert isinstance(qat_model.layers[0], Q.QATLinear)

    def loss_fn(m):
        return nn.functional.cross_entropy(m(x), y)

    from paddle_tpu.core.module import combine, partition_trainable
    params, skel = partition_trainable(qat_model)
    l0 = float(loss_fn(qat_model))
    import paddle_tpu.optimizer as opt
    optimizer = opt.SGD(learning_rate=0.1)
    state = optimizer.init(params)
    for _ in range(5):
        g = jax.grad(lambda p: loss_fn(combine(p, skel)))(params)
        params, state = optimizer.step(params, g, state)
    l1 = float(loss_fn(combine(params, skel)))
    assert l1 < l0  # STE gradients actually train through fake-quant

    ptq_model = Q.PTQ().quantize(model)
    assert isinstance(ptq_model.layers[0], Q.QuantizedLinear)
    out = ptq_model(x)
    assert out.shape == (8, 4) and bool(jnp.isfinite(out).all())


def test_absmax_observer():
    obs = Q.AbsmaxObserver(momentum=0.5)
    obs.observe(jnp.asarray([1.0, -2.0]))
    assert obs.scale == 2.0
    obs.observe(jnp.asarray([4.0]))
    assert np.isclose(obs.scale, 3.0)  # 0.5*2 + 0.5*4
