"""Regressions for the round-4 advisor findings on the serving engine.

* chunked-prefill livelock: two long prompts mid-prefill on a dry pool
  (preemption=True) used to spin forever — prefilling slots are inactive
  and were invisible to _preempt. Now a prefilling request is evictable
  (it re-queues and replays its chunks), so the engine drains and every
  output still equals solo greedy.
* a pool that cannot fit ONE chunk of the sole remaining request raises
  MemoryError instead of spinning.
* windowed growth under preemption: the reservation guard must count
  table POSITIONS (None placeholders from window recycling included),
  not live blocks — the live-only count inflated `need` without bound
  and preempted/crashed healthy long generations.
* RefBlockManager.fork is exception-atomic: a fork that fails for the
  partial-block copy leaves every refcount untouched (callers retry
  after preempting; a leaked retain would shrink the pool forever).

Ref capability: PaddleNLP llm/predict block-attention serving recompute
preemption (vLLM-style), under chunked prefill.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import RefBlockManager
from paddle_tpu.serving import LLMEngine, Request


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _solo(model, p, n):
    return np.asarray(generate(model, jnp.asarray(np.asarray(p)[None]),
                               max_new_tokens=n))[0, len(p):]


def test_chunked_prefill_livelock_drains(model):
    """The advisor's repro: num_blocks=8, block_size=4, max_prompt_len=8,
    two 24-token prompts. Both admit optimistically, chunk-prefill until
    the pool runs dry with NO active decode slot; progress now comes from
    evicting the younger prefilling request."""
    rs = np.random.RandomState(11)
    p1 = rs.randint(0, 64, (24,))
    p2 = rs.randint(0, 64, (24,))
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32, num_blocks=8, preemption=True,
                    prefix_caching=False)
    r1 = eng.add_request(Request(p1, max_new_tokens=4))
    r2 = eng.add_request(Request(p2, max_new_tokens=4))
    for _ in range(300):
        eng.step()
        if not eng.has_work():
            break
    assert not eng.has_work(), "engine did not drain (livelock)"
    assert eng.stats["preemptions"] >= 1
    out = {rid: np.asarray(r.tokens) for rid, r in eng.requests.items()}
    np.testing.assert_array_equal(out[r1], _solo(model, p1, 4))
    np.testing.assert_array_equal(out[r2], _solo(model, p2, 4))


def test_request_bigger_than_pool_refused_at_add(model):
    """A request whose worst case can NEVER fit the pool finishes
    immediately with finish_reason="too_long" (it must not wedge the FCFS
    head waiting for capacity that cannot exist) — the in-engine
    no-progress MemoryError backstop stays as defense-in-depth behind
    this gate."""
    rs = np.random.RandomState(12)
    p = rs.randint(0, 64, (24,))
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32, num_blocks=2, preemption=True,
                    prefix_caching=False)
    req = Request(p, max_new_tokens=4)
    rid = eng.add_request(req)
    assert req.done and req.finish_reason == "too_long"
    assert eng.stats["rejected"] == 1
    res = eng.run()
    assert res[rid] == []


def test_windowed_growth_preemption_no_storm(model):
    """A windowed sequence generating far past its window holds O(window)
    live blocks but a long table of None placeholders; growth must not
    spuriously preempt (there is only one request — a 'preemption' here
    would be the self-eviction crash path)."""
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64, sliding_window=8)
    wmodel = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(13)
    p = rs.randint(0, 64, (6,))
    eng = LLMEngine(wmodel, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=64, num_blocks=6, preemption=True)
    rid = eng.add_request(Request(p, max_new_tokens=40))
    res = eng.run()
    assert eng.stats["preemptions"] == 0
    assert len(res[rid]) == 40


def test_fork_failure_leaks_no_refcounts():
    mgr = RefBlockManager(num_blocks=3, block_size=4)
    mgr.allocate(1, 10)                     # 3 blocks, last one partial
    assert mgr.free_blocks == 0
    with pytest.raises(MemoryError):
        mgr.fork(1, 2, 10)                  # partial copy needs a block
    mgr.free(1)
    assert mgr.free_blocks == 3, "failed fork leaked refcounts"
    assert 2 not in mgr.tables
