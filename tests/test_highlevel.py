"""hapi Model, metrics, regularizer, scan-layers LLaMA (SURVEY.md §2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.hapi import Model
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall, accuracy
from paddle_tpu.regularizer import L1Decay, L2Decay


def test_hapi_model_fit_eval_predict(tmp_path):
    pt.seed(0)
    import paddle_tpu.nn.functional as F
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
    model = Model(net)
    model.prepare(optimizer=opt.Adam(0.05),
                  loss=lambda logits, y: F.cross_entropy(logits, y),
                  metrics=[Accuracy()])
    rs = np.random.RandomState(0)
    X = rs.randn(64, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64) + (X[:, 1] > 0)
    data = [(X[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]
    hist = model.fit(data * 10, verbose=0)
    res = model.evaluate(data, verbose=0)
    assert res["eval_accuracy"] > 0.6
    preds = model.predict(data)
    assert preds[0].shape == (16, 3)
    model.save(tmp_path / "m")
    model.load(tmp_path / "m")


def test_metrics():
    acc = accuracy(np.asarray([[0.9, 0.1], [0.2, 0.8]]), np.asarray([0, 1]))
    assert acc == 1.0
    a5 = Accuracy(topk=(1, 2))
    a5.update(np.eye(3), np.asarray([0, 1, 0]))
    top1, top2 = a5.accumulate()
    assert 0 <= top1 <= top2 <= 1
    p = Precision(); p.update(np.asarray([0.9, 0.8, 0.2]), np.asarray([1, 0, 0]))
    assert p.accumulate() == 0.5
    r = Recall(); r.update(np.asarray([0.9, 0.1]), np.asarray([1, 1]))
    assert r.accumulate() == 0.5
    auc = Auc()
    rs = np.random.RandomState(0)
    scores = rs.rand(1000)
    labels = (scores + rs.randn(1000) * 0.3 > 0.5).astype(np.int64)
    auc.update(scores, labels)
    assert auc.accumulate() > 0.7


def test_chunk_evaluator():
    from paddle_tpu.metric import ChunkEvaluator
    # IOB, 2 chunk types: tag = type*2 + {0:B, 1:I}; O = 4
    ce = ChunkEvaluator(num_chunk_types=2, chunk_scheme="IOB")
    labels = np.asarray([[0, 1, 4, 2, 3, 4]])  # chunks: (0,1,t0), (3,4,t1)
    preds = np.asarray([[0, 1, 4, 2, 4, 4]])   # chunks: (0,1,t0), (3,3,t1)
    p, r, f1 = ce.update(preds, labels)
    assert p == 0.5 and r == 0.5 and abs(f1 - 0.5) < 1e-9
    # perfect second batch improves the running totals
    p, r, f1 = ce.update(labels, labels)
    assert p == 0.75 and r == 0.75
    # seq_lens truncation: trailing positions ignored
    ce2 = ChunkEvaluator(num_chunk_types=1)
    p, r, f1 = ce2.update(np.asarray([[0, 1, 0]]), np.asarray([[0, 1, 2]]),
                          seq_lens=[2])
    assert p == 1.0 and r == 1.0 and f1 == 1.0
    # IOBES single-token chunks
    ce3 = ChunkEvaluator(num_chunk_types=1, chunk_scheme="IOBES")
    p, r, f1 = ce3.update(np.asarray([[3, 4, 3]]), np.asarray([[3, 4, 3]]))
    assert (p, r, f1) == (1.0, 1.0, 1.0)


def test_edit_distance_metric():
    from paddle_tpu.metric import EditDistance
    ed = EditDistance(normalized=False)
    avg, err = ed.update([[1, 2, 3], [1, 2]], [[1, 2, 4], [1, 2]])
    assert avg == 0.5 and err == 0.5  # one sub in seq 1, exact seq 2
    ed_n = EditDistance(normalized=True)
    avg, err = ed_n.update(["kitten"], ["sitting"])
    assert abs(avg - 3 / 7) < 1e-9 and err == 1.0


def test_composite_metric():
    from paddle_tpu.metric import CompositeMetric, Precision, Recall
    cm = CompositeMetric(Precision(), Recall())
    cm.update(np.asarray([0.9, 0.8, 0.2]), np.asarray([1, 0, 0]))
    p, r = cm.accumulate()
    assert p == 0.5 and r == 1.0
    cm.reset()
    assert cm.accumulate() == [0.0, 0.0]


def test_regularizers():
    params = {"w": jnp.ones((2, 2)), "b": jnp.asarray([3.0])}
    np.testing.assert_allclose(float(L2Decay(1.0)(params)), 0.5 * (4 + 9), rtol=1e-6)
    np.testing.assert_allclose(float(L1Decay(1.0)(params)), 4 + 3, rtol=1e-6)


def test_llama_scan_layers_matches_loop():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg_loop = LlamaConfig.tiny()
    m_loop = LlamaForCausalLM(cfg_loop)
    pt.seed(0)
    cfg_scan = LlamaConfig.tiny(scan_layers=True)
    m_scan = LlamaForCausalLM(cfg_scan)
    # same seed -> same params; verify outputs agree
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg_loop.vocab_size, (2, 16)))
    out_a = m_loop(ids)
    out_b = m_scan(ids)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=2e-5, atol=2e-5)
    # and it trains
    labels = jnp.asarray(np.concatenate(
        [np.asarray(ids)[:, 1:], -100 * np.ones((2, 1), np.asarray(ids).dtype)], axis=1))
    loss, grads = pt.value_and_grad(lambda m: m.loss(ids, labels))(m_scan)
    assert np.isfinite(float(loss))
    stacked_grad = grads.model.layers_stacked.self_attn.qkv_proj
    assert stacked_grad.shape[0] == cfg_scan.num_hidden_layers


def test_static_shim_and_onnx_export(tmp_path):
    import numpy as np
    import jax.numpy as jnp
    import pytest as _pytest
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 3))
    p = pt.static.save_inference_model(
        str(tmp_path / "im"), [pt.static.InputSpec((None, 4))], model=net)
    f = pt.static.load_inference_model(p)
    assert f(jnp.ones((2, 4))).shape == (2, 3)
    with _pytest.raises(NotImplementedError):
        pt.static.Program()
    # onnx.export routes to the StableHLO artifact; .onnx path raises clearly
    p2 = pt.onnx.export(net, str(tmp_path / "m"),
                        input_spec=[pt.static.InputSpec((1, 4))])
    assert p2.endswith(".stablehlo")
    with _pytest.raises(NotImplementedError):
        pt.onnx.export(net, str(tmp_path / "m.onnx"),
                       input_spec=[pt.static.InputSpec((1, 4))])


def test_hub_local(tmp_path):
    import paddle_tpu as pt
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(scale=1):\n"
        "    '''a tiny test model'''\n"
        "    return {'scale': scale}\n")
    assert "tiny_model" in pt.hub.list(str(tmp_path))
    assert "tiny" in pt.hub.help(str(tmp_path), "tiny_model")
    assert pt.hub.load(str(tmp_path), "tiny_model", scale=3) == {"scale": 3}


def test_model_batch_level_api():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer=opt.Adam(learning_rate=0.01),
              loss=lambda out, y: nn.functional.cross_entropy(out, y))
    rs = np.random.RandomState(0)
    x = rs.randn(8, 4).astype(np.float32)
    y = rs.randint(0, 2, (8,))
    l0 = m.train_batch(x, y)[0]
    for _ in range(10):
        l1 = m.train_batch(x, y)[0]
    assert l1 < l0
    ev = m.eval_batch(x, y)
    assert np.isfinite(ev[0])
    pred = m.predict_batch(x)
    assert pred[0].shape == (8, 2)
    assert len(m.parameters()) == 4  # 2 weights + 2 biases


def test_eval_batch_runs_in_eval_mode():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Dropout(0.5), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.0),
              loss=lambda out, y: nn.functional.cross_entropy(out, y))
    rs = np.random.RandomState(0)
    x = rs.randn(4, 4).astype(np.float32)
    y = rs.randint(0, 2, (4,))
    # dropout off in eval: repeated eval losses identical
    l1 = m.eval_batch(x, y)[0]
    l2 = m.eval_batch(x, y)[0]
    assert l1 == l2
    p1 = m.predict_batch(x)[0]
    p2 = m.predict_batch(x)[0]
    np.testing.assert_array_equal(p1, p2)
    # training flags restored
    assert net.layers[1].training


def test_metric_compute_hook_used():
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Metric

    class ArgmaxAcc(Metric):
        def __init__(self):
            self.reset()

        def reset(self):
            self.hits, self.total = 0, 0

        def compute(self, pred, label, *a):
            return jnp.argmax(pred, -1), label

        def update(self, pred_ids, label):
            self.hits += int((pred_ids == label).sum())
            self.total += len(label)

        def accumulate(self):
            return self.hits / max(self.total, 1)

    pt.seed(0)
    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.0),
              loss=lambda out, y: nn.functional.cross_entropy(out, y),
              metrics=[ArgmaxAcc()])
    rs = np.random.RandomState(0)
    data = [(rs.randn(8, 4).astype(np.float32), rs.randint(0, 2, (8,)))]
    res = m.evaluate(data, verbose=0)
    assert 0.0 <= res["eval_argmaxacc"] <= 1.0
