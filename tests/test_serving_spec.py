"""Speculative decoding inside the engine (ISSUE 5): greedy output
identity with a draft model in the loop, composition with preemption
chaos, exception-atomicity of the ``serving.spec_verify`` fault site,
the PT_SPEC_DECODE kill switch, adaptive-k behaviour, and the metric
surface (proposed/accepted counters + acceptance-rate gauge)."""
import numpy as np
import pytest

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    # an unrelated tiny model: near-zero acceptance, which stresses the
    # reject/rewind path far harder than a well-matched draft would
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _prompts(n, rs, lo=3, hi=12):
    return [rs.randint(0, 64, (int(l),)) for l in rs.randint(lo, hi, size=n)]


def _run(eng, prompts, max_new=10, **kw):
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=max_new, **kw))
    out = eng.run()
    return {rid: list(map(int, t)) for rid, t in out.items()}


def _baseline(model, prompts, max_new=10, **ekw):
    kw = dict(num_slots=4, block_size=8, max_prompt_len=16, max_seq_len=64)
    kw.update(ekw)
    return _run(LLMEngine(model, **kw), prompts, max_new)


# ------------------------------------------------------ greedy identity

@pytest.mark.parametrize("which_draft", ["unrelated", "self"])
def test_greedy_spec_identical_to_nonspec(model, draft, which_draft):
    """Token-for-token identity at temperature 0, at both extremes of
    draft quality: an unrelated draft (everything rejected — pure rewind
    exercise) and draft==target (everything accepted — pure multi-commit
    exercise)."""
    rs = np.random.RandomState(0)
    prompts = _prompts(6, rs)
    base = _baseline(model, prompts)
    d = model if which_draft == "self" else draft
    eng = LLMEngine(model, draft_model=d, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    spec = _run(eng, prompts)
    assert spec == base
    eng.assert_quiescent()
    assert eng.stats["spec_ticks"] > 0
    assert eng.stats["spec_proposed"] > 0
    if which_draft == "self":
        # draft == target: greedy proposals are the target argmax chain
        assert eng.stats["spec_accepted"] == eng.stats["spec_proposed"]


def test_greedy_spec_identical_under_preemption_chaos(model):
    """The acceptance-criteria schedule: induced preemptions evict
    mid-spec requests (draft cache frontier reset), replay rebuilds
    them, and outputs stay exactly the greedy chain."""
    rs = np.random.RandomState(10)
    prompts = _prompts(5, rs, lo=4, hi=12)
    base = _baseline(model, prompts, max_new=8,
                     num_slots=2, block_size=4, max_seq_len=32,
                     preemption=True)

    # speculation collapses a wave to ~2 ticks, so the cadence must be
    # tight or the schedule exhausts the run before ever firing
    FAULTS.clear()
    FAULTS.install("serving.preempt", every=2, times=8,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=2,
                    block_size=4, max_prompt_len=16, max_seq_len=32,
                    preemption=True)
    spec = _run(eng, prompts, max_new=8)
    assert eng.stats["preemptions"] > 0, "schedule never fired"
    assert spec == base
    eng.assert_quiescent()


def test_spec_with_tight_block_pool_preempt_replay(model):
    """A pool too small for all slots forces organic evict/replay while
    speculation is staging multi-block reservations."""
    rs = np.random.RandomState(3)
    prompts = _prompts(5, rs)
    base = _baseline(model, prompts, max_new=12, num_slots=4,
                     block_size=4, num_blocks=18, preemption=True,
                     max_seq_len=48)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=4, num_blocks=18, max_prompt_len=16,
                    max_seq_len=48, preemption=True)
    spec = _run(eng, prompts, max_new=12)
    assert spec == base
    eng.assert_quiescent()


def test_spec_composes_with_chunked_prefill(model):
    """Prompts longer than max_prompt_len chunk-prefill in; the slot's
    first spec round then catch-up-feeds the whole committed sequence
    into the empty draft cache before proposing."""
    rs = np.random.RandomState(6)
    prompts = _prompts(4, rs, lo=14, hi=30)
    base = _baseline(model, prompts, num_slots=2, block_size=4,
                     max_prompt_len=8, max_seq_len=48)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=2,
                    block_size=4, max_prompt_len=8, max_seq_len=48)
    spec = _run(eng, prompts)
    assert spec == base
    assert eng.stats["spec_ticks"] > 0
    eng.assert_quiescent()


# --------------------------------------------------- chaos: spec_verify

def test_spec_verify_fault_is_exception_atomic(model):
    """An injected fault mid-verify must (a) not leak blocks, (b) fall
    back to the one-token tick for that round, (c) leave outputs exactly
    the non-spec greedy chain."""
    rs = np.random.RandomState(0)
    prompts = _prompts(5, rs)
    base = _baseline(model, prompts)
    FAULTS.clear()
    FAULTS.install("serving.spec_verify", every=2, times=4)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    spec = _run(eng, prompts)
    assert eng.stats["spec_fallbacks"] > 0, "fault never fired"
    assert spec == base
    eng.assert_quiescent()          # no leaked blocks / reservations
    from paddle_tpu.observability import METRICS
    snap = METRICS.snapshot()["counters"]
    assert snap['faults_injected_total{site="serving.spec_verify"}'] > 0
    assert snap["serving_spec_fallbacks_total"] >= eng.stats["spec_fallbacks"]


# ------------------------------------------------- kill switch / gating

def test_kill_switch_disables_speculation(model, monkeypatch):
    monkeypatch.setenv("PT_SPEC_DECODE", "0")
    rs = np.random.RandomState(0)
    prompts = _prompts(4, rs)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    spec = _run(eng, prompts)
    assert eng.stats["spec_ticks"] == 0
    assert spec == _baseline(model, prompts)
    eng.assert_quiescent()


def test_beam_requests_never_speculate(model):
    """Beam search is spec-disabled per request; a mixed batch keeps
    greedy requests speculating while the beam request matches the
    non-spec engine's beam output."""
    rs = np.random.RandomState(5)
    prompts = _prompts(3, rs)

    def run(eng):
        eng.add_request(Request(prompts[0], max_new_tokens=8, num_beams=2))
        for p in prompts[1:]:
            eng.add_request(Request(p, max_new_tokens=8))
        out = eng.run()
        return {rid: list(map(int, t)) for rid, t in out.items()}

    e0 = LLMEngine(model, num_slots=6, block_size=8, max_prompt_len=16,
                   max_seq_len=64)
    base = run(e0)
    e1 = LLMEngine(model, draft_model=model, spec_k=4, num_slots=6,
                   block_size=8, max_prompt_len=16, max_seq_len=64)
    spec = run(e1)
    assert spec == base
    assert e1.stats["spec_ticks"] > 0       # the greedy rows did speculate
    e1.assert_quiescent()


# --------------------------------------------------- sampling / adaptive

def test_stochastic_spec_runs_and_respects_budgets(model):
    """temperature > 0 through the accept/reject/resample path: lengths
    honour max_new_tokens and the engine drains clean. (Distributional
    equivalence of the rule itself is covered by the seeded
    speculative_sample statistical test.)"""
    rs = np.random.RandomState(1)
    prompts = _prompts(5, rs)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    out = _run(eng, prompts, max_new=12, temperature=0.8, top_p=0.95)
    assert all(len(v) == 12 for v in out.values())
    assert eng.stats["spec_accepted"] > 0    # draft==target: plenty accepted
    eng.assert_quiescent()


def test_adaptive_k_shrinks_on_bad_draft(model, draft):
    """With an unrelated draft nearly everything is rejected, so the
    per-slot EMA must drive k to the floor; with draft==target it must
    stay at the ceiling."""
    rs = np.random.RandomState(2)
    prompts = _prompts(4, rs)
    bad = LLMEngine(model, draft_model=draft, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=96)
    _run(bad, prompts, max_new=24)
    good = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                     block_size=8, max_prompt_len=16, max_seq_len=96)
    _run(good, prompts, max_new=24)
    bad_rate = bad.stats["spec_accepted"] / max(bad.stats["spec_proposed"], 1)
    good_rate = (good.stats["spec_accepted"]
                 / max(good.stats["spec_proposed"], 1))
    assert good_rate == 1.0
    assert bad_rate < 0.5
    # adaptive k throttled drafting: fewer proposals per spec tick
    assert (bad.stats["spec_proposed"] / bad.stats["spec_ticks"]
            < good.stats["spec_proposed"] / good.stats["spec_ticks"])


def test_spec_metrics_exported(model):
    rs = np.random.RandomState(0)
    prompts = _prompts(3, rs)
    eng = LLMEngine(model, draft_model=model, spec_k=4, num_slots=4,
                    block_size=8, max_prompt_len=16, max_seq_len=64)
    _run(eng, prompts)
    from paddle_tpu.observability import METRICS
    snap = METRICS.snapshot()
    assert snap["counters"]["serving_spec_proposed_total"] > 0
    assert snap["counters"]["serving_spec_accepted_total"] > 0
    assert 0.0 <= snap["gauges"]["serving_spec_acceptance_rate"] <= 1.0
    hist = [k for k in snap.get("histograms", {})
            if k.startswith("serving_spec_tokens_per_tick")]
    assert hist, "tokens-per-tick histogram missing"


# --------------------------------------------------------- ctor gating

def test_spec_rejects_vocab_mismatch(model):
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=32)
    with pytest.raises(ValueError):
        LLMEngine(model, draft_model=LlamaForCausalLM(cfg), num_slots=2,
                  block_size=8, max_prompt_len=16, max_seq_len=64)
