"""HF-checkpoint conversion parity: logits from converted weights match
the torch ``transformers`` reference implementation to fp32 tolerance.
This is the strongest switch-from-the-reference proof — real pretrained
checkpoints load and reproduce the reference's numerics."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt

transformers = pytest.importorskip("transformers")


def _hf_llama(nkv=2, vocab=96, h=32, layers=2, heads=4, inter=64):
    import torch
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFModel
    torch.manual_seed(0)
    cfg = HFConfig(vocab_size=vocab, hidden_size=h, intermediate_size=inter,
                   num_hidden_layers=layers, num_attention_heads=heads,
                   num_key_value_heads=nkv, max_position_embeddings=64,
                   attn_implementation="eager")
    return HFModel(cfg).eval()


@pytest.mark.parametrize("nkv", [4, 2])
def test_llama_logits_match_transformers(nkv):
    import torch
    hf = _hf_llama(nkv=nkv)
    from paddle_tpu.models.convert import load_llama_state_dict
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=nkv, max_position_embeddings=64,
                      rms_norm_eps=hf.config.rms_norm_eps,
                      dtype=jnp.float32, remat=False)
    ours = load_llama_state_dict(LlamaForCausalLM(cfg).eval(),
                                 hf.state_dict())

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_qwen2_logits_match_transformers():
    import torch
    from transformers import Qwen2Config as HFConfig
    from transformers import Qwen2ForCausalLM as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=64,
                          rope_theta=1e6, tie_word_embeddings=False,
                          attn_implementation="eager")).eval()
    from paddle_tpu.models.convert import load_llama_state_dict
    from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM

    pt.seed(0)
    cfg = Qwen2Config(vocab_size=96, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      rope_theta=1e6, attention_bias=True,
                      rms_norm_eps=hf.config.rms_norm_eps,
                      dtype=jnp.float32, remat=False)
    ours = load_llama_state_dict(Qwen2ForCausalLM(cfg).eval(), hf.state_dict())

    rs = np.random.RandomState(1)
    ids = rs.randint(0, 96, (1, 10))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_mistral_logits_match_transformers():
    import torch
    from transformers import MistralConfig as HFConfig
    from transformers import MistralForCausalLM as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=64,
                          sliding_window=None,
                          attn_implementation="eager")).eval()
    from paddle_tpu.models.convert import load_llama_state_dict
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM

    pt.seed(0)
    cfg = MistralConfig(vocab_size=96, hidden_size=32, intermediate_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=64,
                        sliding_window=None,
                        rms_norm_eps=hf.config.rms_norm_eps,
                        dtype=jnp.float32, remat=False)
    ours = load_llama_state_dict(MistralForCausalLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(2)
    ids = rs.randint(0, 96, (1, 14))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_bert_hidden_states_match_transformers():
    import torch
    from transformers import BertConfig as HFConfig
    from transformers import BertModel as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=64, max_position_embeddings=64,
                          type_vocab_size=2,
                          attn_implementation="eager")).eval()
    from paddle_tpu.models.bert import BertConfig, BertModel
    from paddle_tpu.models.convert import load_bert_state_dict

    pt.seed(0)
    cfg = BertConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=64, type_vocab_size=2,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     dtype=jnp.float32)
    ours = load_bert_state_dict(BertModel(cfg).eval(), hf.state_dict())

    rs = np.random.RandomState(3)
    ids = rs.randint(0, 96, (2, 9))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()
    got = ours(jnp.asarray(ids))
    seq = got[0] if isinstance(got, tuple) else got
    np.testing.assert_allclose(np.asarray(seq, np.float32), ref,
                               rtol=2e-4, atol=2e-4)


def test_safetensors_roundtrip(tmp_path):
    """Minimal-parser path: write via struct, read back."""
    import json
    import struct
    from paddle_tpu.models.convert import load_safetensors
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    raw = arr.tobytes()
    header = {"w": {"dtype": "F32", "shape": [2, 3],
                    "data_offsets": [0, len(raw)]}}
    hb = json.dumps(header).encode()
    path = tmp_path / "x.safetensors"
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hb)))
        f.write(hb)
        f.write(raw)
    out = load_safetensors(str(path))
    np.testing.assert_array_equal(out["w"], arr)


def test_gpt2_logits_match_transformers():
    import torch
    from transformers import GPT2Config as HFConfig
    from transformers import GPT2LMHeadModel as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                          n_positions=64, n_inner=None,
                          attn_implementation="eager",
                          resid_pdrop=0.0, embd_pdrop=0.0,
                          attn_pdrop=0.0)).eval()
    from paddle_tpu.models.convert import load_gpt2_state_dict
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    pt.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, dropout=0.0,
                    layer_norm_eps=hf.config.layer_norm_epsilon,
                    dtype=jnp.float32, remat=False)
    ours = load_gpt2_state_dict(GPTForCausalLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(4)
    ids = rs.randint(0, 96, (2, 11))
    import torch as _t
    with _t.no_grad():
        ref = hf(_t.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_t5_logits_match_transformers():
    import torch
    from transformers import T5Config as HFConfig
    from transformers import T5ForConditionalGeneration as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                          num_layers=2, num_decoder_layers=2, num_heads=4,
                          feed_forward_proj="relu", dropout_rate=0.0,
                          tie_word_embeddings=True)).eval()
    from paddle_tpu.models.convert import load_t5_state_dict
    from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    pt.seed(0)
    cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                   num_decoder_layers=2, num_heads=4,
                   layer_norm_epsilon=hf.config.layer_norm_epsilon,
                   dtype=jnp.float32)
    ours = load_t5_state_dict(T5ForConditionalGeneration(cfg).eval(),
                              hf.state_dict())
    rs = np.random.RandomState(5)
    enc_ids = rs.randint(0, 96, (2, 7))
    dec_ids = rs.randint(0, 96, (2, 5))
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(enc_ids),
                 decoder_input_ids=torch.tensor(dec_ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(enc_ids), jnp.asarray(dec_ids)),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_t5_v11_gated_untied_logits_match_transformers():
    import torch
    from transformers import T5Config as HFConfig
    from transformers import T5ForConditionalGeneration as HFModel
    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                          num_layers=2, num_decoder_layers=2, num_heads=4,
                          feed_forward_proj="gated-gelu", dropout_rate=0.0,
                          tie_word_embeddings=False)).eval()
    from paddle_tpu.models.convert import load_t5_state_dict
    from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    pt.seed(0)
    cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                   num_decoder_layers=2, num_heads=4,
                   feed_forward_proj="gated-gelu", tie_word_embeddings=False,
                   layer_norm_epsilon=hf.config.layer_norm_epsilon,
                   dtype=jnp.float32)
    ours = load_t5_state_dict(T5ForConditionalGeneration(cfg).eval(),
                              hf.state_dict())
    rs = np.random.RandomState(6)
    enc_ids = rs.randint(0, 96, (2, 6))
    dec_ids = rs.randint(0, 96, (2, 4))
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(enc_ids),
                 decoder_input_ids=torch.tensor(dec_ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(enc_ids), jnp.asarray(dec_ids)),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_t5_variant_mismatches_raise():
    import torch
    from transformers import T5Config as HFConfig
    from transformers import T5ForConditionalGeneration as HFModel
    from paddle_tpu.models.convert import load_t5_state_dict
    from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    torch.manual_seed(0)
    tied_relu = HFModel(HFConfig(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                                 num_layers=1, num_decoder_layers=1,
                                 num_heads=4, feed_forward_proj="relu",
                                 tie_word_embeddings=True)).eval()
    pt.seed(0)
    # tied ckpt -> untied config: raises (rescale mismatch)
    untied_cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                          num_layers=1, num_decoder_layers=1, num_heads=4,
                          tie_word_embeddings=False, dtype=jnp.float32)
    with pytest.raises(ValueError):
        load_t5_state_dict(T5ForConditionalGeneration(untied_cfg),
                           tied_relu.state_dict())
    # relu ckpt -> gated config: raises (FF variant mismatch)
    gated_cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                         num_layers=1, num_decoder_layers=1, num_heads=4,
                         feed_forward_proj="gated-gelu", dtype=jnp.float32)
    with pytest.raises(ValueError):
        load_t5_state_dict(T5ForConditionalGeneration(gated_cfg),
                           tied_relu.state_dict())
    # unsupported activation string rejected at config time
    with pytest.raises(ValueError):
        T5Config(feed_forward_proj="gated-silu")


def test_bloom_logits_match_transformers():
    """BLOOM (ALiBi positions, fused head-interleaved QKV re-laid out at
    load): logits match HF. HF materialises the O(S^2) alibi bias; ours
    differs per softmax row only by a constant, which softmax cancels."""
    import torch
    from transformers import BloomConfig as HFConfig
    from transformers import BloomForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, n_layer=2,
                          n_head=4, use_cache=False)).eval()

    from paddle_tpu.models.bloom import BloomConfig, BloomForCausalLM
    from paddle_tpu.models.convert import load_bloom_state_dict

    pt.seed(0)
    cfg = BloomConfig(vocab_size=96, hidden_size=32, n_layer=2, n_head=4,
                      dtype=jnp.float32, remat=False)
    ours = load_bloom_state_dict(BloomForCausalLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_bloom_non_power_of_two_heads():
    """The slope schedule's extra-head branch (n_head not a power of 2)."""
    import torch
    from transformers import BloomConfig as HFConfig
    from transformers import BloomForCausalLM as HFModel

    torch.manual_seed(1)
    hf = HFModel(HFConfig(vocab_size=64, hidden_size=36, n_layer=1,
                          n_head=6, use_cache=False)).eval()

    from paddle_tpu.models.bloom import BloomConfig, BloomForCausalLM
    from paddle_tpu.models.convert import load_bloom_state_dict

    pt.seed(0)
    cfg = BloomConfig(vocab_size=64, hidden_size=36, n_layer=1, n_head=6,
                      dtype=jnp.float32, remat=False)
    ours = load_bloom_state_dict(BloomForCausalLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(1)
    ids = rs.randint(0, 64, (1, 9))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_opt_logits_match_transformers():
    """OPT (learned positions at offset 2, pre-norm): logits match HF."""
    import torch
    from transformers import OPTConfig as HFConfig
    from transformers import OPTForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, ffn_dim=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          max_position_embeddings=64, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_opt_state_dict
    from paddle_tpu.models.opt import OPTConfig, OPTForCausalLM

    pt.seed(0)
    cfg = OPTConfig(vocab_size=96, hidden_size=32, ffn_dim=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, dtype=jnp.float32,
                    remat=False)
    ours = load_opt_state_dict(OPTForCausalLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_opt_350m_shape_project_and_post_norm():
    """The 350m peculiarities: word_embed_proj_dim != hidden (project_in/
    out) AND post-norm blocks (do_layer_norm_before=False, no final LN)."""
    import torch
    from transformers import OPTConfig as HFConfig
    from transformers import OPTForCausalLM as HFModel

    torch.manual_seed(1)
    hf = HFModel(HFConfig(vocab_size=64, hidden_size=32, ffn_dim=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          max_position_embeddings=64, use_cache=False,
                          word_embed_proj_dim=16,
                          do_layer_norm_before=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_opt_state_dict
    from paddle_tpu.models.opt import OPTConfig, OPTForCausalLM

    pt.seed(0)
    cfg = OPTConfig(vocab_size=64, hidden_size=32, ffn_dim=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=64, word_embed_proj_dim=16,
                    do_layer_norm_before=False, dtype=jnp.float32,
                    remat=False)
    ours = load_opt_state_dict(OPTForCausalLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(1)
    ids = rs.randint(0, 64, (1, 9))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("parallel", [True, False])
def test_gpt_neox_logits_match_transformers(parallel):
    """GPT-NeoX/Pythia (partial rotary 25%, parallel residual, fused
    head-interleaved QKV, untied embed_out): logits match HF."""
    import torch
    from transformers import GPTNeoXConfig as HFConfig
    from transformers import GPTNeoXForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=64, rotary_pct=0.25,
                          max_position_embeddings=64, use_cache=False,
                          use_parallel_residual=parallel,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_gpt_neox_state_dict
    from paddle_tpu.models.gpt_neox import (GPTNeoXConfig,
                                            GPTNeoXForCausalLM)

    pt.seed(0)
    cfg = GPTNeoXConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=64,
                        rotary_pct=0.25, max_position_embeddings=64,
                        use_parallel_residual=parallel, dtype=jnp.float32,
                        remat=False)
    ours = load_gpt_neox_state_dict(GPTNeoXForCausalLM(cfg).eval(),
                                    hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("use_task_id", [True, False])
def test_ernie_mlm_logits_match_transformers(use_task_id):
    """ERNIE (Baidu's flagship encoder: BERT blocks + task-type
    embeddings): MLM logits match HF, with and without task ids."""
    import torch
    from transformers import ErnieConfig as HFConfig
    from transformers import ErnieForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64, type_vocab_size=4,
                          use_task_id=use_task_id, task_type_vocab_size=3,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_ernie_state_dict
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForMaskedLM

    pt.seed(0)
    cfg = ErnieConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      max_position_embeddings=64, type_vocab_size=4,
                      use_task_id=use_task_id, task_type_vocab_size=3)
    ours = load_ernie_state_dict(ErnieForMaskedLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 4, (2, 12))
    kw_hf, kw_us = {}, {}
    if use_task_id:
        task = rs.randint(0, 3, (2, 12))
        kw_hf["task_type_ids"] = torch.tensor(task)
        kw_us["task_type_ids"] = jnp.asarray(task)
    with torch.no_grad():
        ref = hf(torch.tensor(ids), token_type_ids=torch.tensor(tt),
                 **kw_hf).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids), token_type_ids=jnp.asarray(tt),
                          **kw_us), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_gptj_logits_match_transformers():
    """GPT-J (interleaved rotary over rotary_dim, single-LN parallel
    block, biasless attention, untied biased head): logits match HF."""
    import torch
    from transformers import GPTJConfig as HFConfig
    from transformers import GPTJForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                          rotary_dim=4, n_positions=64, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_gptj_state_dict
    from paddle_tpu.models.gptj import GPTJConfig, GPTJForCausalLM

    pt.seed(0)
    cfg = GPTJConfig(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                     rotary_dim=4, dtype=jnp.float32, remat=False)
    ours = load_gptj_state_dict(GPTJForCausalLM(cfg).eval(),
                                hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("variant", ["7b", "new", "rw"])
def test_falcon_logits_match_transformers(variant):
    """Falcon's three shapes: 7b (multi-query, single-LN parallel block),
    new decoder architecture (grouped KV, ln_attn/ln_mlp), and rw (ALiBi,
    sequential residuals, biased)."""
    import torch
    from transformers import FalconConfig as HFConfig
    from transformers import FalconForCausalLM as HFModel

    hfkw = dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, use_cache=False,
                attn_implementation="eager")
    uskw = dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, dtype=jnp.float32, remat=False)
    if variant == "7b":
        extra = dict(multi_query=True, parallel_attn=True, bias=False,
                     new_decoder_architecture=False, alibi=False)
    elif variant == "new":
        extra = dict(new_decoder_architecture=True, num_kv_heads=2,
                     multi_query=True, parallel_attn=True, bias=False,
                     alibi=False)
    else:
        extra = dict(multi_query=False, parallel_attn=False, bias=True,
                     new_decoder_architecture=False, alibi=True)
    torch.manual_seed(0)
    hf = HFModel(HFConfig(**hfkw, **extra)).eval()

    from paddle_tpu.models.convert import load_falcon_state_dict
    from paddle_tpu.models.falcon import FalconConfig, FalconForCausalLM

    pt.seed(0)
    ours = load_falcon_state_dict(
        FalconForCausalLM(FalconConfig(**uskw, **extra)).eval(),
        hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=3e-4)


def test_roberta_mlm_logits_match_transformers():
    """RoBERTa (fairseq position offset via pad mask, tied MLM head):
    logits match HF, including rows with padding."""
    import torch
    from transformers import RobertaConfig as HFConfig
    from transformers import RobertaForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=66, type_vocab_size=1,
                          pad_token_id=1,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_roberta_state_dict
    from paddle_tpu.models.roberta import RobertaConfig, RobertaForMaskedLM

    pt.seed(0)
    cfg = RobertaConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=2, intermediate_size=64,
                        max_position_embeddings=66, type_vocab_size=1,
                        pad_token_id=1)
    ours = load_roberta_state_dict(RobertaForMaskedLM(cfg).eval(),
                                   hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(2, 96, (2, 12))
    ids[1, 9:] = 1                       # padded row
    mask = (ids != 1).astype(np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 attention_mask=torch.tensor(mask)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          attention_mask=jnp.asarray(mask)), np.float32)
    valid = mask[:, :, None].astype(bool)
    np.testing.assert_allclose(np.where(valid, got, 0),
                               np.where(valid, ref, 0),
                               rtol=2e-4, atol=2e-4)


def test_electra_discriminator_logits_match_transformers():
    """ELECTRA discriminator (factorized embeddings + projection,
    per-token binary head): logits match HF."""
    import torch
    from transformers import ElectraConfig as HFConfig
    from transformers import ElectraForPreTraining as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, embedding_size=16, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_electra_state_dict
    from paddle_tpu.models.electra import (ElectraConfig,
                                           ElectraForPreTraining)

    pt.seed(0)
    cfg = ElectraConfig(vocab_size=96, embedding_size=16, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=2,
                        intermediate_size=64, max_position_embeddings=64)
    ours = load_electra_state_dict(ElectraForPreTraining(cfg).eval(),
                                   hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_bart_logits_match_transformers():
    """BART (post-LN seq2seq, fairseq-offset learned positions, embedding
    LN, cross-attention, tied head + final_logits_bias): logits match
    HF, including a padded encoder row."""
    import torch
    from transformers import BartConfig as HFConfig
    from transformers import BartForConditionalGeneration as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          pad_token_id=1, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.bart import (BartConfig,
                                        BartForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict

    pt.seed(0)
    cfg = BartConfig(vocab_size=96, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64)
    ours = load_bart_state_dict(BartForConditionalGeneration(cfg).eval(),
                                hf.state_dict())
    rs = np.random.RandomState(0)
    src = rs.randint(2, 96, (2, 10))
    src[1, 8:] = 1
    mask = (src != 1).astype(np.int64)
    tgt = rs.randint(2, 96, (2, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(src), attention_mask=torch.tensor(mask),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(src), jnp.asarray(tgt),
                          attention_mask=jnp.asarray(mask)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_qwen2_moe_logits_match_transformers():
    """Qwen2-MoE: an HF MoE checkpoint runs through OUR sort-based routed
    expert stack (dropless capacity, norm_topk_prob=False raw softmax
    mass, sigmoid-gated shared expert) and matches HF logits — the
    end-to-end proof the MoE machinery computes the reference math."""
    import torch
    from transformers import Qwen2MoeConfig as HFConfig
    from transformers import Qwen2MoeForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, num_experts=8,
                          num_experts_per_tok=2, moe_intermediate_size=16,
                          shared_expert_intermediate_size=48,
                          norm_topk_prob=False, decoder_sparse_step=1,
                          mlp_only_layers=[1], use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_qwen2_moe_state_dict
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                             Qwen2MoeForCausalLM)

    pt.seed(0)
    cfg = Qwen2MoeConfig.tiny(vocab_size=96, mlp_only_layers=(1,))
    ours = load_qwen2_moe_state_dict(Qwen2MoeForCausalLM(cfg).eval(),
                                     hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_gemma_logits_match_transformers():
    """Gemma (zero-centered RMSNorm, decoupled head_dim, sqrt(h)-scaled
    embeddings, tanh-gelu MLP, tied head): logits match HF."""
    import torch
    from transformers import GemmaConfig as HFConfig
    from transformers import GemmaForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          head_dim=16, max_position_embeddings=64,
                          use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_gemma_state_dict
    from paddle_tpu.models.gemma import GemmaConfig, GemmaForCausalLM

    pt.seed(0)
    cfg = GemmaConfig.tiny(vocab_size=96)
    ours = load_gemma_state_dict(GemmaForCausalLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_mixtral_logits_match_transformers():
    """Mixtral (renormalised top-k routed experts, no shared expert):
    HF checkpoint parity through the sort-based MoE stack."""
    import torch
    from transformers import MixtralConfig as HFConfig
    from transformers import MixtralForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=64, num_local_experts=4,
                          num_experts_per_tok=2, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_mixtral_state_dict
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    pt.seed(0)
    cfg = MixtralConfig.tiny(vocab_size=96)
    ours = load_mixtral_state_dict(MixtralForCausalLM(cfg).eval(),
                                   hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_glm_logits_match_transformers():
    """GLM-4 / ChatGLM lineage (partial rotary with INTERLEAVED tables +
    rotate-half pairing, biased qkv, fused gate_up SwiGLU): logits match
    HF."""
    import torch
    from transformers import GlmConfig as HFConfig
    from transformers import GlmForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          partial_rotary_factor=0.5, rms_norm_eps=1e-6,
                          max_position_embeddings=64, use_cache=False,
                          pad_token_id=0, eos_token_id=1, bos_token_id=2,
                          head_dim=8,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_glm_state_dict
    from paddle_tpu.models.glm import GlmConfig, GlmForCausalLM

    pt.seed(0)
    cfg = GlmConfig.tiny(vocab_size=96)
    ours = load_glm_state_dict(GlmForCausalLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_albert_mlm_logits_match_transformers():
    """ALBERT (one shared layer applied L times, factorized embeddings,
    MLM head back in embedding space): logits match HF."""
    import torch
    from transformers import AlbertConfig as HFConfig
    from transformers import AlbertForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, embedding_size=16, hidden_size=32,
                          num_hidden_layers=3, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.albert import AlbertConfig, AlbertForMaskedLM
    from paddle_tpu.models.convert import load_albert_state_dict

    pt.seed(0)
    cfg = AlbertConfig.tiny(vocab_size=96)
    ours = load_albert_state_dict(AlbertForMaskedLM(cfg).eval(),
                                  hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids), token_type_ids=jnp.asarray(tt)),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_deberta_v2_mlm_logits_match_transformers():
    """DeBERTa-v2/v3 (disentangled c2c+c2p+p2c attention over
    log-bucketed relative positions, shared rel table through the q/k
    projections): MLM logits match HF."""
    import torch
    from transformers import DebertaV2Config as HFConfig
    from transformers import DebertaV2ForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64, type_vocab_size=0,
                          position_biased_input=False,
                          relative_attention=True, position_buckets=4,
                          pos_att_type=["p2c", "c2p"], share_att_key=True,
                          norm_rel_ebd="layer_norm",
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_deberta_v2_state_dict
    from paddle_tpu.models.deberta import (DebertaV2Config,
                                           DebertaV2ForMaskedLM)

    pt.seed(0)
    cfg = DebertaV2Config.tiny(vocab_size=96)
    ours = load_deberta_v2_state_dict(DebertaV2ForMaskedLM(cfg).eval(),
                                      hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_mbart_logits_match_transformers():
    """mBART (pre-LN BART + final encoder/decoder LNs + scaled
    embeddings): logits match HF through the shared BART classes."""
    import torch
    from transformers import MBartConfig as HFConfig
    from transformers import MBartForConditionalGeneration as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          scale_embedding=True, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.bart import (MBartConfig,
                                        MBartForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict

    pt.seed(0)
    cfg = MBartConfig.tiny(vocab_size=96)
    ours = load_bart_state_dict(MBartForConditionalGeneration(cfg).eval(),
                                hf.state_dict())
    rs = np.random.RandomState(0)
    src = rs.randint(2, 96, (2, 10))
    tgt = rs.randint(2, 96, (2, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(src),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(src), jnp.asarray(tgt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_codegen_logits_match_transformers():
    """CodeGen (GPT-J block; mp_num-grouped fused QKV unpacked at load):
    logits match HF."""
    import torch
    from transformers import CodeGenConfig as HFConfig
    from transformers import CodeGenForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, n_embd=32, n_layer=2, n_head=4,
                          rotary_dim=4, n_positions=64, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_codegen_state_dict
    from paddle_tpu.models.gptj import CodeGenConfig, CodeGenForCausalLM

    pt.seed(0)
    cfg = CodeGenConfig.tiny(vocab_size=96)
    ours = load_codegen_state_dict(CodeGenForCausalLM(cfg).eval(),
                                   hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_ernie_m_hidden_states_match_transformers():
    """ERNIE-M (multilingual ERNIE: +2 position offset, no token types,
    post-LN): hidden states match HF."""
    import torch
    from transformers import ErnieMConfig as HFConfig
    from transformers import ErnieMModel as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=66,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)).eval()

    from paddle_tpu.models.convert import load_ernie_m_state_dict
    from paddle_tpu.models.ernie_m import ErnieMConfig, ErnieMModel

    pt.seed(0)
    cfg = ErnieMConfig.tiny(vocab_size=96)
    ours = load_ernie_m_state_dict(ErnieMModel(cfg).eval(),
                                   hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(2, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()
    seq, _ = ours(jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq, np.float32), ref,
                               rtol=2e-4, atol=2e-4)


def test_pegasus_logits_match_transformers():
    """Pegasus (pre-LN, static sinusoidal positions, no embedding LN):
    logits match HF through the shared BART classes."""
    import torch
    from transformers import PegasusConfig as HFConfig
    from transformers import PegasusForConditionalGeneration as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          scale_embedding=True, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.bart import (PegasusConfig,
                                        PegasusForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict

    pt.seed(0)
    cfg = PegasusConfig.tiny(vocab_size=96)
    ours = load_bart_state_dict(
        PegasusForConditionalGeneration(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    src = rs.randint(2, 96, (2, 10))
    tgt = rs.randint(2, 96, (2, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(src),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(src), jnp.asarray(tgt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_distilbert_mlm_logits_match_transformers():
    """DistilBERT (no token types, no pooler, tied projector): MLM
    logits match HF."""
    import torch
    from transformers import DistilBertConfig as HFConfig
    from transformers import DistilBertForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, dim=32, n_layers=2, n_heads=2,
                          hidden_dim=64, max_position_embeddings=64,
                          dropout=0.0, attention_dropout=0.0,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_distilbert_state_dict
    from paddle_tpu.models.distilbert import (DistilBertConfig,
                                              DistilBertForMaskedLM)

    pt.seed(0)
    cfg = DistilBertConfig.tiny(vocab_size=96)
    ours = load_distilbert_state_dict(DistilBertForMaskedLM(cfg).eval(),
                                      hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_xlnet_logits_match_transformers():
    """XLNet (Transformer-XL relative attention with rel-shift, learned
    r_w/r_r/r_s biases, segment term): single-stream logits match HF,
    with and without token types."""
    import torch
    from transformers import XLNetConfig as HFConfig
    from transformers import XLNetLMHeadModel as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, n_layer=2, n_head=4,
                          d_inner=64, ff_activation="gelu",
                          use_mems_eval=False, dropout=0.0)).eval()

    from paddle_tpu.models.convert import load_xlnet_state_dict
    from paddle_tpu.models.xlnet import XLNetConfig, XLNetLMHeadModel

    pt.seed(0)
    cfg = XLNetConfig.tiny(vocab_size=96)
    ours = load_xlnet_state_dict(XLNetLMHeadModel(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
        ref_tt = hf(torch.tensor(ids),
                    token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    got_tt = np.asarray(ours(jnp.asarray(ids),
                             token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got_tt, ref_tt, rtol=2e-4, atol=2e-4)

    # padded batch: pad keys are masked out (real-token rows match HF)
    mask = np.ones((2, 12), np.int64)
    mask[1, 9:] = 0
    with torch.no_grad():
        ref_m = hf(torch.tensor(ids),
                   attention_mask=torch.tensor(mask)).logits.numpy()
    got_m = np.asarray(ours(jnp.asarray(ids),
                            attention_mask=jnp.asarray(mask)), np.float32)
    valid = mask[:, :, None].astype(bool)
    np.testing.assert_allclose(np.where(valid, got_m, 0),
                               np.where(valid, ref_m, 0),
                               rtol=2e-4, atol=2e-4)


def test_clip_logits_match_transformers():
    """CLIP (causal quick-gelu text tower pooled at EOS + ViT tower,
    learned-temperature contrastive logits): matches HF CLIPModel."""
    import torch
    from transformers import CLIPConfig as HFConfig
    from transformers import CLIPModel as HFModel
    from transformers import CLIPTextConfig as HFText
    from transformers import CLIPVisionConfig as HFVision

    torch.manual_seed(0)
    hf = HFModel(HFConfig.from_text_vision_configs(
        HFText(vocab_size=96, hidden_size=32, intermediate_size=64,
               num_hidden_layers=2, num_attention_heads=4,
               max_position_embeddings=16, eos_token_id=1,
               attn_implementation="eager"),
        HFVision(hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                 num_attention_heads=4, image_size=32, patch_size=8,
                 attn_implementation="eager"),
        projection_dim=16)).eval()

    from paddle_tpu.models.clip import CLIPConfig, CLIPModel
    from paddle_tpu.models.convert import load_clip_state_dict

    pt.seed(0)
    ours = load_clip_state_dict(CLIPModel(CLIPConfig.tiny()).eval(),
                                hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(3, 96, (3, 12))
    ids[:, -1] = 1                         # EOS-terminated prompts
    ids[1, 7] = 1                          # one early EOS (pooling pos)
    px = rs.randn(2, 3, 32, 32).astype(np.float32)
    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids),
                 pixel_values=torch.tensor(px))
    li, lt = ours(jnp.asarray(ids), jnp.asarray(px))
    np.testing.assert_allclose(np.asarray(li, np.float32),
                               out.logits_per_image.numpy(),
                               rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(np.asarray(lt, np.float32),
                               out.logits_per_text.numpy(),
                               rtol=3e-4, atol=3e-4)


def test_whisper_logits_match_transformers():
    """Whisper (conv front-end over mels, sinusoidal encoder positions,
    pre-LN seq2seq, tied proj_out): logits match HF."""
    import torch
    from transformers import WhisperConfig as HFConfig
    from transformers import WhisperForConditionalGeneration as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, num_mel_bins=8, d_model=32,
                          encoder_layers=2, decoder_layers=2,
                          encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_source_positions=16,
                          max_target_positions=32, use_cache=False,
                          pad_token_id=0, bos_token_id=1, eos_token_id=2,
                          decoder_start_token_id=1, suppress_tokens=None,
                          begin_suppress_tokens=None,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_whisper_state_dict
    from paddle_tpu.models.whisper import (WhisperConfig,
                                           WhisperForConditionalGeneration)

    pt.seed(0)
    cfg = WhisperConfig.tiny(vocab_size=96)
    ours = load_whisper_state_dict(
        WhisperForConditionalGeneration(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    mel = rs.randn(2, 8, 32).astype(np.float32)   # T=32 -> 16 frames
    tgt = rs.randint(0, 96, (2, 7))
    with torch.no_grad():
        ref = hf(input_features=torch.tensor(mel),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(mel), jnp.asarray(tgt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_layoutlm_mlm_logits_match_transformers():
    """LayoutLM (BERT + 2-D bounding-box embeddings): MLM logits match
    HF given token boxes."""
    import torch
    from transformers import LayoutLMConfig as HFConfig
    from transformers import LayoutLMForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64,
                          max_2d_position_embeddings=128,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_layoutlm_state_dict
    from paddle_tpu.models.layoutlm import (LayoutLMConfig,
                                            LayoutLMForMaskedLM)

    pt.seed(0)
    cfg = LayoutLMConfig.tiny(vocab_size=96)
    ours = load_layoutlm_state_dict(LayoutLMForMaskedLM(cfg).eval(),
                                    hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 10))
    x0 = rs.randint(0, 60, (2, 10)); y0 = rs.randint(0, 60, (2, 10))
    bbox = np.stack([x0, y0, x0 + rs.randint(1, 60, (2, 10)),
                     y0 + rs.randint(1, 60, (2, 10))], axis=-1)
    with torch.no_grad():
        ref = hf(torch.tensor(ids), bbox=torch.tensor(bbox)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids), jnp.asarray(bbox)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_phi_logits_match_transformers():
    """Phi (single-LN parallel block, llama-pairing partial rotary,
    biased projections, untied biased head): logits match HF."""
    import torch
    from transformers import PhiConfig as HFConfig
    from transformers import PhiForCausalLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          partial_rotary_factor=0.5,
                          max_position_embeddings=64, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_phi_state_dict
    from paddle_tpu.models.phi import PhiConfig, PhiForCausalLM

    pt.seed(0)
    cfg = PhiConfig.tiny(vocab_size=96)
    ours = load_phi_state_dict(PhiForCausalLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_roformer_mlm_logits_match_transformers():
    """RoFormer (rotary BERT — interleaved RoPE inside post-LN blocks,
    no position table): MLM logits match HF."""
    import torch
    from transformers import RoFormerConfig as HFConfig
    from transformers import RoFormerForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, embedding_size=32,
                          max_position_embeddings=64,
                          rotary_value=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_roformer_state_dict
    from paddle_tpu.models.roformer import (RoFormerConfig,
                                            RoFormerForMaskedLM)

    pt.seed(0)
    cfg = RoFormerConfig.tiny(vocab_size=96)
    ours = load_roformer_state_dict(RoFormerForMaskedLM(cfg).eval(),
                                    hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_fnet_mlm_logits_match_transformers():
    """FNet (attention-free Fourier mixing): MLM logits match HF."""
    import torch
    from transformers import FNetConfig as HFConfig
    from transformers import FNetForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, intermediate_size=64,
                          max_position_embeddings=64, type_vocab_size=4,
                          use_tpu_fourier_optimizations=False)).eval()

    from paddle_tpu.models.convert import load_fnet_state_dict
    from paddle_tpu.models.fnet import FNetConfig, FNetForMaskedLM

    pt.seed(0)
    cfg = FNetConfig.tiny(vocab_size=96, type_vocab_size=4)
    ours = load_fnet_state_dict(FNetForMaskedLM(cfg).eval(),
                                hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 4, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_blenderbot_logits_match_transformers():
    """Blenderbot (conversational seq2seq: pre-LN, final LNs, learned
    offset-0 positions, no embedding LN) through the BART classes."""
    import torch
    from transformers import BlenderbotConfig as HFConfig
    from transformers import BlenderbotForConditionalGeneration as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          scale_embedding=False, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.bart import (BlenderbotConfig,
                                        BlenderbotForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict

    pt.seed(0)
    cfg = BlenderbotConfig.tiny(vocab_size=96)
    ours = load_bart_state_dict(
        BlenderbotForConditionalGeneration(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    src = rs.randint(2, 96, (2, 10))
    tgt = rs.randint(2, 96, (2, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(src),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(src), jnp.asarray(tgt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_mpnet_mlm_logits_match_transformers():
    """MPNet (shared T5-style bucketed relative bias inside post-LN
    blocks, roberta position ids): MLM logits match HF."""
    import torch
    from transformers import MPNetConfig as HFConfig
    from transformers import MPNetForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=66,
                          relative_attention_num_buckets=32,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_mpnet_state_dict
    from paddle_tpu.models.mpnet import MPNetConfig, MPNetForMaskedLM

    pt.seed(0)
    cfg = MPNetConfig.tiny(vocab_size=96)
    ours = load_mpnet_state_dict(MPNetForMaskedLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(2, 96, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_nezha_mlm_logits_match_transformers():
    """NeZha (parameter-free sinusoidal RELATIVE positions added to key
    scores AND value aggregation in every layer): MLM logits match HF."""
    import torch
    from transformers import NezhaConfig as HFConfig
    from transformers import NezhaForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_relative_position=8,
                          max_position_embeddings=64,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_nezha_state_dict
    from paddle_tpu.models.nezha import NezhaConfig, NezhaForMaskedLM

    pt.seed(0)
    cfg = NezhaConfig.tiny(vocab_size=96)
    ours = load_nezha_state_dict(NezhaForMaskedLM(cfg).eval(),
                                 hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_blenderbot_small_logits_match_transformers():
    """Blenderbot-small (BART post-LN with offset-0 positions)."""
    import torch
    from transformers import BlenderbotSmallConfig as HFConfig
    from transformers import (
        BlenderbotSmallForConditionalGeneration as HFModel)

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          scale_embedding=False, use_cache=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.bart import (
        BlenderbotSmallConfig, BlenderbotSmallForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict

    pt.seed(0)
    cfg = BlenderbotSmallConfig.tiny(vocab_size=96)
    ours = load_bart_state_dict(
        BlenderbotSmallForConditionalGeneration(cfg).eval(),
        hf.state_dict())
    rs = np.random.RandomState(0)
    src = rs.randint(2, 96, (2, 10))
    tgt = rs.randint(2, 96, (2, 7))
    with torch.no_grad():
        ref = hf(torch.tensor(src),
                 decoder_input_ids=torch.tensor(tgt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(src), jnp.asarray(tgt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_big_bird_mlm_logits_match_transformers():
    """BigBird in original_full mode (dense attention, gelu_new): MLM
    logits match HF."""
    import torch
    from transformers import BigBirdConfig as HFConfig
    from transformers import BigBirdForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64,
                          attention_type="original_full",
                          rescale_embeddings=False,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.big_bird import BigBirdConfig, BigBirdForMaskedLM
    from paddle_tpu.models.convert import load_big_bird_state_dict

    pt.seed(0)
    cfg = BigBirdConfig.tiny(vocab_size=96)
    ours = load_big_bird_state_dict(BigBirdForMaskedLM(cfg).eval(),
                                    hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_megatron_bert_mlm_logits_match_transformers():
    """MegatronBERT (pre-LN BERT, no embedding LN, final encoder LN):
    MLM logits match HF."""
    import torch
    from transformers import MegatronBertConfig as HFConfig
    from transformers import MegatronBertForMaskedLM as HFModel

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=64,
                          attn_implementation="eager")).eval()

    from paddle_tpu.models.convert import load_megatron_bert_state_dict
    from paddle_tpu.models.megatron_bert import (MegatronBertConfig,
                                                 MegatronBertForMaskedLM)

    pt.seed(0)
    cfg = MegatronBertConfig.tiny(vocab_size=96)
    ours = load_megatron_bert_state_dict(
        MegatronBertForMaskedLM(cfg).eval(), hf.state_dict())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 96, (2, 12))
    tt = rs.randint(0, 2, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids),
                 token_type_ids=torch.tensor(tt)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids),
                          token_type_ids=jnp.asarray(tt)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
