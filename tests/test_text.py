"""SimpleTokenizer: train/encode/decode round trip, static-shape batching."""
import numpy as np

from paddle_tpu.text import SimpleTokenizer, pad_batch


CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs!",
    "the dog barks.",
]


def test_train_encode_decode():
    tok = SimpleTokenizer.train(CORPUS, vocab_size=100)
    assert tok.vocab_size > 10
    text = "the quick dog"
    ids = tok.encode(text)
    assert ids[0] == tok.vocab["[CLS]"] and ids[-1] == tok.vocab["[SEP]"]
    assert tok.decode(ids) == text
    # oov maps to UNK
    ids2 = tok.encode("zyzzyva")
    assert tok.unk_token_id in ids2


def test_batch_static_shapes():
    tok = SimpleTokenizer.train(CORPUS)
    out = tok(["the dog", "the quick brown fox jumps"], max_len=12)
    assert out["input_ids"].shape == (2, 12)
    assert out["attention_mask"].shape == (2, 12)
    assert out["input_ids"].dtype == np.int32
    # padding area is pad_id with mask 0
    assert out["attention_mask"][0].sum() < 12
    pad_area = out["input_ids"][0][out["attention_mask"][0] == 0]
    assert np.all(pad_area == tok.pad_token_id)


def test_pad_batch_truncates():
    ids, mask = pad_batch([[1, 2, 3, 4, 5], [6]], max_len=3, pad_id=9)
    assert ids.tolist() == [[1, 2, 3], [6, 9, 9]]
    assert mask.tolist() == [[1, 1, 1], [1, 0, 0]]


# -- native byte-level BPE ---------------------------------------------------

class TestBPE:
    def _tok(self, native=True):
        from paddle_tpu.text.bpe import BPETokenizer
        texts = ["the quick brown fox jumps over the lazy dog",
                 "pack my box with five dozen liquor jugs"] * 30
        return BPETokenizer.train(texts, vocab_size=320, use_native=native)

    def test_native_matches_python(self):
        tok = self._tok()
        from paddle_tpu.text.bpe import BPETokenizer
        pytok = BPETokenizer(tok.merges, tok.special_tokens, use_native=False)
        for s in ["the quick brown fox", "jugs of liquor", "unseen wørds ✓",
                  "", "a", "double  space", " leading"]:
            assert tok.encode(s) == pytok.encode(s), s

    def test_roundtrip_and_compression(self):
        tok = self._tok()
        s = "the quick brown fox jumps over the lazy dog"
        ids = tok.encode(s)
        assert tok.decode(ids) == s
        assert len(ids) < len(s.encode())  # merges actually compress

    def test_save_load(self, tmp_path):
        tok = self._tok()
        p = str(tmp_path / "bpe.json")
        tok.save(p)
        from paddle_tpu.text.bpe import BPETokenizer
        back = BPETokenizer.load(p)
        s = "the lazy dog packs jugs"
        assert back.encode(s) == tok.encode(s)
        assert back.vocab_size == tok.vocab_size

    def test_batch_threads(self):
        tok = self._tok()
        texts = ["the quick brown fox"] * 64
        out = tok.encode_batch(texts, num_threads=4)
        assert len(out) == 64 and all(o == out[0] for o in out)
