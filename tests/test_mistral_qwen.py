"""Sliding-window attention (XLA + Pallas interpret) and the Mistral/Qwen2
model families on the shared decoder stack."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.attention import xla_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _naive_window_attention(q, k, v, window):
    b, s, h, d = q.shape
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    scores = qt @ jnp.swapaxes(kt, -1, -2) / np.sqrt(d)
    i = np.arange(s)[:, None]
    j = np.arange(s)[None, :]
    keep = (i >= j) & (i - j < window)
    scores = jnp.where(jnp.asarray(keep), scores, -1e30)
    return jnp.swapaxes(jax.nn.softmax(scores, -1) @ vt, 1, 2)


@pytest.mark.parametrize("window", [4, 16, 1000])
def test_xla_window_attention_matches_naive(window):
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
               for _ in range(3))
    got = xla_attention(q, k, v, is_causal=True, window=window)
    want = _naive_window_attention(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("window", [64, 128, 300])
def test_pallas_window_flash_matches_naive(window):
    rs = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rs.randn(1, 256, 2, 64).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, causal=True, window=window, interpret=True)
    want = _naive_window_attention(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pallas_window_flash_grads_match():
    rs = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rs.randn(1, 128, 1, 64).astype(np.float32))
               for _ in range(3))
    window = 32

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=window,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_naive_window_attention(q, k, v, window) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_mistral_tiny_trains():
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    cfg = MistralConfig.tiny()
    assert cfg.sliding_window == 16
    model = MistralForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-3)
    state = init_state(model, optimizer)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 32)))
    labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((2, 1), ids.dtype)], 1)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
    losses = []
    for _ in range(8):
        state, loss = step(state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_mistral_window_changes_output():
    """The window actually bites: long-range token influence is cut."""
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    pt.seed(0)
    cfg = MistralConfig.tiny(sliding_window=4, num_hidden_layers=1)
    model = MistralForCausalLM(cfg).eval()
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 24)))
    out1 = model(ids)
    # perturb token 0: with window 4 and 1 layer, logits at position 23
    # cannot see it
    ids2 = ids.at[0, 0].set((int(ids[0, 0]) + 1) % cfg.vocab_size)
    out2 = model(ids2)
    np.testing.assert_allclose(np.asarray(out1[0, -1]), np.asarray(out2[0, -1]),
                               rtol=1e-5, atol=1e-6)
    # ...but position 2 can
    assert not np.allclose(np.asarray(out1[0, 2]), np.asarray(out2[0, 2]))


def test_qwen2_tiny_trains_with_bias_and_tied_embeddings():
    from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    cfg = Qwen2Config.tiny()
    model = Qwen2ForCausalLM(cfg)
    assert model.lm_head is None  # tied
    assert model.model.layers[0].self_attn.qkv_bias is not None
    optimizer = opt.AdamW(learning_rate=1e-3)
    state = init_state(model, optimizer)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 16)))
    labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((2, 1), ids.dtype)], 1)
    step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer)
    losses = []
    for _ in range(8):
        state, loss = step(state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_qwen2_bias_receives_gradient():
    from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM
    pt.seed(0)
    model = Qwen2ForCausalLM(Qwen2Config.tiny())
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 256, (1, 8)))
    labels = jnp.asarray(rs.randint(0, 256, (1, 8)))
    grads = jax.grad(lambda m: m.loss(ids, labels))(model)
    g = grads.model.layers[0].self_attn.qkv_bias
    assert g is not None and float(jnp.abs(g).max()) > 0


def test_pallas_decode_alignment_sq_ne_sk():
    """Short query block over a longer key axis (KV-cache decode shape):
    queries must align to the END of the key axis, matching xla path."""
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 128, 1, 64).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 256, 1, 64).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 256, 1, 64).astype(np.float32))
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = xla_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # and windowed
    got_w = flash_attention(q, k, v, causal=True, window=96, interpret=True)
    want_w = xla_attention(q, k, v, is_causal=True, window=96)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(want_w),
                               rtol=2e-4, atol=2e-5)


def test_window_without_causal_raises():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 8, 1, 4).astype(np.float32))
    with pytest.raises(ValueError):
        xla_attention(q, q, q, is_causal=False, window=4)


@pytest.mark.slow
def test_mistral_generation_consistent_with_forward():
    """KV-cache decode honors the sliding window: greedy generation must
    match argmax over the full windowed forward."""
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    from paddle_tpu.models.decoding import generate
    pt.seed(0)
    cfg = MistralConfig.tiny(sliding_window=6)
    m = MistralForCausalLM(cfg).eval()
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 10)))
    out = generate(m, prompt, max_new_tokens=5, temperature=0.0)
    toks = np.asarray(out)
    cur = prompt
    for i in range(5):
        logits = m(jnp.asarray(cur))
        nxt = int(jnp.argmax(logits[0, -1]))
        assert nxt == toks[0, 10 + i], (i, nxt, toks)
        cur = np.concatenate([np.asarray(cur), [[nxt]]], axis=1)


def test_qwen2_generation_uses_bias():
    """Decode path must apply the qkv bias (Qwen2) — cache greedy decode
    matches the full forward, which applies it."""
    from paddle_tpu.models.qwen import Qwen2Config, Qwen2ForCausalLM
    from paddle_tpu.models.decoding import generate
    pt.seed(0)
    cfg = Qwen2Config.tiny()
    m = Qwen2ForCausalLM(cfg).eval()
    # make biases visibly non-zero
    import jax.tree_util as jtu
    def bump(mod):
        for lyr in mod.model.layers:
            lyr.self_attn.qkv_bias = lyr.self_attn.qkv_bias + 0.5
        return mod
    m = bump(m)
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 6)))
    out = generate(m, prompt, max_new_tokens=4, temperature=0.0)
    toks = np.asarray(out)
    cur = prompt
    for i in range(4):
        logits = m(jnp.asarray(cur))
        nxt = int(jnp.argmax(logits[0, -1]))
        assert nxt == toks[0, 6 + i], (i, nxt, toks)
        cur = np.concatenate([np.asarray(cur), [[nxt]]], axis=1)


@pytest.mark.parametrize("s,window,bq,bk", [
    (512, 100, 64, 64),    # band strictly smaller than grid
    (512, 64, 128, 64),    # window < block_q
    (384, 130, 64, 128),   # mixed blocks, window spans >1 k block
])
def test_pallas_banded_grid_matches_naive(s, window, bq, bk):
    """Banded-grid path (k-axis spans only the band) == naive windowed."""
    rs = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rs.randn(1, s, 1, 64).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, causal=True, window=window,
                          block_q=bq, block_k=bk, interpret=True)
    want = _naive_window_attention(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # grads through the banded backward
    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=window,
                                       block_q=bq, block_k=bk,
                                       interpret=True) ** 2)
    def loss_r(q, k, v):
        return jnp.sum(_naive_window_attention(q, k, v, window) ** 2)
    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("nh,nkv,window", [(4, 2, None), (4, 1, None),
                                           (8, 2, 100)])
def test_pallas_gqa_zero_copy_matches_xla(nh, nkv, window):
    """GQA flash path (kv row via index map, no repeat) == XLA reference."""
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(2, 256, nh, 64).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 256, nkv, 64).astype(np.float32))
    v = jnp.asarray(rs.randn(2, 256, nkv, 64).astype(np.float32))
    got = flash_attention(q, k, v, causal=True, window=window, interpret=True)
    want = xla_attention(q, k, v, is_causal=True, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_pallas_gqa_grads_match_xla():
    rs = np.random.RandomState(6)
    q = jnp.asarray(rs.randn(1, 128, 4, 64).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 128, 2, 64).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 128, 2, 64).astype(np.float32))

    def lf(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def lr(q, k, v):
        return jnp.sum(xla_attention(q, k, v, is_causal=True) ** 2)

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)
