"""Paged multi-append + rewind helpers (ISSUE 5): the length-pointer
rollback that speculative verification relies on. Rewind touches ONLY
``cache.lens`` — block tables stay intact, stale pool entries beyond
the new length are masked by attention and positionally overwritten by
the next append."""
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import (PagedKVCache, RefBlockManager,
                                     greedy_accept_length,
                                     llama_prefill_chunk_paged,
                                     llama_prefill_paged,
                                     llama_verify_chunk_paged,
                                     spec_advance_frontiers,
                                     spec_rewind_lens,
                                     stochastic_accept_row)


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _fresh(cfg, nb=16, bs=4, slots=2, mb=8):
    return PagedKVCache.init(cfg.num_hidden_layers, nb, bs,
                             cfg.num_key_value_heads,
                             cfg.hidden_size // cfg.num_attention_heads,
                             slots, mb, cfg.dtype)


def _prefill(model, cache, slot, seq, mgr, key, mb=8):
    t = mgr.allocate(key, len(seq))
    rows = np.full((1, mb), mgr.num_blocks, np.int32)
    rows[0, :len(t)] = t
    last, cache = llama_prefill_paged(
        model, jnp.asarray(np.asarray(seq)[None]),
        jnp.asarray([len(seq)]), cache,
        jnp.asarray([slot], jnp.int32), jnp.asarray(rows))
    return last, cache, t


# ------------------------------------------------------- pure-state unit

def test_rewind_touches_only_lens(model):
    cache = _fresh(model.cfg)
    cache = PagedKVCache(cache.k_pools, cache.v_pools, cache.block_tables,
                         cache.lens.at[:].set(jnp.asarray([11, 5])))
    tables_before = np.asarray(cache.block_tables).copy()
    out = spec_rewind_lens(cache, jnp.asarray([0], jnp.int32),
                           jnp.asarray([7], jnp.int32))
    assert np.asarray(out.lens).tolist() == [7, 5]
    np.testing.assert_array_equal(np.asarray(out.block_tables),
                                  tables_before)
    # sentinel slot ids (OOB) must drop, not clamp onto the last row
    out2 = spec_rewind_lens(out, jnp.asarray([0, 99], jnp.int32),
                            jnp.asarray([3, 1], jnp.int32))
    assert np.asarray(out2.lens).tolist() == [3, 5]


def test_advance_frontiers_scalar_and_array():
    pos, dpos = spec_advance_frontiers(10, 12, 3)
    assert (pos, dpos) == (13, 12)
    pos, dpos = spec_advance_frontiers(10, 15, 2)
    assert (pos, dpos) == (12, 12)      # frontier clamped back to pos
    p, d = spec_advance_frontiers(np.array([4, 8]), np.array([9, 8]),
                                  np.array([1, 3]))
    assert p.tolist() == [5, 11] and d.tolist() == [5, 8]


def test_greedy_accept_length_shapes():
    assert int(greedy_accept_length(np.array([3, 5, 7]), [3, 5, 9])) == 2
    assert int(greedy_accept_length(np.array([3, 5, 7]), [1, 5, 7])) == 0
    assert int(greedy_accept_length(np.array([3, 5, 7]), [3, 5, 7])) == 3
    out = greedy_accept_length(np.array([[1, 2], [1, 2]]),
                               np.array([[1, 9], [1, 2]]))
    assert out.tolist() == [1, 2]


def test_stochastic_accept_row_extremes():
    rs = np.random.RandomState(0)
    V = 8
    q = np.zeros(V); q[3] = 1.0
    # p == q on the proposal: always accepted, bonus from p[last]
    p_acc = [q.copy(), q.copy()]
    bonus = np.zeros(V); bonus[5] = 1.0
    new, n_acc = stochastic_accept_row([3], [q], [q, bonus], rs)
    assert (new, n_acc) == ([3, 5], 1)
    # p puts zero mass on the proposal: rejected, resample from p - q
    p0 = np.zeros(V); p0[6] = 1.0
    new, n_acc = stochastic_accept_row([3], [q], [p0, bonus], rs)
    assert (new, n_acc) == ([6], 0)


# -------------------------------------------- functional rewind + reuse

def test_rewind_past_block_boundary_then_reappend(model):
    """Verify writes 5 tokens crossing into a third block (lens 6→11),
    rewind keeps one (lens 7 — back across the block-2 boundary at 8),
    then appending the real continuation over the stale region yields
    logits identical to a straight prefill of the committed sequence."""
    cfg = model.cfg
    rs = np.random.RandomState(0)
    seq0 = rs.randint(0, 64, (6,))
    vtoks = rs.randint(0, 64, (5,))          # speculative: positions 6..10
    cont = rs.randint(0, 64, (3,))           # real continuation: 7..9

    mgr = RefBlockManager(16, 4)
    cache = _fresh(cfg)
    _, cache, _ = _prefill(model, cache, 0, seq0, mgr, "a")
    t = mgr.allocate("a", 11)                # cover the verify worst case
    rows = np.full((1, 8), 16, np.int32)
    rows[0, :len(t)] = t
    _, cache = llama_verify_chunk_paged(
        model, jnp.asarray(vtoks[None]), jnp.asarray([5], jnp.int32),
        jnp.asarray([6], jnp.int32), cache, jnp.asarray([0], jnp.int32),
        jnp.asarray(rows))
    assert int(np.asarray(cache.lens)[0]) == 11
    cache = spec_rewind_lens(cache, jnp.asarray([0], jnp.int32),
                             jnp.asarray([7], jnp.int32))
    assert int(np.asarray(cache.lens)[0]) == 7
    last, cache = llama_prefill_chunk_paged(
        model, jnp.asarray(cont[None]), jnp.asarray([3], jnp.int32),
        jnp.asarray([7], jnp.int32), cache, jnp.asarray([0], jnp.int32),
        jnp.asarray(rows))

    committed = np.concatenate([seq0, vtoks[:1], cont])
    ref_last, _, _ = _prefill(model, _fresh(cfg), 0, committed,
                              RefBlockManager(16, 4), "ref")
    np.testing.assert_allclose(np.asarray(last, np.float32),
                               np.asarray(ref_last, np.float32),
                               rtol=2e-4, atol=2e-5)


def test_rewind_to_zero_reuses_slot(model):
    """Full rollback: lens→0 leaves the slot reusable for an unrelated
    sequence over the same block rows."""
    cfg = model.cfg
    rs = np.random.RandomState(1)
    seq0 = rs.randint(0, 64, (9,))
    seq1 = rs.randint(0, 64, (7,))

    mgr = RefBlockManager(16, 4)
    cache = _fresh(cfg)
    _, cache, t = _prefill(model, cache, 0, seq0, mgr, "a")
    cache = spec_rewind_lens(cache, jnp.asarray([0], jnp.int32),
                             jnp.asarray([0], jnp.int32))
    assert int(np.asarray(cache.lens)[0]) == 0
    rows = np.full((1, 8), 16, np.int32)
    rows[0, :len(t)] = t
    last, cache = llama_prefill_chunk_paged(
        model, jnp.asarray(seq1[None]), jnp.asarray([7], jnp.int32),
        jnp.asarray([0], jnp.int32), cache, jnp.asarray([0], jnp.int32),
        jnp.asarray(rows))
    ref_last, _, _ = _prefill(model, _fresh(cfg), 0, seq1,
                              RefBlockManager(16, 4), "ref")
    np.testing.assert_allclose(np.asarray(last, np.float32),
                               np.asarray(ref_last, np.float32),
                               rtol=2e-4, atol=2e-5)


def test_rewind_after_preempt_replay_in_engine(model):
    """Engine-level: rewinds interleaved with evict/replay (the draft
    frontier resets to zero on preemption) still produce the exact
    greedy chain."""
    from paddle_tpu.serving import LLMEngine, Request
    from paddle_tpu.utils.faults import FAULTS
    rs = np.random.RandomState(4)
    prompts = [rs.randint(0, 64, (int(l),)) for l in rs.randint(4, 12, 3)]

    def run(eng):
        for p in prompts:
            eng.add_request(Request(p, max_new_tokens=8))
        return {r: list(map(int, t)) for r, t in eng.run().items()}

    base = run(LLMEngine(model, num_slots=2, block_size=4,
                         max_prompt_len=16, max_seq_len=32,
                         preemption=True))
    FAULTS.clear()
    FAULTS.install("serving.preempt", every=4, times=5,
                   action=lambda ctx: ctx["engine"]._preempt())
    eng = LLMEngine(model, draft_model=model, spec_k=3, num_slots=2,
                    block_size=4, max_prompt_len=16, max_seq_len=32,
                    preemption=True)
    spec = run(eng)
    FAULTS.clear()
    assert eng.stats["preemptions"] > 0
    assert spec == base
    eng.assert_quiescent()
