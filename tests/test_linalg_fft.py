"""paddle_tpu.linalg / fft / signal vs numpy + torch golden values."""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from paddle_tpu import fft as pfft
from paddle_tpu import linalg as L
from paddle_tpu import signal as S


def _spd(n, rs):
    a = rs.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


rs = np.random.RandomState(0)


def test_cholesky_and_solve():
    a = _spd(5, rs)
    b = rs.randn(5, 3).astype(np.float32)
    low = L.cholesky(jnp.asarray(a))
    assert np.allclose(np.asarray(low @ low.T), a, atol=1e-3)
    up = L.cholesky(jnp.asarray(a), upper=True)
    assert np.allclose(np.asarray(up), np.asarray(low).T, atol=1e-5)
    x = L.cholesky_solve(jnp.asarray(b), low)
    assert np.allclose(np.asarray(jnp.asarray(a) @ x), b, atol=1e-3)
    x2 = L.solve(jnp.asarray(a), jnp.asarray(b))
    assert np.allclose(np.asarray(x2), np.linalg.solve(a, b), atol=1e-3)


def test_det_inv_pinv_rank():
    a = _spd(4, rs)
    assert np.allclose(float(L.det(jnp.asarray(a))), np.linalg.det(a), rtol=1e-3)
    sign, logabs = L.slogdet(jnp.asarray(a))
    s2, l2 = np.linalg.slogdet(a)
    assert float(sign) == s2 and np.allclose(float(logabs), l2, rtol=1e-4)
    assert np.allclose(np.asarray(L.inv(jnp.asarray(a))), np.linalg.inv(a), atol=1e-4)
    r = rs.randn(6, 3).astype(np.float32)
    assert np.allclose(np.asarray(L.pinv(jnp.asarray(r))), np.linalg.pinv(r), atol=1e-4)
    assert int(L.matrix_rank(jnp.asarray(r))) == np.linalg.matrix_rank(r)


def test_qr_svd_eigh():
    a = rs.randn(6, 4).astype(np.float32)
    q, r = L.qr(jnp.asarray(a))
    assert np.allclose(np.asarray(q @ r), a, atol=1e-4)
    assert np.allclose(np.asarray(q.T @ q), np.eye(4), atol=1e-4)
    u, s, vh = L.svd(jnp.asarray(a))
    assert np.allclose(np.asarray((u * s) @ vh), a, atol=1e-4)
    assert np.allclose(np.asarray(L.svdvals(jnp.asarray(a))),
                       np.linalg.svd(a, compute_uv=False), atol=1e-4)
    spd = _spd(5, rs)
    w, v = L.eigh(jnp.asarray(spd))
    assert np.allclose(np.asarray(v @ jnp.diag(w) @ v.T), spd, atol=1e-2)


def test_eig_host_callback():
    a = rs.randn(5, 5).astype(np.float32)
    w, v = L.eig(jnp.asarray(a))
    # A v = v diag(w)
    assert np.allclose(np.asarray(jnp.asarray(a).astype(jnp.complex64) @ v),
                       np.asarray(v @ jnp.diag(w)), atol=1e-3)
    wv = L.eigvals(jnp.asarray(a))
    assert np.allclose(sorted(np.asarray(w).real), sorted(np.asarray(wv).real), atol=1e-3)
    # works under jit too (pure_callback)
    wj = jax.jit(L.eigvals)(jnp.asarray(a))
    assert np.allclose(sorted(np.asarray(wj).real), sorted(np.asarray(wv).real), atol=1e-3)


def test_lu_and_unpack():
    a = rs.randn(5, 5).astype(np.float32)
    lu_data, piv = L.lu(jnp.asarray(a))
    P, Lo, U = L.lu_unpack(lu_data, piv)
    assert np.allclose(np.asarray(P @ Lo @ U), a, atol=1e-4)


def test_householder_product_vs_torch():
    a = rs.randn(6, 4).astype(np.float32)
    ta, tau = torch.geqrf(torch.tensor(a))
    want = torch.linalg.householder_product(ta, tau).numpy()
    got = L.householder_product(jnp.asarray(ta.numpy()), jnp.asarray(tau.numpy()))
    assert np.allclose(np.asarray(got), want, atol=1e-4)


def test_lstsq_triangular_matrix_fns():
    a = rs.randn(8, 3).astype(np.float32)
    b = rs.randn(8, 2).astype(np.float32)
    sol, _, _, _ = L.lstsq(jnp.asarray(a), jnp.asarray(b))
    want, *_ = np.linalg.lstsq(a, b, rcond=None)
    assert np.allclose(np.asarray(sol), want, atol=1e-3)
    tri = np.triu(_spd(4, rs))
    y = rs.randn(4, 2).astype(np.float32)
    x = L.triangular_solve(jnp.asarray(tri), jnp.asarray(y), upper=True)
    assert np.allclose(np.asarray(jnp.asarray(tri) @ x), y, atol=1e-3)
    m = rs.randn(3, 3).astype(np.float32) * 0.1
    assert np.allclose(np.asarray(L.matrix_exp(jnp.asarray(m))),
                       torch.matrix_exp(torch.tensor(m)).numpy(), atol=1e-4)
    assert np.allclose(np.asarray(L.matrix_power(jnp.asarray(m), 3)),
                       np.linalg.matrix_power(m, 3), atol=1e-5)


def test_norms_cond_cov():
    a = rs.randn(4, 5).astype(np.float32)
    for p in ["fro", "nuc", 1, 2, np.inf]:
        want = np.asarray(torch.linalg.matrix_norm(torch.tensor(a), ord=p))
        got = L.norm(jnp.asarray(a), p=p, axis=(-2, -1))
        assert np.allclose(np.asarray(got), want, rtol=1e-4), p
    v = rs.randn(7).astype(np.float32)
    assert np.allclose(float(L.vector_norm(jnp.asarray(v), p=3)),
                       np.sum(np.abs(v) ** 3) ** (1 / 3), rtol=1e-4)
    spd = _spd(4, rs)
    assert np.allclose(float(L.cond(jnp.asarray(spd))), np.linalg.cond(spd), rtol=1e-3)
    x = rs.randn(3, 10).astype(np.float32)
    assert np.allclose(np.asarray(L.cov(jnp.asarray(x))), np.cov(x), atol=1e-4)
    assert np.allclose(np.asarray(L.corrcoef(jnp.asarray(x))), np.corrcoef(x), atol=1e-4)
    assert np.allclose(float(L.dist(jnp.asarray(v), jnp.zeros(7))),
                       np.linalg.norm(v), rtol=1e-5)
    ms = [jnp.asarray(rs.randn(3, 4).astype(np.float32)),
          jnp.asarray(rs.randn(4, 5).astype(np.float32)),
          jnp.asarray(rs.randn(5, 2).astype(np.float32))]
    assert np.allclose(np.asarray(L.multi_dot(ms)),
                       np.asarray(ms[0]) @ np.asarray(ms[1]) @ np.asarray(ms[2]),
                       atol=1e-4)


# -- fft ---------------------------------------------------------------------

def test_fft_roundtrip_and_golden():
    x = rs.randn(4, 16).astype(np.float32)
    X = pfft.fft(jnp.asarray(x))
    assert np.allclose(np.asarray(X), np.fft.fft(x), atol=1e-4)
    assert np.allclose(np.asarray(pfft.ifft(X)).real, x, atol=1e-5)
    Xr = pfft.rfft(jnp.asarray(x), norm="ortho")
    assert np.allclose(np.asarray(Xr), np.fft.rfft(x, norm="ortho"), atol=1e-4)
    assert np.allclose(np.asarray(pfft.irfft(Xr, norm="ortho")), x, atol=1e-5)
    x2 = rs.randn(3, 8, 8).astype(np.float32)
    assert np.allclose(np.asarray(pfft.fft2(jnp.asarray(x2))), np.fft.fft2(x2), atol=1e-3)
    assert np.allclose(np.asarray(pfft.fftshift(jnp.asarray(x))), np.fft.fftshift(x))
    assert np.allclose(np.asarray(pfft.fftfreq(10, 0.1)), np.fft.fftfreq(10, 0.1))
    assert np.allclose(np.asarray(pfft.rfftfreq(10)), np.fft.rfftfreq(10))


# -- signal ------------------------------------------------------------------

def test_frame_overlap_add_roundtrip():
    x = rs.randn(2, 32).astype(np.float32)
    fr = S.frame(jnp.asarray(x), 8, 8)  # non-overlapping
    assert fr.shape == (2, 8, 4)
    back = S.overlap_add(fr, 8)
    assert np.allclose(np.asarray(back), x, atol=1e-6)


def test_stft_istft_vs_torch():
    x = rs.randn(2, 64).astype(np.float32)
    win = np.hanning(16).astype(np.float32)
    got = S.stft(jnp.asarray(x), n_fft=16, hop_length=4, window=jnp.asarray(win))
    want = torch.stft(torch.tensor(x), n_fft=16, hop_length=4,
                      window=torch.tensor(win), return_complex=True,
                      center=True, pad_mode="reflect").numpy()
    assert got.shape == want.shape
    assert np.allclose(np.asarray(got), want, atol=1e-3)
    # istft round-trips
    rec = S.istft(got, n_fft=16, hop_length=4, window=jnp.asarray(win),
                  length=64)
    assert np.allclose(np.asarray(rec), x, atol=1e-3)
