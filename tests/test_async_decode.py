"""Async pipelined decode (ISSUE 20): depth-K deferred-sync decode loop.

* bit-identity at depth ∈ {1, 2, 4} vs depth 0 — greedy, temperature,
  temperature+EOS (rng rewind over the masked suffix), chunked prefill,
  preemption/replay chaos, radix prefix adoption
* forced per-tick drains for grammar slots and spec-decode ticks (the
  pipeline de-pipelines for THAT tick, never permanently)
* device stop mask at the exact EOS boundary: a lone slot bills zero
  ``async_overrun`` waste
* ``serving.tick`` chaos mid-window: exception-atomic drain, identical
  mid-fault and final streams, pool quiescent
* ``PT_ASYNC_DECODE=0`` kill switch traces EXACTLY the pre-PR program
  (breadcrumb-guarded)
* ``async_overrun`` arithmetic: a stream-callback cancel mid-cruise
  bills exactly ``depth`` over-dispatched rows
* satellite: spec-decode host sampling gathers only non-greedy rows
  (fetched byte count asserted), ``PT_GAUGE_EVERY_S`` sweep throttle
  with exact forced sweeps at finish/run()-end boundaries
"""
import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import clear_jit_caches
from paddle_tpu.observability import GOODPUT, METRICS
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=3, block_size=4, max_prompt_len=16,
                max_seq_len=64, seed=7)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(rs, n=6, lo=3, hi=14):
    return [rs.randint(2, 64, (int(l),))
            for l in rs.randint(lo, hi, size=n)]


def _run(eng, prompts, new=10, **rkw):
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=new, **rkw))
    out = eng.run()
    eng.assert_quiescent()
    return {r: list(map(int, t)) for r, t in out.items()}


def _drains():
    c = METRICS.get("serving_async_drains_total")
    return {k[0]: v[0] for k, v in c._series.items()}


# ------------------------------------------------------- bit-identity
@pytest.mark.parametrize("depth", [1, 2, 4])
def test_bit_identity_greedy_temperature_eos(model, depth):
    rs = np.random.RandomState(3)
    prompts = _prompts(rs)
    for kw in (dict(), dict(temperature=0.8),
               dict(temperature=0.8, eos_token_id=1)):
        base = _run(_mk(model, **kw), prompts)
        got = _run(_mk(model, async_depth=depth, **kw), prompts)
        assert got == base, (depth, kw)
    # the pipeline actually engaged (drains observed, depth gauge set)
    assert sum(_drains().values()) > 0
    assert METRICS.get("serving_async_depth").value() == depth


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_bit_identity_chunked_prefill(model, depth):
    rs = np.random.RandomState(5)
    prompts = [rs.randint(2, 64, (40,)), rs.randint(2, 64, (9,)),
               rs.randint(2, 64, (25,))]
    kw = dict(num_slots=2, max_prompt_len=8)
    base = _run(_mk(model, **kw), prompts, new=8)
    got = _run(_mk(model, async_depth=depth, **kw), prompts, new=8)
    assert got == base


@pytest.mark.chaos
def test_bit_identity_preempt_replay_chaos(model):
    rs = np.random.RandomState(3)
    prompts = _prompts(rs)

    def run(depth):
        FAULTS.clear()
        FAULTS.install("serving.preempt", every=4, times=3,
                       action=lambda ctx: ctx["engine"]._preempt())
        eng = _mk(model, num_slots=2, max_seq_len=48, preemption=True,
                  async_depth=depth)
        out = _run(eng, prompts)
        FAULTS.clear()
        assert eng.stats["preemptions"] > 0
        return out

    base = run(0)
    for depth in (1, 2):
        assert run(depth) == base, depth


def test_bit_identity_radix_adoption(model):
    """Two waves of shared-prefix prompts: the second wave adopts
    committed blocks from the radix trie mid-pipeline."""
    rs = np.random.RandomState(11)
    stem = rs.randint(2, 64, (10,))
    waves = [np.concatenate([stem, rs.randint(2, 64, (int(k),))])
             for k in (3, 5, 2)]

    def run(depth):
        eng = _mk(model, prefix_caching=True, async_depth=depth)
        first = _run(eng, [stem], new=6)
        second = {}
        for p in waves:
            rid = eng.add_request(Request(p, max_new_tokens=6))
            second.update({r: list(map(int, t))
                           for r, t in eng.run().items() if r == rid})
        eng.assert_quiescent()
        saved = GOODPUT.saved_total()
        return first, second, saved

    b1, b2, bsaved = run(0)
    g1, g2, gsaved = run(2)
    assert (g1, g2) == (b1, b2)
    assert bsaved > 0 and gsaved > bsaved  # adoption really happened


# ------------------------------------------------------- forced drains
def test_grammar_slot_forces_per_tick_drain(model):
    """A grammar-constrained slot must see the host automaton before
    every next token: while one is live the engine never runs ahead
    (window empty every tick), a mid-cruise grammar arrival drains the
    standing window first, and the streams stay identical."""
    from paddle_tpu.serving.grammar import TokenMaskAutomaton
    vocab = [chr(ord("a") + i % 26) for i in range(63)] + [""]
    aut = TokenMaskAutomaton("[ab]{6}", vocab=vocab, eos_token_id=63)
    rs = np.random.RandomState(4)
    plain = rs.randint(2, 64, (6,))
    gram = rs.randint(2, 64, (5,))

    def run(depth):
        eng = _mk(model, eos_token_id=63, async_depth=depth,
                  block_size=16, max_seq_len=64)
        state = {}

        def arrive(req, tok):
            # token 8 lands mid-cruise (the first ticks drain inside the
            # admission/prefill step itself, before any window forms)
            if len(req.tokens) == 8 and "r1" not in state:
                state["r1"] = eng.add_request(
                    Request(gram, max_new_tokens=6, grammar=aut))

        eng.add_request(Request(plain, max_new_tokens=12, stream=arrive))
        cruised = False
        while eng.has_work():
            eng.step()
            cruised = cruised or bool(eng._async_win)
            if depth and eng._grammar:
                assert not eng._async_win    # grammar => per-tick drain
        eng.assert_quiescent()
        assert "r1" in state                 # arrival really happened
        if depth:
            assert cruised                   # pipeline engaged pre-arrival
        return {r: list(map(int, q.tokens)) for r, q in eng.requests.items()}

    base = run(0)
    got = run(2)
    assert got == base
    assert _drains().get("admit", 0) > 0     # arrival drained the window


def test_spec_tick_forces_drain_not_permanent_depipelining(model, draft):
    rs = np.random.RandomState(6)
    prompts = _prompts(rs, n=4)

    def run(depth):
        eng = _mk(model, draft_model=draft, spec_k=3, async_depth=depth)
        out = _run(eng, prompts, new=8)
        assert eng.stats["spec_ticks"] > 0     # spec still runs at depth>0
        return out, eng.stats["spec_ticks"]

    base, bticks = run(0)
    got, gticks = run(2)
    assert got == base
    assert gticks == bticks                    # same spec cadence, any depth


def test_spec_toggle_mid_cruise_drains_with_why_spec(model, draft,
                                                     monkeypatch):
    """PT_SPEC_DECODE flipped on while the pipeline is cruising: the
    next step must drain the standing window (why=spec) before the spec
    tick runs — and greedy spec identity keeps the stream bit-equal to
    the never-spec baseline."""
    monkeypatch.setenv("PT_SPEC_DECODE", "0")
    rs = np.random.RandomState(7)
    p = rs.randint(2, 64, (6,))
    kw = dict(num_slots=1, block_size=16, max_seq_len=64,
              draft_model=draft, spec_k=3)
    base = _run(_mk(model, **kw), [p], new=12)

    def flip(req, tok):
        if len(req.tokens) == 3:
            os.environ["PT_SPEC_DECODE"] = "1"

    eng = _mk(model, async_depth=2, **kw)
    eng.add_request(Request(p, max_new_tokens=12, stream=flip))
    out = eng.run()
    eng.assert_quiescent()
    assert {r: list(map(int, t)) for r, t in out.items()} == base
    assert _drains().get("spec", 0) > 0
    assert eng.stats["spec_ticks"] > 0         # spec engaged after the flip


# ----------------------------------------------------- EOS stop mask
def test_eos_stop_mask_exact_boundary_no_overrun(model):
    """Lone slot, natural EOS: the device stop mask must catch the
    boundary inside the jit — over-dispatched ticks run fully masked
    (never billed as waste) and the rng rewind leaves the key stream
    exactly where the synchronous loop ends."""
    rs = np.random.RandomState(9)
    p = rs.randint(2, 64, (7,))
    probe = _run(_mk(model, num_slots=1), [p], new=10)
    eos = next(iter(probe.values()))[4]        # a token greedy really emits

    def run(depth):
        eng = _mk(model, num_slots=1, eos_token_id=eos, async_depth=depth)
        out = _run(eng, [p], new=10)
        (req,) = eng.requests.values()
        assert req.finish_reason == "eos"      # the boundary was exercised
        return out

    base = run(0)
    for depth in (1, 2, 4):
        assert run(depth) == base, depth
    assert GOODPUT.waste_by_why().get("async_overrun", 0) == 0


# ------------------------------------------------------------ chaos
@pytest.mark.chaos
def test_tick_chaos_mid_window_exception_atomic(model):
    """A serving.tick fault raised while ticks are in flight must drain
    the window first (why=exception): the request state at the moment
    the fault surfaces — and after recovery — is bit-identical to the
    synchronous engine's, and the pool is clean."""
    rs = np.random.RandomState(3)
    prompts = _prompts(rs, n=2)

    def run(depth):
        FAULTS.clear()
        FAULTS.install("serving.tick", on={5}, exc=InjectedFault)
        eng = _mk(model, num_slots=2, block_size=16, max_seq_len=64,
                  async_depth=depth)
        for p in prompts:
            eng.add_request(Request(p, max_new_tokens=10))
        mid = None
        try:
            while eng.has_work():
                eng.step()
        except InjectedFault:
            mid = {r: list(map(int, q.tokens))
                   for r, q in eng.requests.items()}
            while eng.has_work():          # recover past the fault
                eng.step()
        FAULTS.clear()
        eng.assert_quiescent()
        assert mid is not None             # the fault really fired
        out = {r: list(map(int, q.tokens)) for r, q in eng.requests.items()}
        return mid, out

    b_mid, b_out = run(0)
    for depth in (1, 2):
        g_mid, g_out = run(depth)
        assert g_mid == b_mid, depth       # drained atomically at the fault
        assert g_out == b_out, depth
    assert _drains().get("exception", 0) > 0


# -------------------------------------------------------- kill switch
def test_kill_switch_traces_exact_pre_pr_program(model, monkeypatch):
    """PT_ASYNC_DECODE=0 collapses async_depth at construction: the
    engine never traces the async tick program (breadcrumb-guarded) and
    the stream is bit-exact."""
    rs = np.random.RandomState(13)
    prompts = _prompts(rs, n=4)
    base = _run(_mk(model), prompts)

    clear_jit_caches()
    pa._trace_events.clear()
    got = _run(_mk(model, async_depth=2), prompts)
    assert got == base
    assert "tick:async" in pa._trace_events    # pipeline traced its twin

    monkeypatch.setenv("PT_ASYNC_DECODE", "0")
    before = sum(_drains().values())
    clear_jit_caches()
    pa._trace_events.clear()
    eng = _mk(model, async_depth=2)
    assert eng.async_depth == 0
    killed = _run(eng, prompts)
    assert killed == base
    assert "tick:async" not in pa._trace_events  # the pre-PR program only
    assert sum(_drains().values()) == before     # no window ever formed


def test_async_depth_validation(model):
    with pytest.raises(ValueError, match="async_depth"):
        _mk(model, async_depth=-1)


# ----------------------------------------------------- overrun ledger
def test_async_overrun_arithmetic_exact(model):
    """Cancel fired from a stream callback mid-cruise: the already
    dispatched window ticks keep computing the dead slot — exactly
    ``depth`` rows bill ``async_overrun``, and the cancelled stream is
    bit-identical to the synchronous engine under the same callback."""
    rs = np.random.RandomState(8)
    pa_, pb = rs.randint(2, 64, (4,)), rs.randint(2, 64, (5,))
    depth = 3

    def run(d):
        eng = _mk(model, num_slots=2, block_size=16, max_seq_len=64,
                  async_depth=d)
        state = {}

        def cb(req, tok):
            if len(req.tokens) == 3:
                eng.cancel(state["rb"], reason="cancelled")

        ra = eng.add_request(Request(pa_, max_new_tokens=8, stream=cb))
        state["rb"] = eng.add_request(Request(pb, max_new_tokens=8))
        eng.run()
        eng.assert_quiescent()
        assert eng.requests[state["rb"]].finish_reason == "cancelled"
        return {r: list(map(int, q.tokens)) for r, q in
                eng.requests.items()}

    base = run(0)
    assert GOODPUT.waste_by_why().get("async_overrun", 0) == 0
    got = run(depth)
    assert got == base
    assert GOODPUT.waste_by_why().get("async_overrun", 0) == depth


# ------------------------------------- satellite: spec fetch gathering
def test_spec_fetch_bytes_gathers_only_nongreedy_rows(model, draft,
                                                      monkeypatch):
    """Host spec sampling must fetch the full [rows, V] block only for
    the NON-greedy rows (gathered on device); greedy rows ride the [ns]
    argmax fetch. Byte count asserted exactly."""
    monkeypatch.setenv("PT_SPEC_DECODE", "0")     # admit via the plain tick
    rs = np.random.RandomState(2)
    eng = _mk(model, draft_model=draft, spec_k=3, num_slots=2)
    r0 = eng.add_request(Request(rs.randint(2, 64, (5,)),
                                 max_new_tokens=8))
    r1 = eng.add_request(Request(rs.randint(2, 64, (6,)),
                                 max_new_tokens=8, temperature=0.7))
    eng.step()
    monkeypatch.delenv("PT_SPEC_DECODE")
    eng._spec_fetch_bytes = 0
    staged = [(0, r0, 3), (1, r1, 3)]
    seqs = {s: eng._committed_seq(s) for s in (0, 1)}
    props, _ = eng._spec_draft(staged, seqs)
    assert len(props[0]) == 3 and len(props[1]) == 3
    ns, V, k = 2, 64, 3
    am_item = jnp.argmax(jnp.zeros((2, 2), jnp.float32), axis=-1) \
        .dtype.itemsize
    # 3 pick_all calls (steady + 2 rounds), each: [ns] argmax ints for
    # the greedy row + ONE gathered [1, V] f32 row for the temp slot
    want = k * (ns * am_item + 1 * V * 4)
    assert eng._spec_fetch_bytes == want
    assert want < k * ns * V * 4              # vs the old full-block fetch

    # all-greedy staging never fetches a V-wide row at all
    eng._spec_fetch_bytes = 0
    eng.temps[1] = 0.0
    eng._spec_draft(staged, {s: eng._committed_seq(s) for s in (0, 1)})
    assert eng._spec_fetch_bytes == k * ns * am_item


# --------------------------------------- satellite: gauge sweep throttle
def test_gauge_throttle_skips_sweeps_forces_boundaries(model, monkeypatch):
    rs = np.random.RandomState(3)
    prompts = _prompts(rs)
    eng = _mk(model)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8))
    while eng.has_work():
        eng.step()
    default_sweeps, ticks = eng._gauge_sweeps, eng.stats["ticks"]
    assert default_sweeps >= ticks            # default: every tick, unchanged

    monkeypatch.setenv("PT_GAUGE_EVERY_S", "3600")
    eng2 = _mk(model)
    for p in prompts:
        eng2.add_request(Request(p, max_new_tokens=8))
    out = eng2.run()
    assert len(out) == len(prompts)
    assert eng2._gauge_sweeps < default_sweeps   # the throttle really bit
    # boundary exactness: run()-end forced sweep published final state
    assert METRICS.get("serving_active_slots").value() == 0
    assert METRICS.get("serving_queue_depth").value() == 0
    eng2.assert_quiescent()


def test_gauge_throttle_async_bench_combo(model, monkeypatch):
    """The bench-leg configuration: depth-2 pipeline + throttled sweep
    still emits the bit-identical stream."""
    rs = np.random.RandomState(3)
    prompts = _prompts(rs)
    base = _run(_mk(model), prompts)
    monkeypatch.setenv("PT_GAUGE_EVERY_S", "3600")
    got = _run(_mk(model, async_depth=2), prompts)
    assert got == base
