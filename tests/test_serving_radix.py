"""Radix-trie prefix cache: token-level sharing + copy-on-write (ISSUE 10).

* trie insert/match/split on block-edge boundaries; partial tails match
  at TOKEN granularity (flat hash-block caching would score zero here)
* COW fork mid-block: the boundary block is shared read-only, the
  adopter gets a private copy via the host-side copy plan; a cancelled
  adopter (freed before the plan drains) leaks nothing
* leaf-LRU eviction reclaims parked blocks least-recently-touched
  first; the ``serving.prefix_evict`` chaos site is exception-atomic
* refcount conservation under adopt/free interleavings
* ``PT_RADIX_CACHE=0`` restores the flat manager bit-for-bit
* engine-level: greedy outputs identical cache-on vs cache-off vs fresh
  engine, including preempt+replay and chunked prefill
Ref capability: SGLang RadixAttention over vLLM-style paging.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.paged import (PrefixCachingBlockManager, PrefixMatch,
                                     RadixPrefixBlockManager)
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _solo(model, p, n):
    return np.asarray(generate(model, jnp.asarray(np.asarray(p)[None]),
                               max_new_tokens=n))[0, len(p):]


# ----------------------------------------------------------- trie unit
def test_match_token_granularity_and_cow_offer():
    mgr = RadixPrefixBlockManager(num_blocks=8, block_size=4)
    toks = np.arange(10, dtype=np.int32)           # 2 full blocks + tail(2)
    mgr.allocate(1, 10)
    mgr.commit_prefix(1, toks)
    t1 = list(mgr.tables[1])
    # identical prompt: cap at len-1 -> 9 tokens = 2 full blocks + 1 COW tok
    m = mgr.match_prefix(toks)
    assert isinstance(m, PrefixMatch)
    assert list(m) == t1[:2] and len(m) == 2
    assert m.token_count == 9
    assert m.cow == (t1[2], 1)
    # divergence mid-block 2: 6 shared tokens -> 1 full block + 2 COW toks
    other = np.concatenate([toks[:6], np.full(6, 63)]).astype(np.int32)
    m2 = mgr.match_prefix(other)
    assert list(m2) == t1[:1]
    assert m2.token_count == 6 and m2.cow == (t1[1], 2)
    # exact block-boundary divergence: full blocks only, no COW
    edge = np.concatenate([toks[:8], np.full(4, 63)]).astype(np.int32)
    m3 = mgr.match_prefix(edge)
    assert list(m3) == t1[:2] and m3.cow is None and m3.token_count == 8
    # no overlap at all is falsy
    assert not mgr.match_prefix(np.full(8, 50, np.int32))
    assert mgr.cache_stats["lookup_tokens"] > 0


def test_commit_extends_partial_tail_in_place():
    mgr = RadixPrefixBlockManager(num_blocks=8, block_size=4)
    toks = np.arange(14, dtype=np.int32)
    mgr.allocate(1, 10)
    mgr.commit_prefix(1, toks[:10])                # partial tail (2 tokens)
    mgr.allocate(1, 14)                            # same seq grows
    mgr.commit_prefix(1, toks)                     # extends, no new node
    t1 = list(mgr.tables[1])
    assert len(mgr._root.children) == 1            # one edge, extended
    m = mgr.match_prefix(np.append(toks, 63).astype(np.int32))
    assert list(m) == t1[:3]
    assert m.token_count == 14 and m.cow == (t1[3], 2)


def test_split_on_block_boundary_shares_both_branches():
    mgr = RadixPrefixBlockManager(num_blocks=12, block_size=4)
    a = np.arange(12, dtype=np.int32)
    b = np.concatenate([a[:8], np.full(4, 60)]).astype(np.int32)
    mgr.allocate(1, 12)
    mgr.commit_prefix(1, a)
    ta = list(mgr.tables[1])
    mgr.allocate(2, 12)
    mgr.commit_prefix(2, b)                        # splits a's edge at 8
    tb = list(mgr.tables[2])
    upper = mgr._root.children[0]
    assert len(upper.tokens) == 8 and len(upper.children) == 2
    # querying either branch walks the shared upper then its own tail
    ma = mgr.match_prefix(np.append(a, 63).astype(np.int32))
    assert list(ma) == ta[:3] and ma.token_count == 12
    mb = mgr.match_prefix(np.append(b, 63).astype(np.int32))
    assert list(mb) == ta[:2] + [tb[2]] and mb.token_count == 12


def test_cow_adopt_copy_plan_and_refcounts():
    mgr = RadixPrefixBlockManager(num_blocks=8, block_size=4)
    toks = np.arange(10, dtype=np.int32)
    mgr.allocate(1, 10)
    mgr.commit_prefix(1, toks)
    t1 = list(mgr.tables[1])
    m = mgr.match_prefix(toks)                     # 2 shared + COW on t1[2]
    table = mgr.adopt_prefix(2, m)
    assert table[:2] == t1[:2]
    dst = table[2]
    assert dst not in t1                           # private copy block
    assert mgr._rc[t1[0]] == 2 and mgr._rc[t1[1]] == 2
    assert mgr._rc[t1[2]] == 2                     # src pinned until drain
    assert mgr._rc[dst] == 1
    assert mgr.cache_stats["partial_hits"] == 1
    assert mgr.cache_stats["token_hits"] == 9
    plan = mgr.take_copy_plan()
    assert plan == [(t1[2], dst)]
    assert mgr._rc[t1[2]] == 1                     # pin dropped
    assert mgr.take_copy_plan() == []              # drained once
    mgr.free(2)
    mgr.free(1)
    assert mgr.free_blocks == mgr.num_blocks       # parked counts as free
    assert not mgr._rc


def test_cow_cancelled_before_drain_leaks_nothing():
    mgr = RadixPrefixBlockManager(num_blocks=6, block_size=4)
    toks = np.arange(7, dtype=np.int32)
    mgr.allocate(1, 7)
    mgr.commit_prefix(1, toks)
    mgr.free(1)                                    # both blocks park
    m = mgr.match_prefix(toks)                     # 1 shared + COW (2 toks)
    assert m.cow is not None
    mgr.adopt_prefix(2, m)
    mgr.free(2)                                    # adopter dies pre-drain
    assert mgr.take_copy_plan() == []              # order cancelled
    assert mgr.free_blocks == mgr.num_blocks
    assert not mgr._rc and not mgr._copy_dst


def test_leaf_lru_eviction_order():
    mgr = RadixPrefixBlockManager(num_blocks=4, block_size=4)
    a = np.arange(4, dtype=np.int32)
    b = np.arange(10, 14, dtype=np.int32)
    mgr.allocate(1, 4)
    mgr.commit_prefix(1, a)
    mgr.free(1)
    mgr.allocate(2, 4)
    mgr.commit_prefix(2, b)
    mgr.free(2)                                    # both parked
    assert mgr.free_blocks == 4
    # touch a AFTER b was committed: b is now the LRU leaf
    assert mgr.match_prefix(np.append(a, 63).astype(np.int32)).token_count \
        == 4
    mgr.allocate(3, 12)                            # 2 free + 1 eviction
    assert mgr.cache_stats["evictions"] == 1
    assert not mgr.match_prefix(np.append(b, 63).astype(np.int32))  # b gone
    assert mgr.match_prefix(np.append(a, 63).astype(np.int32)).token_count \
        == 4                                       # a survived
    mgr.allocate(4, 4)                             # forces a's eviction too
    assert mgr.cache_stats["evictions"] == 2
    assert not mgr.match_prefix(np.append(a, 63).astype(np.int32))
    mgr.free(3)
    mgr.free(4)
    assert mgr.free_blocks == mgr.num_blocks


def test_eviction_truncates_tail_blockwise():
    """Eviction reclaims ONE tail block at a time: a 3-block edge loses
    its deepest block first and the shorter prefix stays matchable."""
    mgr = RadixPrefixBlockManager(num_blocks=3, block_size=4)
    toks = np.arange(12, dtype=np.int32)
    mgr.allocate(1, 12)
    mgr.commit_prefix(1, toks)
    mgr.free(1)
    mgr.allocate(2, 4)                             # evicts deepest block
    assert mgr.cache_stats["evictions"] == 1
    m = mgr.match_prefix(np.append(toks, 63).astype(np.int32))
    assert m.token_count == 8                      # first 2 blocks remain
    mgr.free(2)


def test_chaos_prefix_evict_exception_atomic():
    mgr = RadixPrefixBlockManager(num_blocks=2, block_size=4)
    toks = np.arange(8, dtype=np.int32)
    mgr.allocate(1, 8)
    mgr.commit_prefix(1, toks)
    mgr.free(1)                                    # pool fully parked
    epoch = mgr.cache_epoch
    with FAULTS.scope("serving.prefix_evict", exc=InjectedFault,
                      every=1, times=1):
        with pytest.raises(InjectedFault):
            mgr.allocate(2, 4)
    mgr.tables.pop(2, None)                        # caller cleanup on fail
    # pre-mutation site: trie, parked set, stats, epoch all untouched
    assert mgr.cache_stats["evictions"] == 0
    assert mgr.cache_epoch == epoch
    assert mgr.free_blocks == mgr.num_blocks
    assert mgr.match_prefix(np.append(toks, 63).astype(np.int32)) \
        .token_count == 8
    # and the retried allocation succeeds once the fault clears
    mgr.allocate(2, 4)
    assert mgr.cache_stats["evictions"] == 1
    mgr.free(2)


def test_match_memo_invalidated_by_epoch():
    """cache_epoch bumps on commit AND eviction — the scheduler's memo
    key — on both managers."""
    for cls in (RadixPrefixBlockManager, PrefixCachingBlockManager):
        mgr = cls(num_blocks=2, block_size=4)
        e0 = mgr.cache_epoch
        mgr.allocate(1, 8)
        mgr.commit_prefix(1, np.arange(8, dtype=np.int32))
        assert mgr.cache_epoch > e0, cls.__name__
        e1 = mgr.cache_epoch
        mgr.free(1)
        mgr.allocate(2, 8)                         # forces eviction
        assert mgr.cache_epoch > e1, cls.__name__
        mgr.free(2)


# ---------------------------------------------------------- kill switch
def test_kill_switch_selects_flat_manager(model, monkeypatch):
    monkeypatch.setenv("PT_RADIX_CACHE", "0")
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    assert type(eng.mgr) is PrefixCachingBlockManager
    monkeypatch.delenv("PT_RADIX_CACHE")
    eng2 = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    assert type(eng2.mgr) is RadixPrefixBlockManager


# --------------------------------------------------------- engine level
def test_engine_partial_tail_cow_reuse(model):
    """7-token shared prefix over block_size=4: flat caching scores one
    block; the trie shares 7 of 7 tokens (1 block + 3 COW) and the
    output stays exactly solo-greedy."""
    rs = np.random.RandomState(11)
    pre = rs.randint(0, 64, (7,))
    p1 = np.concatenate([pre, rs.randint(0, 64, (4,))])
    p2 = np.concatenate([pre, rs.randint(0, 64, (4,))])
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=24)
    r1 = eng.add_request(Request(p1, max_new_tokens=4))
    out1 = eng.run()
    r2 = eng.add_request(Request(p2, max_new_tokens=4))
    out2 = eng.run()
    assert eng.mgr.cache_stats["partial_hits"] >= 1
    assert eng.mgr.cache_stats["token_hits"] >= 7
    np.testing.assert_array_equal(out1[r1], _solo(model, p1, 4))
    np.testing.assert_array_equal(out2[r2], _solo(model, p2, 4))
    eng.assert_quiescent()


def test_engine_greedy_identity_on_vs_off(model, monkeypatch):
    """The same prompt stream produces bit-identical greedy tokens on a
    warm radix engine, a flat-manager engine (PT_RADIX_CACHE=0), a
    cache-disabled engine, and a fresh solo generate."""
    rs = np.random.RandomState(12)
    pre = rs.randint(0, 64, (9,))
    prompts = [np.concatenate([pre, rs.randint(0, 64, (3,))])
               for _ in range(3)]

    def run_stream(eng):
        outs = []
        for p in prompts:                          # sequential: warm cache
            rid = eng.add_request(Request(p, max_new_tokens=5))
            outs.append(eng.run()[rid])
        return outs

    radix = run_stream(LLMEngine(model, num_slots=2, block_size=4,
                                 max_prompt_len=16, max_seq_len=24))
    monkeypatch.setenv("PT_RADIX_CACHE", "0")
    flat = run_stream(LLMEngine(model, num_slots=2, block_size=4,
                                max_prompt_len=16, max_seq_len=24))
    monkeypatch.delenv("PT_RADIX_CACHE")
    off = run_stream(LLMEngine(model, num_slots=2, block_size=4,
                               max_prompt_len=16, max_seq_len=24,
                               prefix_caching=False))
    for p, a, b, c in zip(prompts, radix, flat, off):
        sol = _solo(model, p, 5)
        np.testing.assert_array_equal(a, sol)
        np.testing.assert_array_equal(b, sol)
        np.testing.assert_array_equal(c, sol)


def test_engine_preempt_replay_radix_identity(model):
    """Oversubscribed pool with preemption: the victim's replay re-shares
    its own committed span through the trie and every output matches
    solo greedy."""
    rs = np.random.RandomState(13)
    p1 = rs.randint(0, 64, (7,))
    p2 = rs.randint(0, 64, (7,))
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=16,
                    max_seq_len=19, num_blocks=7, preemption=True)
    r1 = eng.add_request(Request(p1, max_new_tokens=12))
    r2 = eng.add_request(Request(p2, max_new_tokens=12))
    out = eng.run()
    assert eng.stats["preemptions"] >= 1
    assert eng.mgr.cache_stats["token_hits"] >= 1
    np.testing.assert_array_equal(out[r1], _solo(model, p1, 12))
    np.testing.assert_array_equal(out[r2], _solo(model, p2, 12))
    eng.assert_quiescent()


def test_engine_chunked_prefill_partial_reuse(model):
    """Long prompts (chunked prefill) diverging mid-block: the second
    request resumes from the token frontier, not the block floor."""
    rs = np.random.RandomState(14)
    base = rs.randint(0, 64, (18,))
    p1 = np.concatenate([base, rs.randint(0, 64, (2,))])
    p2 = np.concatenate([base, rs.randint(0, 64, (2,))])  # diverge @18
    eng = LLMEngine(model, num_slots=2, block_size=4, max_prompt_len=8,
                    max_seq_len=32)
    r1 = eng.add_request(Request(p1, max_new_tokens=4))
    out1 = eng.run()
    r2 = eng.add_request(Request(p2, max_new_tokens=4))
    out2 = eng.run()
    # 18 shared tokens = 4 full blocks + 2 COW tokens
    assert eng.mgr.cache_stats["token_hits"] >= 18
    assert eng.mgr.cache_stats["partial_hits"] >= 1
    np.testing.assert_array_equal(out1[r1], _solo(model, p1, 4))
    np.testing.assert_array_equal(out2[r2], _solo(model, p2, 4))
    eng.assert_quiescent()
