"""Weight-only int8/int4 LLM inference quantization (VERDICT r1 missing
#7): RTN + GPTQ (ref PaddleNLP weight_quantize / weight_only_linear /
llm GPTQ)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.quantization import (QuantizedWeight, gptq_quantize,
                                     quantize_llama_weights,
                                     weight_only_linear, weight_quantize,
                                     wo_matmul)


def test_weight_only_int8_close():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 32).astype(np.float32))
    w = jnp.asarray(rs.randn(32, 16).astype(np.float32))
    qw = weight_quantize(w, "weight_only_int8")
    y = weight_only_linear(x, qw)
    ref = x @ w
    rel = np.abs(np.asarray(y - ref)).max() / np.abs(np.asarray(ref)).max()
    assert rel < 0.02, rel
    assert qw.q.dtype == jnp.int8 and qw.q.shape == (32, 16)


@pytest.mark.parametrize("k", [32, 33])  # even + odd in-dims (packing)
def test_weight_only_int4_pack_roundtrip(k):
    rs = np.random.RandomState(1)
    w = jnp.asarray(rs.randn(k, 8).astype(np.float32))
    qw = weight_quantize(w, "weight_only_int4")
    assert qw.q.shape[0] == (k + 1) // 2  # two nibbles per byte along K
    unpacked = np.asarray(qw.unpack())
    assert unpacked.shape == (k, 8)
    assert unpacked.min() >= -8 and unpacked.max() <= 7
    # dequantized weight within one quantization step everywhere
    deq = np.asarray(qw.dequantize())
    step = np.asarray(qw.scale)[0]
    assert np.all(np.abs(deq - np.asarray(w)) <= step * 0.5 + 1e-7)


def test_gptq_beats_rtn_on_calibration():
    """GPTQ's error feedback must beat round-to-nearest on the calibration
    distribution (correlated features make the difference visible)."""
    rs = np.random.RandomState(2)
    m, k, n = 512, 64, 32
    # correlated inputs: low-rank mixing + noise
    basis = rs.randn(8, k)
    X = rs.randn(m, 8) @ basis + 0.1 * rs.randn(m, k)
    W = rs.randn(k, n)
    Xj, Wj = jnp.asarray(X, jnp.float32), jnp.asarray(W, jnp.float32)
    ref = np.asarray(Xj @ Wj)

    rtn = weight_quantize(Wj, "weight_only_int4")
    gptq = gptq_quantize(Wj, Xj, bits=4)
    err_rtn = float(np.mean((np.asarray(weight_only_linear(Xj, rtn)) - ref) ** 2))
    err_gptq = float(np.mean((np.asarray(weight_only_linear(Xj, gptq)) - ref) ** 2))
    assert err_gptq < err_rtn, (err_gptq, err_rtn)


def _tiny_model(seed=0):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(seed)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def test_llama_int8_generates_matching_tokens():
    """int8 weight-only LLaMA: logits within tolerance, greedy decode
    produces the same tokens as fp32 for several steps, and the projection
    memory shrinks ~4x."""
    from paddle_tpu.models.decoding import generate

    model = _tiny_model()
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 64, (2, 12)))
    ref_logits = model(ids)
    ref_tokens = generate(model, ids, max_new_tokens=6)

    qmodel = quantize_llama_weights(_tiny_model(), "weight_only_int8")
    got_logits = qmodel(ids)
    # logits close in the regions that matter (softmax scale)
    assert np.abs(np.asarray(got_logits - ref_logits)).max() < 0.1
    # top-1 agreement on nearly all positions (a random-init tiny model has
    # near-uniform logits, so exact greedy-trajectory equality is brittle)
    agree = np.mean(np.argmax(np.asarray(got_logits), -1)
                    == np.argmax(np.asarray(ref_logits), -1))
    assert agree >= 0.9, agree
    got_tokens = generate(qmodel, ids, max_new_tokens=6)
    assert got_tokens.shape == ref_tokens.shape
    np.testing.assert_array_equal(np.asarray(got_tokens)[:, :ids.shape[1]],
                                  np.asarray(ids))

    # memory: quantized projections ~1/4 the fp32 bytes
    lyr = qmodel.model.layers[0]
    orig = model.model.layers[0]
    for name in ("qkv_proj", "o_proj"):
        q = getattr(lyr.self_attn, name)
        o = getattr(orig.self_attn, name)
        assert isinstance(q, QuantizedWeight)
        assert q.nbytes() < o.size * o.dtype.itemsize / 3.5


def test_llama_int4_and_gptq_end_to_end():
    model = _tiny_model()
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(0, 64, (2, 12)))
    ref_logits = np.asarray(model(ids))

    q4 = quantize_llama_weights(_tiny_model(), "weight_only_int4")
    l4 = np.asarray(q4(ids))
    assert np.all(np.isfinite(l4))
    mse4 = float(np.mean((l4 - ref_logits) ** 2))

    qg = quantize_llama_weights(_tiny_model(), "gptq_int4", calib_ids=ids)
    lg = np.asarray(qg(ids))
    mseg = float(np.mean((lg - ref_logits) ** 2))
    # GPTQ calibrated on these very ids should not be materially worse
    assert mseg < mse4 * 1.5 + 1e-6, (mseg, mse4)

    # int4 projections ~1/8 the fp32 bytes (packed nibbles)
    q = q4.model.layers[0].self_attn.qkv_proj
    o = model.model.layers[0].self_attn.qkv_proj
    assert q.nbytes() < o.size * o.dtype.itemsize / 6


def test_paged_decode_works_with_weight_only():
    """Serving path composes: weight-only model through paged_generate."""
    from paddle_tpu.models.decoding import generate
    from paddle_tpu.models.paged import paged_generate

    qmodel = quantize_llama_weights(_tiny_model(), "weight_only_int8")
    rs = np.random.RandomState(5)
    b, s, new = 2, 10, 5
    ids = jnp.asarray(rs.randint(0, 64, (b, s)))
    ref = generate(qmodel, ids, max_new_tokens=new)
    got, _ = paged_generate(qmodel, ids, np.full((b,), s),
                            max_new_tokens=new, block_size=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
