"""Custom C++ op loading: compile with g++, call through pure_callback,
grads via the <name>_grad sibling (ref paddle.utils.cpp_extension)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

SRC = r"""
#include <cmath>
extern "C" void scaled_square(const float** ins, const long long* sizes,
                              int n_ins, float* out, long long out_size) {
    const float* x = ins[0];
    const float s = ins[1][0];
    for (long long i = 0; i < out_size; ++i) out[i] = s * x[i] * x[i];
}
extern "C" void scaled_square_grad(const float** ins, const long long* sizes,
                                   int n_ins, float* out, long long out_size) {
    // inputs: x, s, upstream g -> dx = 2 s x g
    const float* x = ins[0];
    const float s = ins[1][0];
    const float* g = ins[2];
    for (long long i = 0; i < out_size; ++i) out[i] = 2.0f * s * x[i] * g[i];
}
extern "C" void row_sums(const float** ins, const long long* sizes,
                         int n_ins, float* out, long long out_size) {
    // x flattened [rows, cols]; out [rows]
    long long cols = sizes[0] / out_size;
    for (long long r = 0; r < out_size; ++r) {
        float acc = 0.f;
        for (long long c = 0; c < cols; ++c) acc += ins[0][r * cols + c];
        out[r] = acc;
    }
}
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    return load("testops", [SRC],
                functions={"scaled_square": None,
                           "row_sums": lambda s: (s[0],)},
                build_directory=str(tmp_path_factory.mktemp("ext")))


def test_custom_op_forward(ext):
    x = jnp.asarray([1.0, 2.0, 3.0])
    s = jnp.asarray([2.0])
    out = ext.scaled_square(x, s)
    np.testing.assert_allclose(np.asarray(out), [2.0, 8.0, 18.0])


def test_custom_op_under_jit(ext):
    x = jnp.asarray([1.0, 2.0])
    s = jnp.asarray([3.0])
    out = jax.jit(lambda a, b: ext.scaled_square(a, b) + 1.0)(x, s)
    np.testing.assert_allclose(np.asarray(out), [4.0, 13.0])


def test_custom_op_grad(ext):
    x = jnp.asarray([1.0, 2.0, 3.0])
    s = jnp.asarray([2.0])
    g = jax.grad(lambda a: jnp.sum(ext.scaled_square(a, s)))(x)
    np.testing.assert_allclose(np.asarray(g), [4.0, 8.0, 12.0])


def test_custom_op_shape_fn(ext):
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = ext.row_sums(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(12.0).reshape(3, 4).sum(1))


def test_cuda_extension_raises():
    from paddle_tpu.utils.cpp_extension import CUDAExtension
    with pytest.raises(RuntimeError):
        CUDAExtension()
