"""Custom C++ op loading: compile with g++, call through pure_callback,
grads via the <name>_grad sibling (ref paddle.utils.cpp_extension)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

SRC = r"""
#include <cmath>
extern "C" void scaled_square(const float** ins, const long long* sizes,
                              int n_ins, float* out, long long out_size) {
    const float* x = ins[0];
    const float s = ins[1][0];
    for (long long i = 0; i < out_size; ++i) out[i] = s * x[i] * x[i];
}
extern "C" void scaled_square_grad(const float** ins, const long long* sizes,
                                   int n_ins, float* out, long long out_size) {
    // inputs: x, s, upstream g -> dx = 2 s x g
    const float* x = ins[0];
    const float s = ins[1][0];
    const float* g = ins[2];
    for (long long i = 0; i < out_size; ++i) out[i] = 2.0f * s * x[i] * g[i];
}
extern "C" void scaled_square_grad1(const float** ins, const long long* sizes,
                                    int n_ins, float* out, long long out_size) {
    // inputs: x, s, upstream g -> ds = sum(x^2 * g) (out_size == 1)
    const float* x = ins[0];
    const float* g = ins[2];
    float acc = 0.f;
    for (long long i = 0; i < sizes[0]; ++i) acc += x[i] * x[i] * g[i];
    out[0] = acc;
}
extern "C" void row_sums(const float** ins, const long long* sizes,
                         int n_ins, float* out, long long out_size) {
    // x flattened [rows, cols]; out [rows]
    long long cols = sizes[0] / out_size;
    for (long long r = 0; r < out_size; ++r) {
        float acc = 0.f;
        for (long long c = 0; c < cols; ++c) acc += ins[0][r * cols + c];
        out[r] = acc;
    }
}
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    return load("testops", [SRC],
                functions={"scaled_square": None,
                           "row_sums": lambda s: (s[0],)},
                build_directory=str(tmp_path_factory.mktemp("ext")))


def test_custom_op_forward(ext):
    x = jnp.asarray([1.0, 2.0, 3.0])
    s = jnp.asarray([2.0])
    out = ext.scaled_square(x, s)
    np.testing.assert_allclose(np.asarray(out), [2.0, 8.0, 18.0])


def test_custom_op_under_jit(ext):
    x = jnp.asarray([1.0, 2.0])
    s = jnp.asarray([3.0])
    out = jax.jit(lambda a, b: ext.scaled_square(a, b) + 1.0)(x, s)
    np.testing.assert_allclose(np.asarray(out), [4.0, 13.0])


def test_custom_op_grad(ext):
    x = jnp.asarray([1.0, 2.0, 3.0])
    s = jnp.asarray([2.0])
    g = jax.grad(lambda a: jnp.sum(ext.scaled_square(a, s)))(x)
    np.testing.assert_allclose(np.asarray(g), [4.0, 8.0, 12.0])


def test_custom_op_grad_second_input(ext):
    """<name>_grad1 provides input 1's cotangent (multi-input ABI)."""
    x = jnp.asarray([1.0, 2.0, 3.0])
    s = jnp.asarray([2.0])
    gs = jax.grad(lambda b: jnp.sum(ext.scaled_square(x, b)))(s)
    # d/ds sum(s x^2) = sum(x^2) = 14
    np.testing.assert_allclose(np.asarray(gs), [14.0])


def test_custom_op_missing_grad_is_nan_not_zero(tmp_path_factory):
    """An input without a grad symbol must fail LOUDLY (NaN), not silently
    return zeros (r1 advice / verdict sharp edge)."""
    from paddle_tpu.utils.cpp_extension import load
    src = r"""
extern "C" void mul2(const float** ins, const long long* sizes,
                     int n_ins, float* out, long long out_size) {
    for (long long i = 0; i < out_size; ++i) out[i] = ins[0][i] * ins[1][i];
}
extern "C" void mul2_grad(const float** ins, const long long* sizes,
                          int n_ins, float* out, long long out_size) {
    for (long long i = 0; i < out_size; ++i) out[i] = ins[1][i] * ins[2][i];
}
"""
    with pytest.warns(UserWarning):
        ops = load("mul2ops", [src], functions={"mul2": None},
                   build_directory=str(tmp_path_factory.mktemp("ext2")))
    a = jnp.asarray([1.0, 2.0])
    b = jnp.asarray([3.0, 4.0])
    ga = jax.grad(lambda u: jnp.sum(ops.mul2(u, b)))(a)
    np.testing.assert_allclose(np.asarray(ga), [3.0, 4.0])
    gb = jax.grad(lambda u: jnp.sum(ops.mul2(a, u)))(b)
    assert np.all(np.isnan(np.asarray(gb))), \
        "missing grad symbol must poison the cotangent, not zero it"


def test_custom_op_shape_fn(ext):
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = ext.row_sums(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(12.0).reshape(3, 4).sum(1))


def test_cuda_extension_raises():
    from paddle_tpu.utils.cpp_extension import CUDAExtension
    with pytest.raises(RuntimeError):
        CUDAExtension()
