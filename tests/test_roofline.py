"""Serving roofline ledger (ISSUE 12): peak tables and device-kind
detection (including the empty-kind fix on ``chip_peak_flops``), the
analytic per-phase FLOPs/bytes models and their verdicts, the
``record_serving_throughput`` choke point, the engine's decode-tick
anatomy (breakdown histogram reconciling with ``serving_tick_seconds``
tick-for-tick, by construction), and the acceptance criterion: the
bench-shaped engine exports a nonzero bandwidth-bound
``serving_mbu{decode}`` under ``PT_ROOFLINE_KIND`` while a plain CPU
run exports 0.0 (undefined, never fabricated)."""
import math

import numpy as np
import pytest

from paddle_tpu.observability.flops import PEAK_BF16, chip_peak_flops
from paddle_tpu.observability.metrics import METRICS
from paddle_tpu.observability.roofline import (
    PEAK_HBM_BPS, ModelGeometry, arith_intensity, chip_peak_hbm_bw,
    kv_bytes_per_position, phase_bytes, phase_flops,
    record_serving_throughput, reset_serving_roofline,
    resolve_serving_peaks, roofline_verdict, serving_roofline_report,
    weight_bytes)


@pytest.fixture(autouse=True)
def _clean_roofline():
    reset_serving_roofline()
    yield
    reset_serving_roofline()


class _Dev:
    def __init__(self, kind="", platform=""):
        self.device_kind = kind
        self.platform = platform


# ------------------------------------------------------------- peak tables
def test_peak_tables_cover_the_same_chips():
    assert set(PEAK_HBM_BPS) == set(PEAK_BF16)
    assert PEAK_HBM_BPS["TPU v5 lite"] == pytest.approx(819e9)
    assert PEAK_HBM_BPS["TPU v5p"] == pytest.approx(2765e9)


@pytest.mark.parametrize("kind,bw", [
    ("TPU v5 lite", 819e9), ("TPU v5e", 819e9), ("TPU v5p", 2765e9),
    ("TPU v4", 1228e9), ("TPU v6", 1640e9),
    ("TPU v99", 819e9),          # unknown TPU → v5e-class assumption
    ("cpu", 0.0), ("NVIDIA H100", 0.0),
])
def test_chip_peak_hbm_bw_by_kind(kind, bw):
    assert chip_peak_hbm_bw(kind=kind) == pytest.approx(bw)


def test_empty_kind_is_undefined_not_v5e():
    """The satellite fix: an empty device_kind with no evidence of a TPU
    platform must yield 0.0 (undefined), not a fabricated v5e peak —
    on both tables."""
    assert chip_peak_flops(kind="") == 0.0
    assert chip_peak_hbm_bw(kind="") == 0.0
    assert chip_peak_flops(_Dev()) == 0.0          # mock with empty attrs
    assert chip_peak_hbm_bw(_Dev()) == 0.0
    assert chip_peak_flops(object()) == 0.0        # no attrs at all
    assert chip_peak_hbm_bw(object()) == 0.0
    assert chip_peak_flops(None) == 0.0
    assert chip_peak_hbm_bw(None) == 0.0


def test_tpu_platform_with_empty_kind_assumes_v5e():
    """A device that says platform=tpu but reports no kind string IS a
    TPU — the v5e-class assumption is evidence-based there."""
    dev = _Dev(kind="", platform="tpu")
    assert chip_peak_flops(dev) == pytest.approx(PEAK_BF16["TPU v5e"])
    assert chip_peak_hbm_bw(dev) == pytest.approx(PEAK_HBM_BPS["TPU v5e"])


def test_non_tpu_platform_is_undefined_even_with_tpu_kind():
    dev = _Dev(kind="TPU v5e", platform="cpu")
    assert chip_peak_flops(dev) == 0.0
    assert chip_peak_hbm_bw(dev) == 0.0


def test_resolve_serving_peaks_env_override(monkeypatch):
    monkeypatch.setenv("PT_ROOFLINE_KIND", "TPU v5e")
    pf, pb = resolve_serving_peaks(_Dev(kind="cpu", platform="cpu"))
    assert pf == pytest.approx(PEAK_BF16["TPU v5e"])
    assert pb == pytest.approx(PEAK_HBM_BPS["TPU v5e"])
    monkeypatch.delenv("PT_ROOFLINE_KIND")
    pf, pb = resolve_serving_peaks(_Dev(kind="cpu", platform="cpu"))
    assert (pf, pb) == (0.0, 0.0)


# -------------------------------------------------------- geometry & models
def _llama8b():
    """Llama-3-8B-ish GQA geometry."""
    return ModelGeometry(num_layers=32, hidden=4096, intermediate=14336,
                         vocab=128256, heads=32, kv_heads=8, head_dim=128)


def test_geometry_from_config_duck_types_llama():
    from paddle_tpu.models.llama import LlamaConfig
    cfg = LlamaConfig.tiny(num_hidden_layers=8, vocab_size=512,
                           hidden_size=128, intermediate_size=256,
                           num_attention_heads=8, num_key_value_heads=4)
    g = ModelGeometry.from_config(cfg)
    assert (g.num_layers, g.hidden, g.vocab) == (8, 128, 512)
    assert (g.heads, g.kv_heads, g.head_dim) == (8, 4, 16)
    assert g.num_experts == 0
    assert g.activated_params == g.resident_params   # dense: no experts


def test_moe_geometry_activated_vs_resident():
    dense = _llama8b()
    moe = ModelGeometry(num_layers=32, hidden=4096, intermediate=14336,
                        vocab=128256, heads=32, kv_heads=8, head_dim=128,
                        num_experts=8, experts_per_tok=2)
    # one token activates 2 expert MLPs but a batched forward streams 8
    assert moe.activated_params < moe.resident_params
    per_expert = moe.mlp_params_per_expert
    assert moe.resident_params - moe.activated_params == \
        32 * (8 - 2) * per_expert
    # a dense model of the same shape activates exactly one MLP per layer
    assert dense.activated_params == \
        moe.activated_params - 32 * 1 * per_expert


def test_gqa_shrinks_kv_bytes_by_head_grouping():
    gqa = _llama8b()
    mha = ModelGeometry(num_layers=32, hidden=4096, intermediate=14336,
                        vocab=128256, heads=32, kv_heads=32, head_dim=128)
    assert kv_bytes_per_position(gqa) * (32 // 8) == \
        pytest.approx(kv_bytes_per_position(mha))
    assert kv_bytes_per_position(gqa) == 32 * 2 * 8 * 128 * 2


def test_weight_bytes_counts_all_resident_experts():
    g = _llama8b()
    assert weight_bytes(g) == g.resident_params * 2


def test_phase_models_hand_check():
    g = ModelGeometry(num_layers=2, hidden=8, intermediate=16, vocab=32,
                      heads=2, kv_heads=1, head_dim=4)
    # one decode token against 10 cached positions
    fl = phase_flops(g, tokens=1, kv_read_positions=10)
    assert fl == 2 * g.activated_params + 4 * 2 * 4 * 10
    by = phase_bytes(g, tokens=1, weight_passes=1, kv_read_positions=10)
    assert by == (weight_bytes(g) + 10 * kv_bytes_per_position(g)
                  + 1 * kv_bytes_per_position(g) + 32 * 4)


def test_decode_is_bandwidth_bound_prefill_chunk_compute_bound():
    """The roofline story the ledger exists to tell: a batch-32 decode
    tick at 1k context sits far left of every chip's balance point
    (bandwidth-bound), while a 1k-token causal prefill chunk clears the
    v5p balance (compute-bound) — and decode intensity is decades below
    prefill intensity."""
    g = _llama8b()
    d_fl = phase_flops(g, tokens=32, kv_read_positions=32 * 1024)
    d_by = phase_bytes(g, tokens=32, weight_passes=1,
                       kv_read_positions=32 * 1024)
    d_ai = arith_intensity(d_fl, d_by)
    pairs = 1024 * 1025 // 2
    p_fl = phase_flops(g, tokens=1024, kv_read_positions=pairs)
    p_by = phase_bytes(g, tokens=1024, weight_passes=1,
                       kv_read_positions=pairs)
    p_ai = arith_intensity(p_fl, p_by)
    assert d_ai * 5 < p_ai
    for chip in PEAK_HBM_BPS:
        assert roofline_verdict(d_ai, PEAK_BF16[chip],
                                PEAK_HBM_BPS[chip]) == "bandwidth-bound"
    assert roofline_verdict(p_ai, PEAK_BF16["TPU v5p"],
                            PEAK_HBM_BPS["TPU v5p"]) == "compute-bound"
    assert roofline_verdict(p_ai, 0.0, 0.0) == "undefined"


# ----------------------------------------------------------- choke point
def test_record_serving_throughput_sets_gauges_and_report():
    g = _llama8b()
    rep = record_serving_throughput(
        "decode", seconds=2.0, tokens=64, weight_passes=2,
        kv_read_positions=64 * 512, geom=g,
        peak_flops=PEAK_BF16["TPU v5e"],
        peak_hbm_bps=PEAK_HBM_BPS["TPU v5e"])
    assert rep["bound"] == "bandwidth-bound"
    assert rep["mfu"] > 0 and rep["mbu"] > 0
    assert rep["mbu"] == pytest.approx(rep["bytes"] / 2.0 / 819e9)
    assert METRICS.get("serving_mbu").value(phase="decode") == \
        pytest.approx(rep["mbu"])
    assert METRICS.get("serving_mfu").value(phase="decode") == \
        pytest.approx(rep["mfu"])
    assert METRICS.get("serving_arith_intensity").value(phase="decode") == \
        pytest.approx(rep["arith_intensity"])
    doc = serving_roofline_report()
    assert doc["machine"]["balance_flops_per_byte"] == \
        pytest.approx(PEAK_BF16["TPU v5e"] / PEAK_HBM_BPS["TPU v5e"])
    assert doc["phases"]["decode"]["tokens"] == 64


def test_record_serving_throughput_unknown_peaks_exports_zero_not_fake():
    g = _llama8b()
    rep = record_serving_throughput(
        "decode", seconds=1.0, tokens=8, weight_passes=1,
        kv_read_positions=8 * 64, geom=g)
    assert rep["mfu"] == 0.0 and rep["mbu"] == 0.0
    assert rep["bound"] == "undefined"
    assert rep["arith_intensity"] > 0          # the intensity stays real
    assert METRICS.get("serving_mbu").value(phase="decode") == 0.0


def test_record_serving_throughput_skips_empty_windows():
    g = _llama8b()
    assert record_serving_throughput("decode", seconds=0.0, tokens=5,
                                     weight_passes=1, kv_read_positions=1,
                                     geom=g) == {}
    assert record_serving_throughput("decode", seconds=1.0, tokens=0,
                                     weight_passes=0, kv_read_positions=0,
                                     geom=g) == {}
    assert serving_roofline_report()["phases"] == {}


# --------------------------------------------------------- engine anatomy
_BREAKDOWN_PHASES = ("prefill", "draft", "verify", "sample", "host")


def _bench_shaped_engine(**kw):
    """The bench's Llama-shaped serving config (bench_serving_spec) —
    the acceptance criterion measures THIS engine."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(num_hidden_layers=8, vocab_size=512,
                           hidden_size=128, intermediate_size=256,
                           num_attention_heads=8, num_key_value_heads=4,
                           max_position_embeddings=256)
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.serving import LLMEngine
    return LLMEngine(model, num_slots=4, block_size=8, max_prompt_len=32,
                     max_seq_len=96, **kw)


def _tiny_spec_engine():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    model = LlamaForCausalLM(cfg)
    dcfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                            num_attention_heads=4, num_key_value_heads=2,
                            vocab_size=64)
    draft = LlamaForCausalLM(dcfg)
    from paddle_tpu.serving import LLMEngine
    return LLMEngine(model, draft_model=draft, spec_k=3, num_slots=4,
                     block_size=8, max_prompt_len=16, max_seq_len=64)


def _sums():
    hist = METRICS.get("serving_tick_breakdown_seconds")
    tick = METRICS.get("serving_tick_seconds")
    parts = {p: hist.value(phase=p) for p in _BREAKDOWN_PHASES}
    return parts, tick.value()


def test_tick_breakdown_reconciles_tick_for_tick():
    """After EVERY tick, each breakdown phase has observed exactly as
    many samples as ``serving_tick_seconds`` and the per-tick phase
    sums add up to the tick total — reconciliation by construction,
    checked per tick, not just in aggregate."""
    from paddle_tpu.serving import Request
    eng = _tiny_spec_engine()
    rs = np.random.RandomState(0)
    for l in (4, 7, 11, 5, 9):
        eng.add_request(Request(rs.randint(0, 64, (l,)),
                                max_new_tokens=8))
    ticks = 0
    while eng.has_work():
        eng.step()
        ticks += 1
        parts, tick = _sums()
        assert tick["count"] == ticks
        for p in _BREAKDOWN_PHASES:
            assert parts[p]["count"] == ticks, \
                f"phase {p} missed a tick ({parts[p]['count']} vs {ticks})"
        total = sum(parts[p]["sum"] for p in _BREAKDOWN_PHASES)
        assert math.isclose(total, tick["sum"], rel_tol=1e-9), \
            f"tick {ticks}: breakdown sum {total} != tick sum {tick['sum']}"
    assert ticks > 2
    eng.assert_quiescent()
    # the spec engine exercised every device phase at least once
    hist = METRICS.get("serving_tick_breakdown_seconds")
    for p in ("prefill", "draft", "verify"):
        assert hist.value(phase=p)["sum"] > 0.0


def test_tick_breakdown_reconciles_at_async_depth():
    """ISSUE 20: the five-phase reconciliation must hold tick-for-tick
    at ``async_depth>0`` too — device-overlapped drain/emit work folds
    into the "sample" slice, only exposed host time lands in "host",
    and every tick observes ``serving_tick_host_hidden_seconds`` exactly
    once, so the hidden column reconciles against the tick count."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import LLMEngine, Request
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    eng = LLMEngine(LlamaForCausalLM(cfg), num_slots=4, block_size=8,
                    max_prompt_len=16, max_seq_len=64, async_depth=2)
    rs = np.random.RandomState(0)
    for l in (4, 7, 11, 5):
        eng.add_request(Request(rs.randint(0, 64, (l,)), max_new_tokens=8))
    hid = METRICS.get("serving_tick_host_hidden_seconds")
    ticks = 0
    while eng.has_work():
        eng.step()
        ticks += 1
        parts, tick = _sums()
        assert tick["count"] == ticks
        for p in _BREAKDOWN_PHASES:
            assert parts[p]["count"] == ticks, \
                f"phase {p} missed a tick ({parts[p]['count']} vs {ticks})"
        total = sum(parts[p]["sum"] for p in _BREAKDOWN_PHASES)
        assert math.isclose(total, tick["sum"], rel_tol=1e-9), \
            f"tick {ticks}: breakdown sum {total} != tick sum {tick['sum']}"
        assert hid.value()["count"] == ticks
    eng.assert_quiescent()
    assert ticks > 2
    doc = serving_roofline_report()
    anat = doc["tick_anatomy"]
    assert anat["ticks_seconds"] == pytest.approx(_sums()[1]["sum"])
    assert anat["host_hidden_seconds"] == pytest.approx(hid.value()["sum"])
    assert anat["host_exposed_seconds"] == \
        pytest.approx(_sums()[0]["host"]["sum"])
    assert 0.0 <= anat["overlap_fraction"] <= 1.0


def test_bench_shaped_engine_exports_bandwidth_bound_decode_mbu(monkeypatch):
    """The acceptance criterion: under PT_ROOFLINE_KIND="TPU v5e" the
    bench-shaped engine run exports a nonzero ``serving_mbu{decode}``
    with a bandwidth-bound verdict (the v5e arithmetic exercised on
    CPU), and the whole per-phase report hangs together."""
    monkeypatch.setenv("PT_ROOFLINE_KIND", "TPU v5e")
    from paddle_tpu.serving import Request
    eng = _bench_shaped_engine()
    rs = np.random.RandomState(7)
    for l in (12, 20, 8, 16):
        eng.add_request(Request(rs.randint(0, 512, (l,)),
                                max_new_tokens=12))
    out = eng.run()
    assert len(out) == 4
    mbu = METRICS.get("serving_mbu").value(phase="decode")
    mfu = METRICS.get("serving_mfu").value(phase="decode")
    assert 0.0 < mbu < 1.0       # CPU is far below a v5e HBM roof
    assert 0.0 < mfu < 1.0
    doc = serving_roofline_report()
    dec = doc["phases"]["decode"]
    assert dec["bound"] == "bandwidth-bound"
    assert dec["mbu"] == pytest.approx(mbu)
    assert dec["tokens"] > 0 and dec["seconds"] > 0
    assert doc["phases"]["prefill"]["arith_intensity"] > \
        dec["arith_intensity"]
    assert doc["machine"]["peak_hbm_bps"] == pytest.approx(819e9)


def test_cpu_engine_exports_zero_mbu_not_fabricated(monkeypatch):
    """Without the env override a CPU run must export 0.0 (undefined)
    for MFU/MBU — never a number derived from an assumed chip — while
    the intensity gauge stays real."""
    monkeypatch.delenv("PT_ROOFLINE_KIND", raising=False)
    from paddle_tpu.serving import Request
    eng = _tiny_spec_engine()
    rs = np.random.RandomState(3)
    for l in (5, 9, 6):
        eng.add_request(Request(rs.randint(0, 64, (l,)),
                                max_new_tokens=6))
    eng.run()
    assert METRICS.get("serving_mbu").value(phase="decode") == 0.0
    assert METRICS.get("serving_mfu").value(phase="decode") == 0.0
    assert METRICS.get("serving_arith_intensity").value(phase="decode") > 0
    for ph, rep in serving_roofline_report()["phases"].items():
        assert rep["bound"] == "undefined", ph
