"""Model-zoo tests: tiny configs fwd/bwd, loss decreases, generation
(SURVEY.md §4; ref PaddleNLP test suites)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.models import (
    BertConfig,
    BertForPretraining,
    GPTConfig,
    GPTForCausalLM,
    LlamaConfig,
    LlamaForCausalLM,
    MoEConfig,
    MoEForCausalLM,
    resnet18,
    resnet50,
)
from paddle_tpu.train import make_train_step
from paddle_tpu.train.step import init_state


def _train_decreases(model, loss_args, n=8, lr=1e-3):
    optimizer = opt.AdamW(learning_rate=lr)
    state = init_state(model, optimizer)
    step = make_train_step(lambda m, *a: m.loss(*a), optimizer)
    losses = []
    for _ in range(n):
        state, loss = step(state, *loss_args)
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
    return losses


def _lm_batch(vocab, b=2, s=16, seed=0):
    rs = np.random.RandomState(seed)
    ids = jnp.asarray(rs.randint(0, vocab, (b, s)))
    labels = jnp.concatenate([ids[:, 1:], -100 * jnp.ones((b, 1), ids.dtype)], axis=1)
    return ids, labels


def test_llama_train():
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    _train_decreases(LlamaForCausalLM(cfg), _lm_batch(cfg.vocab_size))


def test_llama_gqa_shapes():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_attention_heads=4, num_key_value_heads=2)
    m = LlamaForCausalLM(cfg)
    ids, _ = _lm_batch(cfg.vocab_size)
    assert m(ids).shape == (2, 16, cfg.vocab_size)


def test_gpt_train():
    pt.seed(0)
    cfg = GPTConfig.tiny()
    _train_decreases(GPTForCausalLM(cfg), _lm_batch(cfg.vocab_size))


def test_bert_pretraining_train():
    pt.seed(0)
    cfg = BertConfig.tiny()
    model = BertForPretraining(cfg).eval()  # eval: disable dropout for determinism
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 16)))
    mlm_labels = jnp.where(jnp.asarray(rs.rand(2, 16)) < 0.15, ids, -100)
    nsp = jnp.asarray(rs.randint(0, 2, (2,)))
    _train_decreases(model, (ids, mlm_labels, nsp))


def test_moe_llm_train():
    pt.seed(0)
    cfg = MoEConfig.tiny(num_experts=4)
    _train_decreases(MoEForCausalLM(cfg), _lm_batch(cfg.base.vocab_size))


@pytest.mark.slow
def test_resnet18_forward_and_grad():
    pt.seed(0)
    m = resnet18(num_classes=10)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32))
    out = m(x)
    assert out.shape == (2, 10)

    m = m.eval()  # frozen BN stats -> pure loss fn
    labels = jnp.asarray([1, 3])

    def loss_fn(mod, x, y):
        import paddle_tpu.nn.functional as F
        return F.cross_entropy(mod(x), y)

    loss, grads = pt.value_and_grad(loss_fn)(m, x, labels)
    assert np.isfinite(float(loss))
    leaves = [l for l in jax.tree_util.tree_leaves(grads) if l is not None]
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)


def test_resnet50_param_count():
    pt.seed(0)
    m = resnet50()
    # torchvision resnet50: 25.557M params; ours must match architecture
    n = m.num_parameters()
    assert 25.4e6 < n < 25.7e6, n


def test_generation_greedy_consistent_with_forward():
    """Greedy KV-cache decode must match argmax over full-context logits."""
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg).eval()
    from paddle_tpu.models.decoding import generate
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 8)))
    out = generate(m, prompt, max_new_tokens=5, temperature=0.0)
    assert out.shape == (1, 13)
    # re-check step by step with full forward
    toks = np.asarray(out)
    cur = prompt
    for i in range(5):
        logits = m(jnp.asarray(cur))
        nxt = int(jnp.argmax(logits[0, -1]))
        assert nxt == toks[0, 8 + i], (i, nxt, toks)
        cur = np.concatenate([np.asarray(cur), [[nxt]]], axis=1)


def test_generation_sampling_shapes():
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg).eval()
    from paddle_tpu.models.decoding import generate
    prompt = jnp.asarray([[1, 2, 3]])
    out = generate(m, prompt, max_new_tokens=4, temperature=0.8, top_k=10,
                   rng=jax.random.PRNGKey(0))
    assert out.shape == (1, 7)
    out2 = generate(m, prompt, max_new_tokens=4, temperature=0.8, top_p=0.9,
                    rng=jax.random.PRNGKey(0))
    assert out2.shape == (1, 7)


# -- Conformer CTC -----------------------------------------------------------

class TestConformer:
    def test_forward_shapes_and_lengths(self):
        import paddle_tpu as pt
        from paddle_tpu.models.conformer import ConformerConfig, ConformerForCTC
        import jax.numpy as jnp, numpy as np

        pt.seed(0)
        cfg = ConformerConfig.tiny()
        model = ConformerForCTC(cfg)
        feats = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 37, cfg.n_mels)), jnp.float32)
        lens = jnp.asarray([37, 20])
        logits, out_len = model(feats, lens)
        assert logits.shape[0] == 2 and logits.shape[2] == cfg.vocab_size
        assert int(out_len[0]) == logits.shape[1]
        assert int(out_len[1]) == (20 + 3) // 4

    def test_ctc_loss_decreases(self):
        import paddle_tpu as pt
        import paddle_tpu.optimizer as opt
        from paddle_tpu.models.conformer import ConformerConfig, ConformerForCTC
        from paddle_tpu.train import make_train_step
        from paddle_tpu.train.step import init_state
        import jax.numpy as jnp, numpy as np

        pt.seed(0)
        cfg = ConformerConfig.tiny()
        model = ConformerForCTC(cfg)
        rng = np.random.default_rng(1)
        feats = jnp.asarray(rng.standard_normal((2, 32, cfg.n_mels)), jnp.float32)
        flens = jnp.asarray([32, 32])
        labels = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 5)))
        llens = jnp.asarray([5, 4])

        optimizer = opt.Adam(learning_rate=3e-3)
        state = init_state(model, optimizer)
        step = make_train_step(
            lambda m, f, fl, y, yl: m.loss(f, fl, y, yl), optimizer)
        losses = []
        for _ in range(10):
            state, loss = step(state, feats, flens, labels, llens)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_greedy_decode_collapses(self):
        import paddle_tpu as pt
        from paddle_tpu.models.conformer import ConformerConfig, ConformerForCTC
        import jax.numpy as jnp, numpy as np

        pt.seed(0)
        cfg = ConformerConfig.tiny()
        model = ConformerForCTC(cfg)
        feats = jnp.asarray(np.random.default_rng(2).standard_normal(
            (1, 16, cfg.n_mels)), jnp.float32)
        ids, out_len = model.greedy_decode(feats)
        arr = np.asarray(ids)[0]
        kept = arr[arr >= 0]
        assert (kept != 0).all()           # no blanks survive
        assert not (np.diff(np.nonzero(arr >= 0)[0]) == 1)[
            np.diff(kept, prepend=kept[0] if len(kept) else 0)[1:] == 0].any() \
            if len(kept) > 1 else True     # no adjacent duplicates
