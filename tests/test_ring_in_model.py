"""Ring attention integrated in the flagship model (sequence_parallel=
"ring"): sharded-sequence training matches single-device math."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import HybridMesh
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _data(cfg, batch=2, seq=32):
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.concatenate(
        [ids[:, 1:], -100 * jnp.ones((batch, 1), ids.dtype)], axis=1)
    return ids, labels


@pytest.mark.slow
def test_ring_model_matches_single_device():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg)
    ref_loss = float(model.loss(ids, labels))
    ref_grads = jax.grad(lambda m: m.loss(ids, labels))(model)

    model_sp = model
    # same weights, ring-attention config (flip per-layer)
    for lyr in model_sp.model.layers:
        lyr.self_attn.sequence_parallel = "ring"
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        sp_loss = float(jax.jit(lambda m, i, l: m.loss(i, l))(
            model_sp, ids, labels))
        sp_grads = jax.jit(jax.grad(lambda m: m.loss(ids, labels)))(model_sp)
    assert abs(sp_loss - ref_loss) < 2e-4, (sp_loss, ref_loss)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(sp_grads)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4)


def test_ring_model_with_tp_and_sp():
    """sp x tp composition: ring over sp with tp-sharded heads."""
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4)
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg)
    ref_loss = float(model.loss(ids, labels))

    for lyr in model.model.layers:
        lyr.self_attn.sequence_parallel = "ring"
    mesh = HybridMesh(tp=2, sp=2, devices=jax.devices()[:4])
    with mesh:
        from paddle_tpu.distributed import shard_module
        model_s = shard_module(model, mesh, min_size=1)
        loss = float(jax.jit(lambda m, i, l: m.loss(i, l))(model_s, ids, labels))
    assert abs(loss - ref_loss) < 2e-4, (loss, ref_loss)


@pytest.mark.slow
def test_ring_model_trains_end_to_end():
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, sequence_parallel="ring")
    mesh = HybridMesh(dp=2, sp=4, devices=jax.devices()[:8])
    with mesh:
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3)
        state = init_state(model, optimizer, mesh)
        ids, labels = _data(cfg, batch=4)
        ids = jax.device_put(ids, mesh.batch_sharding())
        labels = jax.device_put(labels, mesh.batch_sharding())
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, mesh)
        losses = []
        for _ in range(6):
            state, loss = step(state, ids, labels)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_ring_falls_back_without_sp_mesh():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, sequence_parallel="ring")
    m = LlamaForCausalLM(cfg).eval()
    ids, _ = _data(cfg, batch=1, seq=16)
    out = m(ids)  # no mesh: plain attention path
    assert out.shape == (1, 16, cfg.vocab_size)


@pytest.mark.slow
def test_ring_gqa_grouped_matches_full():
    """GQA ring (grouped einsum, unrepeated KV rotation) == full attention."""
    from paddle_tpu.distributed.ring_attention import make_ring_attention
    from paddle_tpu.ops.attention import xla_attention
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, 32, 4, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=True)
    mesh = HybridMesh(sp=8)
    with mesh:
        out = make_ring_attention(mesh, causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_zigzag_ring_gqa():
    from paddle_tpu.distributed.ring_attention import (
        make_zigzag_ring_attention, zigzag_inverse_permutation,
        zigzag_permutation)
    from paddle_tpu.ops.attention import xla_attention
    rs = np.random.RandomState(1)
    s = 32
    q = jnp.asarray(rs.randn(1, s, 4, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(1, s, 2, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(1, s, 2, 8).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=True)
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    perm = zigzag_permutation(s, 4)
    inv = zigzag_inverse_permutation(s, 4)
    with mesh:
        attend = make_zigzag_ring_attention(mesh)
        out = attend(q[:, perm], k[:, perm], v[:, perm])[:, inv]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("window", [
    4,
    pytest.param(9, marks=pytest.mark.slow),
    pytest.param(100, marks=pytest.mark.slow),
])
def test_windowed_ring_matches_windowed_full(window):
    """Global sliding window across shard boundaries == windowed full
    attention."""
    from paddle_tpu.distributed.ring_attention import make_ring_attention
    from paddle_tpu.ops.attention import xla_attention
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
    ref = xla_attention(q, k, v, is_causal=True, window=window)
    mesh = HybridMesh(sp=8)
    with mesh:
        out = make_ring_attention(mesh, causal=True, window=window)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_windowed_ring_grads_match():
    from paddle_tpu.distributed.ring_attention import make_ring_attention
    from paddle_tpu.ops.attention import xla_attention
    rs = np.random.RandomState(8)
    q = jnp.asarray(rs.randn(1, 16, 1, 4).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 16, 1, 4).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 16, 1, 4).astype(np.float32))
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        attend = make_ring_attention(mesh, causal=True, window=5)
        g_ring = jax.grad(lambda a, b, c: jnp.sum(attend(a, b, c) ** 2),
                          argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        xla_attention(a, b, c, is_causal=True, window=5) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_mistral_ring_matches_single_device():
    """Mistral (sliding window) + sequence_parallel='ring' == unsharded."""
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    pt.seed(0)
    cfg = MistralConfig.tiny(sliding_window=10, num_hidden_layers=2)
    model = MistralForCausalLM(cfg)
    ids, labels = _data(cfg)
    ref_loss = float(model.loss(ids, labels))
    for lyr in model.model.layers:
        lyr.self_attn.sequence_parallel = "ring"
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        loss = float(jax.jit(lambda m, i, l: m.loss(i, l))(model, ids, labels))
    assert abs(loss - ref_loss) < 2e-4, (loss, ref_loss)


@pytest.mark.slow
def test_ulysses_with_window_matches_single_device():
    """Round 1 raised here; the window now composes with Ulysses (the
    post-all_to_all inner attention is full-sequence, so the global band
    applies unchanged). Mistral x Ulysses == unsharded."""
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, sliding_window=8,
                           num_key_value_heads=4)
    m = LlamaForCausalLM(cfg)
    ids, _ = _data(cfg, batch=1, seq=16)
    ref = m(ids)
    for lyr in m.model.layers:
        lyr.self_attn.sequence_parallel = "ulysses"
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        got = jax.jit(lambda m, i: m(i))(m, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_model_matches_single_device():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4)
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg)
    ref_loss = float(model.loss(ids, labels))
    for lyr in model.model.layers:
        lyr.self_attn.sequence_parallel = "ulysses"
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        loss = float(jax.jit(lambda m, i, l: m.loss(i, l))(model, ids, labels))
    assert abs(loss - ref_loss) < 2e-4, (loss, ref_loss)


@pytest.mark.slow
def test_ulysses_model_trains():
    import paddle_tpu.optimizer as opt
    from paddle_tpu.train import make_train_step
    from paddle_tpu.train.step import init_state

    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4,
                           sequence_parallel="ulysses")
    mesh = HybridMesh(dp=2, sp=4, devices=jax.devices()[:8])
    with mesh:
        model = LlamaForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3)
        state = init_state(model, optimizer, mesh)
        ids, labels = _data(cfg, batch=4)
        ids = jax.device_put(ids, mesh.batch_sharding())
        labels = jax.device_put(labels, mesh.batch_sharding())
        step = make_train_step(lambda m, i, l: m.loss(i, l), optimizer, mesh)
        losses = []
        for _ in range(6):
            state, loss = step(state, ids, labels)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_ulysses_gqa_kv_replication():
    """nkv < sp: KV groups replicate up to sp (Ulysses-GQA)."""
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, num_attention_heads=4,
                           num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg)
    ref_loss = float(model.loss(ids, labels))
    for lyr in model.model.layers:
        lyr.self_attn.sequence_parallel = "ulysses"
    mesh = HybridMesh(sp=4, devices=jax.devices()[:4])
    with mesh:
        loss = float(jax.jit(lambda m, i, l: m.loss(i, l))(model, ids, labels))
    assert abs(loss - ref_loss) < 2e-4, (loss, ref_loss)
