"""DistributedStrategy knobs act (or warn) — VERDICT r2 item 8.

Each reference knob maps onto the real mechanism:
  amp (pure)     -> amp.decorate O2 param cast + optimizer multi_precision
  recompute      -> model cfg.remat (per-layer jax.checkpoint)
  gradient_merge -> optimizer.GradientMerge(k_steps, avg)
  auto_parallel.Partial -> explicit warning (no top-level GSPMD partial)
Ref: python/paddle/distributed/fleet/base/distributed_strategy.py.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn import Linear
from paddle_tpu.optimizer import SGD, AdamW, GradientMerge


@pytest.fixture
def fleet_state():
    """Isolate fleet's module-global state per test."""
    saved = dict(fleet._STATE)
    yield fleet._STATE
    fleet._STATE.clear()
    fleet._STATE.update(saved)


# ---------------------------------------------------------------- GradientMerge

def test_gradient_merge_equals_merged_step():
    pt.seed(0)
    w0 = jnp.asarray(np.random.RandomState(0).randn(4, 3), jnp.float32)
    g1 = jnp.asarray(np.random.RandomState(1).randn(4, 3), jnp.float32)
    g2 = jnp.asarray(np.random.RandomState(2).randn(4, 3), jnp.float32)

    gm = GradientMerge(SGD(learning_rate=0.1), k_steps=2, avg=True)
    state = gm.init({"w": w0})
    p1, state = gm.step({"w": w0}, {"w": g1}, state)
    # first call accumulates only — params untouched
    np.testing.assert_array_equal(np.asarray(p1["w"]), np.asarray(w0))
    p2, state = gm.step(p1, {"w": g2}, state)

    ref = SGD(learning_rate=0.1)
    rstate = ref.init({"w": w0})
    pref, _ = ref.step({"w": w0}, {"w": (g1 + g2) / 2.0}, rstate)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(pref["w"]),
                               rtol=1e-6)
    # accumulator reset after the apply step
    np.testing.assert_array_equal(np.asarray(state["accum"]["w"]), 0.0)


def test_gradient_merge_sum_mode_and_jit():
    w0 = jnp.ones((3,), jnp.float32)
    gm = GradientMerge(SGD(learning_rate=0.5), k_steps=2, avg=False)
    step = jax.jit(gm.step)
    state = gm.init({"w": w0})
    p, state = step({"w": w0}, {"w": jnp.ones((3,))}, state)
    p, state = step(p, {"w": jnp.ones((3,))}, state)
    # sum mode: effective grad = 2.0, lr 0.5 -> w - 1.0
    np.testing.assert_allclose(np.asarray(p["w"]), 0.0, atol=1e-6)


def test_gradient_merge_inner_state_frozen_between_applies():
    gm = GradientMerge(AdamW(learning_rate=1e-2), k_steps=3)
    w = {"w": jnp.ones((2, 2), jnp.float32)}
    state = gm.init(w)
    g = {"w": jnp.full((2, 2), 0.5)}
    p, state = gm.step(w, g, state)
    # inner Adam step count must not advance on accumulate-only calls
    assert int(state["inner"]["step"]) == 0
    p, state = gm.step(p, g, state)
    p, state = gm.step(p, g, state)
    assert int(state["inner"]["step"]) == 1
    assert not np.allclose(np.asarray(p["w"]), 1.0)


def test_gradient_merge_set_lr_routes_to_inner():
    gm = GradientMerge(SGD(learning_rate=0.1), k_steps=1)
    w = {"w": jnp.ones((2,), jnp.float32)}
    state = gm.init(w)
    state = gm.set_lr(0.5, state)
    assert gm.get_lr(state) == pytest.approx(0.5)
    p, _ = gm.step(w, {"w": jnp.ones((2,))}, state)
    np.testing.assert_allclose(np.asarray(p["w"]), 0.5, atol=1e-6)


# ---------------------------------------------------------------- fleet knobs

def test_fleet_gradient_merge_knob(fleet_state):
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": False}
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(SGD(learning_rate=0.1))
    assert isinstance(opt, GradientMerge)
    assert opt.k_steps == 4 and opt.avg is False
    # idempotent: a second call must not nest wrappers
    opt2 = fleet.distributed_optimizer(opt)
    assert opt2 is opt and not isinstance(opt2.inner, GradientMerge)

    with pytest.warns(UserWarning, match="gradient_merge.*IGNORED"):
        assert fleet.distributed_optimizer("opt") == "opt"


def test_fleet_amp_pure_knob(fleet_state):
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs = {"use_pure_bf16": True}
    mesh = fleet.init(is_collective=True, strategy=strategy)

    opt = fleet.distributed_optimizer(AdamW(learning_rate=1e-3))
    assert opt.multi_precision is True
    # through a wrapper chain the flag lands on the stepping inner optimizer
    wrapped = fleet.distributed_optimizer(
        GradientMerge(AdamW(learning_rate=1e-3), k_steps=2))
    assert wrapped.inner.multi_precision is True

    pt.seed(0)
    with mesh:
        m = fleet.distributed_model(Linear(8, 8), min_size=1)
    assert m.weight.dtype == jnp.bfloat16


def test_fleet_amp_o1_is_native_noop(fleet_state):
    strategy = fleet.DistributedStrategy()
    strategy.amp = True  # O1: bf16 compute is the framework default
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(AdamW(learning_rate=1e-3))
    assert opt.multi_precision is False
    assert not isinstance(opt, GradientMerge)


def test_fleet_recompute_knob(fleet_state):
    strategy = fleet.DistributedStrategy()
    strategy.recompute = True
    mesh = fleet.init(is_collective=True, strategy=strategy)
    pt.seed(0)
    cfg = LlamaConfig.tiny()  # tiny() sets remat=False
    assert cfg.remat is False
    m = LlamaForCausalLM(cfg)
    with mesh:
        fleet.distributed_model(m, min_size=1)
    assert cfg.remat is True

    with pytest.warns(UserWarning, match="recompute.*no remat"):
        with mesh:
            fleet.distributed_model(Linear(4, 4), min_size=1)


# ---------------------------------------------------------------- Partial

def test_auto_parallel_partial_warns():
    from paddle_tpu.distributed.auto_parallel import (Partial, ProcessMesh,
                                                      Replicate, shard_tensor)
    pm = ProcessMesh(np.arange(8), dim_names=["x"])
    x = jnp.ones((4, 4))
    with pytest.warns(UserWarning, match="Partial placement"):
        y = shard_tensor(x, pm, [Partial()])
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        shard_tensor(x, pm, [Replicate()])
