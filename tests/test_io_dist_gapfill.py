"""io datasets/samplers, distribution families, amp helpers, linalg
ormqr/svd_lowrank — round-1 audit additions."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


def test_concat_dataset():
    from paddle_tpu.io import ConcatDataset, TensorDataset
    a = TensorDataset(jnp.arange(3.0))
    b = TensorDataset(jnp.arange(5.0) + 10)
    cd = ConcatDataset([a, b])
    assert len(cd) == 8
    assert float(cd[2][0]) == 2.0
    assert float(cd[3][0]) == 10.0
    assert float(cd[-1][0]) == 14.0


def test_weighted_random_sampler():
    from paddle_tpu.io import WeightedRandomSampler
    s = WeightedRandomSampler([0.0, 0.0, 1.0, 1.0], 100, seed=0)
    idx = list(s)
    assert len(idx) == 100 and set(idx) <= {2, 3}


def test_subset_random_sampler():
    from paddle_tpu.io import SubsetRandomSampler
    s = SubsetRandomSampler([5, 7, 9], seed=0)
    assert sorted(list(s)) == [5, 7, 9]


def test_binomial():
    from paddle_tpu.distribution import Binomial
    import scipy.stats as st
    d = Binomial(10, 0.3)
    np.testing.assert_allclose(float(d.mean), 3.0, rtol=1e-6)
    lp = float(d.log_prob(jnp.asarray(4.0)))
    np.testing.assert_allclose(lp, st.binom.logpmf(4, 10, 0.3), rtol=1e-5)
    s = d.sample((1000,), rng=jax.random.PRNGKey(0))
    assert 2.0 < float(s.mean()) < 4.0


def test_chi2():
    from paddle_tpu.distribution import Chi2
    import scipy.stats as st
    d = Chi2(5.0)
    np.testing.assert_allclose(float(d.mean), 5.0, rtol=1e-6)
    np.testing.assert_allclose(float(d.log_prob(jnp.asarray(3.0))),
                               st.chi2.logpdf(3.0, 5), rtol=1e-5)


def test_continuous_bernoulli():
    from paddle_tpu.distribution import ContinuousBernoulli
    d = ContinuousBernoulli(0.3)
    # pdf integrates to ~1
    xs = np.linspace(1e-4, 1 - 1e-4, 2001)
    pdf = np.exp(np.asarray(d.log_prob(jnp.asarray(xs, jnp.float32))))
    np.testing.assert_allclose(np.trapezoid(pdf, xs), 1.0, atol=1e-3)
    s = d.rsample((2000,), rng=jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(s.mean()), float(d.mean), atol=0.03)
    # near-1/2 limit is stable
    d2 = ContinuousBernoulli(0.5)
    assert np.isfinite(float(d2.log_prob(jnp.asarray(0.3))))


def test_multivariate_normal():
    from paddle_tpu.distribution import MultivariateNormal, kl_divergence
    import scipy.stats as st
    mu = np.array([1.0, -1.0], np.float32)
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    d = MultivariateNormal(mu, covariance_matrix=cov)
    x = np.array([0.5, 0.2], np.float32)
    np.testing.assert_allclose(float(d.log_prob(jnp.asarray(x))),
                               st.multivariate_normal.logpdf(x, mu, cov),
                               rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy()),
                               st.multivariate_normal(mu, cov).entropy(),
                               rtol=1e-5)
    s = d.rsample((4000,), rng=jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.cov(np.asarray(s).T), cov, atol=0.15)
    # KL(p, p) == 0
    assert abs(float(kl_divergence(d, d))) < 1e-5


def test_amp_supported_helpers():
    import paddle_tpu.amp as amp
    assert amp.is_bfloat16_supported() is True
    assert isinstance(amp.is_float16_supported(), bool)


def test_ormqr():
    import paddle_tpu.linalg as L
    import torch
    rs = np.random.RandomState(0)
    a = rs.randn(5, 3).astype(np.float32)
    c = rs.randn(5, 2).astype(np.float32)
    h, tau = torch.geqrf(torch.tensor(a))
    want = torch.ormqr(h, tau, torch.tensor(c)).numpy()
    got = L.ormqr(jnp.asarray(h.numpy()), jnp.asarray(tau.numpy()),
                  jnp.asarray(c))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_svd_lowrank_recovers_low_rank():
    import paddle_tpu.linalg as L
    pt.seed(0)
    rs = np.random.RandomState(0)
    base = rs.randn(20, 3).astype(np.float32) @ rs.randn(3, 15).astype(np.float32)
    u, s, v = L.svd_lowrank(jnp.asarray(base), q=5)
    approx = np.asarray(u) * np.asarray(s) @ np.asarray(v).T
    np.testing.assert_allclose(approx, base, atol=1e-3)


def test_concat_dataset_out_of_range():
    from paddle_tpu.io import ConcatDataset, TensorDataset
    cd = ConcatDataset([TensorDataset(jnp.arange(3.0))])
    with pytest.raises(IndexError):
        cd[3]
    with pytest.raises(IndexError):
        cd[-4]


def test_ormqr_batched():
    import paddle_tpu.linalg as L
    import torch
    rs = np.random.RandomState(1)
    a = rs.randn(3, 5, 4).astype(np.float32)
    c = rs.randn(3, 5, 2).astype(np.float32)
    h, tau = torch.geqrf(torch.tensor(a))
    want = torch.ormqr(h, tau, torch.tensor(c)).numpy()
    got = L.ormqr(jnp.asarray(h.numpy()), jnp.asarray(tau.numpy()),
                  jnp.asarray(c))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_global_bias_initializer_applies_to_conv():
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.initializer as I
    I.set_global_initializer(I.Constant(2.0), I.Constant(1.0))
    try:
        conv = nn.Conv2D(2, 3, 3)
        assert float(conv.bias.min()) == 1.0
        assert float(conv.weight.min()) == 2.0
    finally:
        I.set_global_initializer(None, None)


def test_parallel_env_consistent_with_get_world_size():
    import paddle_tpu.distributed as D
    assert D.ParallelEnv().world_size == D.get_world_size()


def test_data_parallel_pickle_roundtrip():
    import pickle
    import paddle_tpu.distributed as D
    import paddle_tpu.nn as nn
    pt.seed(0)
    dp = D.DataParallel(nn.Linear(2, 2))
    dp2 = pickle.loads(pickle.dumps(dp))
    x = jnp.ones((1, 2))
    np.testing.assert_allclose(np.asarray(dp2(x)), np.asarray(dp(x)))
