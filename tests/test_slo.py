"""Per-tenant SLO tracker + usage-metering cost ledger (ISSUE 19):
burn-rate math checked against hand-computed windows, the multi-window
AND gate (a short spike alone cannot page), tick-for-tick cost-ledger
reconciliation under spec decoding + preemption + injected chaos, the
tenant label-cardinality guard, the PT_SLO=0 kill switch's bit-identity
promise, and the overload acceptance run — a deadline storm breaches
the abused tenant (with a flight event) while the idle tenant's budget
stays untouched."""
import types

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import GOODPUT, METRICS
from paddle_tpu.observability.flight import FLIGHT
from paddle_tpu.observability.slo import (IDLE_TENANT, SYSTEM_TENANT,
                                          CostLedger, Objective, SLOTracker,
                                          default_objectives, slo_doc,
                                          slo_enabled, tenants_doc)
from paddle_tpu.serving import LLMEngine, Request
from paddle_tpu.serving.telemetry import (_TENANT_FINISHED,
                                          _TENANT_REJECTED, _TENANT_TTFT,
                                          TENANT_OVERFLOW_LABEL,
                                          reset_tenant_labels, tenant_label)
from paddle_tpu.utils.faults import FAULTS, InjectedFault


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    cfg = LlamaConfig.tiny(num_hidden_layers=1, hidden_size=32,
                           num_attention_heads=4, num_key_value_heads=2,
                           vocab_size=64)
    return LlamaForCausalLM(cfg)


def _mk(model, **kw):
    args = dict(num_slots=4, block_size=4, max_prompt_len=16,
                max_seq_len=48)
    args.update(kw)
    return LLMEngine(model, **args)


def _prompts(n, rs, lo=3, hi=14):
    return [rs.randint(0, 64, (int(l),)) for l in rs.randint(lo, hi, size=n)]


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _gauge(name, **labels):
    return METRICS.get(name).value(**labels)


# ------------------------------------------------------------- objectives

def test_objective_validation_and_defaults():
    with pytest.raises(ValueError, match="unknown objective"):
        Objective("tail_latency", target=1.0)
    with pytest.raises(ValueError, match="availability target"):
        Objective("availability", target=1.0)
    with pytest.raises(ValueError, match="short_s"):
        Objective("ttft_p95", target=1.0, short_s=10.0, window_s=5.0)
    with pytest.raises(ValueError, match="budget"):
        Objective("ttft_p95", target=1.0, budget=0.0)
    # budget defaults: 1 - target for availability, 5% for the p95s
    assert Objective("availability", target=0.999).budget \
        == pytest.approx(0.001)
    assert Objective("ttft_p95", target=1.0).budget == 0.05
    names = [o.name for o in default_objectives()]
    assert names == ["ttft_p95", "queue_wait_p95", "inter_token_p95",
                     "availability"]
    with pytest.raises(ValueError, match="duplicate objective"):
        SLOTracker([Objective("ttft_p95", target=1.0),
                    Objective("ttft_p95", target=2.0)])


# -------------------------------------------------------- burn-rate math

def test_availability_burn_matches_hand_computed_windows():
    """Windowed deltas → error rate → burn, against hand arithmetic:
    20 finishes of which 2 timed out, against a 0.1%% budget, is a
    rate-0.1 window and exactly burn 100."""
    clk = _Clock()
    obj = Objective("availability", target=0.999, window_s=60.0,
                    short_s=10.0)
    tr = SLOTracker({"*": [obj]}, clock=clk)
    tr.poll()                                 # baseline (empty registry)
    _TENANT_FINISHED.inc(18, tenant="acme", reason="eos")
    _TENANT_FINISHED.inc(2, tenant="acme", reason="timeout")
    clk.t = 5.0
    tr.poll()
    row = tr.state[("acme", "availability")]
    assert row["window_bad"] == 2.0 and row["window_total"] == 20.0
    assert row["burn_short"] == pytest.approx((2 / 20) / 0.001)
    assert row["burn_long"] == pytest.approx((2 / 20) / 0.001)
    assert row["compliance"] == pytest.approx(0.9)
    # budget_remaining = 1 - bad/(budget*total) clamps at 0 when blown
    assert row["budget_remaining"] == 0.0
    assert _gauge("serving_slo_burn_rate", tenant="acme",
                  objective="availability") \
        == pytest.approx(row["burn_short"])
    # rejections count as bad with a clamped denominator: a pure-reject
    # window saturates at error rate 1.0
    _TENANT_REJECTED.inc(5, tenant="acme")
    clk.t = 6.0
    tr.poll()
    row = tr.state[("acme", "availability")]
    assert row["window_bad"] == 7.0 and row["window_total"] == 25.0


def test_latency_objective_is_exact_on_bucket_bounds():
    """target=0.1 sits on a default bucket bound: observations <= 0.1
    are good, the first observation past it lands in the 0.25 bucket
    and counts bad — 2 bad / 4 total, hand-checkable."""
    clk = _Clock()
    obj = Objective("ttft_p95", target=0.1, budget=0.5, window_s=60.0,
                    short_s=60.0, fast_burn=1.0, slow_burn=1.0)
    tr = SLOTracker({"lat": [obj]}, clock=clk)
    tr.poll()
    for v in (0.04, 0.1, 0.11, 0.3):
        _TENANT_TTFT.observe(v, tenant="lat")
    clk.t = 1.0
    tr.poll()
    row = tr.state[("lat", "ttft_p95")]
    assert row["window_bad"] == 2.0 and row["window_total"] == 4.0
    assert row["burn_short"] == pytest.approx((2 / 4) / 0.5)
    assert row["breaching"] is True           # gates lowered to 1.0


def test_multi_window_and_gate_blocks_short_spikes():
    """A burst that saturates the short window cannot page while the
    long window is still healthy; once the long window crosses the slow
    gate too, the breach fires ONCE (rising edge) with a flight event,
    and recovery re-arms it."""
    clk = _Clock()
    obj = Objective("availability", target=0.99, window_s=100.0,
                    short_s=10.0)               # budget 0.01
    tr = SLOTracker({"t": [obj]}, clock=clk)
    tr.poll()
    _TENANT_FINISHED.inc(1000, tenant="t", reason="eos")
    clk.t = 10.0
    tr.poll()
    _TENANT_FINISHED.inc(10, tenant="t", reason="timeout")
    clk.t = 95.0
    tr.poll()
    row = tr.state[("t", "availability")]
    # short window holds only the burst: burn 100 >> fast gate
    assert row["burn_short"] == pytest.approx(100.0)
    # long window dilutes it below the slow gate: 10/1010 / 0.01
    assert row["burn_long"] == pytest.approx(10 / 1010 / 0.01)
    assert row["breaching"] is False
    assert tr.breaches == []
    assert METRICS.get("serving_slo_breaches_total")._series == {}
    # keep burning: the long window crosses 6x and the alert fires
    _TENANT_FINISHED.inc(200, tenant="t", reason="timeout")
    clk.t = 96.0
    tr.poll()
    row = tr.state[("t", "availability")]
    assert row["burn_short"] >= obj.fast_burn
    assert row["burn_long"] == pytest.approx(210 / 1210 / 0.01)
    assert row["burn_long"] >= obj.slow_burn
    assert row["breaching"] is True
    assert [e["kind"] for e in FLIGHT.events()].count(
        "serving.slo_breach") == 1
    assert len(tr.breaches) == 1
    assert tr.breaches[0]["tenant"] == "t"
    # still breaching next poll: no re-fire (edge-triggered)
    _TENANT_FINISHED.inc(50, tenant="t", reason="timeout")
    clk.t = 97.0
    tr.poll()
    assert len(tr.breaches) == 1
    assert _gauge("serving_slo_breaches_total", tenant="t",
                  objective="availability") == 1
    # quiet long enough and the windows drain: re-armed, budget back
    clk.t = 250.0
    tr.poll()
    row = tr.state[("t", "availability")]
    assert row["breaching"] is False
    assert row["budget_remaining"] == 1.0


# ----------------------------------------------------------- cost ledger

class _AuditTracker(SLOTracker):
    """charge_tick spy: after EVERY tick the per-tenant rows must sum
    to the untenanted totals — reconciliation is an invariant of each
    charge, not a property of the final state."""

    def charge_tick(self, engine, seconds):
        super().charge_tick(engine, seconds)
        led = self.ledger
        tick = METRICS.get("serving_tick_seconds")
        hist_sum = sum(s.sum for s in tick._series.values())
        # bit-exact: both accumulate the same floats in the same order
        assert led.device_seconds_total == hist_sum
        assert sum(led.device_seconds.values()) == pytest.approx(
            led.device_seconds_total, rel=1e-12, abs=1e-15)
        assert sum(led.block_seconds.values()) == pytest.approx(
            led.block_seconds_total, rel=1e-12, abs=1e-15)


def test_cost_ledger_reconciles_tick_for_tick(model, draft):
    """Spec decoding + preemption + injected spec-verify chaos, two
    tenants: every tick's device-second shares sum exactly to the tick
    histogram, and the token columns equal the untenanted GOODPUT
    counters column by column."""
    rs = np.random.RandomState(7)
    prompts = _prompts(6, rs)
    FAULTS.install("serving.spec_verify", on={2, 5}, exc=InjectedFault)
    FAULTS.install("serving.preempt", every=5, times=4,
                   action=lambda ctx: ctx["engine"]._preempt())
    tr = _AuditTracker()
    eng = _mk(model, draft_model=draft, spec_k=3, num_slots=2,
              preemption=True, slo=tr)
    for i, p in enumerate(prompts):
        eng.add_request(Request(p, max_new_tokens=8,
                                tenant_id="acme" if i % 2 else "beta"))
    eng.run()
    eng.assert_quiescent()
    led = tr.ledger
    assert led.ticks > 0 and led.device_seconds_total > 0
    assert {"acme", "beta"} <= set(led.tenants())
    # token columns reconcile exactly (integer arithmetic end to end)
    assert led.good_total() == GOODPUT.good_total()
    assert led.waste_total() == GOODPUT.waste_total()
    assert led.saved_total() == GOODPUT.saved_total()
    by_why = {}
    for by in led.waste_tokens.values():
        for why, n in by.items():
            by_why[why] = by_why.get(why, 0) + n
    assert by_why == {k: v for k, v in GOODPUT.waste_by_why().items() if v}
    assert by_why.get("chaos_abort", 0) > 0       # the chaos really bit
    assert eng.stats["preemptions"] > 0           # and preemption too
    # the /tenants document carries the same rows
    doc = tr.tenants_snapshot()
    assert doc["good_tokens_total"] == led.good_total()
    assert doc["tenants"]["acme"]["device_seconds"] > 0
    assert doc["tenants"]["acme"]["block_seconds"] > 0


def test_cost_ledger_reconciles_at_async_depth(model):
    """ISSUE 20: the per-charge reconciliation invariant must survive
    async pipelining — at ``async_depth=2`` with preemption chaos and
    two tenants, every tick's device-second shares still sum bit-exactly
    to the tick histogram (the _AuditTracker asserts inside each
    charge), and the token columns close against GOODPUT."""
    rs = np.random.RandomState(7)
    prompts = _prompts(6, rs)
    FAULTS.install("serving.preempt", every=5, times=3,
                   action=lambda ctx: ctx["engine"]._preempt())
    tr = _AuditTracker()
    eng = _mk(model, num_slots=2, preemption=True, slo=tr, async_depth=2)
    for i, p in enumerate(prompts):
        eng.add_request(Request(p, max_new_tokens=8,
                                tenant_id="acme" if i % 2 else "beta"))
    eng.run()
    eng.assert_quiescent()
    led = tr.ledger
    assert led.ticks > 0 and led.device_seconds_total > 0
    assert {"acme", "beta"} <= set(led.tenants())
    assert led.good_total() == GOODPUT.good_total()
    assert led.waste_total() == GOODPUT.waste_total()
    assert eng.stats["preemptions"] > 0
    # the pipeline really engaged (drained at the chaos boundaries)
    drains = METRICS.get("serving_async_drains_total")
    assert sum(c[0] for c in drains._series.values()) > 0
    # no cancels → no over-dispatched rows billed
    assert GOODPUT.waste_by_why().get("async_overrun", 0) == 0


def test_charge_tick_shares_idle_and_remainder():
    """Direct unit check of the splitting rule: three resident tenants
    share a tick in equal row shares that sum BIT-exactly (the last
    share absorbs the float remainder); an empty tick bills __idle__;
    untenanted work bills __system__."""
    tr = SLOTracker()
    reqs = {1: types.SimpleNamespace(tenant_id="a"),
            2: types.SimpleNamespace(tenant_id="b"),
            3: types.SimpleNamespace(tenant_id=None)}
    eng = types.SimpleNamespace(
        slot_req=np.array([1, 2, -1]), active=np.array([True, True, True]),
        prefilling={3: None}, groups={}, requests=reqs,
        kv=types.SimpleNamespace(
            ledger=types.SimpleNamespace(enabled=False)))
    seconds = 0.1          # 0.1/3 is not exact in binary: remainder test
    tr.charge_tick(eng, seconds)
    led = tr.ledger
    assert set(led.device_seconds) == {"a", "b", SYSTEM_TENANT}
    assert sum(led.device_seconds.values()) == seconds      # bit-exact
    assert led.device_seconds["a"] == pytest.approx(seconds / 3)
    empty = types.SimpleNamespace(
        slot_req=np.array([-1]), active=np.array([True]), prefilling={},
        groups={}, requests={},
        kv=types.SimpleNamespace(
            ledger=types.SimpleNamespace(enabled=False)))
    tr.charge_tick(empty, 0.25)
    assert led.device_seconds[IDLE_TENANT] == 0.25
    assert led.device_seconds_total == pytest.approx(0.35)
    assert led.ticks == 2


def test_goodput_sink_attribution_is_by_construction():
    """Every GOODPUT charge lands in the tracker's ledger with the
    tenant the call site passed; untenanted charges bill __system__."""
    tr = SLOTracker()
    GOODPUT.good(5, tenant="a")
    GOODPUT.good(3)                              # batch-level: __system__
    GOODPUT.waste("pad_rows", 4)
    GOODPUT.waste("spec_rejected", 2, tenant="a")
    GOODPUT.waste("spec_rejected", 0, tenant="a")     # no-op, like _WASTE
    GOODPUT.saved(6, tenant="b")
    led = tr.ledger
    assert led.good_tokens == {"a": 5, SYSTEM_TENANT: 3}
    assert led.waste_tokens == {SYSTEM_TENANT: {"pad_rows": 4},
                                "a": {"spec_rejected": 2}}
    assert led.saved_tokens == {"b": 6}
    assert led.good_total() == GOODPUT.good_total()
    assert led.waste_total() == GOODPUT.waste_total()
    assert led.saved_total() == GOODPUT.saved_total()


# ----------------------------------------------------- cardinality guard

def test_tenant_label_cardinality_guard(monkeypatch):
    monkeypatch.setenv("PT_TENANT_LABEL_CAP", "2")
    reset_tenant_labels()
    assert tenant_label("t1") == "t1"
    assert tenant_label("t2") == "t2"
    assert tenant_label("t3") == TENANT_OVERFLOW_LABEL
    assert tenant_label(999) == TENANT_OVERFLOW_LABEL
    assert tenant_label("t1") == "t1"            # seen names keep passing
    assert _gauge("serving_tenant_label_overflow_total") == 2
    # the guard protects the ledger rows too
    tr = SLOTracker()
    GOODPUT.good(1, tenant="t9")
    assert tr.ledger.good_tokens == {TENANT_OVERFLOW_LABEL: 1}
    monkeypatch.setenv("PT_TENANT_LABEL_CAP", "64")
    reset_tenant_labels()
    assert tenant_label("t3") == "t3"


# ----------------------------------------------------------- kill switch

def test_kill_switch_bit_identical_and_inert(model, monkeypatch):
    """PT_SLO=0: an engine carrying a tracker emits byte-for-byte the
    tokens of a tracker-free build, and every tracker surface — ledger,
    polls, gauges — stays empty."""
    rs = np.random.RandomState(11)
    prompts = _prompts(5, rs)
    eng = _mk(model)
    for p in prompts:
        eng.add_request(Request(p, max_new_tokens=8, tenant_id="a"))
    ref = {rid: list(map(int, t)) for rid, t in eng.run().items()}
    monkeypatch.setenv("PT_SLO", "0")
    assert not slo_enabled()
    tr = SLOTracker()
    eng2 = _mk(model, slo=tr)
    for p in prompts:
        eng2.add_request(Request(p, max_new_tokens=8, tenant_id="a"))
    got = {rid: list(map(int, t)) for rid, t in eng2.run().items()}
    assert got == ref
    assert tr.polls == 0 and tr.state == {}
    assert tr.ledger.ticks == 0
    assert tr.ledger.snapshot()["tenants"] == {}
    for name in ("serving_slo_burn_rate", "serving_slo_budget_remaining",
                 "serving_tenant_device_seconds_total",
                 "serving_tenant_kv_block_seconds_total"):
        assert METRICS.get(name)._series == {}, name
    assert slo_doc()["enabled"] is False
    assert tenants_doc()["enabled"] is False
    # flip back on mid-flight: the very next poll works
    monkeypatch.delenv("PT_SLO")
    tr.poll()
    assert tr.polls == 1


# --------------------------------------------------- overload acceptance

def test_deadline_storm_breaches_abused_tenant_only(model):
    """Acceptance: a tenant whose every request carries an already-blown
    deadline burns its availability budget and fires the breach (flight
    event names it); the well-behaved tenant sharing the engine keeps a
    full budget."""
    obj = Objective("availability", target=0.999, window_s=3600.0,
                    short_s=300.0)
    tr = SLOTracker({"*": [obj]})
    tr.poll()                  # baseline before any traffic
    rs = np.random.RandomState(13)
    eng = _mk(model, slo=tr)
    for p in _prompts(4, rs):
        eng.add_request(Request(p, max_new_tokens=6, tenant_id="calm"))
    for p in _prompts(4, rs):
        eng.add_request(Request(p, max_new_tokens=6, tenant_id="abuser",
                                deadline_s=1e-9))
    eng.run()                  # engine polls the tracker per tick
    eng.assert_quiescent()
    tr.poll()                  # one final sweep past the last finish
    abused = tr.state[("abuser", "availability")]
    calm = tr.state[("calm", "availability")]
    assert eng.stats["timeouts"] == 4
    assert abused["breaching"] is True
    assert abused["budget_remaining"] == 0.0
    assert abused["burn_short"] >= obj.fast_burn
    assert abused["burn_long"] >= obj.slow_burn
    assert calm["breaching"] is False
    assert calm["budget_remaining"] == 1.0
    assert calm["compliance"] == 1.0
    events = [e for e in FLIGHT.events() if e["kind"] == "serving.slo_breach"]
    # the fleet-wide scorecard breaches too (half its finishes timed
    # out) — what matters is that no event ever names the calm tenant
    assert {e["tenant"] for e in events} == {"abuser", "*"}
    assert _gauge("serving_slo_budget_remaining", tenant="calm",
                  objective="availability") == 1.0
    assert _gauge("serving_slo_breaches_total", tenant="abuser",
                  objective="availability") == 1
    # the scorecard document reflects the verdict
    (snap,) = [s for s in slo_doc()["trackers"] if s["tracker"] == tr.seq]
    assert any(r["tenant"] == "abuser" and r["breaching"]
               for r in snap["status"])


def test_cost_ledger_standalone_is_plain_dicts():
    led = CostLedger()
    led.good("a", 2)
    led.waste("a", "pad_rows", 1)
    led.saved(None, 3)
    assert led.tenants() == sorted(["a", SYSTEM_TENANT])
    snap = led.snapshot()
    assert snap["good_tokens_total"] == 2
    assert snap["waste_tokens_total"] == 1
    assert snap["saved_tokens_total"] == 3
