"""Family-agnostic generation: generic_generate (full re-forward, no KV
cache) equals the cached generate on LLaMA, matches HF greedy decode on
non-LLaMA families (BLOOM, GPT-NeoX), and handles EOS/penalties."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate, generic_generate


def test_generic_equals_cached_generate_llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, vocab_size=64))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (2, 7)))
    ref = generate(m, ids, max_new_tokens=8, eos_token_id=1)
    got = generic_generate(m, ids, max_new_tokens=8, eos_token_id=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # with penalties/sampling constraints too
    ref = generate(m, ids, max_new_tokens=6, repetition_penalty=1.3,
                   eos_token_id=1, min_new_tokens=3)
    got = generic_generate(m, ids, max_new_tokens=6,
                           repetition_penalty=1.3, eos_token_id=1,
                           min_new_tokens=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize("family", ["bloom", "gpt_neox"])
def test_generic_generate_matches_hf_greedy(family):
    transformers = pytest.importorskip("transformers")
    import torch

    if family == "bloom":
        from transformers import BloomConfig as HFConfig
        from transformers import BloomForCausalLM as HFModel
        from paddle_tpu.models.bloom import BloomConfig, BloomForCausalLM
        from paddle_tpu.models.convert import load_bloom_state_dict
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, n_layer=2,
                              n_head=4, use_cache=False)).eval()
        pt.seed(0)
        ours = load_bloom_state_dict(
            BloomForCausalLM(BloomConfig.tiny(vocab_size=96)).eval(),
            hf.state_dict())
    else:
        from transformers import GPTNeoXConfig as HFConfig
        from transformers import GPTNeoXForCausalLM as HFModel
        from paddle_tpu.models.convert import load_gpt_neox_state_dict
        from paddle_tpu.models.gpt_neox import (GPTNeoXConfig,
                                                GPTNeoXForCausalLM)
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=4,
                              intermediate_size=64, rotary_pct=0.25,
                              max_position_embeddings=64, use_cache=False,
                              attn_implementation="eager")).eval()
        pt.seed(0)
        ours = load_gpt_neox_state_dict(
            GPTNeoXForCausalLM(GPTNeoXConfig.tiny(vocab_size=96)).eval(),
            hf.state_dict())

    rs = np.random.RandomState(3)
    ids = rs.randint(2, 96, (1, 6))
    new = 8
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids), max_new_tokens=new,
                          do_sample=False, use_cache=False,
                          pad_token_id=0).numpy()
    got = np.asarray(generic_generate(ours, jnp.asarray(ids),
                                      max_new_tokens=new))
    np.testing.assert_array_equal(got, ref)
