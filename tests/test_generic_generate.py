"""Family-agnostic generation: generic_generate (full re-forward, no KV
cache) equals the cached generate on LLaMA, matches HF greedy decode on
non-LLaMA families (BLOOM, GPT-NeoX), and handles EOS/penalties."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.decoding import generate, generic_generate


def test_generic_equals_cached_generate_llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(
        num_hidden_layers=2, hidden_size=32, num_attention_heads=4,
        num_key_value_heads=2, vocab_size=64))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (2, 7)))
    ref = generate(m, ids, max_new_tokens=8, eos_token_id=1)
    got = generic_generate(m, ids, max_new_tokens=8, eos_token_id=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # with penalties/sampling constraints too
    ref = generate(m, ids, max_new_tokens=6, repetition_penalty=1.3,
                   eos_token_id=1, min_new_tokens=3)
    got = generic_generate(m, ids, max_new_tokens=6,
                           repetition_penalty=1.3, eos_token_id=1,
                           min_new_tokens=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize("family", ["bloom", "gpt_neox"])
def test_generic_generate_matches_hf_greedy(family):
    transformers = pytest.importorskip("transformers")
    import torch

    if family == "bloom":
        from transformers import BloomConfig as HFConfig
        from transformers import BloomForCausalLM as HFModel
        from paddle_tpu.models.bloom import BloomConfig, BloomForCausalLM
        from paddle_tpu.models.convert import load_bloom_state_dict
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, hidden_size=32, n_layer=2,
                              n_head=4, use_cache=False)).eval()
        pt.seed(0)
        ours = load_bloom_state_dict(
            BloomForCausalLM(BloomConfig.tiny(vocab_size=96)).eval(),
            hf.state_dict())
    else:
        from transformers import GPTNeoXConfig as HFConfig
        from transformers import GPTNeoXForCausalLM as HFModel
        from paddle_tpu.models.convert import load_gpt_neox_state_dict
        from paddle_tpu.models.gpt_neox import (GPTNeoXConfig,
                                                GPTNeoXForCausalLM)
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=4,
                              intermediate_size=64, rotary_pct=0.25,
                              max_position_embeddings=64, use_cache=False,
                              attn_implementation="eager")).eval()
        pt.seed(0)
        ours = load_gpt_neox_state_dict(
            GPTNeoXForCausalLM(GPTNeoXConfig.tiny(vocab_size=96)).eval(),
            hf.state_dict())

    rs = np.random.RandomState(3)
    ids = rs.randint(2, 96, (1, 6))
    new = 8
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids), max_new_tokens=new,
                          do_sample=False, use_cache=False,
                          pad_token_id=0).numpy()
    got = np.asarray(generic_generate(ours, jnp.asarray(ids),
                                      max_new_tokens=new))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("family", ["bart", "whisper"])
def test_generic_seq2seq_matches_hf_greedy(family):
    import torch
    from paddle_tpu.models.decoding import generic_seq2seq_generate

    if family == "bart":
        from transformers import BartConfig as HFConfig
        from transformers import BartForConditionalGeneration as HFModel
        from paddle_tpu.models.bart import (BartConfig,
                                            BartForConditionalGeneration)
        from paddle_tpu.models.convert import load_bart_state_dict
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                              decoder_layers=2, encoder_attention_heads=4,
                              decoder_attention_heads=4,
                              encoder_ffn_dim=64, decoder_ffn_dim=64,
                              max_position_embeddings=64, pad_token_id=1,
                              use_cache=False,
                              attn_implementation="eager")).eval()
        pt.seed(0)
        ours = load_bart_state_dict(
            BartForConditionalGeneration(BartConfig.tiny(vocab_size=96)),
            hf.state_dict())
        rs = np.random.RandomState(0)
        enc_in = rs.randint(2, 96, (2, 9))
        enc_t = torch.tensor(enc_in)

        def hf_step(dec):
            return hf(enc_t, decoder_input_ids=dec).logits
    else:
        from transformers import WhisperConfig as HFConfig
        from transformers import WhisperForConditionalGeneration as HFModel
        from paddle_tpu.models.convert import load_whisper_state_dict
        from paddle_tpu.models.whisper import (
            WhisperConfig, WhisperForConditionalGeneration)
        torch.manual_seed(0)
        hf = HFModel(HFConfig(vocab_size=96, num_mel_bins=8, d_model=32,
                              encoder_layers=2, decoder_layers=2,
                              encoder_attention_heads=4,
                              decoder_attention_heads=4,
                              encoder_ffn_dim=64, decoder_ffn_dim=64,
                              max_source_positions=16,
                              max_target_positions=32, use_cache=False,
                              pad_token_id=0, bos_token_id=1,
                              eos_token_id=2, decoder_start_token_id=1,
                              suppress_tokens=None,
                              begin_suppress_tokens=None,
                              attn_implementation="eager")).eval()
        pt.seed(0)
        ours = load_whisper_state_dict(
            WhisperForConditionalGeneration(
                WhisperConfig.tiny(vocab_size=96)), hf.state_dict())
        rs = np.random.RandomState(0)
        enc_in = rs.randn(2, 8, 32).astype(np.float32)
        enc_t = torch.tensor(enc_in)

        def hf_step(dec):
            return hf(input_features=enc_t, decoder_input_ids=dec).logits

    new, start = 6, 1
    # manual HF greedy loop (no forced-token machinery)
    dec = torch.full((2, 1), start, dtype=torch.long)
    with torch.no_grad():
        for _ in range(new):
            nxt = hf_step(dec)[:, -1].argmax(-1, keepdim=True)
            dec = torch.cat([dec, nxt], dim=1)
    ref = dec[:, 1:].numpy()
    got = np.asarray(generic_seq2seq_generate(
        ours, jnp.asarray(enc_in), max_new_tokens=new,
        decoder_start_token_id=start))
    np.testing.assert_array_equal(got, ref)


def test_generic_seq2seq_beam_search_bart():
    """Seq2seq beam: beam-1 == greedy; the beam-K winner's EXACT sequence
    log-probability (recomputed independently) is >= greedy's."""
    import torch
    from transformers import BartConfig as HFConfig
    from transformers import BartForConditionalGeneration as HFModel
    from paddle_tpu.models.bart import (BartConfig,
                                        BartForConditionalGeneration)
    from paddle_tpu.models.convert import load_bart_state_dict
    from paddle_tpu.models.decoding import (generic_seq2seq_beam_search,
                                            generic_seq2seq_generate)

    torch.manual_seed(0)
    hf = HFModel(HFConfig(vocab_size=96, d_model=32, encoder_layers=2,
                          decoder_layers=2, encoder_attention_heads=4,
                          decoder_attention_heads=4, encoder_ffn_dim=64,
                          decoder_ffn_dim=64, max_position_embeddings=64,
                          pad_token_id=1, use_cache=False,
                          attn_implementation="eager")).eval()
    pt.seed(0)
    ours = load_bart_state_dict(
        BartForConditionalGeneration(BartConfig.tiny(vocab_size=96)),
        hf.state_dict())
    rs = np.random.RandomState(0)
    enc_in = jnp.asarray(rs.randint(2, 96, (2, 9)))
    new, start = 5, 1

    greedy = np.asarray(generic_seq2seq_generate(
        ours, enc_in, max_new_tokens=new, decoder_start_token_id=start))
    b1, _ = generic_seq2seq_beam_search(
        ours, enc_in, max_new_tokens=new, num_beams=1,
        decoder_start_token_id=start)
    np.testing.assert_array_equal(np.asarray(b1), greedy)

    bk, scores = generic_seq2seq_beam_search(
        ours, enc_in, max_new_tokens=new, num_beams=4,
        decoder_start_token_id=start)

    def seq_logprob(row, gen):
        dec = np.concatenate([[start], gen])
        lg = np.asarray(ours(enc_in[row: row + 1], jnp.asarray(dec[None])),
                        np.float32)[0]
        lp = lg - np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(
            -1, keepdims=True)) - lg.max(-1, keepdims=True)
        return sum(lp[t, int(dec[t + 1])] for t in range(len(gen)))

    for row in range(2):
        s_beam = seq_logprob(row, np.asarray(bk)[row])
        s_greedy = seq_logprob(row, greedy[row])
        np.testing.assert_allclose(float(scores[row]) * new, s_beam,
                                   rtol=1e-4, atol=1e-4)
        assert s_beam >= s_greedy - 1e-5
