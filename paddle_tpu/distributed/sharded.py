"""Parameter/optimizer sharding — GroupSharded / ZeRO (ref:
``python/paddle/distributed/fleet/meta_parallel/sharding/group_sharded_stage2.py``
/ ``group_sharded_stage3.py`` and sharding_optimizer).

The reference partitions params/grads/opt-state across ranks with manual
broadcast/reduce hooks. TPU-native: a *sharding rule* assigns every leaf a
PartitionSpec on the ``fsdp`` axis; jit + donation keep params resident
sharded, XLA all-gathers just-in-time per layer (that IS ZeRO-3/FSDP) and
reduce-scatters grads.

  stage 1: optimizer state sharded         → specs applied to opt_state only
  stage 2: + grads sharded                 → same specs; grads inherit them
  stage 3: + params sharded                → specs applied to params too
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.module import Module, _path_to_str
from paddle_tpu.distributed.mesh import HybridMesh


def _pspec_of_leaf(path_str: str, leaf, module: Module, min_size: int,
                   fsdp_size: int) -> P:
    """Sharding rule: honour an explicit tp pspec if the owning layer set
    one, then shard the largest divisible dim over fsdp."""
    explicit = _explicit_pspec(module, path_str)
    spec = list(explicit) if explicit is not None else [None] * leaf.ndim
    while len(spec) < leaf.ndim:
        spec.append(None)
    used = set()
    for s in spec:
        for a in (s if isinstance(s, tuple) else (s,)):
            if a is not None:
                used.add(a)
    if leaf.size >= min_size and "fsdp" not in used:
        # largest unsharded, fsdp-divisible dim
        cand = sorted(range(leaf.ndim), key=lambda i: -leaf.shape[i])
        for i in cand:
            if spec[i] is None and leaf.shape[i] % max(fsdp_size, 1) == 0:
                spec[i] = "fsdp"
                break
    return P(*spec)


def _explicit_pspec(module: Module, path_str: str) -> Optional[tuple]:
    parts = path_str.split(".")
    obj = module
    for i, part in enumerate(parts[:-1]):
        if isinstance(obj, Module) and hasattr(obj, part):
            obj = getattr(obj, part)
        elif isinstance(obj, (list, tuple)) and part.isdigit():
            obj = obj[int(part)]
        elif isinstance(obj, dict) and part in obj:
            obj = obj[part]
        else:
            return None
    if isinstance(obj, Module):
        spec = obj.pspec(parts[-1])
        if spec is not None:
            return tuple(spec)
    return None


def partition_specs(module: Module, stage: int = 3, min_size: int = 2 ** 16,
                    fsdp_size: int = 1):
    """PartitionSpec pytree matching `module` (params get fsdp+tp specs)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(module)
    specs = []
    for path, leaf in flat:
        if leaf is None or not hasattr(leaf, "ndim"):
            specs.append(None)
            continue
        ps = _path_to_str(path)
        if stage >= 3:
            specs.append(_pspec_of_leaf(ps, leaf, module, min_size, fsdp_size))
        else:
            explicit = _explicit_pspec(module, ps)
            specs.append(P(*explicit) if explicit is not None else P())
    return jax.tree_util.tree_unflatten(treedef, specs)


def opt_state_specs(opt_state: dict, param_specs):
    """Optimizer slots mirror the param tree → same specs; scalars replicated."""
    out = {}
    for k, v in opt_state.items():
        if k == "step":
            out[k] = P()
        else:
            out[k] = jax.tree_util.tree_map(
                lambda leaf, spec=None: spec, v, is_leaf=lambda x: x is None)
            # align by structure: slots mirror params
            out[k] = _mirror_specs(v, param_specs)
    return out


def _mirror_specs(slot_tree, param_specs):
    ps_leaves = jax.tree_util.tree_leaves(param_specs, is_leaf=lambda x: x is None or isinstance(x, P))
    slot_flat, treedef = jax.tree_util.tree_flatten(slot_tree, is_leaf=lambda x: x is None)
    assert len(slot_flat) == len(ps_leaves), (len(slot_flat), len(ps_leaves))
    out = []
    for leaf, spec in zip(slot_flat, ps_leaves):
        out.append(spec if hasattr(leaf, "ndim") else None)
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_module(module: Module, mesh: HybridMesh, stage: int = 3,
                 min_size: int = 2 ** 16) -> Module:
    """Place every param on the mesh per the stage-3 rule (ZeRO-3 resident
    layout). Call once after building the model."""
    specs = partition_specs(module, stage=stage, min_size=min_size,
                            fsdp_size=mesh.fsdp)

    def place(leaf, spec):
        if leaf is None or not hasattr(leaf, "ndim") or spec is None:
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh.mesh, spec))

    return jax.tree_util.tree_map(place, module, specs,
                                  is_leaf=lambda x: x is None)


def with_sharding_constraint(x, *spec):
    return jax.lax.with_sharding_constraint(x, P(*spec))


def maybe_shard(x, *spec):
    """with_sharding_constraint that no-ops when no mesh (or a mesh lacking
    the named axes) is active — models stay runnable single-device."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            return x
        names = set(mesh.axis_names)
        for s in spec:
            for a in (s if isinstance(s, tuple) else (s,)):
                if a is not None and a not in names:
                    return x
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except Exception:
        return x
