"""Auto-parallel annotation API (ref: ``python/paddle/distributed/
auto_parallel/`` — ``shard_tensor``, ``ProcessMesh``, ``Shard``/``Replicate``
placements).

On TPU this IS the native programming model: annotations become
NamedSharding/with_sharding_constraint and GSPMD propagates the rest — the
reference's cost-model planner is XLA's sharding propagation pass.
"""
from __future__ import annotations

import warnings

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ProcessMesh:
    """Ref ProcessMesh([[0,1],[2,3]], dim_names=["x","y"])."""

    def __init__(self, mesh, dim_names=None):
        arr = np.asarray(mesh)
        dim_names = tuple(dim_names or [f"d{i}" for i in range(arr.ndim)])
        devices = np.asarray(jax.devices())[arr]
        self.mesh = Mesh(devices, dim_names)
        self.dim_names = dim_names

    @property
    def shape(self):
        return tuple(self.mesh.shape.values())


class Placement:
    pass


class Shard(Placement):
    """Shard(dim) — shard tensor dim over the corresponding mesh dim."""

    def __init__(self, dim: int):
        self.dim = dim


class Replicate(Placement):
    pass


class Partial(Placement):
    """Pending-reduction placement. GSPMD has no top-level representation
    for "this array holds unreduced partial sums" — partial state only
    exists INSIDE ``shard_map``, where the program ``lax.psum``s it
    explicitly. ``shard_tensor``/``reshard`` therefore treat Partial as
    Replicate and warn (see _placements_to_spec)."""


def _placements_to_spec(ndim, mesh: ProcessMesh, placements):
    spec = [None] * ndim
    for mesh_dim, placement in enumerate(placements):
        if isinstance(placement, Partial):
            warnings.warn(
                "Partial placement has no top-level GSPMD representation; "
                "treating as Replicate. Inside shard_map, lax.psum the "
                "value over the mesh axis instead", stacklevel=3)
        elif isinstance(placement, Shard):
            axis = mesh.dim_names[mesh_dim]
            if spec[placement.dim] is None:
                spec[placement.dim] = axis
            elif isinstance(spec[placement.dim], tuple):
                spec[placement.dim] = spec[placement.dim] + (axis,)
            else:
                spec[placement.dim] = (spec[placement.dim], axis)
    return P(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements):
    """Ref dist.shard_tensor — place `x` per placements on the mesh."""
    spec = _placements_to_spec(np.ndim(x), mesh, placements)
    return jax.device_put(x, NamedSharding(mesh.mesh, spec))


def reshard(x, mesh: ProcessMesh, placements):
    return shard_tensor(x, mesh, placements)


def shard_op(fn, mesh: ProcessMesh, in_placements=None, out_placements=None):
    """Ref dist.shard_op — constrain a function's outputs onto the mesh."""
    def wrapped(*args):
        out = fn(*args)
        if out_placements is not None:
            spec = _placements_to_spec(np.ndim(out), mesh, out_placements)
            out = jax.lax.with_sharding_constraint(out, NamedSharding(mesh.mesh, spec))
        return out
    return wrapped
