"""jax API compatibility shims for the distributed layer."""
import inspect

import jax

try:                                     # newer public name
    from jax import shard_map as _shard_map
except ImportError:                      # older: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma; the old
# experimental checker also lacks rules for several primitives the
# pipeline/MoE paths use, so when a caller doesn't opt in, leave it OFF
_CHECK_KW = ("check_vma" if "check_vma"
             in inspect.signature(_shard_map).parameters else "check_rep")


def shard_map(f, mesh, in_specs, out_specs, **kw):
    check = kw.pop("check_vma", kw.pop("check_rep", False))
    kw[_CHECK_KW] = check
    return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs, **kw)

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        # psum of a Python constant is evaluated statically at trace
        # time (the pre-axis_size idiom), so range()/shape uses stay legal
        return jax.lax.psum(1, axis_name)

__all__ = ["shard_map", "axis_size"]
