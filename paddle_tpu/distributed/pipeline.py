"""Pipeline parallelism (ref: ``python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py`` — PipelineLayer + 1F1B scheduler).

The reference runs an imperative per-rank scheduler exchanging activations
with NCCL send/recv. TPU-native formulation: SPMD over the ``pp`` mesh axis —
stage weights live stacked on a leading pp dimension sharded P("pp", ...),
the microbatch loop is a ``lax.scan``, and the stage handoff is a
``ppermute`` ring. XLA overlaps the permute with the next microbatch's
compute (fill-drain/GPipe schedule; the backward pass is derived by autodiff
through the scan+ppermute, which replays the ring in reverse — activations
are rematerialised per-stage via ``jax.checkpoint`` so pipeline memory
matches 1F1B's working set rather than storing every microbatch).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.module import Module


def stack_layers(layers: list[Module]) -> Module:
    """Stack N structurally-identical layer pytrees on a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *layers)


def unstack_layers(stacked: Module, n: int) -> list[Module]:
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def pipeline_apply(stacked_stage_params, layer_fn: Callable, x_microbatches,
                   *, axis_name: str = "pp", layers_per_stage: int = 1,
                   remat: bool = True):
    """Run microbatches through the pp-stage ring. Call inside shard_map.

    stacked_stage_params: this stage's layers stacked [layers_per_stage, ...]
      (globally [pp * layers_per_stage, ...] sharded on the leading axis).
    layer_fn(layer_params, x) -> x: applies ONE layer.
    x_microbatches: [M, mb, ...] — every stage receives the same microbatch
      stream; non-first stages ignore it (they consume the ring instead).
    Returns [M, mb, ...]: last stage's outputs (valid on the last stage;
      other stages hold garbage — psum/broadcast outside if needed).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m_total = x_microbatches.shape[0]
    ticks = m_total + n_stages - 1

    def apply_stage(params, x):
        def body(h, lyr):
            return layer_fn(lyr, h), None
        if remat:
            run = jax.checkpoint(lambda p, v: lax.scan(body, v, p)[0])
        else:
            run = lambda p, v: lax.scan(body, v, p)[0]
        return run(params, x)

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    mb_shape = x_microbatches.shape[1:]
    out_buf = jnp.zeros((m_total,) + mb_shape, x_microbatches.dtype)
    ring0 = jnp.zeros(mb_shape, x_microbatches.dtype)

    def tick(carry, t):
        ring, out_buf = carry
        # stage 0 feeds microbatch t (clamped); others take the ring value
        mb_idx = jnp.clip(t, 0, m_total - 1)
        feed = lax.dynamic_index_in_dim(x_microbatches, mb_idx, 0, keepdims=False)
        x_in = jnp.where(stage == 0, feed, ring)
        y = apply_stage(stacked_stage_params, x_in)
        # last stage: tick t produced microbatch t-(n_stages-1)
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(stage == n_stages - 1,
                                jnp.logical_and(out_idx >= 0, out_idx < m_total))
        updated = lax.dynamic_update_index_in_dim(
            out_buf, y.astype(out_buf.dtype), jnp.clip(out_idx, 0, m_total - 1), 0)
        out_buf = jnp.where(valid, updated, out_buf)
        ring_next = lax.ppermute(y, axis_name, fwd_perm)
        return (ring_next, out_buf), None

    # initial carry must be marked pp-varying (the loop makes it so)
    try:
        ring0 = lax.pvary(ring0, (axis_name,))
        out_buf = lax.pvary(out_buf, (axis_name,))
    except Exception:
        pass
    (_, out_buf), _ = lax.scan(tick, (ring0, out_buf), jnp.arange(ticks))
    return out_buf


class PipelineLayer(Module):
    """Reference-named wrapper: partitions identical blocks over pp stages.

    Single-program: under a mesh with pp>1 the stacked weights shard
    P("pp", ...); without a mesh it runs the plain sequential loop.
    """

    def __init__(self, layers: list[Module], num_stages: int,
                 num_microbatches: int = 1, remat: bool = True):
        super().__init__()
        assert len(layers) % num_stages == 0, "layers must divide stages"
        self.stacked = stack_layers(layers)
        self.template = layers[0]
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.layers_per_stage = len(layers) // num_stages
        self.n_layers = len(layers)
        self.remat = remat
        # leading axis is the stage axis
        flat, _ = jax.tree_util.tree_flatten(self.stacked)

    def stage_specs(self):
        """PartitionSpecs: leading (layer) axis on pp."""
        def spec(leaf):
            return P(*(("pp",) + (None,) * (leaf.ndim - 1)))
        return jax.tree_util.tree_map(spec, self.stacked)

    def __call__(self, x, layer_call: Callable = None, mesh=None):
        layer_call = layer_call or (lambda lyr, h: lyr(h))
        if mesh is None or mesh.pp == 1:
            def body(h, lyr_params):
                return layer_call(lyr_params, h), None
            out, _ = lax.scan(body, x, self.stacked)
            return out
        from jax import shard_map
        mb = self.num_microbatches
        b = x.shape[0]
        assert b % mb == 0, "batch must divide microbatches"
        xm = x.reshape((mb, b // mb) + x.shape[1:])

        pspec = self.stage_specs()
        data_spec = P(*((None,) * xm.ndim))

        @functools.partial(
            shard_map, mesh=mesh.mesh,
            in_specs=(pspec, data_spec), out_specs=data_spec)
        def run(stage_params, xm):
            out = pipeline_apply(stage_params, layer_call, xm,
                                 axis_name="pp",
                                 layers_per_stage=self.layers_per_stage,
                                 remat=self.remat)
            # broadcast last stage's result to all pp members so downstream
            # (loss) is replicated over pp: zero elsewhere + psum
            n = lax.axis_size("pp")
            is_last = (lax.axis_index("pp") == n - 1).astype(out.dtype)
            return lax.psum(out * is_last, "pp")
        return run(self.stacked, xm).reshape(x.shape)
