"""Pipeline parallelism (ref: ``python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py`` — PipelineLayer + 1F1B scheduler).

The reference runs an imperative per-rank scheduler exchanging activations
with NCCL send/recv. TPU-native formulation: SPMD over the ``pp`` mesh axis —
stage weights live stacked on a leading pp dimension sharded P("pp", ...),
the schedule is a ``lax.scan`` over global ticks, and the stage handoff is a
``ppermute`` ring.

Two schedules live here:

- ``pipeline_apply`` — forward-only fill-drain (GPipe) wavefront. Used for
  inference/eval and by ``PipelineLayer.__call__``; differentiating through
  it gives GPipe's all-forward-then-all-backward with per-stage remat.
- ``pipeline_train_1f1b`` — TRUE 1F1B training schedule with a manually
  written backward pass (the reference's ``_1f1b_schedule``): each global
  tick every stage runs one forward microbatch AND one backward microbatch
  (the SPMD "shifted-buffer" formulation of 1F1B — GSPMD-style), so the
  in-flight residual window is a ring of ``2*pp - 1`` saved stage inputs
  **independent of the number of microbatches M** (GPipe stores M). The
  backward slot recomputes the stage forward from its saved input
  (activation-checkpoint style, like the reference's recompute+1F1B mode)
  and accumulates param grads in fp32. Steady-state bubble fraction is
  ``2(pp-1)/(M + 2(pp-1))`` and vanishes as M grows.

Interleaved virtual stages (Megatron's V>1 chunks per device) are
DELIBERATELY not implemented: in this SPMD lockstep-tick formulation every
device executes every tick's full chunk workload with masking, so
interleaving INCREASES total tick cost — the fill/drain ticks still cost a
full V-chunk step while covering 1/V the work, making the bubble
``2(V*pp-1)`` chunk-slots ≈ strictly worse than the non-interleaved
``2(pp-1)`` full-slots. The interleave only pays off with per-device
dynamic schedules (real divergent control flow between collectives), which
SPMD-with-collectives cannot express safely. Megatron wins that trade
because its per-rank imperative scheduler skips idle slots entirely.

A refinement of that cost model motivates the THIRD schedule here,
``pipeline_train_1f1b(zero_bubble=True)`` — reachable as
``pipeline_train_step(..., schedule="zb1")`` (zero-bubble, ZB-H1 style —
ref: Fleet's
interleaved/zero-bubble pipeline work; paper "Zero Bubble Pipeline
Parallelism"): the scan ticks are NOT a global barrier — the only sync is
the pairwise ``ppermute``, so device ``s`` at tick ``t+1`` waits only for
its neighbours' tick-``t`` sends, and per-device ``lax.cond`` slack flows
through the dependency DAG. The step's wall-clock is the DAG's longest
path: fill chain ``(pp-1)·F``, steady ``M·(F+B_dx+W)``, drain chain
``(pp-1)·B`` — and the drain hop cost is the part a schedule CAN shrink.
1F1B pays the FULL backward (recompute+dx+dw) on every drain hop; ZB-H1
splits it: drain hops compute dx ONLY (the cotangent moves on at
``B_dx ≈ recompute+dx`` cost) while the deferred weight-grads run in tail
ticks OFF the critical path. Saving: ``(pp-1)·W`` per step, ~10-15% of
the 1F1B bubble-dominated regime at small M. Interleaved-VPP remains
rejected: its fill chain still traverses all ``V·pp`` chunks at ``F/V``
each (no path shortening in the SPMD DAG), whereas the W-split shortens a
real chain segment.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed._compat import axis_size

from paddle_tpu.core.module import Module


def stack_layers(layers: list[Module]) -> Module:
    """Stack N structurally-identical layer pytrees on a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *layers)


def unstack_layers(stacked: Module, n: int) -> list[Module]:
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def pipeline_apply(stacked_stage_params, layer_fn: Callable, x_microbatches,
                   *, axis_name: str = "pp", layers_per_stage: int = 1,
                   remat: bool = True):
    """Run microbatches through the pp-stage ring. Call inside shard_map.

    stacked_stage_params: this stage's layers stacked [layers_per_stage, ...]
      (globally [pp * layers_per_stage, ...] sharded on the leading axis).
    layer_fn(layer_params, x) -> x: applies ONE layer.
    x_microbatches: [M, mb, ...] — every stage receives the same microbatch
      stream; non-first stages ignore it (they consume the ring instead).
    Returns [M, mb, ...]: last stage's outputs (valid on the last stage;
      other stages hold garbage — psum/broadcast outside if needed).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m_total = x_microbatches.shape[0]
    ticks = m_total + n_stages - 1

    def apply_stage(params, x):
        def body(h, lyr):
            return layer_fn(lyr, h), None
        if remat:
            run = jax.checkpoint(lambda p, v: lax.scan(body, v, p)[0])
        else:
            run = lambda p, v: lax.scan(body, v, p)[0]
        return run(params, x)

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    mb_shape = x_microbatches.shape[1:]
    out_buf = jnp.zeros((m_total,) + mb_shape, x_microbatches.dtype)
    ring0 = jnp.zeros(mb_shape, x_microbatches.dtype)

    def tick(carry, t):
        ring, out_buf = carry
        # stage 0 feeds microbatch t (clamped); others take the ring value
        mb_idx = jnp.clip(t, 0, m_total - 1)
        feed = lax.dynamic_index_in_dim(x_microbatches, mb_idx, 0, keepdims=False)
        x_in = jnp.where(stage == 0, feed, ring)
        y = apply_stage(stacked_stage_params, x_in)
        # last stage: tick t produced microbatch t-(n_stages-1)
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(stage == n_stages - 1,
                                jnp.logical_and(out_idx >= 0, out_idx < m_total))
        updated = lax.dynamic_update_index_in_dim(
            out_buf, y.astype(out_buf.dtype), jnp.clip(out_idx, 0, m_total - 1), 0)
        out_buf = jnp.where(valid, updated, out_buf)
        ring_next = lax.ppermute(y, axis_name, fwd_perm)
        return (ring_next, out_buf), None

    # initial carry must be marked pp-varying (the loop makes it so)
    ring0 = _pvary(ring0, axis_name)
    out_buf = _pvary(out_buf, axis_name)
    (_, out_buf), _ = lax.scan(tick, (ring0, out_buf), jnp.arange(ticks))
    return out_buf


def _f32_zeros_like(tree):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)


def _pvary(tree, axes):
    """Mark every leaf as varying over ``axes`` (str or tuple; idempotent).

    Needed for params differentiated inside shard_map: AD transposes an
    unvarying→varying broadcast into an implicit psum over that axis, which
    would (a) sum per-stage cotangents before our masking and (b) double-
    count against the schedule's explicit dp reductions — marking the
    primals varying keeps every cross-device reduction explicit.
    """
    if isinstance(axes, str):
        axes = (axes,)

    def mark(v):
        for ax in axes:
            try:
                v = lax.pcast(v, ax, to="varying")
            except ValueError:
                continue  # already varying over ax — idempotent no-op
            except (AttributeError, TypeError):
                try:
                    v = lax.pvary(v, (ax,))
                except Exception:
                    pass
        return v
    return jax.tree_util.tree_map(mark, tree)


def _masked_add(acc, upd, valid):
    return jax.tree_util.tree_map(
        lambda a, u: a + jnp.where(valid, u.astype(a.dtype), 0), acc, upd)


def pipeline_train_1f1b(stage_params, stage_fwd: Callable, x_mb, y_mb, *,
                        axis_name: str = "pp", batch_axes=(),
                        embed_params=None, embed_fn: Callable = None,
                        head_params=None, head_loss_fn: Callable = None,
                        zero_bubble: bool = False):
    """TRUE 1F1B pipeline training step. Call inside ``shard_map``.

    Ref: ``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
    (1F1B) — here as an SPMD shifted-buffer schedule: at global tick ``t``
    stage ``s`` runs the forward of microbatch ``t - s`` and the backward of
    microbatch ``t - (2*(pp-1) - s)``; at the last stage a microbatch's
    backward fires on the SAME tick as its forward (that is the "1B after
    1F" property), and cotangents ride a reverse ``ppermute`` ring one stage
    per tick. Residuals (stage inputs) live in a ring of ``2*pp - 1`` slots
    — constant in M — and the backward slot recomputes the stage forward
    under ``jax.vjp`` (recompute-style 1F1B, the reference's
    recompute+1F1B mode).

    Args:
      stage_params: this stage's parameter pytree (sharded P("pp", ...)
        outside; inside shard_map it is the local stage's block).
      stage_fwd(stage_params, x) -> y: applies the whole local stage.
      x_mb: [M, mb, ...] microbatched stage-0 input (token ids if
        ``embed_fn`` is given, else already-embedded activations).
      y_mb: [M, mb, ...] per-microbatch labels, consumed at the last stage.
      embed_params/embed_fn(embed_params, tokens) -> activations: optional
        replicated pre-stage (embedding) evaluated at stage 0; its grads are
        returned replicated (psum over pp).
      head_params/head_loss_fn(head_params, y, labels) -> scalar mean loss:
        the loss head evaluated at the LAST stage. When ``head_loss_fn`` is
        None, ``y_mb`` must be unused and the loss is mean(y) (testing).

    Returns:
      (loss, dstage, dembed, dhead): scalar mean loss over all microbatches
      (replicated), fp32 grads for the local stage (P("pp", ...)), and
      replicated fp32 grads for embed/head params (``()`` where unused).

    ``zero_bubble`` (ZB-H1 style, see module docstring): stage ``s`` defers
    the WEIGHT-grad halves of its last ``pp-1-s`` microbatch backwards —
    those drain-chain hops compute dx ONLY (so the cotangent ring hop costs
    ``recompute+dx``, not ``recompute+dx+dw``) and the deferred dw's run in
    ``pp-1`` tail ticks off the critical path, from a saved ``(x, g)``
    queue of ``pp`` slots. Loss is bit-identical to 1F1B; grads equal up to
    fp32 accumulation order of the deferred terms.
    """
    pp = axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    R = 2 * pp - 1                      # residual ring slots, M-independent
    T = M + 2 * (pp - 1) + ((pp - 1) if zero_bubble else 0)  # global ticks
    batch_axes = tuple(batch_axes)

    has_head = head_loss_fn is not None
    has_embed = embed_fn is not None
    if not has_head:
        head_params = ()
        head_loss_fn = lambda hp, y, lbl: jnp.mean(y)
    if not has_embed:
        embed_params = ()
        embed_fn = lambda ep, x: x
    # params must be varying over the pp and dp schedule axes before AD
    # (see _pvary). NOTE deliberately NOT over a tp axis: tp-sharded stage
    # leaves arrive varying from their in_specs, while tp-REPLICATED leaves
    # (norms) and the embed/head params stay unvarying — jax's vma-aware AD
    # then auto-psums their cross-member partial grads into the TRUE grad,
    # and activations/cotangents stay tp-invariant so no spurious psum
    # transposes are inserted (a varying-marked cotangent crossing the tp
    # psum transposes would double the grads).
    axes_all = (axis_name,) + batch_axes
    stage_params = _pvary(stage_params, axes_all)
    head_params = _pvary(head_params, axes_all)
    embed_params = _pvary(embed_params, axes_all)

    # activation shape: embed output of one microbatch
    act = jax.eval_shape(embed_fn, embed_params,
                         jax.eval_shape(lambda a: a[0], x_mb))
    act_shape, act_dtype = act.shape, act.dtype

    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
    is_last = s == pp - 1
    is_first = s == 0

    def loss_and_dy(y, labels):
        def f(yy, hp):
            return head_loss_fn(hp, yy, labels)
        (loss, (dy, dhead)) = jax.value_and_grad(f, argnums=(0, 1))(
            y, head_params)
        return loss, dy, dhead

    carry0 = dict(
        fwd_ring=jnp.zeros(act_shape, act_dtype),
        bwd_ring=jnp.zeros(act_shape, act_dtype),
        resid=jnp.zeros((R,) + act_shape, act_dtype),
        loss=jnp.zeros((), jnp.float32),
        # (p * 0) keeps each leaf's varying axes (tp-sharded leaves carry
        # tp-varying grads; fresh zeros would be unvarying and mismatch)
        dstage=jax.tree_util.tree_map(
            lambda p_: (p_ * 0).astype(jnp.float32), stage_params),
        dembed=_f32_zeros_like(embed_params),
        dhead=_f32_zeros_like(head_params),
    )
    if zero_bubble:
        # deferred weight-grad queue: (stage input, upstream cotangent)
        # pairs for the last pp-1-s microbatches, keyed m mod pp (the W
        # tick trails the B tick by pp-1-s < pp, so slots never collide)
        carry0["wq_x"] = jnp.zeros((pp,) + act_shape, act_dtype)
        carry0["wq_g"] = jnp.zeros((pp,) + act_shape, act_dtype)

    tree_add = lambda acc, upd: jax.tree_util.tree_map(
        lambda a, u: a + u.astype(a.dtype), acc, upd)

    def tick(c, t):
        # ---------------- forward slot: microbatch t - s ----------------
        m_f = t - s
        fwd_valid = jnp.logical_and(m_f >= 0, m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        tokens = lax.dynamic_index_in_dim(x_mb, m_f_c, 0, keepdims=False)
        # per-device branch: only stage 0 pays for the embedding gather
        # (inside shard_map the predicate is a local scalar, so lax.cond is
        # real control flow, not a both-sides select)
        x_in = lax.cond(is_first,
                        lambda: embed_fn(embed_params, tokens)
                        .astype(act_dtype),
                        lambda: c["fwd_ring"])
        y = stage_fwd(stage_params, x_in).astype(act_dtype)

        resid_new = lax.dynamic_update_index_in_dim(
            c["resid"], x_in, jnp.mod(m_f_c, R), 0)
        resid = jnp.where(fwd_valid, resid_new, c["resid"])

        # last stage only: loss + cotangent seed for this same microbatch
        # (head fwd+bwd is often the biggest op in the step — gate it)
        labels = lax.dynamic_index_in_dim(y_mb, m_f_c, 0, keepdims=False)
        take_loss = jnp.logical_and(is_last, fwd_valid)

        def head_branch(y, labels):
            loss_m, dy, dhead_m = loss_and_dy(y, labels)
            return (loss_m.astype(jnp.float32), dy.astype(act_dtype),
                    dhead_m)

        def head_skip(y, labels):
            return _pvary((jnp.zeros((), jnp.float32), jnp.zeros_like(y),
                           jax.tree_util.tree_map(jnp.zeros_like,
                                                  head_params)), axes_all)

        loss_m, dy, dhead_m = lax.cond(take_loss, head_branch, head_skip,
                                       y, labels)
        loss = c["loss"] + loss_m
        dhead = tree_add(c["dhead"], dhead_m)

        # ---------------- backward slot: microbatch t - (2(pp-1) - s) ----
        m_b = t - (2 * (pp - 1) - s)
        bwd_valid = jnp.logical_and(m_b >= 0, m_b < M)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        x_saved = lax.dynamic_index_in_dim(resid, jnp.mod(m_b_c, R), 0,
                                           keepdims=False)
        g = jnp.where(is_last, dy, c["bwd_ring"])
        if not zero_bubble:
            _, vjp_fn = jax.vjp(stage_fwd, stage_params, x_saved)
            dp, dx = vjp_fn(g.astype(act.dtype))
            dstage = _masked_add(c["dstage"], dp, bwd_valid)
        else:
            # ZB-H1: the last pp-1-s microbatches' backwards are on the
            # drain critical path — run dx ONLY there (dw deferred)
            d_s = (pp - 1) - s
            deferred = jnp.logical_and(bwd_valid, m_b >= M - d_s)
            p_zeros = jax.tree_util.tree_map(lambda p_: p_ * 0,
                                             stage_params)

            def bwd_full(x_in, gg):
                _, vjp_fn = jax.vjp(stage_fwd, stage_params, x_in)
                return vjp_fn(gg.astype(act.dtype))

            def bwd_dx_only(x_in, gg):
                _, vjp_x = jax.vjp(
                    lambda xx: stage_fwd(stage_params, xx), x_in)
                (dx_,) = vjp_x(gg.astype(act.dtype))
                return p_zeros, dx_

            dp, dx = lax.cond(deferred, bwd_dx_only, bwd_full, x_saved, g)
            dstage = _masked_add(c["dstage"], dp,
                                 jnp.logical_and(bwd_valid, ~deferred))
            wq_slot = jnp.mod(m_b_c, pp)
            wq_x = lax.dynamic_update_index_in_dim(
                c["wq_x"], x_saved, wq_slot, 0)
            wq_g = lax.dynamic_update_index_in_dim(
                c["wq_g"], g.astype(act_dtype), wq_slot, 0)
            keep = lambda new, old: jnp.where(deferred, new, old)
            wq_x, wq_g = keep(wq_x, c["wq_x"]), keep(wq_g, c["wq_g"])

            # ---- deferred W slot: tail ticks, off the critical path ----
            m_w = m_b - d_s
            w_valid = jnp.logical_and(m_w >= jnp.maximum(M - d_s, 0),
                                      m_w < M)
            m_w_c = jnp.clip(m_w, 0, M - 1)
            x_w = lax.dynamic_index_in_dim(wq_x, jnp.mod(m_w_c, pp), 0,
                                           keepdims=False)
            g_w = lax.dynamic_index_in_dim(wq_g, jnp.mod(m_w_c, pp), 0,
                                           keepdims=False)

            def w_branch(x_in, gg):
                _, vjp_p = jax.vjp(lambda p_: stage_fwd(p_, x_in),
                                   stage_params)
                (dpw,) = vjp_p(gg.astype(act.dtype))
                return dpw

            dpw = lax.cond(w_valid, w_branch, lambda x_in, gg: p_zeros,
                           x_w, g_w)
            dstage = _masked_add(dstage, dpw, w_valid)

        # stage 0's backward also flows into the embedding — gated likewise
        tokens_b = lax.dynamic_index_in_dim(x_mb, m_b_c, 0, keepdims=False)

        def embed_grad_branch(dx):
            _, evjp = jax.vjp(
                lambda ep: embed_fn(ep, tokens_b).astype(act_dtype),
                embed_params)
            (dembed_m,) = evjp(dx)
            return dembed_m

        def embed_grad_skip(dx):
            return _pvary(jax.tree_util.tree_map(jnp.zeros_like,
                                                 embed_params), axes_all)

        dembed_m = lax.cond(jnp.logical_and(is_first, bwd_valid),
                            embed_grad_branch, embed_grad_skip, dx)
        dembed = tree_add(c["dembed"], dembed_m)

        # ---------------- ring handoffs ----------------
        fwd_ring = lax.ppermute(y, axis_name, fwd_perm)
        bwd_ring = lax.ppermute(dx.astype(act_dtype), axis_name, bwd_perm)
        out = dict(fwd_ring=fwd_ring, bwd_ring=bwd_ring, resid=resid,
                   loss=loss, dstage=dstage, dembed=dembed, dhead=dhead)
        if zero_bubble:
            out["wq_x"], out["wq_g"] = wq_x, wq_g
        return out, None

    # the loop makes every carry leaf pp(+dp)-varying; mark the init so
    carry0 = _pvary(carry0, axes_all)
    c, _ = lax.scan(tick, carry0, jnp.arange(T))

    inv_m = 1.0 / M
    loss = lax.psum(c["loss"], axis_name) * inv_m
    scale = lambda tr: jax.tree_util.tree_map(lambda g: g * inv_m, tr)
    dstage = scale(c["dstage"])
    dembed = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), scale(c["dembed"]))
    dhead = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axis_name), scale(c["dhead"]))
    if batch_axes:
        # data parallelism over the microbatch's batch dim: every grad and
        # the loss are per-dp-shard means — average across the dp group
        nb = 1
        for a in batch_axes:
            nb *= axis_size(a)
        pmean = lambda v: lax.psum(v, batch_axes) / nb
        loss = pmean(loss)
        dstage = jax.tree_util.tree_map(pmean, dstage)
        dembed = jax.tree_util.tree_map(pmean, dembed)
        dhead = jax.tree_util.tree_map(pmean, dhead)
    return loss, dstage, dembed, dhead


def pipeline_train_step(pipe: "PipelineLayer", mesh, x, y, *,
                        layer_call: Callable = None,
                        head_loss_fn: Callable = None, head_params=None,
                        embed_fn: Callable = None, embed_params=None,
                        batch_axes=(), stage_specs=None,
                        schedule: str = "1f1b"):
    """1F1B loss+grads for a PipelineLayer under ``mesh`` (pp axis).
    ``schedule``: "1f1b" (default) or "zb1" (zero-bubble W-split drain —
    see ``pipeline_train_1f1b(zero_bubble=True)``).

    Splits the batch into ``pipe.num_microbatches``, runs the 1F1B schedule
    in a ``shard_map`` over the pp axis, and returns
    ``(loss, stacked_grads, dembed, dhead)`` — grads are fp32, stacked
    grads sharded P("pp", ...) exactly like the params, embed/head grads
    replicated (``None`` when the corresponding part was not given).

    ``batch_axes`` (e.g. ``("dp",)``) composes pp with data parallelism:
    each microbatch's batch dim is sharded across the dp group, every dp
    member runs the same pipeline on its shard, and loss/grads are
    dp-averaged inside the shard_map.
    """
    from paddle_tpu.distributed._compat import shard_map

    if schedule not in ("1f1b", "zb1"):
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(expected '1f1b' or 'zb1')")
    layer_call = layer_call or (lambda lyr, h: lyr(h))
    mb_n = pipe.num_microbatches
    b = x.shape[0]
    assert b % mb_n == 0, \
        f"num_microbatches ({mb_n}) must divide the batch size ({b})"
    xm = x.reshape((mb_n, b // mb_n) + x.shape[1:])
    ym = y.reshape((mb_n, b // mb_n) + y.shape[1:])

    has_embed = embed_fn is not None
    has_head = head_loss_fn is not None
    embed_params = embed_params if has_embed else ()
    head_params = head_params if has_head else ()

    batch_axes = tuple(batch_axes)
    mb_axis = batch_axes if batch_axes else None
    # stage_specs override: tp-aware per-leaf specs (e.g. llama_tp_stage_specs)
    pspec = stage_specs if stage_specs is not None else pipe.stage_specs()
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
    xspec = P(None, mb_axis, *(None,) * (xm.ndim - 2))
    yspec = P(None, mb_axis, *(None,) * (ym.ndim - 2))

    def stage_fwd(stage_params, h):
        def body(hh, lyr):
            return layer_call(lyr, hh), None
        run = lambda p, v: lax.scan(body, v, p)[0]
        if pipe.remat:
            run = jax.checkpoint(run)
        return run(stage_params, h)

    @functools.partial(
        shard_map, mesh=mesh.mesh,
        in_specs=(pspec, xspec, yspec, rep(embed_params), rep(head_params)),
        out_specs=(P(), pspec, rep(embed_params), rep(head_params)))
    def run(stage_params, xm, ym, embed_params, head_params):
        return pipeline_train_1f1b(
            stage_params, stage_fwd, xm, ym, batch_axes=batch_axes,
            embed_params=embed_params, embed_fn=embed_fn,
            head_params=head_params, head_loss_fn=head_loss_fn,
            zero_bubble=(schedule == "zb1"))

    loss, dstage, dembed, dhead = run(pipe.stacked, xm, ym,
                                      embed_params, head_params)
    return (loss, dstage,
            dembed if has_embed else None, dhead if has_head else None)


class PipelineLayer(Module):
    """Reference-named wrapper: partitions identical blocks over pp stages.

    Single-program: under a mesh with pp>1 the stacked weights shard
    P("pp", ...); without a mesh it runs the plain sequential loop.
    """

    def __init__(self, layers: list[Module], num_stages: int,
                 num_microbatches: int = 1, remat: bool = True):
        super().__init__()
        assert len(layers) % num_stages == 0, \
            f"num_stages ({num_stages}) must divide len(layers) ({len(layers)})"
        self.stacked = stack_layers(layers)
        self.template = layers[0]
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.layers_per_stage = len(layers) // num_stages
        self.n_layers = len(layers)
        self.remat = remat
        # leading axis is the stage axis
        flat, _ = jax.tree_util.tree_flatten(self.stacked)

    @classmethod
    def from_stacked(cls, stacked, *, n_layers: int, num_stages: int,
                     num_microbatches: int = 1, remat: bool = True):
        """Build from an ALREADY-STACKED [L, ...] layer pytree (e.g. the
        canonical param tree of a jitted training loop) with the same
        invariants as __init__."""
        assert n_layers % num_stages == 0, \
            f"num_stages ({num_stages}) must divide n_layers ({n_layers})"
        self = cls.__new__(cls)
        Module.__init__(self)
        self.stacked = stacked
        self.template = None
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.layers_per_stage = n_layers // num_stages
        self.n_layers = n_layers
        self.remat = remat
        return self

    def stage_specs(self):
        """PartitionSpecs: leading (layer) axis on pp."""
        def spec(leaf):
            return P(*(("pp",) + (None,) * (leaf.ndim - 1)))
        return jax.tree_util.tree_map(spec, self.stacked)

    def __call__(self, x, layer_call: Callable = None, mesh=None):
        layer_call = layer_call or (lambda lyr, h: lyr(h))
        if mesh is None or mesh.pp == 1:
            def body(h, lyr_params):
                return layer_call(lyr_params, h), None
            out, _ = lax.scan(body, x, self.stacked)
            return out
        from paddle_tpu.distributed._compat import shard_map
        mb = self.num_microbatches
        b = x.shape[0]
        assert b % mb == 0, "batch must divide microbatches"
        xm = x.reshape((mb, b // mb) + x.shape[1:])

        pspec = self.stage_specs()
        data_spec = P(*((None,) * xm.ndim))

        @functools.partial(
            shard_map, mesh=mesh.mesh,
            in_specs=(pspec, data_spec), out_specs=data_spec)
        def run(stage_params, xm):
            out = pipeline_apply(stage_params, layer_call, xm,
                                 axis_name="pp",
                                 layers_per_stage=self.layers_per_stage,
                                 remat=self.remat)
            # broadcast last stage's result to all pp members so downstream
            # (loss) is replicated over pp: zero elsewhere + psum
            n = axis_size("pp")
            is_last = (lax.axis_index("pp") == n - 1).astype(out.dtype)
            return lax.psum(out * is_last, "pp")
        return run(self.stacked, xm).reshape(x.shape)
