"""One-call sequence-parallel attention dispatch shared by the models.

Both LLaMA-family attention and T5 route their sp path through here so the
ring/Ulysses selection, the optional-(mask, bias) argument assembly, and
future dispatch-contract changes live in ONE place (models keep only their
own mask normalisation).
"""
from __future__ import annotations

import jax.numpy as jnp


def sp_attention(mesh, mode: str, q, k, v, *, causal=True, scale=None,
                 window=None, head_spec=None, attn_mask=None,
                 attn_bias=None):
    """Run [B, S, H, D] attention with S sharded over sp via ``mode``
    ("ring" | "ulysses"). ``attn_mask``: [B, S, S] bool over global
    positions; ``attn_bias``: [B|1, H|1, S, S] float additive scores."""
    kwargs = dict(causal=causal, scale=scale, window=window,
                  head_spec=head_spec, masked=attn_mask is not None,
                  bias_shape=None if attn_bias is None else attn_bias.shape)
    if mode == "ring":
        from paddle_tpu.distributed.ring_attention import (
            make_ring_attention as make)
    elif mode == "ulysses":
        from paddle_tpu.distributed.ulysses import (
            make_ulysses_attention as make)
    else:
        raise ValueError(f"unknown sequence_parallel mode {mode!r}")
    attend = make(mesh, **kwargs)
    args = (q, k, v)
    if attn_mask is not None:
        args += (attn_mask,)
    if attn_bias is not None:
        args += (attn_bias.astype(jnp.float32),)
    return attend(*args)
