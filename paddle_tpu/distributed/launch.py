"""Multi-host launcher (ref: ``python/paddle/distributed/launch`` —
``python -m paddle.distributed.launch --nnodes=...``).

On TPU pods there is no per-GPU process spawning: ONE process per host, all
chips of the host driven by that process, cross-host wiring via
``jax.distributed.initialize`` (coordinator = host 0). This module is the
equivalent entrypoint:

    python -m paddle_tpu.distributed.launch train.py --args...

Env contract (set by the TPU runtime or the user):
  COORDINATOR_ADDRESS host:port of process 0
  NUM_PROCESSES / PROCESS_ID  (optional; auto-detected on Cloud TPU)
"""
from __future__ import annotations

import os
import runpy
import sys


def initialize_cluster():
    """Bring up the JAX distributed runtime across hosts (idempotent)."""
    import jax
    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = os.environ.get("NUM_PROCESSES")
    pid = os.environ.get("PROCESS_ID")
    if coord is None and nproc is None:
        # Cloud TPU pods auto-detect via metadata; single host is a no-op
        try:
            jax.distributed.initialize()
        except Exception:
            pass
        return
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nproc) if nproc else None,
        process_id=int(pid) if pid else None)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 1
    initialize_cluster()
    script, *rest = argv
    sys.argv = [script] + rest
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
