"""Hybrid device mesh (TPU-native answer to Fleet's HybridCommunicateGroup,
ref ``python/paddle/distributed/fleet/base/topology.py``).

The reference wires NCCL communicator groups per parallelism dim (dp/mp/pp/
sharding). Here ONE ``jax.sharding.Mesh`` with named axes carries the whole
topology; every parallel form is a PartitionSpec over these axes and XLA
emits the ICI collectives. Axis order is outermost→innermost with the
fastest-varying axes (tp, sp) innermost so their collectives ride the
shortest ICI hops on a real slice.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep", "cp")


class HybridMesh:
    """dp × fsdp × ep × pp × tp × sp × cp over the device grid.

    ``ep`` is a first-class expert-parallel axis: MoE expert weights carry
    ``P("ep", ...)`` and the MoE dispatcher's ``lax.all_to_all`` runs over
    it (ref: the MoE NCCL group's ``c_alltoall``). Tokens/batch are sharded
    over (dp, fsdp, ep) — experts ride chips that also carry data, the
    reference's "ep on dp" layout, but with an explicit named axis.

    ``cp`` is the serving-side context-parallel axis (ISSUE 18): the paged
    KV pool shards its physical blocks over cp while weights stay
    replicated; prefill partials merge via ring rotation or Ulysses
    all_to_all and decode merges via psum. Innermost so the per-tick
    O(heads·dim) merge rides the shortest ICI hops.
    """

    def __init__(self, dp: int = 1, fsdp: int = 1, pp: int = 1, tp: int = 1,
                 sp: int = 1, ep: int = 1, cp: int = 1,
                 devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        n = dp * fsdp * ep * pp * tp * sp * cp
        if n != len(devices):
            raise ValueError(
                f"mesh {dp}x{fsdp}x{ep}x{pp}x{tp}x{sp}x{cp}={n} != "
                f"{len(devices)} devices")
        grid = np.array(devices).reshape(dp, fsdp, ep, pp, tp, sp, cp)
        self.mesh = Mesh(grid, ("dp", "fsdp", "ep", "pp", "tp", "sp", "cp"))
        self.dp, self.fsdp, self.pp, self.tp, self.sp = dp, fsdp, pp, tp, sp
        self.ep = ep
        self.cp = cp

    # -- reference-style queries (HybridCommunicateGroup API) ---------------
    def get_data_parallel_world_size(self):
        return self.dp * self.fsdp

    def get_model_parallel_world_size(self):
        return self.tp

    def get_pipe_parallel_world_size(self):
        return self.pp

    def get_sharding_parallel_world_size(self):
        return self.fsdp

    # -- sharding helpers ----------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def batch_sharding(self) -> NamedSharding:
        """Global-batch sharding over all data axes."""
        return NamedSharding(self.mesh, P(("dp", "fsdp", "ep"),))

    def batch_spec(self) -> P:
        return P(("dp", "fsdp", "ep"),)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def __enter__(self):
        self.mesh.__enter__()
        _CURRENT.append(self)
        return self

    def __exit__(self, *exc):
        _CURRENT.pop()
        return self.mesh.__exit__(*exc)

    @property
    def axis_names(self):
        return self.mesh.axis_names

    def size(self, axis: str) -> int:
        return self.mesh.shape[axis] if axis in self.mesh.shape else 1


_CURRENT: list[HybridMesh] = []


def current_mesh() -> Optional[HybridMesh]:
    return _CURRENT[-1] if _CURRENT else None


def single_device_mesh() -> HybridMesh:
    return HybridMesh(dp=1, fsdp=1, pp=1, tp=1, sp=1, devices=jax.devices()[:1])


def make_mesh(shape: dict, devices=None) -> HybridMesh:
    """shape e.g. {"dp":2, "tp":4} — unspecified axes default 1."""
    kw = {a: int(shape.get(a, 1)) for a in AXES}
    return HybridMesh(**kw, devices=devices)
