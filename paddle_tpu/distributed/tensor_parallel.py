"""Tensor (model) parallel layers (ref: ``python/paddle/distributed/fleet/
layers/mpu/mp_layers.py`` — ColumnParallelLinear, RowParallelLinear,
VocabParallelEmbedding; ``mp_ops.py`` — parallel cross-entropy).

TPU-native: the reference shards weights manually per-rank and calls NCCL
all_reduce/identity in forward/backward. Here each layer holds the FULL
logical weight with a PartitionSpec over the ``tp`` mesh axis; under pjit,
GSPMD partitions the matmul and inserts the same collectives the reference
hand-codes (column: no comm fwd / all-reduce bwd; row: all-reduce fwd).
The layer classes therefore stay pure and single-program — the mesh does
the distribution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


class ColumnParallelLinear(Module):
    """weight [in, out] sharded on out (tp). gather_output mirrors the ref flag."""

    def __init__(self, in_features, out_features, bias_attr=True,
                 gather_output=False, weight_init=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = weight_init or I.XavierNormal()
        self.weight = init((in_features, out_features), dtype)
        self.bias = I.Constant(0.0)((out_features,), dtype) if bias_attr else None
        self.set_pspec("weight", P(None, "tp"))
        if bias_attr:
            self.set_pspec("bias", P("tp"))
        self.gather_output = gather_output

    def __call__(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            from paddle_tpu.distributed.sharded import maybe_shard
            y = maybe_shard(y)
        return y


class RowParallelLinear(Module):
    """weight [in, out] sharded on in (tp); input arrives tp-sharded from a
    preceding column-parallel layer, XLA all-reduces the partial sums."""

    def __init__(self, in_features, out_features, bias_attr=True,
                 input_is_parallel=True, weight_init=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = weight_init or I.XavierNormal()
        self.weight = init((in_features, out_features), dtype)
        self.bias = I.Constant(0.0)((out_features,), dtype) if bias_attr else None
        self.set_pspec("weight", P("tp", None))
        self.input_is_parallel = input_is_parallel

    def __call__(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Module):
    """Embedding table sharded over vocab (tp). GSPMD turns the gather into
    per-shard gathers + all-reduce, matching the reference's masked lookup."""

    def __init__(self, num_embeddings, embedding_dim, weight_init=None, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = weight_init or I.Normal(0.0, 0.02)
        self.weight = init((num_embeddings, embedding_dim), dtype)
        self.set_pspec("weight", P("tp", None))
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim

    def __call__(self, x):
        return jnp.take(self.weight, x, axis=0)


def parallel_cross_entropy(logits, labels, *, label_smoothing=0.0):
    """Ref mp_ops.c_softmax_with_cross_entropy: CE over tp-sharded logits
    without materialising the full softmax on one chip. Under GSPMD the
    standard formulation compiles to the same sharded log-sum-exp, so this
    simply keeps logits sharded and computes in fp32."""
    logits32 = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits32, axis=-1, keepdims=True))
    shifted = logits32 - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + jnp.squeeze(m, -1)
    true_logit = jnp.take_along_axis(logits32, labels[..., None], axis=-1)[..., 0]
    loss = lse - true_logit
    if label_smoothing > 0.0:
        n = logits.shape[-1]
        mean_logit = jnp.mean(logits32, axis=-1)
        loss = (1 - label_smoothing) * loss + label_smoothing * (lse - mean_logit)
    return loss
