"""Mixture-of-Experts with expert parallelism (ref: ``python/paddle/
incubate/distributed/models/moe/`` — MoELayer, gate, dispatcher using
``c_alltoall`` over the expert-parallel NCCL group).

TPU-native design, two layers:

* **Sort-based routing** (``top_k_route``): tokens are argsort-grouped by
  expert id and capacity is enforced by position-within-group — the
  megablox-style O(T·k) formulation. No ``[T, E, C]`` one-hot
  dispatch/combine tensor is ever materialised (the GShard dense einsum
  form is O(T·E·C) memory and unusable at E=64, T=16k); dispatch is a
  scatter-add into ``[E·C, H]`` slots, combine a gather +
  scatter-add-by-token. Slot priority is (choice j, token t) — exactly the
  classic GShard queue order, so routing decisions (who is kept, who is
  dropped) are identical to the dense reference formulation
  (``top_k_gate`` below, kept as the executable spec).

* **Explicit expert-parallel dispatch** (``MoELayer`` under a mesh with an
  ``ep`` axis): a ``shard_map`` over ``ep`` where each shard routes its
  local tokens with LOCAL capacity (the reference's per-rank capacity
  semantics), builds an ``[E, C_local, H]`` send buffer, and a
  ``lax.all_to_all`` exchanges expert slices — the literal ``c_alltoall``
  the reference hand-codes, here riding ICI. Token results are invariant
  to slot order, so with no drops this equals the single-device layer
  exactly.

**Expert compute is a grouped GEMM** (``ops/pallas/grouped_matmul``): the
sorted route already lays tokens out contiguously per expert, so the MLP
runs directly over the ragged row partition — per-expert row offsets, no
``[E, C]`` slot padding in the FLOPs (MegaBlocks-style dropless; with
``capacity_factor=None`` nothing is ever dropped). On the EP path the
``[E, C_local, H]`` all_to_all wire format is kept, but each rank compacts
the received slots (occupancy counts ride a second tiny all_to_all) and
runs its local experts over ``sum(counts)`` rows instead of
``E_local·ep·C_local`` padded slots. ``PT_GROUPED_GEMM=0`` restores the
dense capacity-padded dispatch/compute path bit-for-bit (read at trace
time; re-trace after flipping).

The gate also reports a **drop rate** (fraction of routing choices that
overflowed capacity) so saturation is observable (the reference exposes
drop behaviour through its gate counters).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops.pallas.grouped_matmul import (
    grouped_gemm_enabled,
    grouped_matmul,
)


def _gate_probs(logits, k, renormalize=True):
    """softmax -> top-k -> (optionally) renormalised gates. Returns
    ([T,k] vals, idx, probs). ``renormalize=False`` keeps the raw softmax
    mass at the top-k (Qwen2-MoE's norm_topk_prob=False convention)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
    if renormalize:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9)
    return gate_vals, gate_idx, probs


def _aux_parts(probs, gate_idx):
    """Switch load-balance loss ingredients: (mean prob/expert, frac top-1
    tokens/expert). aux = E * sum(me * ce); kept split so an ep shard_map
    can pmean the parts for the exact global loss."""
    e = probs.shape[-1]
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                  axis=0)
    return me, ce


def top_k_gate(logits, k: int, capacity: int, *, jitter_rng=None):
    """DENSE top-k gating with capacity — the executable GShard spec
    (ref gate/naive_gate.py). O(T·E·C) memory; kept as the reference
    semantics that ``top_k_route`` is tested against. Production paths use
    the sort-based route below.

    logits: [T, E]. Returns (dispatch [T, E, C] bool, combine [T, E, C]
    float, aux_loss scalar).
    """
    t, e = logits.shape
    gate_vals, gate_idx, probs = _gate_probs(logits, k)

    # GShard position computation: queue slot per token per choice
    dispatch = jnp.zeros((t, e, capacity), bool)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    offset = jnp.zeros((e,), jnp.int32)  # slots consumed by earlier choices
    for j in range(k):
        choice = jax.nn.one_hot(gate_idx[:, j], e, dtype=jnp.int32)  # [T,E]
        pos_in_e = jnp.cumsum(choice, axis=0) - 1 + offset[None, :]
        within = (pos_in_e < capacity) & (choice > 0)
        pos = jnp.sum(jnp.where(within, pos_in_e, 0), axis=1)  # [T]
        oh_pos = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        d_j = within[..., None] & (oh_pos[:, None, :] > 0.5)
        dispatch = dispatch | d_j
        combine = combine + d_j.astype(jnp.float32) * gate_vals[:, j][:, None, None]
        offset = offset + jnp.sum(choice, axis=0)

    me, ce = _aux_parts(probs, gate_idx)
    aux = e * jnp.sum(me * ce)
    return dispatch, combine, aux


def top_k_route(logits, k: int, capacity: int, renormalize: bool = True):
    """Sort-based top-k routing — O(T·k log) compute, O(T·k) memory.

    logits: [T, E]. Returns ``(route, aux, drop_rate)`` where ``route`` is a
    dict of [N = T·k] arrays in expert-sorted order:

      tok   int32  source token index
      expert int32 destination expert
      pos   int32  slot within the expert's queue (GShard (j, t) priority)
      keep  bool   pos < capacity (False = dropped)
      gate  f32    renormalised combine weight

    plus ``counts`` — the [E] per-expert assignment totals (pre-drop):
    exactly the segment sizes of the sorted layout, i.e. the
    ``group_sizes`` argument of the grouped GEMM.

    Identical keep/drop decisions to ``top_k_gate`` by construction: the
    flat assignment list is laid out choice-major (all j=0 entries before
    j=1) and the stable argsort preserves that order within each expert.
    """
    t, e = logits.shape
    n = t * k
    gate_vals, gate_idx, probs = _gate_probs(logits, k, renormalize)

    flat_e = gate_idx.T.reshape(n)                 # choice-major [k*T]
    flat_gate = gate_vals.T.reshape(n)
    flat_tok = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)

    order = jnp.argsort(flat_e, stable=True)
    se = flat_e[order]
    counts = jnp.zeros((e,), jnp.int32).at[flat_e].add(1)
    starts = jnp.cumsum(counts) - counts           # exclusive prefix
    pos = jnp.arange(n, dtype=jnp.int32) - starts[se]
    keep = pos < capacity

    me, ce = _aux_parts(probs, gate_idx)
    # me/ce ride along so a distributed caller can pmean them for the
    # exact global aux loss without recomputing the gate
    route = dict(tok=flat_tok[order], expert=se, pos=pos, keep=keep,
                 gate=flat_gate[order], me=me, ce=ce, counts=counts)
    aux = e * jnp.sum(me * ce)
    drop_rate = 1.0 - jnp.mean(keep.astype(jnp.float32))
    return route, aux, drop_rate


def sparse_dispatch(xt, route, num_experts: int, capacity: int):
    """Scatter tokens into expert slots: [T, H] -> [E, C, H]. Dropped
    assignments scatter out of bounds and are discarded (mode='drop')."""
    t, h = xt.shape
    dest = jnp.where(route["keep"],
                     route["expert"] * capacity + route["pos"],
                     num_experts * capacity)        # OOB sentinel
    x_e = jnp.zeros((num_experts * capacity, h), xt.dtype)
    x_e = x_e.at[dest].add(xt[route["tok"]], mode="drop")
    return x_e.reshape(num_experts, capacity, h), dest


def sparse_combine(y_e, route, dest, num_tokens: int):
    """Gather expert outputs back to tokens with gate weights:
    [E, C, H] -> [T, H]. Dropped assignments contribute zero."""
    e, c, h = y_e.shape
    y_flat = y_e.reshape(e * c, h)
    gathered = y_flat.at[dest].get(mode="fill", fill_value=0)
    gathered = gathered * route["gate"][:, None].astype(y_flat.dtype)
    yt = jnp.zeros((num_tokens, h), y_e.dtype)
    return yt.at[route["tok"]].add(gathered, mode="drop")


class ExpertMLP(Module):
    """E SwiGLU expert MLPs with a leading expert axis, ep-sharded."""

    def __init__(self, num_experts, hidden, intermediate, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = I.Normal(0.0, 0.02)
        self.gate_up = init((num_experts, hidden, 2 * intermediate), dtype)
        self.down = init((num_experts, intermediate, hidden), dtype)
        # experts over the dedicated ep mesh axis (expert parallelism)
        self.set_pspec("gate_up", P("ep", None, None))
        self.set_pspec("down", P("ep", None, None))

    def __call__(self, x_e):
        """x_e: [E, C, H] — per-expert token slots."""
        return expert_mlp_apply(x_e, *_expert_arrays(self, x_e.dtype))


def _expert_arrays(experts, dtype):
    """Weight-only-quantized expert stacks (``serving.quant.
    QuantizedExpertStack``) dequantize on the fly inside the jitted
    forward; plain arrays pass through untouched. Duck-typed on
    ``dequantize`` so this module never imports the serving layer."""
    gu, dn = experts.gate_up, experts.down
    if hasattr(gu, "dequantize"):
        gu = gu.dequantize(dtype)
    if hasattr(dn, "dequantize"):
        dn = dn.dequantize(dtype)
    return gu, dn


def expert_mlp_apply(x_e, gate_up, down):
    """Row-independent SwiGLU over expert slots (also used with LOCAL
    weight shards inside the ep shard_map)."""
    gu = jnp.einsum("ech,ehm->ecm", x_e, gate_up)
    gate, up = jnp.split(gu, 2, axis=-1)
    act = jax.nn.silu(gate) * up
    return jnp.einsum("ecm,emh->ech", act, down)


def grouped_mlp_apply(x_sorted, gate_up, down, group_sizes):
    """SwiGLU over the ragged sorted layout: ``x_sorted`` [N, H] rows
    contiguous per expert, ``group_sizes`` [E] segment sizes. Two grouped
    GEMMs — FLOPs track N, not E·capacity."""
    gu = grouped_matmul(x_sorted, gate_up, group_sizes)
    gate, up = jnp.split(gu, 2, axis=-1)
    act = jax.nn.silu(gate) * up
    return grouped_matmul(act, down, group_sizes)


def grouped_forward(xt, route, gate_up, down, num_tokens: int):
    """Sorted-layout expert forward + combine: gather tokens into
    expert-sorted rows (``route`` is already sorted), run the grouped
    SwiGLU over segment offsets, scatter-add back by source token with
    gate x keep weights. Dropped assignments ride through the GEMM with
    weight zero — identical results to the capacity path, without the
    ``[E, C, H]`` dispatch buffer."""
    x_sorted = xt[route["tok"]]
    y_sorted = grouped_mlp_apply(x_sorted, gate_up, down, route["counts"])
    wgt = (route["gate"] * route["keep"]).astype(y_sorted.dtype)
    yt = jnp.zeros((num_tokens, xt.shape[1]), y_sorted.dtype)
    return yt.at[route["tok"]].add(y_sorted * wgt[:, None], mode="drop")


class MoELayer(Module):
    """Drop-in MLP replacement (ref MoELayer). Sort-based routing
    everywhere; under a mesh with ep > 1 the forward is a shard_map whose
    ``lax.all_to_all`` over the ep axis is the reference's ``c_alltoall``.
    The aux loss is returned for the trainer to add; the last drop rate is
    exposed via ``return_metrics=True``."""

    def __init__(self, hidden, intermediate, num_experts, k=2,
                 capacity_factor=1.25, dtype=None, norm_topk_prob=True):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.gate_w = I.Normal(0.0, 0.02)((hidden, num_experts), jnp.float32)
        self.experts = ExpertMLP(num_experts, hidden, intermediate, dtype)
        self.num_experts, self.k, self.capacity_factor = num_experts, k, capacity_factor
        self.norm_topk_prob = norm_topk_prob

    def _capacity(self, tokens: int) -> int:
        if self.capacity_factor is None:
            # EXACT (dropless) mode: every expert can take every token —
            # HF-style eval/inference semantics; memory O(T) per expert
            return max(tokens, 4)
        cap = int(self.capacity_factor * self.k * tokens / self.num_experts
                  + 0.999)
        return max(cap, 4)

    def __call__(self, x, return_aux=True, return_metrics=False):
        from paddle_tpu.distributed.mesh import current_mesh
        mesh = current_mesh()
        ep = mesh.size("ep") if mesh is not None else 1
        if ep > 1:
            y, aux, drop = self._forward_ep(x, mesh, ep)
        else:
            y, aux, drop = self._forward_local(x)
        if return_metrics:
            return y, aux, {"drop_rate": drop}
        return (y, aux) if return_aux else y

    # -- single-shard (or pure-GSPMD) path ----------------------------------
    def _forward_local(self, x):
        b, s, h = x.shape
        t = b * s
        e = self.num_experts
        cap = self._capacity(t)
        xt = x.reshape(t, h)
        logits = xt.astype(jnp.float32) @ self.gate_w
        route, aux, drop = top_k_route(logits, self.k, cap,
                                       self.norm_topk_prob)
        gate_up, down = _expert_arrays(self.experts, x.dtype)
        if grouped_gemm_enabled():
            yt = grouped_forward(xt, route, gate_up, down, t)
        else:
            x_e, dest = sparse_dispatch(xt, route, e, cap)
            y_e = expert_mlp_apply(x_e, gate_up, down)
            yt = sparse_combine(y_e, route, dest, t)
        return yt.reshape(b, s, h), aux, drop

    # -- expert-parallel path: shard_map + all_to_all over the ep axis ------
    def _forward_ep(self, x, mesh, ep):
        from paddle_tpu.distributed._compat import shard_map

        e = self.num_experts
        if e % ep != 0:
            raise ValueError(f"num_experts={e} not divisible by ep={ep}")
        b, s, h = x.shape
        # tokens are sharded over ALL data axes, not just ep — over the
        # FLATTENED token dim, so any (b, s) with b*s divisible by the
        # shard count works (serving's chunked prefill runs b=1). When b
        # itself divides, each shard gets the same whole sequences as the
        # old batch-dim sharding (row-major flatten), so results are
        # unchanged.
        data_shards = mesh.dp * mesh.fsdp * ep
        t = b * s
        if t % data_shards != 0:
            raise ValueError(
                f"tokens {t} (= {b}x{s}) not divisible by "
                f"dp*fsdp*ep={data_shards} "
                "(tokens are sharded over the data axes)")
        # LOCAL capacity — the reference's per-rank semantics: each rank may
        # fill at most C_local slots of each (global) expert
        cap = self._capacity(t // data_shards)
        k = self.k
        renorm = self.norm_topk_prob

        batch_axes = ("dp", "fsdp", "ep")
        xspec = P(batch_axes, None)

        def local(xt, gate_w, gate_up, down):
            tl, hl = xt.shape
            logits = xt.astype(jnp.float32) @ gate_w
            route, _, _ = top_k_route(logits, k, cap, renorm)
            # exact global aux loss: pmean the gate's ingredients
            me = jax.lax.pmean(route["me"], batch_axes)
            ce = jax.lax.pmean(route["ce"], batch_axes)
            aux = e * jnp.sum(me * ce)
            drop = 1.0 - jax.lax.pmean(
                jnp.mean(route["keep"].astype(jnp.float32)), batch_axes)

            # send buffer: my tokens in every expert's queue -> [E, C, H]
            x_send, dest = sparse_dispatch(xt, route, e, cap)
            # [E, C, H] -> [ep, E_loc, C, H]; a2a: recv[s] = shard s's slots
            # for MY experts (the c_alltoall)
            x_send = x_send.reshape(ep, e // ep, cap, hl)
            x_recv = jax.lax.all_to_all(x_send, "ep", split_axis=0,
                                        concat_axis=0)
            el = e // ep
            if grouped_gemm_enabled():
                # occupancy counts ride a second (tiny) all_to_all:
                # cnt_recv[s, el] = slots shard s filled for my expert el.
                # Kept assignments fill slots 0..kept-1 contiguously, so
                # the received ragged rows compact into per-expert
                # segments and the MLP runs over sum(counts) rows instead
                # of el*ep*cap padded slots.
                kept = route["keep"].astype(jnp.int32)
                cnt_send = jnp.zeros((e,), jnp.int32).at[route["expert"]] \
                    .add(kept).reshape(ep, el)
                cnt_recv = jax.lax.all_to_all(cnt_send, "ep", split_axis=0,
                                              concat_axis=0)
                flat = jnp.swapaxes(x_recv, 0, 1).reshape(el * ep * cap, hl)
                sizes = jnp.sum(cnt_recv, axis=0)             # [el]
                seg_start = jnp.cumsum(sizes) - sizes
                # rank of slot (el, s, c) within its expert's segment:
                # senders before s, then c within sender s
                before = (jnp.cumsum(cnt_recv, 0) - cnt_recv).T  # [el, ep]
                c_idx = jnp.arange(cap)[None, None, :]
                valid = c_idx < cnt_recv.T[:, :, None]
                destc = jnp.where(
                    valid,
                    (seg_start[:, None] + before)[:, :, None] + c_idx,
                    el * ep * cap).reshape(-1)
                xc = jnp.zeros((el * ep * cap, hl), xt.dtype) \
                    .at[destc].set(flat, mode="drop")
                yc = grouped_mlp_apply(xc, gate_up, down, sizes)
                y_flat = yc.at[destc].get(mode="fill", fill_value=0)
                y_loc = y_flat.reshape(el, ep, cap, hl)
            else:
                # dense path: fold senders into the slot dim, padded MLP
                x_loc = jnp.swapaxes(x_recv, 0, 1).reshape(el, ep * cap, hl)
                y_loc = expert_mlp_apply(x_loc, gate_up, down) \
                    .reshape(el, ep, cap, hl)
            # reverse exchange back to the senders
            y_back = jnp.swapaxes(y_loc, 0, 1)
            y_recv = jax.lax.all_to_all(y_back, "ep", split_axis=0,
                                        concat_axis=0)
            y_e = y_recv.reshape(e, cap, hl)
            yt = sparse_combine(y_e, route, dest, tl)
            return yt, aux, drop

        fn = shard_map(
            local, mesh=mesh.mesh,
            in_specs=(xspec, P(), P("ep", None, None), P("ep", None, None)),
            out_specs=(xspec, P(), P()))
        # quantized stacks dequantize BEFORE the shard_map (codes would
        # need their own ep pspecs); the all_to_all wire format and the
        # per-shard compute are unchanged
        gate_up, down = _expert_arrays(self.experts, x.dtype)
        yt, aux, drop = fn(x.reshape(t, h), self.gate_w, gate_up, down)
        return yt.reshape(b, s, h), aux, drop
