"""Mixture-of-Experts with expert parallelism (ref: ``python/paddle/
incubate/distributed/models/moe/`` — MoELayer, gate, dispatcher using
``c_alltoall`` over the expert-parallel NCCL group).

TPU-native: GShard/Switch dense-dispatch formulation. Tokens are combined
with a capacity-limited one-hot dispatch tensor via einsum; expert weights
carry a leading expert axis sharded on the data axes (experts ride the same
chips as data parallelism, the reference's ``ep on dp`` layout). Under
GSPMD the dispatch/combine einsums lower to the SAME all_to_all pattern the
reference hand-codes — but fused and overlapped by XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.dtypes import get_default_dtype
from paddle_tpu.core.module import Module
from paddle_tpu.nn import initializer as I


def top_k_gate(logits, k: int, capacity: int, *, jitter_rng=None):
    """Top-k gating with capacity (ref gate/naive_gate.py + GShard aux loss).

    logits: [T, E]. Returns (dispatch [T, E, C] bool, combine [T, E, C] float,
    aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
    # renormalise the k gates
    gate_vals = gate_vals / jnp.maximum(jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    # GShard position computation: queue slot per token per choice
    dispatch = jnp.zeros((t, e, capacity), bool)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    offset = jnp.zeros((e,), jnp.int32)  # slots consumed by earlier choices
    for j in range(k):
        choice = jax.nn.one_hot(gate_idx[:, j], e, dtype=jnp.int32)  # [T,E]
        pos_in_e = jnp.cumsum(choice, axis=0) - 1 + offset[None, :]
        within = (pos_in_e < capacity) & (choice > 0)
        pos = jnp.sum(jnp.where(within, pos_in_e, 0), axis=1)  # [T]
        oh_pos = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        d_j = within[..., None] & (oh_pos[:, None, :] > 0.5)
        dispatch = dispatch | d_j
        combine = combine + d_j.astype(jnp.float32) * gate_vals[:, j][:, None, None]
        offset = offset + jnp.sum(choice, axis=0)

    # load-balancing aux loss (Switch): E * sum_e (frac_tokens_e * mean_prob_e)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    return dispatch, combine, aux


class ExpertMLP(Module):
    """E SwiGLU expert MLPs with a leading expert axis, ep-sharded."""

    def __init__(self, num_experts, hidden, intermediate, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        init = I.Normal(0.0, 0.02)
        self.gate_up = init((num_experts, hidden, 2 * intermediate), dtype)
        self.down = init((num_experts, intermediate, hidden), dtype)
        # experts across the data axes = expert parallelism on (dp, fsdp)
        self.set_pspec("gate_up", P(("dp", "fsdp"), None, None))
        self.set_pspec("down", P(("dp", "fsdp"), None, None))

    def __call__(self, x_e):
        """x_e: [E, C, H] — per-expert token slots."""
        gu = jnp.einsum("ech,ehm->ecm", x_e, self.gate_up)
        gate, up = jnp.split(gu, 2, axis=-1)
        act = jax.nn.silu(gate) * up
        return jnp.einsum("ecm,emh->ech", act, self.down)


class MoELayer(Module):
    """Drop-in MLP replacement (ref MoELayer). combine/dispatch einsums are
    the all_to_all; aux loss is returned for the trainer to add."""

    def __init__(self, hidden, intermediate, num_experts, k=2,
                 capacity_factor=1.25, dtype=None):
        super().__init__()
        dtype = dtype or get_default_dtype()
        self.gate_w = I.Normal(0.0, 0.02)((hidden, num_experts), jnp.float32)
        self.experts = ExpertMLP(num_experts, hidden, intermediate, dtype)
        self.num_experts, self.k, self.capacity_factor = num_experts, k, capacity_factor

    def __call__(self, x, return_aux=True):
        b, s, h = x.shape
        t = b * s
        e = self.num_experts
        cap = int(self.capacity_factor * self.k * t / e + 0.999)
        cap = max(cap, 4)
        xt = x.reshape(t, h)
        logits = xt.astype(jnp.float32) @ self.gate_w
        dispatch, combine, aux = top_k_gate(logits, self.k, cap)
        # dispatch: [T,E,C] — route tokens to expert slots (≙ all_to_all)
        x_e = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
        y_e = self.experts(x_e)
        # combine back (≙ reverse all_to_all)
        yt = jnp.einsum("tec,ech->th", combine.astype(x.dtype), y_e)
        y = yt.reshape(b, s, h)
        return (y, aux) if return_aux else y
