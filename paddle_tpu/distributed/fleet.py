"""Fleet facade (ref: ``python/paddle/distributed/fleet/fleet.py`` —
``fleet.init(is_collective=True, strategy=DistributedStrategy())`` and the
hybrid-parallel config dict).

Maps the reference's strategy knobs onto a HybridMesh + sharding levels so
reference training scripts translate line-for-line:

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 2, "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group()
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax

from paddle_tpu.distributed.mesh import HybridMesh


@dataclass
class DistributedStrategy:
    hybrid_configs: dict = field(default_factory=dict)
    # reference knobs kept for parity; consumed where meaningful
    amp: bool = False
    amp_configs: dict = field(default_factory=dict)
    recompute: bool = False
    sharding: bool = False
    sharding_configs: dict = field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: dict = field(default_factory=dict)


_STATE: dict = {"mesh": None, "strategy": None}


def init(is_collective: bool = True, strategy: Optional[DistributedStrategy] = None,
         devices=None) -> HybridMesh:
    """Build the mesh from the strategy's hybrid_configs (ref fleet.init)."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    n = len(devices) if devices is not None else jax.device_count()
    dp = int(hc.get("dp_degree", 0)) or 0
    tp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sd = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    if dp == 0:  # infer dp as the remainder, reference behaviour
        denom = tp * pp * sd * sep
        assert n % denom == 0, (n, hc)
        dp = n // denom
    mesh = HybridMesh(dp=dp, fsdp=sd, pp=pp, tp=tp, sp=sep, devices=devices)
    _STATE["mesh"] = mesh
    _STATE["strategy"] = strategy
    return mesh


def get_hybrid_communicate_group() -> Optional[HybridMesh]:
    return _STATE["mesh"]


def distributed_model(model, min_size: int = 2 ** 16):
    """Ref: fleet.distributed_model — places params on the mesh (ZeRO-3 layout
    honouring tp pspecs). Sharding stage comes from strategy.sharding_configs."""
    from paddle_tpu.distributed.sharded import shard_module
    mesh = _STATE["mesh"]
    if mesh is None:
        return model
    strategy = _STATE["strategy"]
    stage = 3
    if strategy and strategy.sharding_configs:
        stage = int(strategy.sharding_configs.get("stage", 3))
    return shard_module(model, mesh, stage=stage, min_size=min_size)


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def distributed_optimizer(optimizer, strategy=None):
    """Ref fleet.distributed_optimizer. Under GSPMD the optimizer needs no
    wrapping — its state pytree mirrors the (sharded) param pytree, so
    ZeRO-style partitioning falls out of init_state(model, optimizer, mesh).
    Returned unchanged for API parity."""
    return optimizer


class _FleetUtils:
    """Ref fleet.utils namespace (recompute + helpers)."""

    @staticmethod
    def recompute(fn, *args, **kwargs):
        from paddle_tpu.distributed import recompute as _rc
        return _rc(fn, *args, **kwargs)


utils = _FleetUtils()
