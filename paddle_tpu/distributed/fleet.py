"""Fleet facade (ref: ``python/paddle/distributed/fleet/fleet.py`` —
``fleet.init(is_collective=True, strategy=DistributedStrategy())`` and the
hybrid-parallel config dict).

Maps the reference's strategy knobs onto a HybridMesh + sharding levels so
reference training scripts translate line-for-line:

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 2, "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group()
"""
from __future__ import annotations

import warnings
from dataclasses import dataclass, field
from typing import Optional

import jax

from paddle_tpu.distributed.mesh import HybridMesh


@dataclass
class DistributedStrategy:
    hybrid_configs: dict = field(default_factory=dict)
    # reference knobs, each mapped onto the real mechanism:
    #   amp            -> amp.decorate(model, "O2") + multi_precision master
    #                     weights when pure (use_pure_fp16/bf16 or level O2);
    #                     plain O1 autocast is the framework default (bf16
    #                     compute) so it needs no transformation
    #   recompute      -> model's remat flag (per-layer jax.checkpoint)
    #   sharding       -> distributed_model's ZeRO stage placement
    #   gradient_merge -> optimizer.GradientMerge(k_steps, avg) wrapper
    amp: bool = False
    amp_configs: dict = field(default_factory=dict)
    recompute: bool = False
    sharding: bool = False
    sharding_configs: dict = field(default_factory=dict)
    gradient_merge: bool = False
    gradient_merge_configs: dict = field(default_factory=dict)

    def _amp_pure(self) -> bool:
        c = self.amp_configs or {}
        if c.get("use_pure_fp16"):
            warnings.warn(
                "DistributedStrategy.amp_configs use_pure_fp16: TPU's "
                "native reduced precision is bfloat16 — params are cast to "
                "bf16, not fp16 (no loss scaling needed)", stacklevel=3)
            return True
        return bool(c.get("use_pure_bf16")
                    or str(c.get("level", "O1")).upper() == "O2")


_STATE: dict = {"mesh": None, "strategy": None}


def init(is_collective: bool = True, strategy: Optional[DistributedStrategy] = None,
         devices=None) -> HybridMesh:
    """Build the mesh from the strategy's hybrid_configs (ref fleet.init)."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    n = len(devices) if devices is not None else jax.device_count()
    dp = int(hc.get("dp_degree", 0)) or 0
    tp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sd = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    if dp == 0:  # infer dp as the remainder, reference behaviour
        denom = tp * pp * sd * sep
        assert n % denom == 0, (n, hc)
        dp = n // denom
    mesh = HybridMesh(dp=dp, fsdp=sd, pp=pp, tp=tp, sp=sep, devices=devices)
    _STATE["mesh"] = mesh
    _STATE["strategy"] = strategy
    return mesh


def get_hybrid_communicate_group() -> Optional[HybridMesh]:
    return _STATE["mesh"]


def distributed_model(model, min_size: int = 2 ** 16):
    """Ref: fleet.distributed_model — places params on the mesh (ZeRO-3 layout
    honouring tp pspecs). Sharding stage comes from strategy.sharding_configs.

    Strategy knobs applied here: ``amp`` (pure level: amp.decorate casts the
    params to bf16; O1 is the framework's native default and needs nothing),
    ``recompute`` (sets the model's remat flag when it has one — per-layer
    jax.checkpoint — else warns that it is ignored)."""
    from paddle_tpu.distributed.sharded import shard_module
    strategy = _STATE["strategy"]
    if strategy is not None:
        if strategy.recompute:
            cfg = getattr(model, "cfg", None)
            if cfg is not None and hasattr(cfg, "remat"):
                cfg.remat = True
            else:
                warnings.warn(
                    "DistributedStrategy.recompute: this model has no remat "
                    "flag; the knob is IGNORED — wrap the forward with "
                    "fleet.utils.recompute / paddle_tpu.distributed."
                    "recompute (jax.checkpoint) instead", stacklevel=2)
        if strategy.amp and strategy._amp_pure():
            from paddle_tpu import amp as _amp
            model = _amp.decorate(model, level="O2")
    mesh = _STATE["mesh"]
    if mesh is None:
        return model
    stage = 3
    if strategy and strategy.sharding_configs:
        stage = int(strategy.sharding_configs.get("stage", 3))
    return shard_module(model, mesh, stage=stage, min_size=min_size)


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def distributed_optimizer(optimizer, strategy=None):
    """Ref fleet.distributed_optimizer. Under GSPMD the optimizer needs no
    DISTRIBUTION wrapping — its state pytree mirrors the (sharded) param
    pytree, so ZeRO-style partitioning falls out of init_state(model,
    optimizer, mesh). Strategy knobs DO act here:

    * ``amp`` (pure level) -> ``multi_precision=True`` (fp32 master weights,
      the reference's O2 recipe); plain O1 needs no optimizer change.
    * ``gradient_merge`` -> wrapped in ``optimizer.GradientMerge`` with
      ``k_steps``/``avg`` from gradient_merge_configs.
    """
    strategy = strategy or _STATE["strategy"]
    if strategy is None:
        return optimizer
    if strategy.amp and strategy._amp_pure():
        # walk wrapper chains (GradientMerge/LookAhead): the flag must land
        # on the optimizer whose step actually applies updates
        target = optimizer
        while hasattr(target, "inner"):
            target = target.inner
        if hasattr(target, "multi_precision"):
            target.multi_precision = True
        else:
            warnings.warn(
                "DistributedStrategy.amp (pure): optimizer has no "
                "multi_precision attribute; the knob is IGNORED for it",
                stacklevel=2)
    if strategy.gradient_merge:
        from paddle_tpu.optimizer import GradientMerge, Optimizer
        cfgs = strategy.gradient_merge_configs or {}
        k_steps = int(cfgs.get("k_steps", 1))
        if isinstance(optimizer, GradientMerge):
            pass  # idempotent: nested wrapping would compound k/avg
        elif isinstance(optimizer, Optimizer):
            if k_steps > 1:  # k=1 would be a no-op carrying fp32 accum HBM
                optimizer = GradientMerge(optimizer, k_steps=k_steps,
                                          avg=bool(cfgs.get("avg", True)))
        else:
            warnings.warn(
                "DistributedStrategy.gradient_merge: not a paddle_tpu "
                "Optimizer; the knob is IGNORED — wrap it in "
                "paddle_tpu.optimizer.GradientMerge yourself", stacklevel=2)
    return optimizer


class _FleetUtils:
    """Ref fleet.utils namespace (recompute + helpers)."""

    @staticmethod
    def recompute(fn, *args, **kwargs):
        from paddle_tpu.distributed import recompute as _rc
        return _rc(fn, *args, **kwargs)


utils = _FleetUtils()
