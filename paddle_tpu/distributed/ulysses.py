"""DeepSpeed-Ulysses-style sequence parallelism (ref capability:
``paddle.distributed.fleet`` sep-parallel / PaddleNLP sequence-parallel
attention).

Complement to ring attention (`ring_attention.py`): instead of rotating KV
blocks around the ring, one ``all_to_all`` re-shards activations from
sequence-sharded to head-sharded, runs ordinary full attention on a head
slice, and a second ``all_to_all`` restores sequence sharding. Two
collectives per layer, overlap-friendly on ICI, and the inner attention can
use the Pallas flash kernel unchanged — the better choice when
``num_heads >= sp`` and sequence length per chip is small.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed._compat import axis_size
from paddle_tpu.ops import attention as A


def ulysses_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                      scale=None, window=None, kv_lens=None, attn_mask=None,
                      attn_bias=None):
    """Attention over the full sequence with inputs sequence-sharded on
    ``axis_name``. [B, S_local, H, D] in and out; H must divide by the axis
    size. Call inside shard_map.

    ``kv_lens``: [B] global valid key lengths (padded varlen) — applied by
    the inner attention after the head-scatter, so the fused kernel's
    varlen path still runs. ``attn_mask``: [B, S, S] bool over GLOBAL
    positions, replicated (after the all_to_all every member holds the full
    sequence for its head slice, so the full mask is needed anyway).
    ``attn_bias``: [B|1, H_local|1, S, S] float ADDITIVE scores (T5
    relative bias, ALiBi) for THIS member's post-exchange head slice —
    ``make_ulysses_attention`` shards a global per-head bias over
    (tp, sp) so the slice lines up with the heads the all_to_all assigns."""
    sp = axis_size(axis_name)
    if q.shape[2] % sp != 0:
        raise ValueError(
            f"ulysses_attention: num_heads={q.shape[2]} must be divisible by "
            f"axis '{axis_name}' size {sp}")
    if k.shape[2] % sp != 0:
        # GQA with fewer KV heads than sp: replicate KV groups up to sp so
        # the head-scatter has something to split (standard Ulysses-GQA).
        # COST: the repeat materialises rep x the local KV before the
        # all_to_all (transient memory) and the exchange then moves
        # S_local*(sp-1)*D bytes/device instead of the no-GQA
        # S_local*kv_heads*(sp-1)/sp*D — an ICI multiplier of
        # rep = sp/kv_heads. There is no "repeat after the exchange"
        # alternative here: with kv_heads < sp the heads cannot be split sp
        # ways un-replicated, and an all_gather(seq) of the original KV
        # costs MORE ((sp-1)*S_local*kv_heads*D). When this bites, prefer
        # sequence_parallel="ring" (rotates un-replicated KV).
        if sp % k.shape[2] == 0:
            rep = sp // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        else:
            raise ValueError(
                f"ulysses_attention: num_key_value_heads={k.shape[2]} must "
                f"divide by (or into) axis '{axis_name}' size {sp}; use "
                "sequence_parallel='ring' for this head configuration")
    # seq-sharded -> head-sharded: gather sequence, scatter heads
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    mask = attn_mask[:, None] if attn_mask is not None else None  # [B,1,S,S]
    if attn_bias is not None:
        # merge additive bias with any bool mask: the XLA attention core
        # takes ONE attn_mask, so fold blocks into the bias as -inf
        bias = attn_bias.astype(jnp.float32)
        mask = bias if mask is None else jnp.where(mask, bias, -1e30)
    # window works unchanged: after the all_to_all the inner attention sees
    # the FULL sequence (global positions intact), so the sliding window is
    # exactly the single-device banded computation on a head slice
    out = A.scaled_dot_product_attention(qh, kh, vh, is_causal=causal,
                                         scale=scale, window=window,
                                         kv_lens=kv_lens, attn_mask=mask)
    # head-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_merge_partials(o, m, l, axis_name: str = "cp"):
    """Ulysses-style merge of per-shard online-softmax partials
    (context-parallel serving, ISSUE 18). Trailing-head layout like the
    ring variant: ``o`` [..., H, D] with ``m``/``l`` shaped
    ``o.shape[:-1]``.

    Instead of rotating whole triples around the ring, one tiled
    ``all_to_all`` re-shards them from partial-per-member to
    head-sharded — member j receives head slice j of ALL n partials,
    stacked along a leading axis in source-member (= global shard)
    order. Each member folds its slice 0..n-1 and an ``all_gather``
    restores the full head dim, so every member ends with the same
    bit-identical merged triple. Bytes moved per member:
    2·(n-1)/n · H·(D+2) floats — same order as the ring, but in one
    collective round instead of n-1. Requires H % n == 0."""
    n = axis_size(axis_name)
    if n == 1:
        return o, m, l
    ho, hm = o.ndim - 2, m.ndim - 1
    if o.shape[ho] % n != 0:
        raise ValueError(
            f"ulysses_merge_partials: heads={o.shape[ho]} must divide by "
            f"axis '{axis_name}' size {n}; use PT_CP_IMPL=ring")

    def split(x, ax):
        y = lax.all_to_all(x, axis_name, split_axis=ax, concat_axis=0,
                           tiled=True)
        return y.reshape((n,) + x.shape[:ax]
                         + (x.shape[ax] // n,) + x.shape[ax + 1:])

    from paddle_tpu.distributed.ring_attention import merge_partials
    o_s, m_s, l_s = split(o, ho), split(m, hm), split(l, hm)
    o_a, m_a, l_a = o_s[0], m_s[0], l_s[0]
    for g in range(1, n):
        o_a, m_a, l_a = merge_partials(o_a, m_a, l_a,
                                       o_s[g], m_s[g], l_s[g])
    o_a = lax.all_gather(o_a, axis_name, axis=ho, tiled=True)
    m_a = lax.all_gather(m_a, axis_name, axis=hm, tiled=True)
    l_a = lax.all_gather(l_a, axis_name, axis=hm, tiled=True)
    return o_a, m_a, l_a


def make_ulysses_attention(mesh, causal: bool = True, axis_name: str = "sp",
                           head_spec=None, batch_axes=("dp", "fsdp"),
                           window: int | None = None,
                           varlen: bool = False, masked: bool = False,
                           bias_shape=None, scale=None):
    """Bind ulysses_attention onto a HybridMesh via shard_map: takes/returns
    [B, S, H, D] arrays sequence-sharded over ``axis_name``; batch sharded
    over ``batch_axes``; ``head_spec="tp"`` composes with tensor
    parallelism (each tp member re-shards its own head slice over sp, so
    local heads must divide by sp * tp).
    ``varlen=True``: attend(q, k, v, kv_lens) with [B] key lengths.
    ``masked=True``: attend(..., attn_mask) with [B, S, S] bool (replicated
    over sp — the head-sharded inner attention needs the whole mask).
    ``bias_shape``: shape of a [B|1, H|1, S, S] ADDITIVE float bias passed
    as the last argument. A per-head bias is sharded over (tp, sp) on the
    head dim — tp-major, sp-minor, exactly the head range device
    (tp_j, sp_i) ends up computing after the all_to_all."""
    from paddle_tpu.distributed._compat import shard_map

    spec = P(batch_axes, axis_name, head_spec, None)
    in_specs = [spec, spec, spec]
    if varlen:
        in_specs.append(P(batch_axes))
    if masked:
        in_specs.append(P(batch_axes, None, None))
    if bias_shape is not None:
        from paddle_tpu.distributed.ring_attention import bias_spec
        in_specs.append(bias_spec(
            bias_shape,
            (head_spec, axis_name) if head_spec else (axis_name,),
            batch_axes=batch_axes, rows_axis=None))

    def fn(q, k, v, *extra):
        it = iter(extra)
        lens = next(it) if varlen else None
        mask = next(it) if masked else None
        bias = next(it) if bias_shape is not None else None
        return ulysses_attention(q, k, v, axis_name=axis_name, causal=causal,
                                 scale=scale, window=window, kv_lens=lens,
                                 attn_mask=mask, attn_bias=bias)

    return shard_map(fn, mesh=mesh.mesh, in_specs=tuple(in_specs),
                     out_specs=spec, check_vma=False)
