"""Collective ops (ref: ``python/paddle/distributed/communication/`` —
all_reduce, all_gather, reduce_scatter, alltoall, broadcast, send/recv over
ProcessGroupNCCL, ``paddle/fluid/distributed/collective/process_group_nccl.cc``).

TPU-native: these are thin wrappers over lax collectives, valid INSIDE
``shard_map``/``pmap`` where a mesh axis name is bound. Outside shard_map,
GSPMD inserts collectives automatically from shardings — prefer that; use
these only where the schedule must be explicit (pipeline, ring attention,
MoE all-to-all).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.distributed._compat import axis_size
from paddle_tpu.observability import METRICS
from paddle_tpu.utils.faults import fault_point

# Host-side collective accounting. These wrappers run at TRACE time (the
# executed program is XLA's), so the counters measure how many collective
# ops each compiled program CONTAINS — per-trace, not per-device-launch.
# That is the number that matters for schedule review ("why does this
# step all-gather 40 times?") and it is exactly once per compilation, so
# the hot path stays untouched.
_COLL_OPS = METRICS.counter(
    "collective_ops_total", "collective ops traced, by op kind",
    labelnames=("op",))
_COLL_BYTES = METRICS.counter(
    "collective_bytes_total",
    "per-member payload bytes of traced collective ops", labelnames=("op",))


def _count(op: str, x):
    _COLL_OPS.inc(op=op)
    try:
        _COLL_BYTES.inc(x.size * x.dtype.itemsize, op=op)
    except (AttributeError, TypeError):   # python scalars / exotic leaves
        pass


# ReduceOp parity (ref communication/reduce.py)
class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def all_reduce(x, op: str = ReduceOp.SUM, *, axis_name: str):
    # chaos site (ROADMAP multi-host slice): an installed rule can raise
    # (collective timeout → surfaces as a trace-time error the elastic
    # layer restarts through) or stall (straggler host). Host-side at
    # trace time — nothing is injected into the compiled program.
    fault_point("collective.all_reduce", op=op, axis_name=axis_name)
    _count("all_reduce", x)
    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis_name)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(x), axis_name))
    raise ValueError(op)


def all_gather(x, *, axis_name: str, axis: int = 0, tiled: bool = True):
    _count("all_gather", x)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, *, axis_name: str, axis: int = 0):
    _count("reduce_scatter", x)
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, *, axis_name: str, split_axis: int, concat_axis: int):
    _count("all_to_all", x)
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, *, axis_name: str):
    """Every member gets member `src`'s value."""
    _count("broadcast", x)
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    sel = jnp.where(jnp.arange(n) == src, 1.0, 0.0).astype(x.dtype)
    gathered = lax.all_gather(x, axis_name, axis=0)
    return jnp.tensordot(sel, gathered, axes=([0], [0])).astype(x.dtype)


def permute(x, perm: list[tuple[int, int]], *, axis_name: str):
    """Point-to-point send/recv pattern (ref send/recv): perm = [(src,dst)...]."""
    _count("permute", x)
    return lax.ppermute(x, axis_name, perm)


def shift(x, offset: int = 1, *, axis_name: str):
    """Ring shift: member i's value goes to member (i+offset) % n."""
    _count("shift", x)
    n = axis_size(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def barrier(*, axis_name: str):
    """Collectives are compiler-ordered on TPU; a psum serves as sync point."""
    return lax.psum(jnp.zeros((), jnp.float32), axis_name)


def reduce(x, dst: int = 0, op: str = ReduceOp.SUM, *, axis_name: str):
    """Reduce to member ``dst`` (ref communication/reduce.py). Other members
    get their input back unchanged — on TPU the all-reduce already rode ICI;
    masking to dst would only add work, so this is all_reduce + select."""
    red = all_reduce(x, op, axis_name=axis_name)
    return jnp.where(lax.axis_index(axis_name) == dst, red, x)


def scatter(x, src: int = 0, *, axis_name: str):
    """Member ``src``'s value, split over the axis: member i receives the
    i-th chunk of src's leading dim (ref communication/scatter.py)."""
    full = broadcast(x, src, axis_name=axis_name)
    n = axis_size(axis_name)
    i = lax.axis_index(axis_name)
    if full.shape[0] % n != 0:
        raise ValueError(
            f"scatter: leading dim {full.shape[0]} must divide evenly over "
            f"{n} members (reference scatter requires an exact split)")
    chunk = full.shape[0] // n
    return lax.dynamic_slice_in_dim(full, i * chunk, chunk, axis=0)


def gather(x, dst: int = 0, *, axis_name: str, axis: int = 0):
    """All members' values concatenated; valid on every member (TPU
    collectives are SPMD — restricting to dst would not save ICI traffic)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def _p2p_edge(x, src: int, dst: int, axis_name: str):
    out = lax.ppermute(x, axis_name, [(src, dst)])
    return jnp.where(lax.axis_index(axis_name) == dst, out, x)


def send(x, dst: int, *, src: int, axis_name: str):
    """P2P send (ref communication/send.py). SPMD note: the reference calls
    send on one rank and recv on another; under XLA every member traces the
    same program, so both endpoints must be static — ``send``/``recv`` are
    two names for the same single-edge ppermute. Member ``dst`` receives
    ``src``'s value; everyone else keeps their input."""
    return _p2p_edge(x, src, dst, axis_name)


def recv(x, src: int, *, dst: int, axis_name: str):
    """P2P receive — see ``send``."""
    return _p2p_edge(x, src, dst, axis_name)


def all_gather_object(obj, group=None):
    """Gather arbitrary picklable objects across hosts (ref
    communication/all_gather.py:all_gather_object). Host-side (not traced):
    single-process returns [obj]; multi-host pickles into padded uint8
    arrays and rides ``multihost_utils.process_allgather``."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils
    data = np.frombuffer(pickle.dumps(obj), np.uint8)
    n = np.asarray([data.size], np.int64)
    sizes = multihost_utils.process_allgather(n)
    cap = int(sizes.max())
    padded = np.zeros(cap, np.uint8)
    padded[:data.size] = data
    gathered = multihost_utils.process_allgather(padded)
    return [pickle.loads(gathered[i, :int(sizes[i])].tobytes())
            for i in range(gathered.shape[0])]
