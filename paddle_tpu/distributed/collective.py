"""Collective ops (ref: ``python/paddle/distributed/communication/`` —
all_reduce, all_gather, reduce_scatter, alltoall, broadcast, send/recv over
ProcessGroupNCCL, ``paddle/fluid/distributed/collective/process_group_nccl.cc``).

TPU-native: these are thin wrappers over lax collectives, valid INSIDE
``shard_map``/``pmap`` where a mesh axis name is bound. Outside shard_map,
GSPMD inserts collectives automatically from shardings — prefer that; use
these only where the schedule must be explicit (pipeline, ring attention,
MoE all-to-all).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# ReduceOp parity (ref communication/reduce.py)
class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def all_reduce(x, op: str = ReduceOp.SUM, *, axis_name: str):
    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis_name)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(x), axis_name))
    raise ValueError(op)


def all_gather(x, *, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, *, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, *, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, *, axis_name: str):
    """Every member gets member `src`'s value."""
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    sel = jnp.where(jnp.arange(n) == src, 1.0, 0.0).astype(x.dtype)
    gathered = lax.all_gather(x, axis_name, axis=0)
    return jnp.tensordot(sel, gathered, axes=([0], [0])).astype(x.dtype)


def permute(x, perm: list[tuple[int, int]], *, axis_name: str):
    """Point-to-point send/recv pattern (ref send/recv): perm = [(src,dst)...]."""
    return lax.ppermute(x, axis_name, perm)


def shift(x, offset: int = 1, *, axis_name: str):
    """Ring shift: member i's value goes to member (i+offset) % n."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def barrier(*, axis_name: str):
    """Collectives are compiler-ordered on TPU; a psum serves as sync point."""
    return lax.psum(jnp.zeros((), jnp.float32), axis_name)
