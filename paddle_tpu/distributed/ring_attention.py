"""Ring attention — sequence/context parallelism for long sequences.

Reference capability: PaddleNLP sequence-parallel + the reference's
``paddle.distributed.fleet`` sep-parallel group (``sep_degree``); the TPU
design follows the ring-attention formulation (blockwise attention with KV
rotation over the ``sp`` axis) so attention over a sequence sharded across
chips never materialises the full S×S score matrix and overlaps KV transfer
with compute (ppermute rides ICI while the MXU works on the current block).

Use inside ``shard_map`` with the sequence axis sharded on ``sp``:
each member holds q,k,v of shape [B, S/sp, H, D].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """Scores for one (q_block, kv_block) pair in fp32.
    q: [B,Sq,H,D] k,v: [B,Sk,H,D]; mask: [Sq,Sk] bool or None."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: float | None = None):
    """Blockwise ring attention with online-softmax accumulation.

    Equals full attention over the gathered sequence (see
    tests/test_ring_attention.py). Gradient flows through ppermute, so the
    backward pass is itself a ring pass — no full-sequence gather ever.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5

    o = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)

    causal_in_block = jnp.tril(jnp.ones((s_loc, s_loc), bool)) if causal else None
    perm = [(i, (i + 1) % n) for i in range(n)]

    k_blk, v_blk = k, v
    for step in range(n):
        src = (my - step) % n  # which sequence block k_blk/v_blk holds
        if causal:
            # src > my: future block — fully masked; src == my: in-block causal
            block_mask = jnp.where(src == my, causal_in_block,
                                   jnp.full((s_loc, s_loc), True))
            allowed = (src <= my)
        else:
            block_mask = None
            allowed = True
        o_b, m_b, l_b = _block_attend(q, k_blk, v_blk, scale, block_mask)
        if causal:
            o_b = jnp.where(allowed, o_b, 0.0)
            m_b = jnp.where(allowed, m_b, _NEG_INF)
            l_b = jnp.where(allowed, l_b, 0.0)
        # online softmax merge
        m_new = jnp.maximum(m, m_b)
        c1 = jnp.exp(m - m_new)
        c2 = jnp.exp(m_b - m_new)
        o = o * jnp.moveaxis(c1, 1, 2)[..., None] + o_b * jnp.moveaxis(c2, 1, 2)[..., None]
        l = l * c1 + l_b * c2
        m = m_new
        if step != n - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = o / jnp.maximum(jnp.moveaxis(l, 1, 2)[..., None], 1e-30)
    return out.astype(q.dtype)


def make_ring_attention(mesh, causal=True):
    """shard_map-wrapped ring attention: global [B, S, H, D] with S sharded
    over sp; drop-in replacement for full attention."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), "sp", None, None)

    @functools.partial(shard_map, mesh=mesh.mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def attend(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=causal)

    return attend
