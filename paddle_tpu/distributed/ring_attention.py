"""Ring attention — sequence/context parallelism for long sequences.

Reference capability: PaddleNLP sequence-parallel + the reference's
``paddle.distributed.fleet`` sep-parallel group (``sep_degree``); the TPU
design follows the ring-attention formulation (blockwise attention with KV
rotation over the ``sp`` axis) so attention over a sequence sharded across
chips never materialises the full S×S score matrix and overlaps KV transfer
with compute (ppermute rides ICI while the MXU works on the current block).

Use inside ``shard_map`` with the sequence axis sharded on ``sp``:
each member holds q,k,v of shape [B, S/sp, H, D].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.distributed._compat import axis_size

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask, bias=None):
    """Scores for one (q_block, kv_block) pair in fp32.
    q: [B,Sq,H,D] k,v: [B,Sk,Hkv,D]; mask: bool, broadcastable to
    [B,H,Sq,Sk] (e.g. [1,1,Sq,Sk] causal or [B,1,Sq,Sk] varlen), or None.
    ``bias``: ADDITIVE float scores (T5 relative bias / ALiBi),
    broadcastable to [B,H,Sq,Sk]; applied after scaling, before the mask.
    GQA (Hkv < H) runs as a grouped einsum — repeated K/V is never
    materialised, so the ring rotates 1/rep the bytes."""
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hq != hk:
        rep = hq // hk
        qg = q.reshape(b, sq, hk, rep, d)
        s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
        s = s.reshape(b, hq, sq, sk)  # head h = g*rep + r (q head order)
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    if mask is not None:
        # a fully-masked row has m = NEG_INF and exp(s - m) = 1 — zero the
        # masked entries explicitly so dead rows contribute l = 0, not Sk
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    if hq != hk:
        pg = p.reshape(b, hk, rep, sq, sk).astype(v.dtype)
        o = jnp.einsum("bgrqk,bkgd->bqgrd", pg, v).reshape(b, sq, hq, d)
        o = o.astype(jnp.float32)
    else:
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v
                       ).astype(jnp.float32)
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: float | None = None, window: int | None = None,
                   kv_lens=None, attn_mask=None, attn_bias=None):
    """Blockwise ring attention with online-softmax accumulation.

    Equals full attention over the gathered sequence (see
    tests/test_ring_attention.py). Gradient flows through ppermute, so the
    backward pass is itself a ring pass — no full-sequence gather ever.
    ``window``: Mistral-style causal sliding window over GLOBAL positions
    (query position i sees [i-window+1, i] across shard boundaries).
    ``kv_lens``: [B] GLOBAL valid key lengths (padded-varlen batches) —
    per-step masking against the rotating block's global key positions, no
    mask tensor materialised.
    ``attn_mask``: [B, S_loc, S_global] bool — this rank's query rows vs
    ALL global key columns (the O(S^2/sp)-per-device general-mask path);
    each ring step slices the arriving block's column range.
    ``attn_bias``: [B|1, H|1, S_loc, S_global] float ADDITIVE scores (T5
    relative bias, ALiBi) — same row/column layout as ``attn_mask``, with
    a broadcastable head dim; sliced per ring step like the mask. Must be
    finite (use ``attn_mask`` to fully block positions). Differentiable —
    d(bias) flows back through the per-step slices.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5

    o = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)

    causal_in_block = jnp.tril(jnp.ones((s_loc, s_loc), bool)) if causal else None
    a_ix = jnp.arange(s_loc)[:, None]
    b_ix = jnp.arange(s_loc)[None, :]
    perm = [(i, (i + 1) % n) for i in range(n)]

    # a window bounds how far back any query looks: ring step s covers
    # global distance >= s*s_loc - (s_loc-1) on every rank, so steps past
    # ceil((window + s_loc - 1) / s_loc) are dead EVERYWHERE — prune them
    # at trace time (no compute, no ppermute): windowed ring costs
    # O(S * window), not O(S^2)
    live_steps = n
    if causal and window is not None:
        live_steps = min(n, -(-(window + s_loc - 1) // s_loc))

    k_blk, v_blk = k, v
    for step in range(live_steps):
        src = (my - step) % n  # which sequence block k_blk/v_blk holds
        if causal:
            # src > my: future block — fully masked; src == my: in-block causal
            block_mask = jnp.where(src == my, causal_in_block,
                                   jnp.full((s_loc, s_loc), True))
            allowed = (src <= my)
            if window is not None:
                # global-position band: qg - kg < window
                dist = (my - src) * s_loc + a_ix - b_ix
                block_mask = block_mask & (dist < window)
                allowed = allowed & ((my - src) * s_loc - (s_loc - 1) < window)
            block_mask = block_mask[None, None]  # [1,1,Sq,Sk]
        else:
            block_mask = None
            allowed = True
        if kv_lens is not None:
            # this block's keys hold global positions src*s_loc + [0, s_loc)
            g_idx = src * s_loc + jnp.arange(s_loc)
            key_ok = (g_idx[None, :] < jnp.asarray(kv_lens)[:, None]
                      )[:, None, None, :]  # [B,1,1,Sk]
            block_mask = key_ok if block_mask is None else block_mask & key_ok
        if attn_mask is not None:
            cols = lax.dynamic_slice_in_dim(attn_mask, src * s_loc, s_loc,
                                            axis=2)  # [B, Sq, Sk]
            cols = cols[:, None]  # [B,1,Sq,Sk]
            block_mask = cols if block_mask is None else block_mask & cols
        bias_blk = None
        if attn_bias is not None:
            bias_blk = lax.dynamic_slice_in_dim(attn_bias, src * s_loc,
                                                s_loc, axis=3)
        o_b, m_b, l_b = _block_attend(q, k_blk, v_blk, scale, block_mask,
                                      bias_blk)
        if causal:
            o_b = jnp.where(allowed, o_b, 0.0)
            m_b = jnp.where(allowed, m_b, _NEG_INF)
            l_b = jnp.where(allowed, l_b, 0.0)
        # online softmax merge
        m_new = jnp.maximum(m, m_b)
        c1 = jnp.exp(m - m_new)
        c2 = jnp.exp(m_b - m_new)
        o = o * jnp.moveaxis(c1, 1, 2)[..., None] + o_b * jnp.moveaxis(c2, 1, 2)[..., None]
        l = l * c1 + l_b * c2
        m = m_new
        if step != live_steps - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = o / jnp.maximum(jnp.moveaxis(l, 1, 2)[..., None], 1e-30)
    return out.astype(q.dtype)


# -- online-softmax partial merges (context-parallel serving, ISSUE 18) -----
#
# The paged attention kernels emit per-shard (acc, m, l) partials in the
# TRAILING-head layout — o [..., H, D] with m, l shaped o.shape[:-1] — and
# the serving engine combines them across the ``cp`` mesh axis. Both merge
# strategies below are DETERMINISTIC ACROSS MEMBERS: every shard folds the
# same partials in the same global order (ring) or through symmetric
# reductions (psum), so the merged result is bit-identical on every member
# and replicated sampling / quantize-on-write scatters never diverge.

def merge_partials(o, m, l, o_b, m_b, l_b):
    """One pairwise online-softmax merge of two partial triples
    (trailing-head layout: m/l shaped ``o.shape[:-1]``)."""
    m_new = jnp.maximum(m, m_b)
    c1 = jnp.exp(m - m_new)
    c2 = jnp.exp(m_b - m_new)
    return (o * c1[..., None] + o_b * c2[..., None], m_new,
            l * c1 + l_b * c2)


def finalize_partials(o, l, dtype=None):
    """Normalise a merged accumulator; ``max(l, eps)`` keeps fully-masked
    rows (padding / all keys on other shards pre-merge) at 0, not NaN."""
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out if dtype is None else out.astype(dtype)


def ring_merge_partials(o, m, l, axis_name: str = "cp"):
    """Ring merge: rotate the triples with ppermute (the same rotation
    pattern the training ring uses for KV blocks) until every member has
    collected all ``n`` shard partials, then fold them in GLOBAL shard
    order 0..n-1. The fold's fp rounding sequence is identical on every
    member — unlike folding in arrival order, which would differ per
    member by a rotation and break the bit-identical-replicas contract."""
    n = axis_size(axis_name)
    if n == 1:
        return o, m, l
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    os_, ms_, ls_ = [o], [m], [l]
    ob, mb, lb = o, m, l
    for _ in range(n - 1):
        ob = lax.ppermute(ob, axis_name, perm)
        mb = lax.ppermute(mb, axis_name, perm)
        lb = lax.ppermute(lb, axis_name, perm)
        os_.append(ob)
        ms_.append(mb)
        ls_.append(lb)
    # after s rotations the copy at stack position s came from member
    # (my - s) % n — shard g therefore sits at position (my - g) % n
    take = (my - jnp.arange(n)) % n

    def reorder(xs):
        return jnp.take(jnp.stack(xs), take, axis=0)

    o_s, m_s, l_s = reorder(os_), reorder(ms_), reorder(ls_)
    o_a, m_a, l_a = o_s[0], m_s[0], l_s[0]
    for g in range(1, n):
        o_a, m_a, l_a = merge_partials(o_a, m_a, l_a,
                                       o_s[g], m_s[g], l_s[g])
    return o_a, m_a, l_a


def psum_merge_partials(o, m, l, axis_name: str = "cp"):
    """Flat merge through symmetric reductions: one pmax for the global
    row max, one fused psum for the rescaled (acc, l). O(heads·dim)
    bytes per member per step — the decode-tick cross-shard merge.
    pmax/psum are member-order-invariant, so the result is bit-identical
    on every member by construction."""
    if axis_size(axis_name) == 1:
        return o, m, l
    m_max = lax.pmax(m, axis_name)
    c = jnp.exp(m - m_max)
    o, l = lax.psum((o * c[..., None], l * c), axis_name)
    return o, m_max, l


def bias_spec(bias_shape, head_spec, batch_axes=("dp", "fsdp"),
              rows_axis="sp"):
    """PartitionSpec for a [B|1, H|1, Sq, Sk] additive bias: shard only the
    non-broadcast dims (a size-1 batch/head dim must stay replicated)."""
    from jax.sharding import PartitionSpec as P
    b_ax = batch_axes if bias_shape[0] > 1 else None
    h_ax = head_spec if bias_shape[1] > 1 else None
    return P(b_ax, h_ax, rows_axis, None)


def make_ring_attention(mesh, causal=True, head_spec=None, window=None,
                        varlen=False, masked=False, bias_shape=None,
                        scale=None):
    """shard_map-wrapped ring attention: global [B, S, H, D] with S sharded
    over sp; drop-in replacement for full attention. ``head_spec="tp"``
    composes with tensor parallelism (heads stay tp-sharded through the
    ring — each tp member rings its own head slice over sp); ``window``
    applies a global causal sliding window (Mistral).
    ``varlen=True``: attend(q, k, v, kv_lens) with [B] global key lengths.
    ``masked=True``: attend(..., attn_mask) with a [B, S, S] bool mask
    (sharded on q rows); combine with varlen by passing both in order.
    ``bias_shape``: pass the [B|1, H|1, S, S] shape of an ADDITIVE float
    bias (T5 relative bias, ALiBi) to accept it as the last argument —
    q rows sharded over sp, head dim over ``head_spec`` when per-head."""
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), "sp", head_spec, None)
    in_specs = [spec, spec, spec]
    if varlen:
        in_specs.append(P(("dp", "fsdp")))            # kv_lens [B]
    if masked:
        # [B, S, S_global]: q rows sharded over sp, key columns replicated
        in_specs.append(P(("dp", "fsdp"), "sp", None))
    if bias_shape is not None:
        in_specs.append(bias_spec(bias_shape, head_spec))

    @functools.partial(shard_map, mesh=mesh.mesh,
                       in_specs=tuple(in_specs), out_specs=spec)
    def attend(q, k, v, *extra):
        it = iter(extra)
        lens = next(it) if varlen else None
        mask = next(it) if masked else None
        bias = next(it) if bias_shape is not None else None
        return ring_attention(q, k, v, axis_name="sp", causal=causal,
                              scale=scale, window=window, kv_lens=lens,
                              attn_mask=mask, attn_bias=bias)

    return attend


# -- zigzag (load-balanced causal) ring attention ----------------------------
#
# With contiguous block sharding, causal masking makes rank r do r+1 visible
# kv blocks while rank 0 does one — the ring's wall-clock is set by the last
# rank (~2× waste). Zigzag assignment (rank r holds chunks r and 2n-1-r of a
# 2n-chunk split) gives every rank one early and one late chunk, so visible
# work is equal across ranks. Same trick as the public zigzag/striped ring
# attention formulations; outputs stay in zigzag layout (invert with
# zigzag_inverse_permutation).

def zigzag_permutation(seq_len: int, n_shards: int):
    """Index array mapping zigzag order → original positions: apply
    ``x[:, perm]`` BEFORE sharding on sp."""
    import numpy as np
    assert seq_len % (2 * n_shards) == 0, "2*n_shards must divide seq_len"
    c = seq_len // (2 * n_shards)
    order = []
    for r in range(n_shards):
        order.extend(range(r * c, (r + 1) * c))                       # chunk r
        order.extend(range((2 * n_shards - 1 - r) * c,
                           (2 * n_shards - r) * c))                   # chunk 2n-1-r
    return np.asarray(order)


def zigzag_inverse_permutation(seq_len: int, n_shards: int):
    import numpy as np
    perm = zigzag_permutation(seq_len, n_shards)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return inv


def zigzag_ring_attention(q, k, v, *, axis_name: str = "sp",
                          scale: float | None = None):
    """Causal ring attention over zigzag-laid-out shards (see
    zigzag_permutation). [B, S/sp, H, D] per member; the local sequence is
    [chunk_my, chunk_{2n-1-my}] (chunks A and B).

    Per ring step this computes exactly TWO s2×s2 block-attends — the dead
    quadrants are never evaluated, which is the point of the zigzag layout.
    With chunk ids a = my < n ≤ b = 2n-1-my and kv ids c = src, d = 2n-1-src:
      * A never sees D (a < n ≤ d), B always fully sees C (b ≥ n > c)
      * step 0 (src == my): A·C causal + B·C full + B·D causal
      * src < my: A·C full + B·C full          (B·D dead: b < d)
      * src > my: B·C full + B·D full          (A·C dead: a < c)
    The traced src<my / src>my choice is made by SELECTING OPERANDS
    (qA vs qB, C vs D) into one dense block-attend — shapes stay static.
    """
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    assert s_loc % 2 == 0, "zigzag needs an even local length"
    s2 = s_loc // 2
    scale = scale if scale is not None else d ** -0.5

    tril = jnp.tril(jnp.ones((s2, s2), bool))
    qA, qB = q[:, :s2], q[:, s2:]

    # accumulators per half
    def zero_acc():
        return (jnp.zeros((b, s2, h, d), jnp.float32),
                jnp.full((b, h, s2), _NEG_INF, jnp.float32),
                jnp.zeros((b, h, s2), jnp.float32))

    accA, accB = zero_acc(), zero_acc()

    def merge(acc, o_b, m_b, l_b):
        o, m, l = acc
        m_new = jnp.maximum(m, m_b)
        c1 = jnp.exp(m - m_new)
        c2 = jnp.exp(m_b - m_new)
        o = (o * jnp.moveaxis(c1, 1, 2)[..., None]
             + o_b * jnp.moveaxis(c2, 1, 2)[..., None])
        return o, m_new, l * c1 + l_b * c2

    def merge_where(pred, acc, o_b, m_b, l_b):
        """Merge only where pred (per-member traced bool)."""
        o, m, l = acc
        o2, m2, l2 = merge(acc, o_b, m_b, l_b)
        sel = lambda x2, x1: jnp.where(pred, x2, x1)
        return sel(o2, o), sel(m2, m), sel(l2, l)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v
    for step in range(n):
        kC, vC = k_blk[:, :s2], v_blk[:, :s2]
        kD, vD = k_blk[:, s2:], v_blk[:, s2:]
        if step == 0:
            # diagonal: A·A causal, B·[A full | B causal]
            accA = merge(accA, *_block_attend(qA, kC, vC, scale, tril))
            accB = merge(accB, *_block_attend(qB, kC, vC, scale, None))
            accB = merge(accB, *_block_attend(qB, kD, vD, scale, tril))
        else:
            src = (my - step) % n
            pred = src < my              # else src > my (never equal here)
            # block 1: B·C — visible in both cases
            accB = merge(accB, *_block_attend(qB, kC, vC, scale, None))
            # block 2: A·C (pred) or B·D (!pred) — select operands, one dense
            qx = jnp.where(pred, qA, qB)
            ky = jnp.where(pred, kC, kD)
            vy = jnp.where(pred, vC, vD)
            o_b, m_b, l_b = _block_attend(qx, ky, vy, scale, None)
            accA = merge_where(pred, accA, o_b, m_b, l_b)
            accB = merge_where(~pred, accB, o_b, m_b, l_b)
        if step != n - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    def finalize(acc):
        o, m, l = acc
        return o / jnp.maximum(jnp.moveaxis(l, 1, 2)[..., None], 1e-30)

    out = jnp.concatenate([finalize(accA), finalize(accB)], axis=1)
    return out.astype(q.dtype)


def make_zigzag_ring_attention(mesh):
    """shard_map-wrapped zigzag ring attention (inputs already in zigzag
    layout, S sharded over sp)."""
    from paddle_tpu.distributed._compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), "sp", None, None)

    @functools.partial(shard_map, mesh=mesh.mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def attend(q, k, v):
        return zigzag_ring_attention(q, k, v, axis_name="sp")

    return attend
